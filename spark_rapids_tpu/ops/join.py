"""Device equi-join kernels.

Reference parity: GpuHashJoin.scala:104 (gather-map producing probe) +
JoinGatherer chunked assembly. cuDF builds a device hash table; the
TPU-idiomatic design is sort + binary-search:

1. normalize join keys to uint64 planes (ops.kernels.normalize_key),
2. combine multi-column keys into one u64 by hash mixing,
3. sort the BUILD side once by combined key,
4. per probe row, searchsorted left/right gives the hash-equal candidate
   range -- O(log n) per row, fully vectorized on the VPU,
5. count-then-gather: expand candidate ranges into (probe, build) pairs
   (host reads back ONE scalar = total candidates), then verify exact key
   equality per pair over the normalized planes and compact.

Null join keys never match (SQL semantics): null build rows are compacted
away before the sort; null probe rows force empty candidate ranges.
String keys use the equality-faithful 64-bit double-hash from
normalize_key (collision odds ~2^-64 per pair; documented incompat,
mirror of the reference's incompatOps discipline).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnVector, round_capacity
from spark_rapids_tpu.ops import kernels as K
from spark_rapids_tpu.runtime import compile_cache as _cc


def _combine_keys(cols: List[ColumnVector], num_rows: int, live=None
                  ) -> Tuple[jax.Array, List[jax.Array], jax.Array]:
    """Returns (combined u64 hash, per-col normalized planes, any_null)."""
    planes = []
    any_null = None
    for c in cols:
        k, nulls = K.normalize_key(c, num_rows, live=live)
        planes.append(k)
        any_null = nulls if any_null is None else (any_null | nulls)
    h = jnp.zeros_like(planes[0])
    for k in planes:
        # 64-bit mix (splitmix64 finalizer per plane)
        x = h ^ k
        x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
        h = x ^ (x >> jnp.uint64(31))
    return h, planes, any_null


#: direct-address table budget (int32 entries): dense integer join keys
#: (TPC-H orderkeys, dimension ids) take the 2-gather path below this
DENSE_KEY_RANGE_LIMIT = 1 << 26


def _dense_int_eligible(build_keys: List[ColumnVector],
                        probe_key_types) -> bool:
    if len(build_keys) != 1 or len(probe_key_types) != 1:
        return False
    bt, pt = build_keys[0].dtype, probe_key_types[0]
    from spark_rapids_tpu import types as T
    ok_types = (T.Int8Type, T.Int16Type, T.Int32Type, T.Int64Type,
                T.DateType, T.BooleanType)
    return isinstance(bt, ok_types) and isinstance(pt, ok_types)


class DenseBuildTable:
    """Direct-address layout of a build side with a single bounded integer
    key: starts[span+1] + sorted_orig[bcap] (counting sort by key), plus
    host facts (bmin, span, max_dup) fetched ONCE at prepare time. When
    max_dup == 1 (unique build keys — the star-schema shape), probing is
    completely sync-free: two gathers yield the matching build row per
    probe row, enabling mask-through join output with no pair expansion."""

    __slots__ = ("starts", "sorted_orig", "bmin", "span", "max_dup",
                 "bcap", "build_rows", "slot_idx")

    def __init__(self, starts, sorted_orig, bmin, span, max_dup, bcap,
                 build_rows):
        self.starts = starts
        self.sorted_orig = sorted_orig
        self.bmin = bmin
        self.span = span
        self.max_dup = max_dup
        self.bcap = bcap
        self.build_rows = build_rows
        #: unique-key builds: build row per key slot (-1 empty), computed
        #: once over the SPAN so probing is a single gather
        self.slot_idx = None
        if max_dup <= 1:
            occ = starts[1:] > starts[:-1]
            cand = sorted_orig[jnp.clip(starts[:-1], 0, bcap - 1)]
            self.slot_idx = jnp.where(occ, cand, -1)


def prepare_dense_build(build_keys: List[ColumnVector], build_rows: int,
                        probe_key_types) -> Optional[DenseBuildTable]:
    """Build the direct-address table when the dense-int path applies.
    probe_key_types: the probe keys' DataTypes (columns not needed).
    ONE host fetch (4 scalars). Returns None when ineligible."""
    if not _dense_int_eligible(build_keys, probe_key_types):
        return None
    bcap = build_keys[0].capacity
    bv = build_keys[0].data.astype(jnp.int64)
    valid = build_keys[0].validity_or_default(build_rows)
    b_in = (jnp.arange(bcap) < build_rows) & valid
    bmin_d = jnp.min(jnp.where(b_in, bv, jnp.int64(2**62)))
    bmax_d = jnp.max(jnp.where(b_in, bv, jnp.int64(-2**62)))
    nbuild_d = jnp.sum(b_in.astype(jnp.int32))
    bmin, bmax, nbuild = (int(x) for x in
                          jax.device_get([bmin_d, bmax_d, nbuild_d]))
    span = bmax - bmin + 1
    if nbuild <= 0 or not (0 < span <= DENSE_KEY_RANGE_LIMIT):
        return None
    starts, sorted_orig = _dense_table(bv, b_in, bcap, jnp.int64(bmin), span)
    cnt = starts[1:] - starts[:-1]
    max_dup = int(jnp.max(cnt)) if span > 0 else 0
    return DenseBuildTable(starts, sorted_orig, jnp.int64(bmin), span,
                           max_dup, bcap, build_rows)


def dense_lookup_planes(slot_idx: jax.Array, bmin, pv: jax.Array,
                        p_in: jax.Array) -> jax.Array:
    """Traced core of the sync-free unique-key probe: int32 build row
    index per probe row, -1 when unmatched. Shared by the eager path
    below and the fused masked-probe kernel (exec/tpu_nodes)."""
    span = slot_idx.shape[0]
    slot = pv - bmin
    inside = p_in & (slot >= 0) & (slot < span)
    sl = jnp.where(inside, slot, 0).astype(jnp.int32)
    return jnp.where(inside, slot_idx[sl], -1)


def dense_lookup(table: DenseBuildTable, probe_keys: List[ColumnVector],
                 probe_rows: int, probe_live=None) -> jax.Array:
    """Sync-free unique-key probe: int32[pcap] build row index per probe
    row, -1 when unmatched. Requires table.max_dup <= 1."""
    pv = probe_keys[0].data.astype(jnp.int64)
    # masked batches have live rows at ARBITRARY positions: combine the
    # column validity with the live mask directly, never arange<num_rows
    if probe_live is not None:
        p_in = probe_live if probe_keys[0].validity is None \
            else (probe_live & probe_keys[0].validity)
    else:
        p_in = probe_keys[0].validity_or_default(probe_rows)
    return dense_lookup_planes(table.slot_idx, table.bmin, pv, p_in)


def join_pairs(build_keys: List[ColumnVector], build_rows: int,
               probe_keys: List[ColumnVector], probe_rows: int,
               probe_live=None) -> Tuple[np.ndarray, np.ndarray]:
    """Compute matching (probe_idx, build_idx) pairs for an equi-join.
    Returned as device arrays (int32) with -1 padding; second return is the
    match count. Output order: probe-major (stable for the probe side).

    Two probe strategies:
    - DENSE-INT fast path: a single bounded integer key builds a
      direct-address (start, end) table over the key range — the probe is
      TWO O(probe) gathers and needs no hash verification. On this
      hardware a 32M-row binary search costs ~6s (22 round-trip gathers,
      64-bit lanes emulated); the dense path is ~50x cheaper and covers
      the TPC-H/star-schema join shape.
    - general path: sort build by 64-bit key hash, find each probe row's
      equal-hash candidate run by a SORT-MERGE rank over the hash union
      (measured: ``searchsorted`` on 64-bit lanes costs 8.7 s for 20M
      probes on v5e — 25x the cost of sorting the union), then expand +
      verify exact equality over the normalized planes."""
    table = prepare_dense_build(build_keys, build_rows,
                                [c.dtype for c in probe_keys])
    if table is not None:
        pcap0 = probe_keys[0].capacity
        if probe_live is not None:
            p_in0 = probe_live if probe_keys[0].validity is None \
                else (probe_live & probe_keys[0].validity)
        else:
            p_in0 = probe_keys[0].validity_or_default(probe_rows)
        return _dense_int_pairs(table,
                                probe_keys[0].data.astype(jnp.int64),
                                p_in0, pcap0)

    bh, bplanes, bnull = _combine_keys(build_keys, build_rows)
    ph, pplanes, pnull = _combine_keys(probe_keys, probe_rows,
                                       live=probe_live)
    bcap = bh.shape[0]
    pcap = ph.shape[0]
    b_in = (jnp.arange(bcap) < build_rows) & ~bnull
    # masked probe batches join WITHOUT compaction: liveness rides in
    p_in = ((probe_live if probe_live is not None
             else (jnp.arange(pcap) < probe_rows)) & ~pnull)

    # compact non-null build rows, then sort by hash
    bidx, bcount = K.filter_indices(b_in, bcap)
    bsel = jnp.clip(bidx, 0, bcap - 1)
    bh_c = jnp.where(bidx >= 0, bh[bsel], jnp.uint64(0xFFFFFFFFFFFFFFFF))
    order = jnp.argsort(bh_c)  # padded sentinel rows sort last
    sorted_h = bh_c[order]
    sorted_orig = jnp.where(bidx >= 0, bidx, -1)[order]

    lo, hi = _merge_rank_ranges(sorted_h, bcount, ph, p_in)
    total = int(jnp.sum((hi - lo).astype(jnp.int64)))

    probe_i, build_pos = K.expand_ranges(lo, hi, total)
    build_i = jnp.where(build_pos >= 0,
                        sorted_orig[jnp.clip(build_pos, 0, bcap - 1)], -1)

    # exact verification over normalized planes (hash could collide)
    ok = (probe_i >= 0) & (build_i >= 0)
    psel = jnp.clip(probe_i, 0, pcap - 1)
    bsel2 = jnp.clip(build_i, 0, bcap - 1)
    for pp, bp in zip(pplanes, bplanes):
        ok = ok & (pp[psel] == bp[bsel2])
    idx, match_count = K.filter_indices(ok, ok.shape[0])
    sel = jnp.clip(idx, 0, ok.shape[0] - 1)
    out_p = jnp.where(idx >= 0, probe_i[sel], -1)
    out_b = jnp.where(idx >= 0, build_i[sel], -1)
    return out_p, out_b, match_count


@_cc.jit(static_argnames=("bcap", "span"))
def _dense_table(bv, b_in, bcap, bmin, span):
    """(starts[span+1], sorted_orig[bcap]): direct-address layout of build
    rows grouped by key value (counting sort by key)."""
    slot = jnp.where(b_in, (bv - bmin).astype(jnp.int32), span)
    cnt = jax.ops.segment_sum(jnp.ones(bcap, jnp.int32), slot,
                              num_segments=span + 1)[:span]
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(cnt).astype(jnp.int32)])
    # stable counting sort: rows ordered by (key, original index)
    order = jnp.argsort(jnp.where(b_in, (bv - bmin),
                                  jnp.int64(1) << 62).astype(jnp.int64))
    sorted_orig = jnp.where(jnp.arange(bcap) < jnp.sum(b_in.astype(jnp.int32)),
                            order, -1)
    return starts, sorted_orig


def _dense_int_pairs(table: DenseBuildTable, pv, p_in, pcap):
    starts, sorted_orig, bcap = table.starts, table.sorted_orig, table.bcap
    slot = pv - table.bmin
    inside = p_in & (slot >= 0) & (slot < table.span)
    sl = jnp.where(inside, slot, 0).astype(jnp.int32)
    lo = jnp.where(inside, starts[sl], 0)
    hi = jnp.where(inside, starts[sl + 1], 0)
    counts = hi - lo
    if table.max_dup <= 1:
        # unique build keys (the dominant case): pairs ARE the matching
        # probe rows — no range expansion at all
        m = counts > 0
        idx, match_count = K.filter_indices(m, pcap)
        sel = jnp.clip(idx, 0, pcap - 1)
        out_p = jnp.where(idx >= 0, sel, -1)
        bpos = jnp.where(idx >= 0, lo[sel], 0)
        out_b = jnp.where(idx >= 0,
                          sorted_orig[jnp.clip(bpos, 0, bcap - 1)], -1)
        return out_p, out_b, match_count
    total = int(jnp.sum(counts.astype(jnp.int64)))
    probe_i, build_pos = K.expand_ranges(lo, hi, total)
    build_i = jnp.where(build_pos >= 0,
                        sorted_orig[jnp.clip(build_pos, 0, bcap - 1)], -1)
    return probe_i, build_i, total


def _merge_rank_ranges(sorted_h: jax.Array, bcount, ph: jax.Array,
                       p_in: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per probe row, the candidate run [lo, hi) of equal hashes in the
    sorted build plane — via ONE stable sort of the hash union (build rows
    tie-break before probe rows) instead of two 64-bit binary searches.
    sorted_h must carry the all-ones sentinel beyond bcount."""
    bcap = sorted_h.shape[0]
    pcap = ph.shape[0]
    # dead probe rows get the sentinel too: their run resolves empty below
    php = jnp.where(p_in, ph, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    allh = jnp.concatenate([sorted_h, php])
    isq = jnp.concatenate([jnp.zeros(bcap, jnp.uint8),
                           jnp.ones(pcap, jnp.uint8)])
    iota = jnp.arange(bcap + pcap, dtype=jnp.int32)
    sh, sq, si = jax.lax.sort((allh, isq, iota), num_keys=2, is_stable=True)
    # build rows at union positions <= i (build sorts before equal probes)
    nb_prefix = jnp.cumsum((sq == 0).astype(jnp.int32))
    # scatter each probe row's prefix count back to its original position
    dest = jnp.where(sq == 1, si - bcap, pcap)
    r = jnp.zeros(pcap + 1, jnp.int32).at[dest].set(nb_prefix,
                                                    mode="drop")[:pcap]
    r = jnp.minimum(r, bcount)  # sentinel pad rows are not candidates
    last_b = r - 1  # compact index of the last build row with h <= h_p
    lb = jnp.clip(last_b, 0, bcap - 1)
    eq = (last_b >= 0) & (last_b < bcount) & (sorted_h[lb] == ph) & p_in
    # first row of each equal-hash run in the sorted build plane
    pos = jnp.arange(bcap, dtype=jnp.int32)
    bound = jnp.concatenate([jnp.ones(1, jnp.bool_),
                             sorted_h[1:] != sorted_h[:-1]])
    run_start = jax.lax.cummax(jnp.where(bound, pos, 0))
    lo = jnp.where(eq, run_start[lb], 0)
    hi = jnp.where(eq, r, 0)
    return lo, hi


def probe_matched_mask(pairs_idx: jax.Array, cap: int) -> jax.Array:
    """bool[cap]: rows of a side that appear in the matched pairs. Pairs
    only ever reference LIVE rows (join_pairs gates on the live mask), so
    no in-range clamp — masked probe batches have live rows at arbitrary
    positions."""
    m = jnp.zeros(cap + 1, jnp.bool_)
    sel = jnp.where(pairs_idx >= 0, pairs_idx, cap)
    m = m.at[sel].set(True, mode="drop")
    return m[:cap]


def unmatched_indices(mask_matched: jax.Array, live: jax.Array
                      ) -> Tuple[jax.Array, int]:
    """Indices of LIVE rows not matched (for outer-join completion).
    `live` is the side's liveness plane (bool[cap])."""
    cap = mask_matched.shape[0]
    un = (~mask_matched) & live
    return K.filter_indices(un, cap)
