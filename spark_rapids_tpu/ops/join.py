"""Device equi-join kernels.

Reference parity: GpuHashJoin.scala:104 (gather-map producing probe) +
JoinGatherer chunked assembly. cuDF builds a device hash table; the
TPU-idiomatic design is sort + binary-search:

1. normalize join keys to uint64 planes (ops.kernels.normalize_key),
2. combine multi-column keys into one u64 by hash mixing,
3. sort the BUILD side once by combined key,
4. per probe row, searchsorted left/right gives the hash-equal candidate
   range -- O(log n) per row, fully vectorized on the VPU,
5. count-then-gather: expand candidate ranges into (probe, build) pairs
   (host reads back ONE scalar = total candidates), then verify exact key
   equality per pair over the normalized planes and compact.

Null join keys never match (SQL semantics): null build rows are compacted
away before the sort; null probe rows force empty candidate ranges.
String keys use the equality-faithful 64-bit double-hash from
normalize_key (collision odds ~2^-64 per pair; documented incompat,
mirror of the reference's incompatOps discipline).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnVector, round_capacity
from spark_rapids_tpu.ops import kernels as K


def _combine_keys(cols: List[ColumnVector], num_rows: int
                  ) -> Tuple[jax.Array, List[jax.Array], jax.Array]:
    """Returns (combined u64 hash, per-col normalized planes, any_null)."""
    planes = []
    any_null = None
    for c in cols:
        k, nulls = K.normalize_key(c, num_rows)
        planes.append(k)
        any_null = nulls if any_null is None else (any_null | nulls)
    h = jnp.zeros_like(planes[0])
    for k in planes:
        # 64-bit mix (splitmix64 finalizer per plane)
        x = h ^ k
        x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
        h = x ^ (x >> jnp.uint64(31))
    return h, planes, any_null


def join_pairs(build_keys: List[ColumnVector], build_rows: int,
               probe_keys: List[ColumnVector], probe_rows: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Compute matching (probe_idx, build_idx) pairs for an equi-join.
    Returned as device arrays (int32) with -1 padding; second return is the
    match count. Output order: probe-major (stable for the probe side)."""
    bh, bplanes, bnull = _combine_keys(build_keys, build_rows)
    ph, pplanes, pnull = _combine_keys(probe_keys, probe_rows)
    bcap = bh.shape[0]
    pcap = ph.shape[0]
    b_in = (jnp.arange(bcap) < build_rows) & ~bnull
    p_in = (jnp.arange(pcap) < probe_rows) & ~pnull

    # compact non-null build rows, then sort by hash
    bidx, bcount = K.filter_indices(b_in, bcap)
    bsel = jnp.clip(bidx, 0, bcap - 1)
    bh_c = jnp.where(bidx >= 0, bh[bsel], jnp.uint64(0xFFFFFFFFFFFFFFFF))
    order = jnp.argsort(bh_c)  # padded sentinel rows sort last
    sorted_h = bh_c[order]
    sorted_orig = jnp.where(bidx >= 0, bidx, -1)[order]

    lo = jnp.searchsorted(sorted_h, ph, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(sorted_h, ph, side="right").astype(jnp.int32)
    lo = jnp.where(p_in, lo, 0)
    hi = jnp.where(p_in, hi, 0)
    hi = jnp.minimum(hi, bcount)
    lo = jnp.minimum(lo, hi)
    total = int(jnp.sum((hi - lo).astype(jnp.int64)))

    probe_i, build_pos = K.expand_ranges(lo, hi, total)
    build_i = jnp.where(build_pos >= 0,
                        sorted_orig[jnp.clip(build_pos, 0, bcap - 1)], -1)

    # exact verification over normalized planes (hash could collide)
    ok = (probe_i >= 0) & (build_i >= 0)
    psel = jnp.clip(probe_i, 0, pcap - 1)
    bsel2 = jnp.clip(build_i, 0, bcap - 1)
    for pp, bp in zip(pplanes, bplanes):
        ok = ok & (pp[psel] == bp[bsel2])
    idx, match_count = K.filter_indices(ok, ok.shape[0])
    sel = jnp.clip(idx, 0, ok.shape[0] - 1)
    out_p = jnp.where(idx >= 0, probe_i[sel], -1)
    out_b = jnp.where(idx >= 0, build_i[sel], -1)
    return out_p, out_b, match_count


def probe_matched_mask(pairs_idx: jax.Array, n: int, cap: int) -> jax.Array:
    """bool[cap]: rows of a side that appear in the matched pairs."""
    m = jnp.zeros(cap + 1, jnp.bool_)
    sel = jnp.where(pairs_idx >= 0, pairs_idx, cap)
    m = m.at[sel].set(True, mode="drop")
    return m[:cap] & (jnp.arange(cap) < n)


def unmatched_indices(mask_matched: jax.Array, n: int) -> Tuple[jax.Array, int]:
    """Indices of in-range rows NOT matched (for outer joins)."""
    cap = mask_matched.shape[0]
    un = (~mask_matched) & (jnp.arange(cap) < n)
    return K.filter_indices(un, cap)
