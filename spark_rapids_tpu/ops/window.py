"""Segmented-scan window kernels.

Reference parity: the device algorithms behind GpuRunningWindowExec /
GpuBatchedBoundedWindowExec / rank-family expressions (SURVEY.md §2.4) —
re-designed as whole-plane prefix scans instead of cuDF rolling-window
kernels: after ONE sort by (partition, order) keys, every window function
is O(n) cumulative ops (cumsum / associative_scan with a segment-reset
combiner), which XLA fuses into the surrounding stage.

All kernels run over the SORTED row order. Inputs:
  seg_start[i]  — index of the first row of i's partition
  peer_start[i] — index of the first row of i's peer group (same partition
                  AND equal order keys; rank/range-frame semantics)
  live[i]       — rows beyond num_rows are dead (sorted to the tail)
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def segment_layout(seg_boundary: jax.Array, peer_boundary: jax.Array
                   ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """From boundary masks to (seg_start, seg_end, peer_start, peer_end),
    all inclusive row indices in sorted order."""
    n = seg_boundary.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    seg_start = lax.cummax(jnp.where(seg_boundary, idx, 0))
    peer_start = lax.cummax(jnp.where(peer_boundary, idx, 0))
    # end = (next boundary) - 1, scanning from the right
    big = jnp.int32(n - 1)
    nxt_seg = jnp.where(seg_boundary, idx, n)
    seg_end = jnp.minimum(
        jnp.flip(lax.cummin(jnp.flip(jnp.roll(nxt_seg, -1).at[-1].set(n)))) - 1, big)
    nxt_peer = jnp.where(peer_boundary, idx, n)
    peer_end = jnp.minimum(
        jnp.flip(lax.cummin(jnp.flip(jnp.roll(nxt_peer, -1).at[-1].set(n)))) - 1, big)
    return seg_start, seg_end, peer_start, peer_end


def row_number(seg_start: jax.Array) -> jax.Array:
    n = seg_start.shape[0]
    return (jnp.arange(n, dtype=jnp.int32) - seg_start + 1).astype(jnp.int32)


def rank(seg_start: jax.Array, peer_start: jax.Array) -> jax.Array:
    return (peer_start - seg_start + 1).astype(jnp.int32)


def dense_rank(seg_boundary: jax.Array, peer_boundary: jax.Array,
               seg_start: jax.Array) -> jax.Array:
    peers_before = jnp.cumsum(peer_boundary.astype(jnp.int32))
    return (peers_before - peers_before[seg_start] + 1).astype(jnp.int32)


def _seg_cumsum(x: jax.Array, seg_start: jax.Array) -> jax.Array:
    """Inclusive cumulative sum reset at segment starts."""
    cs = jnp.cumsum(x)
    return cs - cs[seg_start] + x[seg_start]


def running_sum_count(vals: jax.Array, valid: jax.Array, seg_start: jax.Array,
                      frame_end: jax.Array
                      ) -> Tuple[jax.Array, jax.Array]:
    """sum/count over [segment start, frame_end[i]] (frame_end = i for ROWS
    current-row, peer_end for RANGE current-row). Returns (sum, nvalid)."""
    masked = jnp.where(valid, vals, jnp.zeros_like(vals))
    cs = _seg_cumsum(masked, seg_start)
    cnt = _seg_cumsum(valid.astype(jnp.int64), seg_start)
    return cs[frame_end], cnt[frame_end]


def bounded_sum_count(vals: jax.Array, valid: jax.Array, seg_start: jax.Array,
                      seg_end: jax.Array, lower: Optional[int],
                      upper: Optional[int]
                      ) -> Tuple[jax.Array, jax.Array]:
    """sum/count over ROWS BETWEEN lower AND upper (offsets; None =
    unbounded). Prefix-difference over the segment-reset cumsum."""
    n = vals.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    lo = seg_start if lower is None else jnp.maximum(idx + lower, seg_start)
    hi = seg_end if upper is None else jnp.minimum(idx + upper, seg_end)
    masked = jnp.where(valid, vals, jnp.zeros_like(vals))
    cs = _seg_cumsum(masked, seg_start)
    cnt = _seg_cumsum(valid.astype(jnp.int64), seg_start)
    empty = hi < lo
    lo_c = jnp.clip(lo, 0, n - 1)
    hi_c = jnp.clip(hi, 0, n - 1)
    # sum over [lo, hi] = cs[hi] - cs[lo] + x[lo]
    s = cs[hi_c] - cs[lo_c] + masked[lo_c]
    c = cnt[hi_c] - cnt[lo_c] + jnp.where(valid[lo_c], 1, 0)
    return jnp.where(empty, jnp.zeros_like(s), s), jnp.where(empty, 0, c)


def _seg_scan(op, x: jax.Array, seg_id: jax.Array) -> jax.Array:
    """Inclusive segmented scan with combiner `op` (max/min), reset at
    segment changes, via associative_scan over (seg_id, value) pairs."""

    def combine(a, b):
        sa, va = a
        sb, vb = b
        same = sa == sb
        return sb, jnp.where(same, op(va, vb), vb)

    _, out = lax.associative_scan(combine, (seg_id, x))
    return out


def running_minmax(op: str, vals: jax.Array, valid: jax.Array,
                   seg_id: jax.Array, seg_start: jax.Array,
                   frame_end: jax.Array,
                   ) -> Tuple[jax.Array, jax.Array]:
    """min/max over [segment start, frame_end[i]]; NaN handled by Spark
    total order (NaN > +inf) via where-substitution."""
    vdt = vals.dtype
    is_float = np.dtype(vdt) in (np.dtype(np.float32), np.dtype(np.float64))
    nvalid = _seg_cumsum(valid.astype(jnp.int32), seg_start)
    if is_float:
        nanmask = jnp.isnan(vals)
        sentinel = jnp.array(np.inf if op == "min" else -np.inf, vdt)
        clean = jnp.where(valid & ~nanmask, vals, jnp.full_like(vals, sentinel))
        red = _seg_scan(jnp.minimum if op == "min" else jnp.maximum, clean, seg_id)
        any_nan = _seg_scan(jnp.maximum, (valid & nanmask).astype(jnp.int32),
                            seg_id) > 0
        any_nonnan = _seg_scan(jnp.maximum, (valid & ~nanmask).astype(jnp.int32),
                               seg_id) > 0
        if op == "max":
            out = jnp.where(any_nan, jnp.array(np.nan, vdt), red)
        else:
            out = jnp.where(any_nonnan, red, jnp.array(np.nan, vdt))
        return out[frame_end], nvalid[frame_end]
    if np.dtype(vdt) == np.dtype(np.bool_):
        ident = jnp.array(True if op == "min" else False)
        neutral = jnp.where(valid, vals, ident)
        red = _seg_scan(jnp.logical_and if op == "min" else jnp.logical_or,
                        neutral, seg_id)
        return red[frame_end], nvalid[frame_end]
    info = np.iinfo(np.dtype(vdt))
    ident = jnp.array(info.max if op == "min" else info.min, vdt)
    neutral = jnp.where(valid, vals, jnp.full_like(vals, ident))
    red = _seg_scan(jnp.minimum if op == "min" else jnp.maximum, neutral, seg_id)
    return red[frame_end], nvalid[frame_end]


def lead_lag(vals: jax.Array, valid: jax.Array, seg_id: jax.Array,
             offset: int) -> Tuple[jax.Array, jax.Array]:
    """value at row i+offset if still in the same partition, else null.
    (lag = negative offset)."""
    n = vals.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32) + offset
    in_range = (idx >= 0) & (idx < n)
    safe = jnp.clip(idx, 0, n - 1)
    same = in_range & (seg_id[safe] == seg_id)
    return vals[safe], same & valid[safe]


def ntile(n_tiles: int, seg_start: jax.Array, seg_end: jax.Array) -> jax.Array:
    """Spark ntile: first (size % n) tiles get one extra row."""
    size = (seg_end - seg_start + 1).astype(jnp.int64)
    pos = (jnp.arange(seg_start.shape[0], dtype=jnp.int64) - seg_start)
    base = size // n_tiles
    rem = size % n_tiles
    cut = (base + 1) * rem  # rows covered by the bigger tiles
    in_big = pos < cut
    tile_big = pos // jnp.maximum(base + 1, 1)
    tile_small = rem + (pos - cut) // jnp.maximum(base, 1)
    return (jnp.where(in_big, tile_big, tile_small) + 1).astype(jnp.int32)
