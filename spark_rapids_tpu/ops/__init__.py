from spark_rapids_tpu.ops import kernels  # noqa: F401
