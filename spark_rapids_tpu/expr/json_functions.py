"""JSON expressions: get_json_object, from_json.

Reference parity: GpuGetJsonObject.scala (JNI JSONUtils path query),
GpuJsonToStructs.scala / GpuJsonReadCommon.scala. The reference runs these
in native JNI kernels; here JSON parsing is host-side (the CPU fallback
tier, expr/cpu_functions.py discipline) with the same Spark semantics:

- get_json_object: a JSONPath subset ($, .field, ['field'], [index], [*]);
  matched scalars render unquoted, objects/arrays re-serialize compactly,
  invalid JSON or missing path -> null.
- from_json: schema'd parse into a struct; missing fields -> null, type
  mismatches -> null field (PERMISSIVE mode), invalid JSON -> null row.
"""
from __future__ import annotations

import json
import re
from typing import List, Optional

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import CpuCol, Expression, SparkException
from spark_rapids_tpu.expr.cpu_functions import CpuRowFunction

_PATH_TOKEN = re.compile(
    r"\.(?P<field>[^.\[\]]+)|\[(?P<index>\d+)\]|\[\*\]|\['(?P<qfield>[^']+)'\]")


def parse_json_path(path: str) -> Optional[List]:
    """'$.a.b[0]' -> ['a', 'b', 0]; None when the path is unsupported.
    '[*]' parses to the wildcard marker '*'."""
    if not path or not path.startswith("$"):
        return None
    rest = path[1:]
    out: List = []
    pos = 0
    while pos < len(rest):
        m = _PATH_TOKEN.match(rest, pos)
        if m is None:
            return None
        if m.group("field") is not None:
            out.append(m.group("field"))
        elif m.group("qfield") is not None:
            out.append(m.group("qfield"))
        elif m.group("index") is not None:
            out.append(int(m.group("index")))
        else:
            out.append("*")
        pos = m.end()
    return out


def _walk(value, steps: List):
    if not steps:
        return value
    step, rest = steps[0], steps[1:]
    if step == "*":
        if not isinstance(value, list):
            return None
        hits = [_walk(v, rest) for v in value]
        hits = [h for h in hits if h is not None]
        if not hits:
            return None
        # Spark unwraps a wildcard that matched exactly one element
        # ('$.a[*].b' over a one-element array returns the element, not
        # a one-element JSON array)
        return hits[0] if len(hits) == 1 else hits
    if isinstance(step, int):
        if not isinstance(value, list) or step >= len(value):
            return None
        return _walk(value[step], rest)
    if not isinstance(value, dict) or step not in value:
        return None
    return _walk(value[step], rest)


def _render(v) -> Optional[str]:
    if v is None:
        return None
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return json.dumps(v)
    return json.dumps(v, separators=(",", ":"))


class GetJsonObject(CpuRowFunction):
    """get_json_object(json, path) (reference GpuGetJsonObject.scala)."""

    name = "get_json_object"
    result = T.STRING

    def __init__(self, *children, params=()):
        super().__init__(*children, params=params)
        self._steps = parse_json_path(self.params[0])

    def row_fn(self, s):
        if self._steps is None:
            return None
        try:
            v = json.loads(s)
        except (ValueError, TypeError):
            return None
        return _render(_walk(v, self._steps))


def _coerce(v, dt: T.DataType):
    """PERMISSIVE-mode coercion of one parsed JSON value to a field type."""
    if v is None:
        return None
    try:
        if isinstance(dt, T.StringType):
            return v if isinstance(v, str) else _render(v)
        if isinstance(dt, T.BooleanType):
            return v if isinstance(v, bool) else None
        if dt.is_integral:
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            iv = int(v)
            return iv if float(iv) == float(v) else None
        if isinstance(dt, (T.Float32Type, T.Float64Type)):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return None
            return float(v)
        if isinstance(dt, T.ArrayType):
            if not isinstance(v, list):
                return None
            return [_coerce(x, dt.element) for x in v]
        if isinstance(dt, T.StructType):
            if not isinstance(v, dict):
                return None
            return {f.name: _coerce(v.get(f.name), f.dtype)
                    for f in dt.fields}
        if isinstance(dt, T.MapType):
            if not isinstance(v, dict):
                return None
            return [(k, _coerce(x, dt.value)) for k, x in v.items()]
    except (ValueError, TypeError):
        return None
    return None


class JsonToStructs(CpuRowFunction):
    """from_json(json, schema) -> struct (reference GpuJsonToStructs)."""

    name = "from_json"

    def __init__(self, *children, params=()):
        super().__init__(*children, params=params)
        self.result = self.params[0]
        if not isinstance(self.result, (T.StructType, T.ArrayType, T.MapType)):
            raise SparkException(
                f"from_json schema must be struct/array/map, got {self.result!r}")

    def row_fn(self, s):
        try:
            v = json.loads(s)
        except (ValueError, TypeError):
            return None
        return _coerce(v, self.result)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        out = []
        ok = []
        for v, valid in zip(c.values, c.valid):
            r = self.row_fn(v) if valid else None
            out.append(r)
            ok.append(r is not None)
        vals = np.empty(len(out), object)
        vals[:] = out
        return CpuCol(self.result, vals, np.asarray(ok, np.bool_))


JSON_FUNCTIONS = [GetJsonObject, JsonToStructs]
