"""Math expressions (reference mathExpressions.scala).

Unary double functions follow Spark semantics: null-propagating, NaN for
out-of-domain inputs (sqrt(-1) -> NaN, log(0) -> null in Spark? -- no:
Spark log(0) = null pre-3.0? Current Spark returns null for log(x<=0) only
under ANSI; standard returns NULL for x<=0 via strictness of Logarithm.
We match current Spark: log/ln of non-positive -> null).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector
from spark_rapids_tpu.expr.core import (
    CpuCol, Expression, _promote, _promote_cpu, _valid_of,
)


class _UnaryDouble(Expression):
    fn_tpu = None
    fn_cpu = None
    #: rows where the input is outside the domain become null (Spark).
    domain = None  # fn(values) -> bool mask of in-domain rows

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.FLOAT64

    def with_children(self, children):
        return type(self)(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        v = c.data.astype(np.float64)
        valid = _valid_of(c, ctx)
        if type(self).domain is not None:
            ok = type(self).domain(v)
            valid = valid & ok
            v = jnp.where(ok, v, 1.0)
        return ColumnVector(T.FLOAT64, type(self).fn_tpu(v), valid)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        v = c.values.astype(np.float64)
        valid = c.valid
        with np.errstate(all="ignore"):
            if type(self).domain is not None:
                ok = type(self).domain(v)
                valid = valid & ok
                v = np.where(ok, v, 1.0)
            return CpuCol(T.FLOAT64, type(self).fn_cpu(v), valid)


class Sqrt(_UnaryDouble):
    fn_tpu = staticmethod(jnp.sqrt)
    fn_cpu = staticmethod(np.sqrt)


class Exp(_UnaryDouble):
    fn_tpu = staticmethod(jnp.exp)
    fn_cpu = staticmethod(np.exp)


class Log(_UnaryDouble):
    fn_tpu = staticmethod(jnp.log)
    fn_cpu = staticmethod(np.log)
    domain = staticmethod(lambda v: v > 0)


class Log10(_UnaryDouble):
    fn_tpu = staticmethod(jnp.log10)
    fn_cpu = staticmethod(np.log10)
    domain = staticmethod(lambda v: v > 0)


class Log2(_UnaryDouble):
    fn_tpu = staticmethod(jnp.log2)
    fn_cpu = staticmethod(np.log2)
    domain = staticmethod(lambda v: v > 0)


class Acosh(_UnaryDouble):
    """acosh (reference mathExpressions.scala GpuAcosh): out-of-domain
    inputs produce NaN like Spark's log-formula evaluation, not NULL."""
    fn_tpu = staticmethod(jnp.arccosh)
    fn_cpu = staticmethod(np.arccosh)


class Asinh(_UnaryDouble):
    fn_tpu = staticmethod(jnp.arcsinh)
    fn_cpu = staticmethod(np.arcsinh)


class Atanh(_UnaryDouble):
    fn_tpu = staticmethod(jnp.arctanh)
    fn_cpu = staticmethod(np.arctanh)


class Sin(_UnaryDouble):
    fn_tpu = staticmethod(jnp.sin)
    fn_cpu = staticmethod(np.sin)


class Cos(_UnaryDouble):
    fn_tpu = staticmethod(jnp.cos)
    fn_cpu = staticmethod(np.cos)


class Tan(_UnaryDouble):
    fn_tpu = staticmethod(jnp.tan)
    fn_cpu = staticmethod(np.tan)


class Asin(_UnaryDouble):
    fn_tpu = staticmethod(jnp.arcsin)
    fn_cpu = staticmethod(np.arcsin)


class Acos(_UnaryDouble):
    fn_tpu = staticmethod(jnp.arccos)
    fn_cpu = staticmethod(np.arccos)


class Atan(_UnaryDouble):
    fn_tpu = staticmethod(jnp.arctan)
    fn_cpu = staticmethod(np.arctan)


class Sinh(_UnaryDouble):
    fn_tpu = staticmethod(jnp.sinh)
    fn_cpu = staticmethod(np.sinh)


class Cosh(_UnaryDouble):
    fn_tpu = staticmethod(jnp.cosh)
    fn_cpu = staticmethod(np.cosh)


class Tanh(_UnaryDouble):
    fn_tpu = staticmethod(jnp.tanh)
    fn_cpu = staticmethod(np.tanh)


_LONG_MIN = -(2 ** 63)
_LONG_MAX = 2 ** 63 - 1


def _double_to_long_tpu(v):
    """Scala Double.toLong semantics: NaN -> 0, clamp to Long range."""
    v = jnp.where(jnp.isnan(v), 0.0, v)
    return jnp.clip(v, float(_LONG_MIN), float(_LONG_MAX)).astype(np.int64)


def _double_to_long_np(v):
    v = np.where(np.isnan(v), 0.0, v)
    return np.clip(v, float(_LONG_MIN), float(_LONG_MAX)).astype(np.int64)


class Ceil(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT64

    def with_children(self, children):
        return Ceil(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        v = jnp.ceil(c.data.astype(np.float64))
        return ColumnVector(T.INT64, _double_to_long_tpu(v), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        with np.errstate(all="ignore"):
            v = np.ceil(c.values.astype(np.float64))
            return CpuCol(T.INT64, _double_to_long_np(v), c.valid)


class Floor(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT64

    def with_children(self, children):
        return Floor(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        v = jnp.floor(c.data.astype(np.float64))
        return ColumnVector(T.INT64, _double_to_long_tpu(v), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        with np.errstate(all="ignore"):
            v = np.floor(c.values.astype(np.float64))
            return CpuCol(T.INT64, _double_to_long_np(v), c.valid)


class Pow(Expression):
    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self):
        return T.FLOAT64

    def with_children(self, children):
        return Pow(children[0], children[1])

    def eval_tpu(self, ctx):
        l = self.children[0].eval_tpu(ctx)
        r = self.children[1].eval_tpu(ctx)
        v = jnp.power(l.data.astype(np.float64), r.data.astype(np.float64))
        return ColumnVector(T.FLOAT64, v, _valid_of(l, ctx) & _valid_of(r, ctx))

    def eval_cpu(self, cols, ansi=False):
        l = self.children[0].eval_cpu(cols, ansi)
        r = self.children[1].eval_cpu(cols, ansi)
        with np.errstate(all="ignore"):
            v = np.power(l.values.astype(np.float64), r.values.astype(np.float64))
        return CpuCol(T.FLOAT64, v, l.valid & r.valid)


class Round(Expression):
    """round(x, d): HALF_UP for decimals/integers, HALF_EVEN quirk: Spark
    round() on doubles is HALF_UP too (BigDecimal HALF_UP)."""

    def __init__(self, child, scale: int = 0):
        self.children = [child]
        self.scale = scale

    def data_type(self):
        dt = self.children[0].data_type()
        return dt if dt.is_numeric else T.FLOAT64

    def _params(self):
        return str(self.scale)

    def with_children(self, children):
        return Round(children[0], self.scale)

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        valid = _valid_of(c, ctx)
        dt = self.data_type()
        if dt.is_integral:
            if self.scale >= 0:
                return c
            f = 10 ** (-self.scale)
            half = f // 2
            sign = jnp.sign(c.data)
            mag = jnp.abs(c.data.astype(np.int64))
            v = sign * (((mag + half) // f) * f)
            return ColumnVector(dt, v.astype(dt.np_dtype), valid)
        v = c.data.astype(np.float64)
        f = 10.0 ** self.scale
        inv = 10.0 ** (-self.scale)
        scaled = v * f
        # HALF_UP: away from zero. Rescale by multiply (not divide): XLA
        # strength-reduces constant division to reciprocal-multiply anyway,
        # and the CPU path mirrors it so both engines agree bit-for-bit
        # (<=1 ulp from Spark's BigDecimal rounding; documented incompat
        # like the reference's improvedFloatOps).
        r = jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)
        return ColumnVector(T.FLOAT64, r * inv, valid)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        dt = self.data_type()
        with np.errstate(all="ignore"):
            if dt.is_integral:
                if self.scale >= 0:
                    return c
                f = 10 ** (-self.scale)
                half = f // 2
                sign = np.sign(c.values)
                mag = np.abs(c.values.astype(np.int64))
                v = sign * (((mag + half) // f) * f)
                return CpuCol(dt, v.astype(dt.np_dtype), c.valid)
            v = c.values.astype(np.float64)
            f = 10.0 ** self.scale
            inv = 10.0 ** (-self.scale)
            scaled = v * f
            r = np.sign(scaled) * np.floor(np.abs(scaled) + 0.5)
            return CpuCol(T.FLOAT64, r * inv, c.valid)


class Signum(_UnaryDouble):
    fn_tpu = staticmethod(jnp.sign)
    fn_cpu = staticmethod(np.sign)


class Atan2(Expression):
    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self):
        return T.FLOAT64

    def with_children(self, children):
        return Atan2(children[0], children[1])

    def eval_tpu(self, ctx):
        l = self.children[0].eval_tpu(ctx)
        r = self.children[1].eval_tpu(ctx)
        v = jnp.arctan2(l.data.astype(np.float64), r.data.astype(np.float64))
        return ColumnVector(T.FLOAT64, v, _valid_of(l, ctx) & _valid_of(r, ctx))

    def eval_cpu(self, cols, ansi=False):
        l = self.children[0].eval_cpu(cols, ansi)
        r = self.children[1].eval_cpu(cols, ansi)
        with np.errstate(all="ignore"):
            v = np.arctan2(l.values.astype(np.float64), r.values.astype(np.float64))
        return CpuCol(T.FLOAT64, v, l.valid & r.valid)


class Greatest(Expression):
    """greatest(...): max ignoring nulls; null only if all null."""

    largest = True

    def __init__(self, *children):
        self.children = list(children)

    def data_type(self):
        dt = self.children[0].data_type()
        for c in self.children[1:]:
            dt = T.common_type(dt, c.data_type())
        return dt

    def with_children(self, children):
        return type(self)(*children)

    def eval_tpu(self, ctx):
        out = self.data_type()
        cs = [c.eval_tpu(ctx) for c in self.children]
        acc = None
        acc_valid = None
        for c in cs:
            v = c.data.astype(out.np_dtype)
            cv = _valid_of(c, ctx)
            if acc is None:
                acc, acc_valid = v, cv
            else:
                pick_new = cv & (~acc_valid | (v > acc if self.largest else v < acc))
                acc = jnp.where(pick_new, v, acc)
                acc_valid = acc_valid | cv
        return ColumnVector(out, acc, acc_valid)

    def eval_cpu(self, cols, ansi=False):
        out = self.data_type()
        cs = [c.eval_cpu(cols, ansi) for c in self.children]
        acc = None
        acc_valid = None
        with np.errstate(all="ignore"):
            for c in cs:
                v = c.values.astype(out.np_dtype)
                cv = c.valid
                if acc is None:
                    acc, acc_valid = v.copy(), cv.copy()
                else:
                    pick_new = cv & (~acc_valid | (v > acc if self.largest else v < acc))
                    acc = np.where(pick_new, v, acc)
                    acc_valid = acc_valid | cv
        return CpuCol(out, acc, acc_valid)


class Least(Greatest):
    largest = False


class _Bitwise(Expression):
    """Bitwise binary ops over integral types (reference bitwise exprs)."""

    op = "and"

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self):
        from spark_rapids_tpu.types import common_type
        return common_type(self.children[0].data_type(),
                           self.children[1].data_type())

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def _apply(self, a, b):
        import operator
        return {"and": operator.and_, "or": operator.or_,
                "xor": operator.xor}[self.op](a, b)

    def eval_tpu(self, ctx):
        from spark_rapids_tpu.expr.core import _valid_of
        l = self.children[0].eval_tpu(ctx)
        r = self.children[1].eval_tpu(ctx)
        dt = self.data_type()
        out = self._apply(l.data.astype(dt.np_dtype), r.data.astype(dt.np_dtype))
        return ColumnVector(dt, out, _valid_of(l, ctx) & _valid_of(r, ctx))

    def eval_cpu(self, cols, ansi=False):
        l = self.children[0].eval_cpu(cols, ansi)
        r = self.children[1].eval_cpu(cols, ansi)
        dt = self.data_type()
        out = self._apply(l.values.astype(dt.np_dtype),
                          r.values.astype(dt.np_dtype))
        return CpuCol(dt, out, l.valid & r.valid)


class BitwiseAnd(_Bitwise):
    op = "and"


class BitwiseOr(_Bitwise):
    op = "or"


class BitwiseXor(_Bitwise):
    op = "xor"


class BitwiseNot(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return self.children[0].data_type()

    def with_children(self, children):
        return BitwiseNot(children[0])

    def eval_tpu(self, ctx):
        from spark_rapids_tpu.expr.core import _valid_of
        c = self.children[0].eval_tpu(ctx)
        return ColumnVector(c.dtype, ~c.data, _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        return CpuCol(c.dtype, ~c.values, c.valid)


class _Shift(Expression):
    """shiftleft/shiftright: Java semantics — the shift distance wraps mod
    the value's bit width."""

    left = True
    arithmetic = True

    def __init__(self, value, amount):
        self.children = [value, amount]

    def data_type(self):
        return self.children[0].data_type()

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def _shift(self, xp, v, n):
        width = v.dtype.itemsize * 8
        n = n % width
        if self.left:
            return v << n
        if self.arithmetic:
            return v >> n
        # logical right shift: through the unsigned view
        udt = {1: xp.uint8, 2: xp.uint16, 4: xp.uint32, 8: xp.uint64}[v.dtype.itemsize]
        return (v.astype(udt) >> n.astype(udt)).astype(v.dtype)

    def eval_tpu(self, ctx):
        from spark_rapids_tpu.expr.core import _valid_of
        v = self.children[0].eval_tpu(ctx)
        n = self.children[1].eval_tpu(ctx)
        out = self._shift(jnp, v.data, n.data.astype(v.data.dtype))
        return ColumnVector(v.dtype, out, _valid_of(v, ctx) & _valid_of(n, ctx))

    def eval_cpu(self, cols, ansi=False):
        v = self.children[0].eval_cpu(cols, ansi)
        n = self.children[1].eval_cpu(cols, ansi)
        out = self._shift(np, v.values, n.values.astype(v.values.dtype))
        return CpuCol(v.dtype, out, v.valid & n.valid)


class ShiftLeft(_Shift):
    left = True


class ShiftRight(_Shift):
    left = False
    arithmetic = True


class ShiftRightUnsigned(_Shift):
    left = False
    arithmetic = False


class Murmur3Hash(Expression):
    """hash(...): Spark Murmur3 (seed 42) over any number of columns —
    bit-parity with the reference's GPU murmur3 (HashFunctions.scala)."""

    def __init__(self, *children):
        self.children = list(children)

    def data_type(self):
        from spark_rapids_tpu import types as TT
        return TT.INT32

    def with_children(self, children):
        return Murmur3Hash(*children)

    def eval_tpu(self, ctx):
        from spark_rapids_tpu import types as TT
        from spark_rapids_tpu.ops import kernels as K
        cols = [c.eval_tpu(ctx) for c in self.children]
        h = K.spark_murmur3_batch(cols, ctx.num_rows, live=ctx.row_mask)
        import jax.numpy as jnp2
        return ColumnVector(TT.INT32, h.astype(jnp2.int32), None)

    def eval_cpu(self, cols, ansi=False):
        # reuse the device kernel on the CPU backend for bit parity
        import jax.numpy as jnp2
        from spark_rapids_tpu import types as TT
        from spark_rapids_tpu.columnar.batch import ColumnarBatch, from_pydict
        from spark_rapids_tpu.ops import kernels as K
        ins = [c.eval_cpu(cols, ansi) for c in self.children]
        n = len(ins[0].values)
        import pyarrow as pa
        arrays = {}
        from spark_rapids_tpu.exec.cpu_backend import cols_to_table
        table = cols_to_table(ins, [f"c{i}" for i in range(len(ins))])
        from spark_rapids_tpu.columnar.batch import from_arrow
        batch = from_arrow(table)
        h = K.spark_murmur3_batch(batch.columns, batch.num_rows)
        vals = np.asarray(h).astype(np.int32)[:n]
        return CpuCol(TT.INT32, vals, np.ones(n, np.bool_))


# ---------------------------------------------------------------------------
# Extended math breadth (reference mathExpressions.scala second tier)
# ---------------------------------------------------------------------------

class Cbrt(_UnaryDouble):
    fn_tpu = staticmethod(jnp.cbrt)
    fn_cpu = staticmethod(np.cbrt)


class Cot(_UnaryDouble):
    fn_tpu = staticmethod(lambda v: 1.0 / jnp.tan(v))
    fn_cpu = staticmethod(lambda v: 1.0 / np.tan(v))


class Sec(_UnaryDouble):
    fn_tpu = staticmethod(lambda v: 1.0 / jnp.cos(v))
    fn_cpu = staticmethod(lambda v: 1.0 / np.cos(v))


class Csc(_UnaryDouble):
    fn_tpu = staticmethod(lambda v: 1.0 / jnp.sin(v))
    fn_cpu = staticmethod(lambda v: 1.0 / np.sin(v))


class ToDegrees(_UnaryDouble):
    fn_tpu = staticmethod(jnp.degrees)
    fn_cpu = staticmethod(np.degrees)


class ToRadians(_UnaryDouble):
    fn_tpu = staticmethod(jnp.radians)
    fn_cpu = staticmethod(np.radians)


class Expm1(_UnaryDouble):
    fn_tpu = staticmethod(jnp.expm1)
    fn_cpu = staticmethod(np.expm1)


class Log1p(_UnaryDouble):
    fn_tpu = staticmethod(jnp.log1p)
    fn_cpu = staticmethod(np.log1p)
    domain = staticmethod(lambda v: v > -1)


class Rint(_UnaryDouble):
    fn_tpu = staticmethod(jnp.rint)
    fn_cpu = staticmethod(np.rint)


class Hypot(Expression):
    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self):
        return T.FLOAT64

    def with_children(self, children):
        return Hypot(children[0], children[1])

    def eval_tpu(self, ctx):
        l = self.children[0].eval_tpu(ctx)
        r = self.children[1].eval_tpu(ctx)
        v = jnp.hypot(l.data.astype(np.float64), r.data.astype(np.float64))
        return ColumnVector(T.FLOAT64, v, _valid_of(l, ctx) & _valid_of(r, ctx))

    def eval_cpu(self, cols, ansi=False):
        l = self.children[0].eval_cpu(cols, ansi)
        r = self.children[1].eval_cpu(cols, ansi)
        return CpuCol(T.FLOAT64,
                      np.hypot(l.values.astype(np.float64),
                               r.values.astype(np.float64)),
                      l.valid & r.valid)


class Logarithm(Expression):
    """log(base, expr) (reference GpuLogarithm,
    mathExpressions.scala): ln(expr)/ln(base), NULL when either side
    is non-positive (non-ANSI strictness; base == 1 keeps Java's
    divide-by-zero Inf/NaN result)."""

    def __init__(self, base, child):
        self.children = [base, child]

    def data_type(self):
        return T.FLOAT64

    def with_children(self, children):
        return Logarithm(children[0], children[1])

    def eval_tpu(self, ctx):
        b = self.children[0].eval_tpu(ctx)
        c = self.children[1].eval_tpu(ctx)
        bv = b.data.astype(np.float64)
        cv = c.data.astype(np.float64)
        ok = (bv > 0) & (cv > 0)
        v = jnp.log(jnp.where(ok, cv, 1.0)) / jnp.log(jnp.where(ok, bv, 2.0))
        return ColumnVector(T.FLOAT64, v,
                            _valid_of(b, ctx) & _valid_of(c, ctx) & ok)

    def eval_cpu(self, cols, ansi=False):
        b = self.children[0].eval_cpu(cols, ansi)
        c = self.children[1].eval_cpu(cols, ansi)
        bv = b.values.astype(np.float64)
        cv = c.values.astype(np.float64)
        ok = (bv > 0) & (cv > 0)
        with np.errstate(divide="ignore", invalid="ignore"):
            v = np.log(np.where(ok, cv, 1.0)) / np.log(np.where(ok, bv, 2.0))
        return CpuCol(T.FLOAT64, v, b.valid & c.valid & ok)


#: 0!..20! fit int64 (Spark returns null outside [0, 20])
_FACTORIALS = np.cumprod([1] + list(range(1, 21)), dtype=np.int64)


class Factorial(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT64

    def with_children(self, children):
        return Factorial(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        v = c.data.astype(jnp.int64)  # range-check BEFORE any narrowing
        ok = (v >= 0) & (v <= 20)
        out = jnp.asarray(_FACTORIALS)[jnp.clip(v, 0, 20).astype(jnp.int32)]
        return ColumnVector(T.INT64, out, _valid_of(c, ctx) & ok)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        v = c.values.astype(np.int64)
        ok = (v >= 0) & (v <= 20)
        out = _FACTORIALS[np.clip(v, 0, 20)]
        return CpuCol(T.INT64, out, c.valid & ok)


class Pmod(Expression):
    """pmod(a, b): the non-negative remainder ((a % b) + b) % b
    (reference GpuPmod); b == 0 is NULL outside ANSI."""

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self):
        # same numeric promotion as Remainder (mixed widths/floats)
        return T.common_type(self.children[0].data_type(),
                             self.children[1].data_type())

    def with_children(self, children):
        return Pmod(children[0], children[1])

    # Spark pmod is Java % (fmod: dividend sign) followed by ONE
    # conditional fold: if r < 0 then r = (r + n) % n. Both operands go
    # through the same numeric promotion as Remainder (decimal unscaled
    # values rescale to the common type before the mod).

    def eval_tpu(self, ctx):
        l = self.children[0].eval_tpu(ctx)
        r = self.children[1].eval_tpu(ctx)
        out = self.data_type()
        ld, rd = _promote(l, r, out)
        valid = _valid_of(l, ctx) & _valid_of(r, ctx)
        # decimals promote to unscaled int64 lanes: integer arithmetic
        int_like = out.is_integral or isinstance(out, T.DecimalType)
        zero = rd == 0
        safe = jnp.where(zero, 1, rd) if int_like \
            else jnp.where(zero, 1.0, rd)
        rem = jnp.fmod(ld, safe)
        rem = jnp.where(rem < 0, jnp.fmod(rem + safe, safe), rem)
        return ColumnVector(out, jnp.where(zero, 0 if int_like else jnp.nan,
                                           rem), valid & ~zero)

    def eval_cpu(self, cols, ansi=False):
        l = self.children[0].eval_cpu(cols, ansi)
        r = self.children[1].eval_cpu(cols, ansi)
        out = self.data_type()
        ld, rd = _promote_cpu(l, r, out)
        valid = l.valid & r.valid
        with np.errstate(all="ignore"):
            zero = rd == 0
            safe = np.where(zero, 1, rd)
            rem = np.fmod(ld, safe)
            rem = np.where(rem < 0, np.fmod(rem + safe, safe), rem)
            rem = np.where(zero, 0, rem)
        return CpuCol(out, rem, valid & ~zero)


class UnaryPositive(Expression):
    """+expr: the identity (reference registers it as a pass-through)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return self.children[0].data_type()

    def with_children(self, children):
        return UnaryPositive(children[0])

    def eval_tpu(self, ctx):
        return self.children[0].eval_tpu(ctx)

    def eval_cpu(self, cols, ansi=False):
        return self.children[0].eval_cpu(cols, ansi)


class WidthBucket(Expression):
    """width_bucket(v, lo, hi, n): 1-based equi-width histogram bucket;
    0 below, n+1 above; NULL for invalid n or lo == hi with NaN rules
    (Spark WidthBucket semantics)."""

    def __init__(self, value, lo, hi, nb):
        self.children = [value, lo, hi, nb]

    def data_type(self):
        return T.INT64

    def with_children(self, children):
        return WidthBucket(*children)

    @staticmethod
    def _compute(xp, v, lo, hi, nb):
        ok = (nb > 0) & (lo != hi) & xp.isfinite(v) & xp.isfinite(lo) \
            & xp.isfinite(hi)
        span = xp.where(ok, hi - lo, 1.0)
        raw = xp.floor((v - lo) / span * nb) + 1
        # descending ranges (lo > hi) bucket in reverse, like Spark
        raw = xp.clip(raw, 0, nb + 1)
        return ok, raw

    def eval_tpu(self, ctx):
        v, lo, hi, nb = [c.eval_tpu(ctx) for c in self.children]
        vals = [v.data.astype(np.float64), lo.data.astype(np.float64),
                hi.data.astype(np.float64), nb.data.astype(np.float64)]
        ok, raw = self._compute(jnp, *vals)
        valid = ok
        for c in (v, lo, hi, nb):
            valid = valid & _valid_of(c, ctx)
        return ColumnVector(T.INT64, raw.astype(np.int64), valid)

    def eval_cpu(self, cols, ansi=False):
        cs = [c.eval_cpu(cols, ansi) for c in self.children]
        with np.errstate(all="ignore"):
            ok, raw = self._compute(
                np, *[c.values.astype(np.float64) for c in cs])
        valid = ok
        for c in cs:
            valid = valid & c.valid
        return CpuCol(T.INT64, raw.astype(np.int64), valid)


class NaNvl(Expression):
    """nanvl(a, b): b where a is NaN."""

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self):
        return T.FLOAT64

    def with_children(self, children):
        return NaNvl(children[0], children[1])

    def eval_tpu(self, ctx):
        l = self.children[0].eval_tpu(ctx)
        r = self.children[1].eval_tpu(ctx)
        lv = l.data.astype(np.float64)
        rv = r.data.astype(np.float64)
        nan = jnp.isnan(lv)
        out = jnp.where(nan, rv, lv)
        lval = _valid_of(l, ctx)
        rval = _valid_of(r, ctx)
        return ColumnVector(T.FLOAT64, out, jnp.where(nan, rval, lval))

    def eval_cpu(self, cols, ansi=False):
        l = self.children[0].eval_cpu(cols, ansi)
        r = self.children[1].eval_cpu(cols, ansi)
        lv = l.values.astype(np.float64)
        nan = np.isnan(lv)
        return CpuCol(T.FLOAT64, np.where(nan, r.values.astype(np.float64), lv),
                      np.where(nan, r.valid, l.valid))


class BitwiseCount(Expression):
    """bit_count(x): number of set bits (negative ints count two's-
    complement bits; booleans count as 0/1). Result int32."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return BitwiseCount(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        if isinstance(c.dtype, T.BooleanType):
            out = c.data.astype(jnp.int32)
        else:
            w = 64 if np.dtype(c.dtype.np_dtype).itemsize == 8 else 32
            u = c.data.astype(jnp.int64).astype(jnp.uint64) \
                if w == 64 else c.data.astype(jnp.int32).astype(jnp.uint32)
            if w == 32:
                # mask sign-extension of narrow types
                nbits = np.dtype(c.dtype.np_dtype).itemsize * 8
                u = u & jnp.uint32((1 << nbits) - 1) if nbits < 32 else u
            out = jax.lax.population_count(u).astype(jnp.int32)
        return ColumnVector(T.INT32, out, _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        if isinstance(c.dtype, T.BooleanType):
            out = c.values.astype(np.int32)
        else:
            nbits = np.dtype(c.dtype.np_dtype).itemsize * 8
            u = c.values.astype(np.int64).astype(np.uint64)
            if nbits < 64:
                u = u & np.uint64((1 << nbits) - 1)
            out = np.array([bin(int(x)).count("1") for x in u], np.int32)
        return CpuCol(T.INT32, out, c.valid)


class BitwiseGet(Expression):
    """getbit(x, pos): bit at position pos (0 = LSB); error on pos out of
    range in ANSI, null otherwise? Spark: error always — we null outside
    range non-ANSI for fallback-free columnar eval and error in ANSI."""

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self):
        return T.INT8

    def with_children(self, children):
        return BitwiseGet(children[0], children[1])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        p = self.children[1].eval_tpu(ctx)
        nbits = np.dtype(c.dtype.np_dtype).itemsize * 8
        pos = p.data.astype(jnp.int32)
        in_range = (pos >= 0) & (pos < nbits)
        if ctx.ansi:
            ctx.add_error("BitPosOutOfRange",
                          _valid_of(p, ctx) & ~in_range)
        v = c.data.astype(jnp.int64)
        out = ((v >> jnp.clip(pos, 0, nbits - 1).astype(jnp.int64))
               & jnp.int64(1)).astype(jnp.int8)
        return ColumnVector(T.INT8, out,
                            _valid_of(c, ctx) & _valid_of(p, ctx) & in_range)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        p = self.children[1].eval_cpu(cols, ansi)
        nbits = np.dtype(c.dtype.np_dtype).itemsize * 8
        pos = p.values.astype(np.int64)
        in_range = (pos >= 0) & (pos < nbits)
        if ansi and bool((p.valid & ~in_range).any()):
            from spark_rapids_tpu.expr.core import SparkException
            raise SparkException("bit position out of range")
        out = ((c.values.astype(np.int64) >> np.clip(pos, 0, nbits - 1))
               & 1).astype(np.int8)
        return CpuCol(T.INT8, out, c.valid & p.valid & in_range)


class BRound(Expression):
    """bround(x, scale): HALF_EVEN rounding (Spark Round is HALF_UP)."""

    def __init__(self, child, scale: int = 0):
        self.children = [child]
        self.scale = int(scale)

    def _params(self):
        return str(self.scale)

    def with_children(self, children):
        return BRound(children[0], self.scale)

    def data_type(self):
        dt = self.children[0].data_type()
        return dt if not isinstance(dt, T.Float32Type) else T.FLOAT32

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        dt = self.data_type()
        p = np.float64(10.0 ** self.scale)
        if dt.is_integral:
            if self.scale >= 0:
                return ColumnVector(dt, c.data, _valid_of(c, ctx))
            q = np.int64(10 ** (-self.scale))
            v = c.data.astype(jnp.int64)
            half = q // 2
            base = jnp.floor_divide(v, q)
            rem = v - base * q
            up = (rem > half) | ((rem == half) & (base % 2 != 0))
            out = (base + up.astype(jnp.int64)) * q
            return ColumnVector(dt, out.astype(dt.np_dtype),
                                _valid_of(c, ctx))
        v = c.data.astype(jnp.float64) * p
        out = (jnp.round(v) / p).astype(dt.np_dtype)  # jnp.round = half-even
        return ColumnVector(dt, out, _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        dt = self.data_type()
        if dt.is_integral:
            if self.scale >= 0:
                return CpuCol(dt, c.values, c.valid)
            q = 10 ** (-self.scale)
            v = c.values.astype(np.int64)
            half = q // 2
            base = np.floor_divide(v, q)
            rem = v - base * q
            up = (rem > half) | ((rem == half) & (base % 2 != 0))
            return CpuCol(dt, ((base + up) * q).astype(dt.np_dtype), c.valid)
        p = 10.0 ** self.scale
        out = (np.round(c.values.astype(np.float64) * p) / p).astype(dt.np_dtype)
        return CpuCol(dt, out, c.valid)
