"""Device regex: a Java-regex subset compiled to a bit-parallel NFA.

Reference parity: RegexParser.scala (2151 LoC) — the reference parses Java
regex and TRANSPILES to cudf's regex engine, rejecting unsupported
constructs so the expression falls back to CPU. This module keeps the
transpile-or-reject contract but targets a TPU-shaped execution model:

- Parse a subset: literals, escapes, char classes (incl. \\d \\w \\s and
  negations), ``.``, alternation, groups, greedy quantifiers * + ? {m,n},
  anchors ^ $. Rejected (-> CPU fallback): backreferences, lookaround,
  lazy/possessive quantifiers, flags, named groups, unicode classes.
- Compile via the Glushkov construction to a <=32-state NFA whose step
  factorizes as ``next = reach(S) & B[byte]`` with reach(S) a fold over
  HOST-CONSTANT follow masks — the whole match is a `lax.fori_loop` of
  pure vector ops over the byte planes, no gather tables, no branching.
  (The reference's RegexComplexityEstimator analog: patterns with more
  than 32 positions are rejected.)
- ``.`` and negated classes expand to proper UTF-8 char alternations so
  multibyte characters count as ONE character.

Matching modes: "find" (Spark RLIKE: pattern matches anywhere) and
"match" (full-string, Java matches()).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

MAX_STATES = 32


class RegexUnsupported(Exception):
    pass


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RxNode:
    pass


@dataclasses.dataclass
class Atom(RxNode):
    """One byte-class position (bitset over byte values 0..255)."""
    bits: np.ndarray  # bool[256]


@dataclasses.dataclass
class Concat(RxNode):
    parts: List[RxNode]


@dataclasses.dataclass
class Alt(RxNode):
    parts: List[RxNode]


@dataclasses.dataclass
class Repeat(RxNode):
    child: RxNode
    min: int
    max: Optional[int]  # None = unbounded


@dataclasses.dataclass
class Empty(RxNode):
    pass


@dataclasses.dataclass
class Group(RxNode):
    """Capturing group marker (index 1-based). Transparent for matching;
    the tagged extraction path records its position spans."""
    index: int
    child: RxNode


def _bits_of(chars: str) -> np.ndarray:
    b = np.zeros(256, np.bool_)
    for ch in chars:
        for byte in ch.encode("utf-8"):
            if ord(ch) > 127:
                raise RegexUnsupported("non-ASCII literal in pattern")
        b[ord(ch)] = True
    return b


def _range_bits(lo: str, hi: str) -> np.ndarray:
    if ord(lo) > 127 or ord(hi) > 127:
        raise RegexUnsupported("non-ASCII class range")
    b = np.zeros(256, np.bool_)
    b[ord(lo): ord(hi) + 1] = True
    return b


_DIGIT = _range_bits("0", "9")
_WORD = _range_bits("a", "z") | _range_bits("A", "Z") | _DIGIT | _bits_of("_")
_SPACE = np.zeros(256, np.bool_)
for _c in " \t\n\x0b\f\r":
    _SPACE[ord(_c)] = True

_ASCII = np.zeros(256, np.bool_)
_ASCII[:128] = True
_LEAD2 = np.zeros(256, np.bool_)
_LEAD2[0xC0:0xE0] = True
_LEAD3 = np.zeros(256, np.bool_)
_LEAD3[0xE0:0xF0] = True
_LEAD4 = np.zeros(256, np.bool_)
_LEAD4[0xF0:0xF8] = True
_CONT = np.zeros(256, np.bool_)
_CONT[0x80:0xC0] = True


def _one_char(ascii_bits: np.ndarray) -> RxNode:
    """A class over CHARACTERS: the given ASCII bytes, or (for inclusive
    classes like ``.`` and negations) any multibyte UTF-8 character."""
    return Alt([Atom(ascii_bits & _ASCII),
                Concat([Atom(_LEAD2), Atom(_CONT)]),
                Concat([Atom(_LEAD3), Atom(_CONT), Atom(_CONT)]),
                Concat([Atom(_LEAD4), Atom(_CONT), Atom(_CONT), Atom(_CONT)])])


_ESCAPES = {
    "d": _DIGIT, "D": None, "w": _WORD, "W": None, "s": _SPACE, "S": None,
    "n": _bits_of("\n"), "t": _bits_of("\t"), "r": _bits_of("\r"),
}
_META = set(".^$*+?()[]{}|\\")


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.anchored_start = False
        self.anchored_end = False
        self.ngroups = 0

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    # pattern := alt ; alt := concat ('|' concat)*
    def parse(self) -> RxNode:
        node = self.alt(top=True)
        if self.i != len(self.p):
            raise RegexUnsupported(f"unexpected {self.p[self.i]!r}")
        return node

    def alt(self, top: bool = False) -> RxNode:
        before = (self.anchored_start, self.anchored_end)
        parts = [self.concat(top)]
        while self.peek() == "|":
            self.take()
            parts.append(self.concat(top))
        if len(parts) > 1 and (self.anchored_start, self.anchored_end) != before:
            # an anchor inside ONE branch must not anchor the others; the
            # flag model can't express per-branch anchors -> reject
            # (write ^(a|b) instead of ^a|b)
            raise RegexUnsupported("anchor inside alternation branch")
        return parts[0] if len(parts) == 1 else Alt(parts)

    def concat(self, top: bool) -> RxNode:
        parts: List[RxNode] = []
        first = True
        while True:
            ch = self.peek()
            if ch is None or ch in ")|":
                break
            if ch == "^":
                if not (top and first):
                    raise RegexUnsupported("interior ^")
                self.take()
                self.anchored_start = True
                first = False
                continue
            if ch == "$":
                self.take()
                if self.peek() not in (None, "|"):
                    raise RegexUnsupported("interior $")
                self.anchored_end = True
                continue
            parts.append(self.quantified())
            first = False
        return Concat(parts) if parts else Empty()

    def quantified(self) -> RxNode:
        atom = self.atom()
        ch = self.peek()
        if ch in ("*", "+", "?"):
            self.take()
            if self.peek() in ("?", "+"):
                raise RegexUnsupported("lazy/possessive quantifier")
            lo, hi = {"*": (0, None), "+": (1, None), "?": (0, 1)}[ch]
            return Repeat(atom, lo, hi)
        if ch == "{":
            j = self.p.find("}", self.i)
            if j < 0:
                raise RegexUnsupported("unterminated {")
            body = self.p[self.i + 1: j]
            self.i = j + 1
            if self.peek() in ("?", "+"):
                raise RegexUnsupported("lazy/possessive quantifier")
            if "," in body:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s) if lo_s else 0
                hi = int(hi_s) if hi_s else None
            else:
                lo = hi = int(body)
            if hi is not None and hi < lo:
                raise RegexUnsupported("bad {m,n}")
            if (hi or lo) > 16:
                raise RegexUnsupported("{m,n} too large for device NFA")
            return Repeat(atom, lo, hi)
        return atom

    def atom(self) -> RxNode:
        ch = self.take()
        if ch == "(":
            if self.peek() == "?":
                raise RegexUnsupported("(?...) group")
            self.ngroups += 1
            gidx = self.ngroups
            inner = self.alt()
            if self.peek() != ")":
                raise RegexUnsupported("unterminated (")
            self.take()
            return Group(gidx, inner)
        if ch == "[":
            return self.char_class()
        if ch == ".":
            nl = np.zeros(256, np.bool_)
            nl[ord("\n")] = True
            return _one_char(_ASCII & ~nl)
        if ch == "\\":
            return self.escape()
        if ch in _META:
            raise RegexUnsupported(f"meta {ch!r}")
        if ord(ch) > 127:
            raise RegexUnsupported("non-ASCII literal")
        return Atom(_bits_of(ch))

    def escape(self) -> RxNode:
        ch = self.take()
        if ch in "\\.^$*+?()[]{}|/-":
            return Atom(_bits_of(ch))
        if ch in _ESCAPES:
            if ch == "D":
                return _one_char(_ASCII & ~_DIGIT)
            if ch == "W":
                return _one_char(_ASCII & ~_WORD)
            if ch == "S":
                return _one_char(_ASCII & ~_SPACE)
            return Atom(_ESCAPES[ch])
        raise RegexUnsupported(f"escape \\{ch}")

    def char_class(self) -> RxNode:
        neg = False
        if self.peek() == "^":
            self.take()
            neg = True
        bits = np.zeros(256, np.bool_)
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise RegexUnsupported("unterminated [")
            if ch == "]" and not first:
                self.take()
                break
            self.take()
            first = False
            if ch == "\\":
                e = self.take()
                if e in _ESCAPES and _ESCAPES[e] is not None:
                    bits |= _ESCAPES[e]
                    continue
                if e in "\\.^$*+?()[]{}|/-":
                    ch = e
                else:
                    raise RegexUnsupported(f"class escape \\{e}")
            if ord(ch) > 127:
                raise RegexUnsupported("non-ASCII in class")
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self.take()
                hi = self.take()
                if hi == "\\":
                    hi = self.take()
                bits |= _range_bits(ch, hi)
            else:
                bits[ord(ch)] = True
        if neg:
            return _one_char(_ASCII & ~bits)
        return Atom(bits)


# ---------------------------------------------------------------------------
# Glushkov construction -> bit-parallel NFA
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class NFA:
    n: int                      # number of positions (states 1..n; 0 = start)
    byte_classes: np.ndarray    # bool[n, 256]
    first: int                  # bitmask of initial positions
    last: int                   # bitmask of accepting positions
    follow: List[int]           # per position, bitmask of successors
    nullable: bool
    anchored_start: bool
    anchored_end: bool
    #: Java matches() semantics: the WHOLE input must match, and the
    #: find-mode `$`-before-trailing-newline concession does NOT apply
    full_match: bool = False


def _expand_repeat(node: RxNode) -> RxNode:
    """{m,n} -> explicit concatenation (Glushkov needs *,+,? only)."""
    if isinstance(node, Repeat):
        c = _expand_repeat(node.child)
        if (node.min, node.max) in ((0, None), (1, None), (0, 1)):
            return Repeat(c, node.min, node.max)
        parts = [c] * node.min
        if node.max is None:
            parts.append(Repeat(c, 0, None))
        else:
            parts += [Repeat(c, 0, 1)] * (node.max - node.min)
        return Concat([_clone(p) for p in parts])
    if isinstance(node, Concat):
        return Concat([_expand_repeat(p) for p in node.parts])
    if isinstance(node, Alt):
        return Alt([_expand_repeat(p) for p in node.parts])
    if isinstance(node, Group):
        return Group(node.index, _expand_repeat(node.child))
    return node


def _clone(node: RxNode) -> RxNode:
    if isinstance(node, Atom):
        return Atom(node.bits.copy())
    if isinstance(node, Concat):
        return Concat([_clone(p) for p in node.parts])
    if isinstance(node, Alt):
        return Alt([_clone(p) for p in node.parts])
    if isinstance(node, Repeat):
        return Repeat(_clone(node.child), node.min, node.max)
    if isinstance(node, Group):
        return Group(node.index, _clone(node.child))
    return Empty()


def glushkov(ast: RxNode, anchored_start: bool, anchored_end: bool) -> NFA:
    ast = _expand_repeat(ast)
    atoms: List[Atom] = []

    def number(node):
        if isinstance(node, Atom):
            atoms.append(node)
            if len(atoms) > MAX_STATES - 1:
                raise RegexUnsupported(
                    f"pattern needs > {MAX_STATES - 1} NFA positions")
            return
        if isinstance(node, (Concat, Alt)):
            for p in node.parts:
                number(p)
        elif isinstance(node, (Repeat, Group)):
            number(node.child)

    number(ast)
    pos_of = {id(a): i + 1 for i, a in enumerate(atoms)}

    def analyze(node) -> Tuple[int, int, bool]:
        """returns (first_mask, last_mask, nullable); fills follow."""
        if isinstance(node, Empty):
            return 0, 0, True
        if isinstance(node, Atom):
            m = 1 << pos_of[id(node)]
            return m, m, False
        if isinstance(node, Alt):
            f = l = 0
            nul = False
            for p in node.parts:
                pf, pl, pn = analyze(p)
                f |= pf
                l |= pl
                nul = nul or pn
            return f, l, nul
        if isinstance(node, Concat):
            f = l = 0
            nul = True
            for p in node.parts:
                pf, pl, pn = analyze(p)
                # follow: every last of the prefix connects to first of p
                for i in range(1, len(atoms) + 1):
                    if l & (1 << i):
                        follow[i] |= pf
                if nul:
                    f |= pf
                l = pl | (l if pn else 0)
                nul = nul and pn
            return f, l, nul
        if isinstance(node, Repeat):
            cf, cl, cn = analyze(node.child)
            if node.max is None:  # * or +
                for i in range(1, len(atoms) + 1):
                    if cl & (1 << i):
                        follow[i] |= cf
            nul = cn or node.min == 0
            return cf, cl, nul
        if isinstance(node, Group):
            return analyze(node.child)
        raise RegexUnsupported(type(node).__name__)

    follow = [0] * (len(atoms) + 1)
    first, last, nullable = analyze(ast)
    bc = np.zeros((len(atoms) + 1, 256), np.bool_)
    for a, i in ((a, pos_of[id(a)]) for a in atoms):
        bc[i] = a.bits
    return NFA(len(atoms), bc, first, last, follow, nullable,
               anchored_start, anchored_end)


def compile_pattern(pattern: str, mode: str = "find") -> NFA:
    """Parse + compile, raising RegexUnsupported for constructs outside the
    device subset. mode='find' (RLIKE semantics) treats the pattern as
    unanchored unless ^/$ appear."""
    p = _Parser(pattern)
    ast = p.parse()
    nfa = glushkov(ast, p.anchored_start, p.anchored_end)
    if mode == "match":
        nfa.anchored_start = True
        nfa.anchored_end = True
        nfa.full_match = True
    return nfa


# ---------------------------------------------------------------------------
# Device evaluation over flat string planes
# ---------------------------------------------------------------------------

def nfa_eval(nfa: NFA, offsets: jax.Array, raw: jax.Array, valid
             ) -> jax.Array:
    """bool[n_rows]: does each row's string match? One fori_loop over the
    max row length; each step is reach(S) & B[byte] in u32 lanes."""
    n = offsets.shape[0] - 1
    starts = offsets[:-1]
    lens = offsets[1:] - offsets[:-1]
    maxlen = jnp.max(jnp.where(valid, lens, 0)) if valid is not None \
        else jnp.max(lens)
    B = jnp.asarray(_byte_table(nfa))          # u32[256]
    follow = [int(f) for f in nfa.follow]
    first = jnp.uint32(nfa.first)
    last = jnp.uint32(nfa.last)
    seed = not nfa.anchored_start
    nbytes = int(raw.shape[0])

    def step(pos, carry):
        S, done, pre_nl = carry
        idx = jnp.clip(starts + pos, 0, nbytes - 1)
        byte = raw[idx].astype(jnp.int32)
        active = pos < lens
        if not nfa.full_match:
            # Java `$` (find mode) also matches just before a single
            # trailing newline: hit when the un-consumed suffix is "\n"
            pre_nl = pre_nl | (active & (pos == lens - 1) & (byte == 10)
                               & ((S & last) != 0))
        reach = jnp.zeros_like(S)
        # start state: active while unanchored (find) or at pos 0
        s0 = S & jnp.uint32(1)
        reach = jnp.where(s0 != 0, reach | jnp.uint32(nfa.first), reach)
        for i in range(1, nfa.n + 1):
            if follow[i]:
                reach = jnp.where((S >> jnp.uint32(i)) & jnp.uint32(1) != 0,
                                  reach | jnp.uint32(follow[i]), reach)
        nxt = reach & B[byte]
        keep_start = jnp.uint32(1) if seed else jnp.uint32(0)
        nxt = nxt | (S & keep_start)
        S = jnp.where(active, nxt, S)
        hit = (S & last) != 0
        if not nfa.anchored_end:
            done = done | (hit & active)
        return S, done, pre_nl

    S0 = jnp.full(n, 1, jnp.uint32)  # start state only
    done0 = jnp.zeros(n, jnp.bool_)
    S, done, pre_nl = lax.fori_loop(0, maxlen.astype(jnp.int32),
                                    step, (S0, done0, done0))
    if nfa.anchored_end:
        res = ((S & last) != 0) | pre_nl
    else:
        res = done | ((S & last) != 0)
    if nfa.nullable:
        if nfa.anchored_start and nfa.anchored_end:
            # full-string semantics: the empty match covers "" (and, in
            # find mode's ^...$ form, a lone line terminator)
            res = res | (lens == 0)
            if not nfa.full_match:
                first_byte = raw[jnp.clip(starts, 0, nbytes - 1)]
                res = res | ((lens == 1) & (first_byte == 10))
        else:
            # an unanchored side means the empty match fits anywhere
            res = jnp.ones_like(res)
    if valid is not None:
        res = res & valid
    return res


def _byte_table(nfa: NFA) -> np.ndarray:
    """u32[256]: for each byte value, the set of positions matching it."""
    tbl = np.zeros(256, np.uint32)
    for i in range(1, nfa.n + 1):
        tbl |= np.where(nfa.byte_classes[i], np.uint32(1 << i), np.uint32(0))
    return tbl


# ---------------------------------------------------------------------------
# Tagged extraction (regexp_extract): leftmost-greedy submatch spans
# ---------------------------------------------------------------------------

MAX_TAG_STATES = 12


def _first_set(node, pos_of) -> int:
    """first-position bitmask of a subtree (mirrors analyze())."""
    if isinstance(node, Empty):
        return 0
    if isinstance(node, Atom):
        return 1 << pos_of[id(node)]
    if isinstance(node, Alt):
        f = 0
        for p in node.parts:
            f |= _first_set(p, pos_of)
        return f
    if isinstance(node, Concat):
        f = 0
        for p in node.parts:
            f |= _first_set(p, pos_of)
            if not _nullable(p):
                break
        return f
    if isinstance(node, (Repeat, Group)):
        return _first_set(node.child, pos_of)
    return 0


def _nullable(node) -> bool:
    if isinstance(node, Empty):
        return True
    if isinstance(node, Atom):
        return False
    if isinstance(node, Alt):
        return any(_nullable(p) for p in node.parts)
    if isinstance(node, Concat):
        return all(_nullable(p) for p in node.parts)
    if isinstance(node, Repeat):
        return node.min == 0 or _nullable(node.child)
    if isinstance(node, Group):
        return _nullable(node.child)
    return False


def _members(node, pos_of) -> int:
    if isinstance(node, Atom):
        return 1 << pos_of[id(node)]
    m = 0
    for c in (node.parts if isinstance(node, (Concat, Alt))
              else [node.child] if isinstance(node, (Repeat, Group))
              else []):
        m |= _members(c, pos_of)
    return m


def _has_alt(node) -> bool:
    if isinstance(node, Alt):
        return True
    kids = (node.parts if isinstance(node, (Concat, Alt))
            else [node.child] if isinstance(node, (Repeat, Group)) else [])
    return any(_has_alt(k) for k in kids)


@dataclasses.dataclass
class TaggedNFA:
    """NFA + capture-group metadata for ONE group. The tagged simulation
    is restricted to alternation-free patterns, where leftmost-greedy
    disambiguation reduces to (minimal match start, then per-step
    preference for the lowest predecessor position) — the linear-spine
    subset the reference's transpiler also handles most cleanly.
    group 0 = the whole match.

    reset_edges: (f, to) pairs whose traversal RESTARTS the group span —
    entries from outside the group plus loop-back edges of repeats that
    wrap the group (Java keeps the LAST iteration's capture); loop edges
    of repeats INSIDE the group extend the span instead.
    """
    nfa: NFA
    member_mask: int
    entry_mask: int
    reset_edges: frozenset


def compile_extract(pattern: str, group: int) -> TaggedNFA:
    """Compile for submatch extraction. Raises RegexUnsupported outside
    the tagged subset (alternation, > MAX_TAG_STATES positions, bad
    group index)."""
    p = _Parser(pattern)
    ast0 = p.parse()
    if group < 0 or group > p.ngroups:
        raise RegexUnsupported(f"group {group} of {p.ngroups}")
    if _has_alt(ast0):
        raise RegexUnsupported("alternation in extract pattern")
    if p.anchored_end:
        # the tagged accept snapshot records matches at every position;
        # $-anchoring needs an end-of-row gate (and the Java trailing-\n
        # concession) — reject to CPU rather than diverge
        raise RegexUnsupported("$-anchored extract pattern")
    ast = _expand_repeat(ast0)
    atoms: List[Atom] = []

    def number(node):
        if isinstance(node, Atom):
            atoms.append(node)
        elif isinstance(node, (Concat, Alt)):
            for q in node.parts:
                number(q)
        elif isinstance(node, (Repeat, Group)):
            number(node.child)

    number(ast)
    if len(atoms) > MAX_TAG_STATES:
        raise RegexUnsupported(
            f"extract pattern needs > {MAX_TAG_STATES} positions")
    pos_of = {id(a): i + 1 for i, a in enumerate(atoms)}

    # members/entries of every clone of the requested group (group 0 =
    # whole pattern). Multiple clones arise from {m,n} expansion; their
    # masks union — the per-edge reset set disambiguates instances.
    member_mask = 0
    entry_mask = 0
    if group == 0:
        member_mask = _members(ast, pos_of)
        entry_mask = _first_set(ast, pos_of)
    else:
        def collect(node):
            nonlocal member_mask, entry_mask
            if isinstance(node, Group) and node.index == group:
                member_mask |= _members(node, pos_of)
                entry_mask |= _first_set(node, pos_of)
                return
            for c in (node.parts if isinstance(node, (Concat, Alt))
                      else [node.child]
                      if isinstance(node, (Repeat, Group)) else []):
                collect(c)
        collect(ast)
        if member_mask == 0:
            raise RegexUnsupported("empty or never-matching group")

    # Re-run the follow analysis with edge attribution: an edge resets
    # the group when it ENTERS the group from outside, or when it is a
    # loop-back added by a repeat that is NOT inside the group.
    reset_edges = set()

    def record_edges(last_mask, first_mask, inside_group):
        for f in range(1, len(atoms) + 1):
            if last_mask & (1 << f):
                for to in range(1, len(atoms) + 1):
                    if first_mask & (1 << to) and entry_mask & (1 << to):
                        from_outside = not (member_mask & (1 << f))
                        if from_outside or not inside_group:
                            reset_edges.add((f, to))

    def analyze2(node, inside_group):
        if isinstance(node, Empty):
            return 0, 0, True
        if isinstance(node, Atom):
            m = 1 << pos_of[id(node)]
            return m, m, False
        if isinstance(node, Group):
            return analyze2(node.child,
                            inside_group
                            or (group != 0 and node.index == group))
        if isinstance(node, Concat):
            f = l = 0
            nul = True
            for q in node.parts:
                qf, ql, qn = analyze2(q, inside_group)
                record_edges(l, qf, inside_group)
                if nul:
                    f |= qf
                l = ql | (l if qn else 0)
                nul = nul and qn
            return f, l, nul
        if isinstance(node, Repeat):
            cf, cl, cn = analyze2(node.child, inside_group)
            if node.max is None:
                record_edges(cl, cf, inside_group)
            return cf, cl, cn or node.min == 0
        raise RegexUnsupported(type(node).__name__)

    analyze2(ast, group == 0)
    # seed entries (from the start state) always reset
    nfa = glushkov(ast, p.anchored_start, p.anchored_end)
    for to in range(1, nfa.n + 1):
        if nfa.first & (1 << to) and entry_mask & (1 << to):
            reset_edges.add((0, to))
        # entries reached from non-member positions reset too (concat
        # edges from before the group)
        for f in range(1, nfa.n + 1):
            if nfa.follow[f] & (1 << to) and entry_mask & (1 << to)                     and not (member_mask & (1 << f)):
                reset_edges.add((f, to))
    return TaggedNFA(nfa, member_mask, entry_mask, frozenset(reset_edges))


def nfa_extract(t: TaggedNFA, offsets: jax.Array, raw: jax.Array):
    """Per row: (matched bool, group byte start, group byte end) —
    offsets are row-relative byte positions; a matched row whose group
    did not participate reports start=end (empty string, Spark
    regexp_extract semantics)."""
    nfa = t.nfa
    n = nfa.n
    nrows = offsets.shape[0] - 1
    starts = offsets[:-1]
    lens = offsets[1:] - offsets[:-1]
    maxlen = jnp.max(lens)
    nbytes = int(raw.shape[0])
    B = jnp.asarray(_byte_table(nfa))
    member_all = t.member_mask

    #: per-state predecessor lists (f=0 is the seed/start state)
    preds = [[] for _ in range(n + 1)]
    for i in range(1, n + 1):
        if nfa.first & (1 << i):
            preds[i].append(0)
        for f in range(1, n + 1):
            if nfa.follow[f] & (1 << i):
                preds[i].append(f)

    BIG = jnp.int32(np.iinfo(np.int32).max)

    def in_mask(state: int, mask: int) -> bool:
        return bool(mask & (1 << state))

    def step(pos, carry):
        S, ms, gs, ge, best = carry
        # ms/gs/ge: i32[n+1, rows] per-state registers (match start,
        # group start, group end; -1 = not participating)
        idx = jnp.clip(starts + pos, 0, nbytes - 1)
        byte = raw[idx].astype(jnp.int32)
        active = pos < lens
        hit_bits = B[byte]
        new_ms, new_gs, new_ge = [], [], []
        alive_bits = []
        for to in range(1, n + 1):
            to_hit = (hit_bits >> jnp.uint32(to)) & jnp.uint32(1) != 0
            cand_ms = BIG * jnp.ones(nrows, jnp.int32)
            cand_gs = -jnp.ones(nrows, jnp.int32)
            cand_ge = -jnp.ones(nrows, jnp.int32)
            got = jnp.zeros(nrows, jnp.bool_)
            is_entry = in_mask(to, t.entry_mask)
            # predecessors in priority order: smaller position first,
            # seed (0) LAST (a new thread only wins on smaller start,
            # which cannot happen — existing threads started earlier)
            order = sorted([f for f in preds[to] if f != 0]) + \
                ([0] if 0 in preds[to] else [])
            for f in order:
                if f == 0:
                    f_alive = jnp.ones(nrows, jnp.bool_) \
                        if not nfa.anchored_start else \
                        jnp.full(nrows, pos == 0)
                    f_ms = jnp.full(nrows, pos, jnp.int32)
                    f_gs = -jnp.ones(nrows, jnp.int32)
                    f_ge = -jnp.ones(nrows, jnp.int32)
                else:
                    f_alive = (S >> jnp.uint32(f)) & jnp.uint32(1) != 0
                    f_ms, f_gs, f_ge = ms[f], gs[f], ge[f]
                # group-register transition for this STATIC (f, to)
                # edge (Java last-iteration capture: the precomputed
                # reset set restarts the span; other in-group edges
                # extend it)
                if in_mask(to, member_all):
                    if (f, to) in t.reset_edges or (is_entry and f == 0):
                        e_gs = jnp.full(nrows, pos, jnp.int32)
                    else:
                        e_gs = f_gs
                    e_ge = jnp.full(nrows, pos + 1, jnp.int32)
                else:
                    e_gs, e_ge = f_gs, f_ge
                better = f_alive & (~got | (f_ms < cand_ms))
                cand_ms = jnp.where(better, f_ms, cand_ms)
                cand_gs = jnp.where(better, e_gs, cand_gs)
                cand_ge = jnp.where(better, e_ge, cand_ge)
                got = got | f_alive
            ok = got & to_hit & active
            new_ms.append(jnp.where(ok, cand_ms, BIG))
            new_gs.append(jnp.where(ok, cand_gs, -1))
            new_ge.append(jnp.where(ok, cand_ge, -1))
            alive_bits.append(ok)
        S2 = jnp.zeros(nrows, jnp.uint32)
        for to, ok in zip(range(1, n + 1), alive_bits):
            S2 = S2 | jnp.where(ok, jnp.uint32(1 << to), jnp.uint32(0))
        ms2 = jnp.stack([jnp.full(nrows, BIG, jnp.int32)] + new_ms)
        gs2 = jnp.stack([-jnp.ones(nrows, jnp.int32)] + new_gs)
        ge2 = jnp.stack([-jnp.ones(nrows, jnp.int32)] + new_ge)
        ms2 = jnp.where(active, ms2, ms)
        gs2 = jnp.where(active, gs2, gs)
        ge2 = jnp.where(active, ge2, ge)
        S2 = jnp.where(active, S2, S)
        # accept snapshot: leftmost start, then longest end (= latest pos)
        b_has, b_ms, b_gs, b_ge = best
        acc_has = jnp.zeros(nrows, jnp.bool_)
        acc_ms = jnp.full(nrows, BIG, jnp.int32)
        acc_gs = -jnp.ones(nrows, jnp.int32)
        acc_ge = -jnp.ones(nrows, jnp.int32)
        for i in sorted(range(1, n + 1)):
            if nfa.last & (1 << i):
                alive = (S2 >> jnp.uint32(i)) & jnp.uint32(1) != 0
                alive = alive & active
                better = alive & (~acc_has | (ms2[i] < acc_ms))
                acc_ms = jnp.where(better, ms2[i], acc_ms)
                acc_gs = jnp.where(better, gs2[i], acc_gs)
                acc_ge = jnp.where(better, ge2[i], acc_ge)
                acc_has = acc_has | alive
        replace = acc_has & (~b_has | (acc_ms <= b_ms))
        best = (b_has | acc_has,
                jnp.where(replace, acc_ms, b_ms),
                jnp.where(replace, acc_gs, b_gs),
                jnp.where(replace, acc_ge, b_ge))
        return S2, ms2, gs2, ge2, best

    S0 = jnp.zeros(nrows, jnp.uint32)
    ms0 = jnp.full((n + 1, nrows), BIG, jnp.int32)
    gs0 = -jnp.ones((n + 1, nrows), jnp.int32)
    ge0 = -jnp.ones((n + 1, nrows), jnp.int32)
    best0 = (jnp.zeros(nrows, jnp.bool_), jnp.full(nrows, BIG, jnp.int32),
             -jnp.ones(nrows, jnp.int32), -jnp.ones(nrows, jnp.int32))
    _, _, _, _, best = lax.fori_loop(0, maxlen.astype(jnp.int32), step,
                                     (S0, ms0, gs0, ge0, best0))
    has, bms, bgs, bge = best
    if nfa.nullable:
        # empty match at position 0 wins when nothing matched earlier
        has_empty = jnp.ones(nrows, jnp.bool_)
        take = has_empty & ~has
        has = has | has_empty
        bgs = jnp.where(take, 0, bgs)
        bge = jnp.where(take, 0, bge)
    # non-participating group -> empty span
    g0 = jnp.where(has & (bgs >= 0), bgs, 0)
    g1 = jnp.where(has & (bge >= 0), bge, 0)
    g1 = jnp.maximum(g1, g0)
    return has, g0, g1


# ---------------------------------------------------------------------------
# Replace-all spans (regexp_replace): leftmost-greedy non-overlapping
# ---------------------------------------------------------------------------


def compile_replace(pattern: str) -> TaggedNFA:
    """Compile for replace-all. The tagged whole-match subset, minus
    patterns that can match the empty string (Java inserts a replacement
    at every position for those — reject to the CPU tier rather than
    emulate) and $-anchoring (inherited from compile_extract)."""
    t = compile_extract(pattern, 0)
    if t.nfa.nullable:
        raise RegexUnsupported("pattern matches the empty string")
    return t


def nfa_match_spans(t: TaggedNFA, offsets: jax.Array, raw: jax.Array):
    """Per-BYTE match layout for replace-all: (start_flags bool[nbytes],
    span_len i32[nbytes]) where start_flags marks the first byte of each
    committed match and span_len its byte length.

    One vectorized left-to-right pass (rows in parallel): per-state
    MATCH-START registers merge by minimum (leftmost wins), a candidate
    (start, end) extends greedily while any thread with that start is
    alive, and commits — one scatter into the byte planes — the moment
    no alive thread could produce an equal-or-earlier start, or at end
    of row. The cursor then jumps past the match (non-overlapping, like
    Java's appendReplacement loop)."""
    nfa = t.nfa
    n = nfa.n
    nrows = offsets.shape[0] - 1
    starts = offsets[:-1].astype(jnp.int32)
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.int32)
    maxlen = jnp.max(lens)
    nbytes = int(raw.shape[0])
    B = jnp.asarray(_byte_table(nfa))
    BIG = jnp.int32(np.iinfo(np.int32).max)

    preds = [[] for _ in range(n + 1)]
    for i in range(1, n + 1):
        if nfa.first & (1 << i):
            preds[i].append(0)
        for f in range(1, n + 1):
            if nfa.follow[f] & (1 << i):
                preds[i].append(f)
    accepting = [i for i in range(1, n + 1) if nfa.last & (1 << i)]

    def step(pos, carry):
        ms, cand_s, cand_e, cursor, flags, slen = carry
        idx = jnp.clip(starts + pos, 0, nbytes - 1)
        byte = raw[idx].astype(jnp.int32)
        in_row = pos < lens
        hit_bits = B[byte]
        new_ms = []
        for to in range(1, n + 1):
            to_hit = (hit_bits >> jnp.uint32(to)) & jnp.uint32(1) != 0
            best = jnp.full(nrows, BIG, jnp.int32)
            for f in preds[to]:
                if f == 0:
                    seed_ok = (pos >= cursor) & (
                        jnp.full(nrows, pos == 0, jnp.bool_)
                        if nfa.anchored_start
                        else jnp.ones(nrows, jnp.bool_))
                    cand = jnp.where(seed_ok, jnp.full(nrows, pos,
                                                       jnp.int32), BIG)
                else:
                    cand = ms[f - 1]
                best = jnp.minimum(best, cand)
            new_ms.append(jnp.where(to_hit & in_row, best, BIG))
        # accept: minimal start among accepting states
        acc = jnp.full(nrows, BIG, jnp.int32)
        for i in accepting:
            acc = jnp.minimum(acc, new_ms[i - 1])
        better = acc < cand_s
        extend = acc == cand_s
        cand_e = jnp.where((better | extend) & (acc < BIG),
                           pos + 1, cand_e)
        cand_s = jnp.where(better, acc, cand_s)
        # commit when no alive thread can reach an <= start, or row end
        min_alive = jnp.full(nrows, BIG, jnp.int32)
        for i in range(1, n + 1):
            min_alive = jnp.minimum(min_alive, new_ms[i - 1])
        done_row = (pos + 1) >= lens
        commit = (cand_s < BIG) & ((min_alive > cand_s) | done_row)
        tgt = jnp.where(commit, starts + cand_s, nbytes)  # pad slot
        flags = flags.at[tgt].add(commit.astype(jnp.int32))
        slen = slen.at[tgt].add(jnp.where(commit, cand_e - cand_s, 0))
        cursor = jnp.where(commit, cand_e, cursor)
        # kill threads inside the committed span; a fresh accept this
        # same step at/after the new cursor becomes the next candidate
        ms = [jnp.where(m < cursor, BIG, m) for m in new_ms]
        resee = commit & (acc >= cursor) & (acc < BIG)
        cand_s = jnp.where(commit, jnp.where(resee, acc, BIG), cand_s)
        cand_e = jnp.where(commit, jnp.where(resee, pos + 1, -1), cand_e)
        return ms, cand_s, cand_e, cursor, flags, slen

    ms0 = [jnp.full(nrows, BIG, jnp.int32) for _ in range(n)]
    carry0 = (ms0, jnp.full(nrows, BIG, jnp.int32),
              jnp.full(nrows, -1, jnp.int32),
              jnp.zeros(nrows, jnp.int32),
              jnp.zeros(nbytes + 1, jnp.int32),
              jnp.zeros(nbytes + 1, jnp.int32))
    out = lax.fori_loop(0, maxlen, step, carry0)
    flags, slen = out[4][:nbytes], out[5][:nbytes]
    return flags > 0, slen
