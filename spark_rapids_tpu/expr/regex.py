"""Device regex: a Java-regex subset compiled to a bit-parallel NFA.

Reference parity: RegexParser.scala (2151 LoC) — the reference parses Java
regex and TRANSPILES to cudf's regex engine, rejecting unsupported
constructs so the expression falls back to CPU. This module keeps the
transpile-or-reject contract but targets a TPU-shaped execution model:

- Parse a subset: literals, escapes, char classes (incl. \\d \\w \\s and
  negations), ``.``, alternation, groups, greedy quantifiers * + ? {m,n},
  anchors ^ $. Rejected (-> CPU fallback): backreferences, lookaround,
  lazy/possessive quantifiers, flags, named groups, unicode classes.
- Compile via the Glushkov construction to a <=32-state NFA whose step
  factorizes as ``next = reach(S) & B[byte]`` with reach(S) a fold over
  HOST-CONSTANT follow masks — the whole match is a `lax.fori_loop` of
  pure vector ops over the byte planes, no gather tables, no branching.
  (The reference's RegexComplexityEstimator analog: patterns with more
  than 32 positions are rejected.)
- ``.`` and negated classes expand to proper UTF-8 char alternations so
  multibyte characters count as ONE character.

Matching modes: "find" (Spark RLIKE: pattern matches anywhere) and
"match" (full-string, Java matches()).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

MAX_STATES = 32


class RegexUnsupported(Exception):
    pass


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RxNode:
    pass


@dataclasses.dataclass
class Atom(RxNode):
    """One byte-class position (bitset over byte values 0..255)."""
    bits: np.ndarray  # bool[256]


@dataclasses.dataclass
class Concat(RxNode):
    parts: List[RxNode]


@dataclasses.dataclass
class Alt(RxNode):
    parts: List[RxNode]


@dataclasses.dataclass
class Repeat(RxNode):
    child: RxNode
    min: int
    max: Optional[int]  # None = unbounded


@dataclasses.dataclass
class Empty(RxNode):
    pass


def _bits_of(chars: str) -> np.ndarray:
    b = np.zeros(256, np.bool_)
    for ch in chars:
        for byte in ch.encode("utf-8"):
            if ord(ch) > 127:
                raise RegexUnsupported("non-ASCII literal in pattern")
        b[ord(ch)] = True
    return b


def _range_bits(lo: str, hi: str) -> np.ndarray:
    if ord(lo) > 127 or ord(hi) > 127:
        raise RegexUnsupported("non-ASCII class range")
    b = np.zeros(256, np.bool_)
    b[ord(lo): ord(hi) + 1] = True
    return b


_DIGIT = _range_bits("0", "9")
_WORD = _range_bits("a", "z") | _range_bits("A", "Z") | _DIGIT | _bits_of("_")
_SPACE = np.zeros(256, np.bool_)
for _c in " \t\n\x0b\f\r":
    _SPACE[ord(_c)] = True

_ASCII = np.zeros(256, np.bool_)
_ASCII[:128] = True
_LEAD2 = np.zeros(256, np.bool_)
_LEAD2[0xC0:0xE0] = True
_LEAD3 = np.zeros(256, np.bool_)
_LEAD3[0xE0:0xF0] = True
_LEAD4 = np.zeros(256, np.bool_)
_LEAD4[0xF0:0xF8] = True
_CONT = np.zeros(256, np.bool_)
_CONT[0x80:0xC0] = True


def _one_char(ascii_bits: np.ndarray) -> RxNode:
    """A class over CHARACTERS: the given ASCII bytes, or (for inclusive
    classes like ``.`` and negations) any multibyte UTF-8 character."""
    return Alt([Atom(ascii_bits & _ASCII),
                Concat([Atom(_LEAD2), Atom(_CONT)]),
                Concat([Atom(_LEAD3), Atom(_CONT), Atom(_CONT)]),
                Concat([Atom(_LEAD4), Atom(_CONT), Atom(_CONT), Atom(_CONT)])])


_ESCAPES = {
    "d": _DIGIT, "D": None, "w": _WORD, "W": None, "s": _SPACE, "S": None,
    "n": _bits_of("\n"), "t": _bits_of("\t"), "r": _bits_of("\r"),
}
_META = set(".^$*+?()[]{}|\\")


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.anchored_start = False
        self.anchored_end = False

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    # pattern := alt ; alt := concat ('|' concat)*
    def parse(self) -> RxNode:
        node = self.alt(top=True)
        if self.i != len(self.p):
            raise RegexUnsupported(f"unexpected {self.p[self.i]!r}")
        return node

    def alt(self, top: bool = False) -> RxNode:
        before = (self.anchored_start, self.anchored_end)
        parts = [self.concat(top)]
        while self.peek() == "|":
            self.take()
            parts.append(self.concat(top))
        if len(parts) > 1 and (self.anchored_start, self.anchored_end) != before:
            # an anchor inside ONE branch must not anchor the others; the
            # flag model can't express per-branch anchors -> reject
            # (write ^(a|b) instead of ^a|b)
            raise RegexUnsupported("anchor inside alternation branch")
        return parts[0] if len(parts) == 1 else Alt(parts)

    def concat(self, top: bool) -> RxNode:
        parts: List[RxNode] = []
        first = True
        while True:
            ch = self.peek()
            if ch is None or ch in ")|":
                break
            if ch == "^":
                if not (top and first):
                    raise RegexUnsupported("interior ^")
                self.take()
                self.anchored_start = True
                first = False
                continue
            if ch == "$":
                self.take()
                if self.peek() not in (None, "|"):
                    raise RegexUnsupported("interior $")
                self.anchored_end = True
                continue
            parts.append(self.quantified())
            first = False
        return Concat(parts) if parts else Empty()

    def quantified(self) -> RxNode:
        atom = self.atom()
        ch = self.peek()
        if ch in ("*", "+", "?"):
            self.take()
            if self.peek() in ("?", "+"):
                raise RegexUnsupported("lazy/possessive quantifier")
            lo, hi = {"*": (0, None), "+": (1, None), "?": (0, 1)}[ch]
            return Repeat(atom, lo, hi)
        if ch == "{":
            j = self.p.find("}", self.i)
            if j < 0:
                raise RegexUnsupported("unterminated {")
            body = self.p[self.i + 1: j]
            self.i = j + 1
            if self.peek() in ("?", "+"):
                raise RegexUnsupported("lazy/possessive quantifier")
            if "," in body:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s) if lo_s else 0
                hi = int(hi_s) if hi_s else None
            else:
                lo = hi = int(body)
            if hi is not None and hi < lo:
                raise RegexUnsupported("bad {m,n}")
            if (hi or lo) > 16:
                raise RegexUnsupported("{m,n} too large for device NFA")
            return Repeat(atom, lo, hi)
        return atom

    def atom(self) -> RxNode:
        ch = self.take()
        if ch == "(":
            if self.peek() == "?":
                raise RegexUnsupported("(?...) group")
            inner = self.alt()
            if self.peek() != ")":
                raise RegexUnsupported("unterminated (")
            self.take()
            return inner
        if ch == "[":
            return self.char_class()
        if ch == ".":
            nl = np.zeros(256, np.bool_)
            nl[ord("\n")] = True
            return _one_char(_ASCII & ~nl)
        if ch == "\\":
            return self.escape()
        if ch in _META:
            raise RegexUnsupported(f"meta {ch!r}")
        if ord(ch) > 127:
            raise RegexUnsupported("non-ASCII literal")
        return Atom(_bits_of(ch))

    def escape(self) -> RxNode:
        ch = self.take()
        if ch in "\\.^$*+?()[]{}|/-":
            return Atom(_bits_of(ch))
        if ch in _ESCAPES:
            if ch == "D":
                return _one_char(_ASCII & ~_DIGIT)
            if ch == "W":
                return _one_char(_ASCII & ~_WORD)
            if ch == "S":
                return _one_char(_ASCII & ~_SPACE)
            return Atom(_ESCAPES[ch])
        raise RegexUnsupported(f"escape \\{ch}")

    def char_class(self) -> RxNode:
        neg = False
        if self.peek() == "^":
            self.take()
            neg = True
        bits = np.zeros(256, np.bool_)
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                raise RegexUnsupported("unterminated [")
            if ch == "]" and not first:
                self.take()
                break
            self.take()
            first = False
            if ch == "\\":
                e = self.take()
                if e in _ESCAPES and _ESCAPES[e] is not None:
                    bits |= _ESCAPES[e]
                    continue
                if e in "\\.^$*+?()[]{}|/-":
                    ch = e
                else:
                    raise RegexUnsupported(f"class escape \\{e}")
            if ord(ch) > 127:
                raise RegexUnsupported("non-ASCII in class")
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self.take()
                hi = self.take()
                if hi == "\\":
                    hi = self.take()
                bits |= _range_bits(ch, hi)
            else:
                bits[ord(ch)] = True
        if neg:
            return _one_char(_ASCII & ~bits)
        return Atom(bits)


# ---------------------------------------------------------------------------
# Glushkov construction -> bit-parallel NFA
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class NFA:
    n: int                      # number of positions (states 1..n; 0 = start)
    byte_classes: np.ndarray    # bool[n, 256]
    first: int                  # bitmask of initial positions
    last: int                   # bitmask of accepting positions
    follow: List[int]           # per position, bitmask of successors
    nullable: bool
    anchored_start: bool
    anchored_end: bool
    #: Java matches() semantics: the WHOLE input must match, and the
    #: find-mode `$`-before-trailing-newline concession does NOT apply
    full_match: bool = False


def _expand_repeat(node: RxNode) -> RxNode:
    """{m,n} -> explicit concatenation (Glushkov needs *,+,? only)."""
    if isinstance(node, Repeat):
        c = _expand_repeat(node.child)
        if (node.min, node.max) in ((0, None), (1, None), (0, 1)):
            return Repeat(c, node.min, node.max)
        parts = [c] * node.min
        if node.max is None:
            parts.append(Repeat(c, 0, None))
        else:
            parts += [Repeat(c, 0, 1)] * (node.max - node.min)
        return Concat([_clone(p) for p in parts])
    if isinstance(node, Concat):
        return Concat([_expand_repeat(p) for p in node.parts])
    if isinstance(node, Alt):
        return Alt([_expand_repeat(p) for p in node.parts])
    return node


def _clone(node: RxNode) -> RxNode:
    if isinstance(node, Atom):
        return Atom(node.bits.copy())
    if isinstance(node, Concat):
        return Concat([_clone(p) for p in node.parts])
    if isinstance(node, Alt):
        return Alt([_clone(p) for p in node.parts])
    if isinstance(node, Repeat):
        return Repeat(_clone(node.child), node.min, node.max)
    return Empty()


def glushkov(ast: RxNode, anchored_start: bool, anchored_end: bool) -> NFA:
    ast = _expand_repeat(ast)
    atoms: List[Atom] = []

    def number(node):
        if isinstance(node, Atom):
            atoms.append(node)
            if len(atoms) > MAX_STATES - 1:
                raise RegexUnsupported(
                    f"pattern needs > {MAX_STATES - 1} NFA positions")
            return
        if isinstance(node, (Concat, Alt)):
            for p in node.parts:
                number(p)
        elif isinstance(node, Repeat):
            number(node.child)

    number(ast)
    pos_of = {id(a): i + 1 for i, a in enumerate(atoms)}

    def analyze(node) -> Tuple[int, int, bool]:
        """returns (first_mask, last_mask, nullable); fills follow."""
        if isinstance(node, Empty):
            return 0, 0, True
        if isinstance(node, Atom):
            m = 1 << pos_of[id(node)]
            return m, m, False
        if isinstance(node, Alt):
            f = l = 0
            nul = False
            for p in node.parts:
                pf, pl, pn = analyze(p)
                f |= pf
                l |= pl
                nul = nul or pn
            return f, l, nul
        if isinstance(node, Concat):
            f = l = 0
            nul = True
            for p in node.parts:
                pf, pl, pn = analyze(p)
                # follow: every last of the prefix connects to first of p
                for i in range(1, len(atoms) + 1):
                    if l & (1 << i):
                        follow[i] |= pf
                if nul:
                    f |= pf
                l = pl | (l if pn else 0)
                nul = nul and pn
            return f, l, nul
        if isinstance(node, Repeat):
            cf, cl, cn = analyze(node.child)
            if node.max is None:  # * or +
                for i in range(1, len(atoms) + 1):
                    if cl & (1 << i):
                        follow[i] |= cf
            nul = cn or node.min == 0
            return cf, cl, nul
        raise RegexUnsupported(type(node).__name__)

    follow = [0] * (len(atoms) + 1)
    first, last, nullable = analyze(ast)
    bc = np.zeros((len(atoms) + 1, 256), np.bool_)
    for a, i in ((a, pos_of[id(a)]) for a in atoms):
        bc[i] = a.bits
    return NFA(len(atoms), bc, first, last, follow, nullable,
               anchored_start, anchored_end)


def compile_pattern(pattern: str, mode: str = "find") -> NFA:
    """Parse + compile, raising RegexUnsupported for constructs outside the
    device subset. mode='find' (RLIKE semantics) treats the pattern as
    unanchored unless ^/$ appear."""
    p = _Parser(pattern)
    ast = p.parse()
    nfa = glushkov(ast, p.anchored_start, p.anchored_end)
    if mode == "match":
        nfa.anchored_start = True
        nfa.anchored_end = True
        nfa.full_match = True
    return nfa


# ---------------------------------------------------------------------------
# Device evaluation over flat string planes
# ---------------------------------------------------------------------------

def nfa_eval(nfa: NFA, offsets: jax.Array, raw: jax.Array, valid
             ) -> jax.Array:
    """bool[n_rows]: does each row's string match? One fori_loop over the
    max row length; each step is reach(S) & B[byte] in u32 lanes."""
    n = offsets.shape[0] - 1
    starts = offsets[:-1]
    lens = offsets[1:] - offsets[:-1]
    maxlen = jnp.max(jnp.where(valid, lens, 0)) if valid is not None \
        else jnp.max(lens)
    B = jnp.asarray(_byte_table(nfa))          # u32[256]
    follow = [int(f) for f in nfa.follow]
    first = jnp.uint32(nfa.first)
    last = jnp.uint32(nfa.last)
    seed = not nfa.anchored_start
    nbytes = int(raw.shape[0])

    def step(pos, carry):
        S, done, pre_nl = carry
        idx = jnp.clip(starts + pos, 0, nbytes - 1)
        byte = raw[idx].astype(jnp.int32)
        active = pos < lens
        if not nfa.full_match:
            # Java `$` (find mode) also matches just before a single
            # trailing newline: hit when the un-consumed suffix is "\n"
            pre_nl = pre_nl | (active & (pos == lens - 1) & (byte == 10)
                               & ((S & last) != 0))
        reach = jnp.zeros_like(S)
        # start state: active while unanchored (find) or at pos 0
        s0 = S & jnp.uint32(1)
        reach = jnp.where(s0 != 0, reach | jnp.uint32(nfa.first), reach)
        for i in range(1, nfa.n + 1):
            if follow[i]:
                reach = jnp.where((S >> jnp.uint32(i)) & jnp.uint32(1) != 0,
                                  reach | jnp.uint32(follow[i]), reach)
        nxt = reach & B[byte]
        keep_start = jnp.uint32(1) if seed else jnp.uint32(0)
        nxt = nxt | (S & keep_start)
        S = jnp.where(active, nxt, S)
        hit = (S & last) != 0
        if not nfa.anchored_end:
            done = done | (hit & active)
        return S, done, pre_nl

    S0 = jnp.full(n, 1, jnp.uint32)  # start state only
    done0 = jnp.zeros(n, jnp.bool_)
    S, done, pre_nl = lax.fori_loop(0, maxlen.astype(jnp.int32),
                                    step, (S0, done0, done0))
    if nfa.anchored_end:
        res = ((S & last) != 0) | pre_nl
    else:
        res = done | ((S & last) != 0)
    if nfa.nullable:
        if nfa.anchored_start and nfa.anchored_end:
            # full-string semantics: the empty match covers "" (and, in
            # find mode's ^...$ form, a lone line terminator)
            res = res | (lens == 0)
            if not nfa.full_match:
                first_byte = raw[jnp.clip(starts, 0, nbytes - 1)]
                res = res | ((lens == 1) & (first_byte == 10))
        else:
            # an unanchored side means the empty match fits anywhere
            res = jnp.ones_like(res)
    if valid is not None:
        res = res & valid
    return res


def _byte_table(nfa: NFA) -> np.ndarray:
    """u32[256]: for each byte value, the set of positions matching it."""
    tbl = np.zeros(256, np.uint32)
    for i in range(1, nfa.n + 1):
        tbl |= np.where(nfa.byte_classes[i], np.uint32(1 << i), np.uint32(0))
    return tbl
