"""Date/time expressions (reference datetimeExpressions.scala).

DateType = days since 1970-01-01 (int32); TimestampType = microseconds since
epoch UTC (int64). Civil-date decomposition uses the proleptic-Gregorian
days-from-civil algorithm expressed branch-free in jnp; this is the same
date algebra Spark uses (java.time), so results match for the full range.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector
from spark_rapids_tpu.expr.core import CpuCol, Expression, _valid_of


def _civil_from_days(days):
    """days since epoch -> (year, month, day). Branch-free version of the
    public-domain civil_from_days algorithm (Howard Hinnant)."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097                                   # [0, 146096]
    yoe = jnp.floor_divide(doe - jnp.floor_divide(doe, 1460)
                           + jnp.floor_divide(doe, 36524)
                           - jnp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _civil_from_days_np(days):
    z = days.astype(np.int64) + 719468
    era = np.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = np.floor_divide(doe - np.floor_divide(doe, 1460)
                          + np.floor_divide(doe, 36524)
                          - np.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + np.floor_divide(yoe, 4) - np.floor_divide(yoe, 100))
    mp = np.floor_divide(5 * doy + 2, 153)
    d = doy - np.floor_divide(153 * mp + 2, 5) + 1
    m = mp + np.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _days_of(col, is_ts: bool):
    if is_ts:
        return jnp.floor_divide(col, 86_400_000_000)
    return col


def _days_of_np(c) -> np.ndarray:
    """CPU twin: days-since-epoch from a date OR timestamp CpuCol."""
    v = c.values.astype(np.int64)
    if isinstance(c.dtype, T.TimestampType):
        return np.floor_divide(v, 86_400_000_000)
    return v


class _DatePart(Expression):
    part = "year"

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return type(self)(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        is_ts = isinstance(c.dtype, T.TimestampType)
        days = _days_of(c.data.astype(jnp.int64), is_ts)
        y, m, d = _civil_from_days(days)
        val = {"year": y, "month": m, "day": d}[self.part]
        return ColumnVector(T.INT32, val.astype(jnp.int32), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        is_ts = isinstance(c.dtype, T.TimestampType)
        days = (np.floor_divide(c.values.astype(np.int64), 86_400_000_000)
                if is_ts else c.values.astype(np.int64))
        y, m, d = _civil_from_days_np(days)
        val = {"year": y, "month": m, "day": d}[self.part]
        return CpuCol(T.INT32, val.astype(np.int32), c.valid)


class Year(_DatePart):
    part = "year"


class Month(_DatePart):
    part = "month"


class DayOfMonth(_DatePart):
    part = "day"


class _TimePart(Expression):
    """hour/minute/second from a timestamp (UTC session tz for round 1)."""

    part = "hour"

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return type(self)(children[0])

    @staticmethod
    def _compute(us):
        sec_of_day = jnp.mod(jnp.floor_divide(us, 1_000_000), 86400)
        return {
            "hour": jnp.floor_divide(sec_of_day, 3600),
            "minute": jnp.mod(jnp.floor_divide(sec_of_day, 60), 60),
            "second": jnp.mod(sec_of_day, 60),
        }

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        v = self._compute(c.data.astype(jnp.int64))[self.part]
        return ColumnVector(T.INT32, v.astype(jnp.int32), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        us = c.values.astype(np.int64)
        sec_of_day = np.mod(np.floor_divide(us, 1_000_000), 86400)
        val = {
            "hour": np.floor_divide(sec_of_day, 3600),
            "minute": np.mod(np.floor_divide(sec_of_day, 60), 60),
            "second": np.mod(sec_of_day, 60),
        }[self.part]
        return CpuCol(T.INT32, val.astype(np.int32), c.valid)


class Hour(_TimePart):
    part = "hour"


class Minute(_TimePart):
    part = "minute"


class Second(_TimePart):
    part = "second"


class DayOfWeek(Expression):
    """Spark dayofweek: 1 = Sunday ... 7 = Saturday."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return DayOfWeek(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        days = _days_of(c.data.astype(jnp.int64), isinstance(c.dtype, T.TimestampType))
        dow = jnp.mod(days + 4, 7) + 1  # 1970-01-01 was a Thursday (=5)
        return ColumnVector(T.INT32, dow.astype(jnp.int32), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        is_ts = isinstance(c.dtype, T.TimestampType)
        days = (np.floor_divide(c.values.astype(np.int64), 86_400_000_000)
                if is_ts else c.values.astype(np.int64))
        return CpuCol(T.INT32, (np.mod(days + 4, 7) + 1).astype(np.int32), c.valid)


class WeekDay(Expression):
    """Spark weekday: 0 = Monday ... 6 = Sunday (reference registers
    WeekDay alongside DayOfWeek in GpuOverrides)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return WeekDay(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        days = _days_of(c.data.astype(jnp.int64),
                        isinstance(c.dtype, T.TimestampType))
        wd = jnp.mod(days + 3, 7)  # 1970-01-01 was a Thursday (=3)
        return ColumnVector(T.INT32, wd.astype(jnp.int32), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        is_ts = isinstance(c.dtype, T.TimestampType)
        days = (np.floor_divide(c.values.astype(np.int64), 86_400_000_000)
                if is_ts else c.values.astype(np.int64))
        return CpuCol(T.INT32, np.mod(days + 3, 7).astype(np.int32), c.valid)


class DateAdd(Expression):
    """date_add(date, n)."""

    negate = False

    def __init__(self, left, right):
        self.children = [left, right]

    def data_type(self):
        return T.DATE

    def with_children(self, children):
        return type(self)(children[0], children[1])

    def eval_tpu(self, ctx):
        l = self.children[0].eval_tpu(ctx)
        r = self.children[1].eval_tpu(ctx)
        n = r.data.astype(jnp.int32)
        if self.negate:
            n = -n
        return ColumnVector(T.DATE, l.data.astype(jnp.int32) + n,
                            _valid_of(l, ctx) & _valid_of(r, ctx))

    def eval_cpu(self, cols, ansi=False):
        l = self.children[0].eval_cpu(cols, ansi)
        r = self.children[1].eval_cpu(cols, ansi)
        n = r.values.astype(np.int32)
        if self.negate:
            n = -n
        return CpuCol(T.DATE, l.values.astype(np.int32) + n, l.valid & r.valid)


class DateSub(DateAdd):
    negate = True


class DateDiff(Expression):
    """datediff(end, start) in days."""

    def __init__(self, end, start):
        self.children = [end, start]

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return DateDiff(children[0], children[1])

    def eval_tpu(self, ctx):
        e = self.children[0].eval_tpu(ctx)
        s = self.children[1].eval_tpu(ctx)
        return ColumnVector(T.INT32, e.data.astype(jnp.int32) - s.data.astype(jnp.int32),
                            _valid_of(e, ctx) & _valid_of(s, ctx))

    def eval_cpu(self, cols, ansi=False):
        e = self.children[0].eval_cpu(cols, ansi)
        s = self.children[1].eval_cpu(cols, ansi)
        return CpuCol(T.INT32, e.values.astype(np.int32) - s.values.astype(np.int32),
                      e.valid & s.valid)


class LastDay(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.DATE

    def with_children(self, children):
        return LastDay(children[0])

    @staticmethod
    def _month_len(y, m):
        leap = ((jnp.mod(y, 4) == 0) & (jnp.mod(y, 100) != 0)) | (jnp.mod(y, 400) == 0)
        lengths = jnp.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])
        base = lengths[jnp.clip(m - 1, 0, 11)]
        return jnp.where((m == 2) & leap, 29, base)

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        days = c.data.astype(jnp.int64)
        y, m, d = _civil_from_days(days)
        return ColumnVector(T.DATE,
                            (days - d + self._month_len(y, m)).astype(jnp.int32),
                            _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        import calendar
        import datetime
        c = self.children[0].eval_cpu(cols, ansi)
        out = np.zeros(len(c.values), np.int32)
        for i, v in enumerate(c.values):
            if c.valid[i]:
                d = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))
                last = calendar.monthrange(d.year, d.month)[1]
                out[i] = (d.replace(day=last) - datetime.date(1970, 1, 1)).days
        return CpuCol(T.DATE, out, c.valid)


def _days_from_civil(y, m, d):
    """(year, month, day) -> days since epoch (inverse of _civil_from_days,
    same Hinnant algorithm, branch-free)."""
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.mod(m + 9, 12)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


class Quarter(_DatePart):
    part = "quarter"

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        is_ts = isinstance(c.dtype, T.TimestampType)
        _, m, _ = _civil_from_days(_days_of(c.data.astype(jnp.int64), is_ts))
        return ColumnVector(T.INT32, ((m - 1) // 3 + 1).astype(jnp.int32),
                            _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        days = _days_of_np(c)
        _, m, _ = _civil_from_days_np(days)
        return CpuCol(T.INT32, ((m - 1) // 3 + 1).astype(np.int32), c.valid)


class DayOfYear(_DatePart):
    part = "doy"

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        is_ts = isinstance(c.dtype, T.TimestampType)
        days = _days_of(c.data.astype(jnp.int64), is_ts)
        y, _, _ = _civil_from_days(days)
        jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return ColumnVector(T.INT32, (days - jan1 + 1).astype(jnp.int32),
                            _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        import datetime
        c = self.children[0].eval_cpu(cols, ansi)
        days = _days_of_np(c)
        out = np.zeros(len(c.values), np.int32)
        for i, v in enumerate(days):
            if c.valid[i]:
                d = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))
                out[i] = d.timetuple().tm_yday
        return CpuCol(T.INT32, out, c.valid)


class WeekOfYear(_DatePart):
    """ISO-8601 week number (Spark weekofyear)."""

    part = "week"

    @staticmethod
    def _iso_week(days):
        # ISO week: Thursday of the current week determines the year;
        # 1970-01-01 was a Thursday -> dow (Mon=0) = (days + 3) % 7
        dow = jnp.mod(days + 3, 7)
        thursday = days - dow + 3
        y, _, _ = _civil_from_days(thursday)
        jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return (jnp.floor_divide(thursday - jan1, 7) + 1).astype(jnp.int32)

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        is_ts = isinstance(c.dtype, T.TimestampType)
        days = _days_of(c.data.astype(jnp.int64), is_ts)
        return ColumnVector(T.INT32, self._iso_week(days), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        import datetime
        c = self.children[0].eval_cpu(cols, ansi)
        days = _days_of_np(c)
        out = np.zeros(len(c.values), np.int32)
        for i, v in enumerate(days):
            if c.valid[i]:
                d = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))
                out[i] = d.isocalendar()[1]
        return CpuCol(T.INT32, out, c.valid)


class AddMonths(Expression):
    """add_months(date, n): day-of-month clamps to the target month's end
    (Spark semantics)."""

    def __init__(self, child, months):
        self.children = [child, months]

    def data_type(self):
        return T.DATE

    def with_children(self, children):
        return AddMonths(children[0], children[1])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        n = self.children[1].eval_tpu(ctx)
        days = _days_of(c.data.astype(jnp.int64),
                        isinstance(c.dtype, T.TimestampType))
        y, m, d = _civil_from_days(days)
        tot = y * 12 + (m - 1) + n.data.astype(jnp.int64)
        ny = jnp.floor_divide(tot, 12)
        nm = jnp.mod(tot, 12) + 1
        nd = jnp.minimum(d, LastDay._month_len(ny, nm))
        out = _days_from_civil(ny, nm, nd).astype(jnp.int32)
        valid = _valid_of(c, ctx) & _valid_of(n, ctx)
        return ColumnVector(T.DATE, out, valid)

    def eval_cpu(self, cols, ansi=False):
        import calendar
        import datetime
        c = self.children[0].eval_cpu(cols, ansi)
        n = self.children[1].eval_cpu(cols, ansi)
        out = np.zeros(len(c.values), np.int32)
        valid = c.valid & n.valid
        cdays = _days_of_np(c)
        for i in range(len(out)):
            if valid[i]:
                d = datetime.date(1970, 1, 1) + datetime.timedelta(days=int(cdays[i]))
                tot = d.year * 12 + d.month - 1 + int(n.values[i])
                ny, nm = tot // 12, tot % 12 + 1
                nd = min(d.day, calendar.monthrange(ny, nm)[1])
                out[i] = (datetime.date(ny, nm, nd) - datetime.date(1970, 1, 1)).days
        return CpuCol(T.DATE, out, valid)


class TruncTimestamp(Expression):
    """date_trunc(fmt, ts) -> timestamp (reference GpuOverrides registers
    TruncTimestamp; GpuDateTimeUtils truncation levels). Sub-day levels
    are floor-mod on microseconds; day-and-up reuses the civil-date
    truncation and returns midnight. Unsupported fmt yields NULL rows
    (Spark's null-on-bad-format behavior outside ANSI)."""

    _US = {"microsecond": 1, "millisecond": 1_000, "second": 1_000_000,
           "minute": 60_000_000, "hour": 3_600_000_000,
           "day": 86_400_000_000, "dd": 86_400_000_000}
    _CIVIL = {"week": "w", "month": "m", "mon": "m", "mm": "m",
              "quarter": "q", "year": "y", "yyyy": "y", "yy": "y"}

    def __init__(self, child, fmt: str):
        self.children = [child]
        self.fmt = fmt.lower()

    def _params(self):
        return self.fmt

    def data_type(self):
        return T.TIMESTAMP

    def with_children(self, children):
        return TruncTimestamp(children[0], self.fmt)

    def _trunc_us(self, us, mod, floordiv, ones_like):
        if self.fmt in self._US:
            return us - mod(us, self._US[self.fmt])
        day_us = 86_400_000_000
        days = floordiv(us, day_us)
        kind = self._CIVIL[self.fmt]
        if kind == "w":
            days = days - mod(days + 3, 7)
        else:
            y, m, d = _civil_from_days(days)
            if kind == "y":
                days = _days_from_civil(y, ones_like(m), ones_like(d))
            elif kind == "m":
                days = _days_from_civil(y, m, ones_like(d))
            else:
                qm = ((m - 1) // 3) * 3 + 1
                days = _days_from_civil(y, qm, ones_like(d))
        return days * day_us

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        us = c.data.astype(jnp.int64)
        if not isinstance(c.dtype, T.TimestampType):
            us = us * 86_400_000_000  # DATE child: implicit cast (days)
        if self.fmt not in self._US and self.fmt not in self._CIVIL:
            n = us.shape[0]
            return ColumnVector(T.TIMESTAMP, jnp.zeros(n, jnp.int64),
                                jnp.zeros(n, jnp.bool_))
        out = self._trunc_us(us, jnp.mod, jnp.floor_divide, jnp.ones_like)
        return ColumnVector(T.TIMESTAMP, out, _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        us = c.values.astype(np.int64)
        if not isinstance(c.dtype, T.TimestampType):
            us = us * 86_400_000_000
        if self.fmt not in self._US and self.fmt not in self._CIVIL:
            return CpuCol(T.TIMESTAMP, np.zeros(len(us), np.int64),
                          np.zeros(len(us), np.bool_))
        out = np.asarray(  # civil helpers are jnp-backed; pin numpy out
            self._trunc_us(us, np.mod, np.floor_divide, np.ones_like),
            np.int64)
        return CpuCol(T.TIMESTAMP, out, c.valid)


class TruncDate(Expression):
    """trunc(date, fmt) for fmt in year/yyyy/yy/month/mon/mm/quarter/week."""

    _FMTS = {"year": "y", "yyyy": "y", "yy": "y", "month": "m", "mon": "m",
             "mm": "m", "quarter": "q", "week": "w"}

    def __init__(self, child, fmt: str):
        self.children = [child]
        self.fmt = fmt.lower()

    def _params(self):
        return self.fmt

    def data_type(self):
        return T.DATE

    def with_children(self, children):
        return TruncDate(children[0], self.fmt)

    def supported_on_tpu(self):
        return self.fmt in self._FMTS

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        days = _days_of(c.data.astype(jnp.int64),
                        isinstance(c.dtype, T.TimestampType))
        kind = self._FMTS[self.fmt]
        y, m, d = _civil_from_days(days)
        if kind == "y":
            out = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        elif kind == "m":
            out = _days_from_civil(y, m, jnp.ones_like(d))
        elif kind == "q":
            qm = ((m - 1) // 3) * 3 + 1
            out = _days_from_civil(y, qm, jnp.ones_like(d))
        else:  # week: Monday
            out = days - jnp.mod(days + 3, 7)
        return ColumnVector(T.DATE, out.astype(jnp.int32), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        import datetime
        c = self.children[0].eval_cpu(cols, ansi)
        out = np.zeros(len(c.values), np.int32)
        valid = c.valid.copy()
        kind = self._FMTS.get(self.fmt)
        epoch = datetime.date(1970, 1, 1)
        for i, v in enumerate(_days_of_np(c)):
            if not c.valid[i]:
                continue
            if kind is None:
                valid[i] = False
                continue
            d = epoch + datetime.timedelta(days=int(v))
            if kind == "y":
                d = d.replace(month=1, day=1)
            elif kind == "m":
                d = d.replace(day=1)
            elif kind == "q":
                d = d.replace(month=(d.month - 1) // 3 * 3 + 1, day=1)
            else:
                d = d - datetime.timedelta(days=d.weekday())
            out[i] = (d - epoch).days
        return CpuCol(T.DATE, out, valid)


class UnixTimestampFromTs(Expression):
    """unix_timestamp(ts): seconds since epoch (floor division)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT64

    def with_children(self, children):
        return UnixTimestampFromTs(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        v = c.data.astype(jnp.int64)
        if isinstance(c.dtype, T.DateType):
            out = v * 86_400
        else:
            out = jnp.floor_divide(v, 1_000_000)
        return ColumnVector(T.INT64, out, _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        v = c.values.astype(np.int64)
        if isinstance(c.dtype, T.DateType):
            out = v * 86_400
        else:
            out = np.floor_divide(v, 1_000_000)
        return CpuCol(T.INT64, out, c.valid)


class TimestampSeconds(Expression):
    """timestamp_seconds(long) -> timestamp."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.TIMESTAMP

    def with_children(self, children):
        return TimestampSeconds(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        return ColumnVector(T.TIMESTAMP, c.data.astype(jnp.int64) * 1_000_000,
                            _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        return CpuCol(T.TIMESTAMP, c.values.astype(np.int64) * 1_000_000, c.valid)


# ---------------------------------------------------------------------------
# Timezone conversion (reference TimeZoneDB.scala + JNI GpuTimeZoneDB:
# non-UTC sessions keep datetime expressions on device via an IANA
# transition table; here the table is parsed host-side from TZif files
# (expr/tzdb.py) and applied with a searchsorted over the few-hundred-entry
# transition plane)
# ---------------------------------------------------------------------------

import datetime as _dt  # noqa: E402

_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)


class _TzShiftBase(Expression):
    """Shared machinery: per-row offset lookup from a zone's transition
    table. The zone is plan-time constant (literal); non-literal zones
    are tagged to CPU by the rule."""

    def __init__(self, child: Expression, zone: str):
        self.children = [child]
        self.zone = str(zone)

    def _params(self):
        return self.zone

    def with_children(self, children):
        return type(self)(children[0], self.zone)

    def data_type(self):
        return T.TIMESTAMP

    def supported_on_tpu(self):
        from spark_rapids_tpu.expr import tzdb
        return tzdb.is_valid_zone(self.zone)

    def _table(self):
        from spark_rapids_tpu.expr import tzdb
        return tzdb.zone_table(self.zone)


class FromUtcTimestamp(_TzShiftBase):
    """from_utc_timestamp(ts, zone): shift a UTC instant so its UTC
    rendering equals the zone's wall clock."""

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        trans, offs = self._table()
        v = c.data.astype(jnp.int64)
        if len(trans) == 0:
            out = v + jnp.int64(int(offs[0]))
        else:
            idx = jnp.searchsorted(jnp.asarray(trans), v, side="right")
            out = v + jnp.asarray(offs)[idx]
        return ColumnVector(T.TIMESTAMP, out, _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        from zoneinfo import ZoneInfo
        c = self.children[0].eval_cpu(cols, ansi)
        z = ZoneInfo(self.zone)
        out = np.zeros(len(c.values), np.int64)
        for i, (v, ok) in enumerate(zip(c.values, c.valid)):
            if not ok:
                continue
            dt = _EPOCH + _dt.timedelta(microseconds=int(v))
            off = dt.astimezone(z).utcoffset().total_seconds()
            out[i] = int(v) + int(off * 1_000_000)
        return CpuCol(T.TIMESTAMP, out, c.valid.copy())


class ToUtcTimestamp(_TzShiftBase):
    """to_utc_timestamp(ts, zone): interpret the timestamp's UTC rendering
    as the zone's wall clock and return the instant. Gap/overlap times
    resolve to the pre-transition (earlier) offset via the fold=0
    local-boundary table (tzdb.local_boundaries), matching java.time and
    this expression's zoneinfo-based CPU tier."""

    def eval_tpu(self, ctx):
        from spark_rapids_tpu.expr import tzdb
        c = self.children[0].eval_tpu(ctx)
        bounds, offs = tzdb.local_boundaries(self.zone)
        v = c.data.astype(jnp.int64)
        if len(bounds) == 0:
            out = v - jnp.int64(int(offs[0]))
        else:
            idx = jnp.searchsorted(jnp.asarray(bounds), v, side="right")
            out = v - jnp.asarray(offs)[idx]
        return ColumnVector(T.TIMESTAMP, out, _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        from zoneinfo import ZoneInfo
        c = self.children[0].eval_cpu(cols, ansi)
        z = ZoneInfo(self.zone)
        out = np.zeros(len(c.values), np.int64)
        for i, (v, ok) in enumerate(zip(c.values, c.valid)):
            if not ok:
                continue
            # interpret the UTC civil fields as zone-local (fold=0 picks
            # the earlier offset in overlaps, pre-gap offset in gaps)
            naive = _EPOCH + _dt.timedelta(microseconds=int(v))
            local = naive.replace(tzinfo=z, fold=0)
            out[i] = int(v) - int(local.utcoffset().total_seconds()
                                  * 1_000_000)
        return CpuCol(T.TIMESTAMP, out, c.valid.copy())


# ---------------------------------------------------------------------------
# Datetime breadth second tier (reference datetimeExpressions.scala)
# ---------------------------------------------------------------------------

def _days_from_civil(y, m, d):
    """(y, m, d) -> days since epoch; branch-free days_from_civil."""
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + jnp.where(m > 2, -3, 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


class MakeDate(Expression):
    """make_date(y, m, d): null (ANSI: error) on invalid components."""

    def __init__(self, y, m, d):
        self.children = [y, m, d]

    def data_type(self):
        return T.DATE

    def with_children(self, children):
        return MakeDate(*children)

    #: Spark/LocalDate year bounds; also keeps the day count in int32
    _YMIN, _YMAX = -999_999_999, 999_999_999

    def eval_tpu(self, ctx):
        cy, cm, cd = [c.eval_tpu(ctx) for c in self.children]
        y = cy.data.astype(jnp.int64)
        m = cm.data.astype(jnp.int64)
        d = cd.data.astype(jnp.int64)
        yc = jnp.clip(y, -6_000_000, 6_000_000)  # int32-day-safe window
        days = _days_from_civil(yc, m, d)
        # validity: round-trip check catches day overflow per month
        yy, mm, dd = _civil_from_days(days)
        ok = ((m >= 1) & (m <= 12) & (d >= 1) & (yy == yc) & (mm == m)
              & (dd == d) & (y == yc)
              & (days >= -(2 ** 31)) & (days < 2 ** 31))
        valid = _valid_of(cy, ctx) & _valid_of(cm, ctx) & _valid_of(cd, ctx)
        if ctx.ansi:
            ctx.add_error("InvalidDate", valid & ~ok)
        return ColumnVector(T.DATE, days.astype(jnp.int32), valid & ok)

    def eval_cpu(self, cols, ansi=False):
        # same civil arithmetic as the device path (python datetime.date
        # caps years at 9999 — Spark's LocalDate does not)
        cy, cm, cd = [c.eval_cpu(cols, ansi) for c in self.children]
        y = cy.values.astype(np.int64)
        m = cm.values.astype(np.int64)
        d = cd.values.astype(np.int64)
        yc = np.clip(y, -6_000_000, 6_000_000)
        ym = yc - (m <= 2)
        era = np.floor_divide(ym, 400)
        yoe = ym - era * 400
        mp = m + np.where(m > 2, -3, 9)
        doy = np.floor_divide(153 * mp + 2, 5) + d - 1
        doe = yoe * 365 + np.floor_divide(yoe, 4) \
            - np.floor_divide(yoe, 100) + doy
        days = era * 146097 + doe - 719468
        yy, mm, dd = _civil_from_days_np(days)
        ok = ((m >= 1) & (m <= 12) & (d >= 1) & (yy == yc) & (mm == m)
              & (dd == d) & (y == yc)
              & (days >= -(2 ** 31)) & (days < 2 ** 31))
        valid = cy.valid & cm.valid & cd.valid
        if ansi and bool((valid & ~ok).any()):
            from spark_rapids_tpu.expr.core import SparkException
            raise SparkException("invalid date components")
        return CpuCol(T.DATE, days.astype(np.int32), valid & ok)


class NextDay(Expression):
    """next_day(date, dayOfWeek): the next date AFTER `date` that falls on
    the given weekday. Null for an unrecognized weekday name."""

    #: Spark getDayOfWeekFromString: exact 2/3-letter abbreviations or
    #: full names only — "FRIENDS" is invalid, not Friday
    _DOW = {}
    for _i, _names in enumerate([("MO", "MON", "MONDAY"),
                                 ("TU", "TUE", "TUESDAY"),
                                 ("WE", "WED", "WEDNESDAY"),
                                 ("TH", "THU", "THURSDAY"),
                                 ("FR", "FRI", "FRIDAY"),
                                 ("SA", "SAT", "SATURDAY"),
                                 ("SU", "SUN", "SUNDAY")]):
        for _n in _names:
            _DOW[_n] = _i

    def __init__(self, child, day: str):
        self.children = [child]
        self.day = str(day)
        self._target = self._DOW.get(self.day.strip().upper())

    def _params(self):
        return self.day

    def with_children(self, children):
        return NextDay(children[0], self.day)

    def data_type(self):
        return T.DATE

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        valid = _valid_of(c, ctx)
        if self._target is None:
            return ColumnVector(T.DATE, jnp.zeros(ctx.capacity, jnp.int32),
                                jnp.zeros(ctx.capacity, jnp.bool_))
        d = c.data.astype(jnp.int64)
        dow = jnp.mod(d + 3, 7)  # 1970-01-01 was a Thursday (MO=0)
        delta = jnp.mod(jnp.int64(self._target) - dow + 6, 7) + 1
        return ColumnVector(T.DATE, (d + delta).astype(jnp.int32), valid)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        if self._target is None:
            return CpuCol(T.DATE, np.zeros(len(c.values), np.int32),
                          np.zeros(len(c.values), np.bool_))
        d = c.values.astype(np.int64)
        dow = np.mod(d + 3, 7)
        delta = np.mod(self._target - dow + 6, 7) + 1
        return CpuCol(T.DATE, (d + delta).astype(np.int32), c.valid)


class MonthsBetween(Expression):
    """months_between(end, start[, roundOff]): whole months plus a
    31-day-month fraction; both-last-day-of-month counts as whole."""

    def __init__(self, end, start, round_off: bool = True):
        self.children = [end, start]
        self.round_off = bool(round_off)

    def _params(self):
        return str(self.round_off)

    def with_children(self, children):
        return MonthsBetween(children[0], children[1], self.round_off)

    def data_type(self):
        return T.FLOAT64

    @staticmethod
    def _split(ts_us):
        days = jnp.floor_divide(ts_us, 86_400_000_000)
        tod = ts_us - days * 86_400_000_000
        y, m, d = _civil_from_days(days)
        return y, m, d, tod, days

    def eval_tpu(self, ctx):
        e = self.children[0].eval_tpu(ctx)
        s = self.children[1].eval_tpu(ctx)

        def as_us(c):
            if isinstance(c.dtype, T.DateType):
                return c.data.astype(jnp.int64) * 86_400_000_000
            return c.data.astype(jnp.int64)

        ey, em, ed, etod, edays = self._split(as_us(e))
        sy, sm, sd, stod, sdays = self._split(as_us(s))
        # last-day-of-month detection via next-day month change
        _, em2, _ = _civil_from_days(edays + 1)
        _, sm2, _ = _civil_from_days(sdays + 1)
        e_last = em2 != em
        s_last = sm2 != sm
        months = (ey - sy) * 12 + (em - sm)
        same_day = ed == sd
        whole = (e_last & s_last) | same_day
        esec = ed.astype(jnp.float64) * 86400 + etod.astype(jnp.float64) / 1e6
        ssec = sd.astype(jnp.float64) * 86400 + stod.astype(jnp.float64) / 1e6
        frac = jnp.where(whole, 0.0, (esec - ssec) / (31.0 * 86400))
        out = months.astype(jnp.float64) + frac
        if self.round_off:
            out = jnp.round(out * 1e8) / 1e8
        return ColumnVector(T.FLOAT64, out, _valid_of(e, ctx) & _valid_of(s, ctx))

    def eval_cpu(self, cols, ansi=False):
        import calendar
        import datetime as dtm
        e = self.children[0].eval_cpu(cols, ansi)
        s = self.children[1].eval_cpu(cols, ansi)

        def as_dt(c, i):
            v = int(c.values[i])
            if isinstance(c.dtype, T.DateType):
                return dtm.datetime(1970, 1, 1) + dtm.timedelta(days=v)
            return dtm.datetime(1970, 1, 1) + dtm.timedelta(microseconds=v)

        out = np.zeros(len(e.values), np.float64)
        for i in range(len(out)):
            if not (e.valid[i] and s.valid[i]):
                continue
            de, ds = as_dt(e, i), as_dt(s, i)
            e_last = de.day == calendar.monthrange(de.year, de.month)[1]
            s_last = ds.day == calendar.monthrange(ds.year, ds.month)[1]
            months = (de.year - ds.year) * 12 + (de.month - ds.month)
            if (e_last and s_last) or de.day == ds.day:
                v = float(months)
            else:
                esec = de.day * 86400 + de.hour * 3600 + de.minute * 60 \
                    + de.second + de.microsecond / 1e6
                ssec = ds.day * 86400 + ds.hour * 3600 + ds.minute * 60 \
                    + ds.second + ds.microsecond / 1e6
                v = months + (esec - ssec) / (31.0 * 86400)
            out[i] = round(v, 8) if self.round_off else v
        return CpuCol(T.FLOAT64, out, e.valid & s.valid)


class _TrivialConvert(Expression):
    """Base for unit conversions that are a single multiply/divide."""

    in_t = T.TIMESTAMP
    out_t = T.INT64

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return self.out_t

    def with_children(self, children):
        return type(self)(children[0])

    def _fn(self, v, xp):
        raise NotImplementedError

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        out = self._fn(c.data.astype(jnp.int64), jnp)
        return ColumnVector(self.out_t, out.astype(self.out_t.np_dtype),
                            _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        out = self._fn(c.values.astype(np.int64), np)
        return CpuCol(self.out_t, out.astype(self.out_t.np_dtype), c.valid)


class UnixDate(_TrivialConvert):
    """unix_date(date) -> days since epoch (int32)."""
    in_t = T.DATE
    out_t = T.INT32

    def _fn(self, v, xp):
        return v


class DateFromUnixDate(_TrivialConvert):
    in_t = T.INT32
    out_t = T.DATE

    def _fn(self, v, xp):
        return v


class UnixMicros(_TrivialConvert):
    def _fn(self, v, xp):
        return v


class UnixMillis(_TrivialConvert):
    def _fn(self, v, xp):
        return xp.floor_divide(v, 1000)


class UnixSeconds(_TrivialConvert):
    def _fn(self, v, xp):
        return xp.floor_divide(v, 1_000_000)


class TimestampMillis(_TrivialConvert):
    in_t = T.INT64
    out_t = T.TIMESTAMP

    def _fn(self, v, xp):
        return v * 1000


class TimestampMicros(_TrivialConvert):
    in_t = T.INT64
    out_t = T.TIMESTAMP

    def _fn(self, v, xp):
        return v
