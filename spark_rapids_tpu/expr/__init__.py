from spark_rapids_tpu.expr.core import (  # noqa: F401
    Expression, BoundRef, Col, Literal, Alias, EvalCtx, CpuCol,
    Add, Subtract, Multiply, Divide, IntegralDivide, Remainder, UnaryMinus, Abs,
    EqualTo, EqualNullSafe, LessThan, LessThanOrEqual, GreaterThan,
    GreaterThanOrEqual, And, Or, Not, IsNull, IsNotNull, IsNaN, In,
    If, CaseWhen, Coalesce, Cast, SparkException,
    col, lit,
)
