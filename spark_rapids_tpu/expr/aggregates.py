"""Aggregate function descriptors.

Reference parity: aggregate/aggregateFunctions.scala (GpuSum, GpuCount,
GpuMin, GpuMax, GpuAverage, GpuFirst/Last, M2/stddev/variance) and the
update/merge/evaluate phase structure of GpuAggregateExec.

An AggFunction declares, like the reference's CudfAggregate pairs:
- state_schema: the partial-aggregation buffer columns
- update ops: segmented reductions applied to input rows per group
- merge ops: segmented reductions combining partial states per group
- evaluate: final projection from state columns to the result column

The exec layer (exec/aggregate.py) drives these through the sort-based
segmented kernels in ops/groupby.py. The CPU differential path uses pandas
groupby -- an independent implementation.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector
from spark_rapids_tpu.expr.core import CpuCol, Expression, SparkException


class AggFunction:
    """Base; children are input expressions evaluated before aggregation."""

    def __init__(self, *children: Expression):
        self.children = list(children)

    def result_type(self) -> T.DataType:
        raise NotImplementedError

    def state_schema(self) -> List[Tuple[str, T.DataType]]:
        raise NotImplementedError

    def update_ops(self) -> List[Tuple[str, int]]:
        """[(segmented_op, input_index)] producing each state column."""
        raise NotImplementedError

    def merge_ops(self) -> List[str]:
        """Segmented op per state column for combining partials."""
        raise NotImplementedError

    def evaluate_tpu(self, state_cols: List[ColumnVector], n_groups: int) -> ColumnVector:
        raise NotImplementedError

    def pandas_spec(self):
        """(colname_fn, agg) description for the CPU pandas path; see
        exec/cpu_exec.py."""
        raise NotImplementedError

    def fingerprint(self) -> str:
        kids = ",".join(c.fingerprint() for c in self.children)
        return f"{type(self).__name__}({kids})"

    def over(self, spec):
        """agg OVER window-spec -> WindowExpr (pyspark F.sum(c).over(w))."""
        from spark_rapids_tpu.expr.window import over as _over
        return _over(self, spec)

    def transform(self, fn):
        clone = type(self)(*[c.transform(fn) for c in self.children])
        return clone

    def alias(self, name):
        return NamedAgg(self, name)

    def __repr__(self):
        return self.fingerprint()


class NamedAgg:
    def __init__(self, fn: AggFunction, name: str):
        self.fn = fn
        self.name = name

    def transform(self, f):
        return NamedAgg(self.fn.transform(f), self.name)


class Sum(AggFunction):
    """Spark sum: int inputs -> long; float -> double; null if all null."""

    def result_type(self):
        dt = self.children[0].data_type()
        if dt.is_integral:
            return T.INT64
        if isinstance(dt, T.DecimalType):
            return T.DecimalType(min(dt.precision + 10, 18), dt.scale)
        return T.FLOAT64

    def state_schema(self):
        return [("sum", self.result_type())]

    def update_ops(self):
        return [("sum", 0)]

    def merge_ops(self):
        return ["sum"]

    def evaluate_tpu(self, state_cols, n_groups):
        return state_cols[0]

    def pandas_spec(self):
        return "sum"


class Count(AggFunction):
    def result_type(self):
        return T.INT64

    def state_schema(self):
        return [("count", T.INT64)]

    def update_ops(self):
        return [("count", 0)]

    def merge_ops(self):
        return ["sum"]

    def evaluate_tpu(self, state_cols, n_groups):
        c = state_cols[0]
        return ColumnVector(T.INT64, c.data, None)

    def pandas_spec(self):
        return "count"


class CountAll(AggFunction):
    """count(*) / count(1)."""

    def __init__(self):
        super().__init__()

    def result_type(self):
        return T.INT64

    def state_schema(self):
        return [("count", T.INT64)]

    def update_ops(self):
        return [("count_all", -1)]  # -1: no input column needed

    def merge_ops(self):
        return ["sum"]

    def evaluate_tpu(self, state_cols, n_groups):
        return ColumnVector(T.INT64, state_cols[0].data, None)

    def pandas_spec(self):
        return "size"

    def transform(self, fn):
        return self


class Min(AggFunction):
    def result_type(self):
        return self.children[0].data_type()

    def state_schema(self):
        return [("min", self.result_type())]

    def update_ops(self):
        return [("min", 0)]

    def merge_ops(self):
        return ["min"]

    def evaluate_tpu(self, state_cols, n_groups):
        return state_cols[0]

    def pandas_spec(self):
        return "min"


class Max(AggFunction):
    def result_type(self):
        return self.children[0].data_type()

    def state_schema(self):
        return [("max", self.result_type())]

    def update_ops(self):
        return [("max", 0)]

    def merge_ops(self):
        return ["max"]

    def evaluate_tpu(self, state_cols, n_groups):
        return state_cols[0]

    def pandas_spec(self):
        return "max"


class Average(AggFunction):
    """avg: state (sum: double, count: long); result double.
    (Decimal avg via double in round 1, documented incompat.)"""

    def result_type(self):
        return T.FLOAT64

    def state_schema(self):
        return [("sum", T.FLOAT64), ("count", T.INT64)]

    def update_ops(self):
        return [("sum", 0), ("count", 0)]

    def merge_ops(self):
        return ["sum", "sum"]

    def evaluate_tpu(self, state_cols, n_groups):
        s, c = state_cols
        cnt = c.data.astype(jnp.float64)
        val = s.data.astype(jnp.float64) / jnp.where(cnt == 0, 1.0, cnt)
        dt = self.children[0].data_type()
        if isinstance(dt, T.DecimalType):
            val = val / np.float64(10.0 ** dt.scale)  # unscaled -> value
        return ColumnVector(T.FLOAT64, val, (c.data > 0))

    def pandas_spec(self):
        return "mean"


class First(AggFunction):
    """first(expr, ignoreNulls=True) -- our batch-sorted implementation picks
    the first non-null in group-sorted order; with ignore_nulls=False Spark's
    result is non-deterministic anyway."""

    op = "first"

    def result_type(self):
        return self.children[0].data_type()

    def state_schema(self):
        return [("val", self.result_type())]

    def update_ops(self):
        return [(self.op, 0)]

    def merge_ops(self):
        return [self.op]

    def evaluate_tpu(self, state_cols, n_groups):
        return state_cols[0]

    def pandas_spec(self):
        return "first"


class Last(First):
    op = "last"

    def pandas_spec(self):
        return "last"


class _MomentAgg(AggFunction):
    """Shared machinery for variance/stddev via (n, sum, sum_sq) states with
    the final moment computed as m2 = sumsq - sum^2/n. The reference uses
    cudf M2 merging; sum-of-squares is algebraically identical with double
    precision and our deterministic sorted-order summation keeps it stable
    enough for SQL parity tests."""

    ddof = 1  # 1 = sample, 0 = population

    def result_type(self):
        return T.FLOAT64

    def state_schema(self):
        return [("n", T.INT64), ("sum", T.FLOAT64), ("sumsq", T.FLOAT64)]

    def update_ops(self):
        return [("count", 0), ("sum", 0), ("sumsq", 0)]

    def merge_ops(self):
        return ["sum", "sum", "sum"]

    def _moments(self, state_cols):
        n = state_cols[0].data.astype(jnp.float64)
        s = state_cols[1].data.astype(jnp.float64)
        ss = state_cols[2].data.astype(jnp.float64)
        denom = n - self.ddof
        m2 = ss - (s * s) / jnp.where(n == 0, 1.0, n)
        m2 = jnp.maximum(m2, 0.0)
        var = m2 / jnp.where(denom <= 0, 1.0, denom)
        return n, denom, var


class VarianceSamp(_MomentAgg):
    ddof = 1

    def evaluate_tpu(self, state_cols, n_groups):
        # n == 1 -> NULL (Spark 3.1+ default, legacy NaN mode off)
        n, denom, var = self._moments(state_cols)
        return ColumnVector(T.FLOAT64, var, (n > 0) & (denom > 0))

    def pandas_spec(self):
        return "var"


class VariancePop(_MomentAgg):
    ddof = 0

    def evaluate_tpu(self, state_cols, n_groups):
        n, denom, var = self._moments(state_cols)
        return ColumnVector(T.FLOAT64, var, (n > 0))

    def pandas_spec(self):
        return ("var", 0)


class StddevSamp(_MomentAgg):
    ddof = 1

    def evaluate_tpu(self, state_cols, n_groups):
        # n == 1 -> NULL (Spark 3.1+ default, legacy NaN mode off)
        n, denom, var = self._moments(state_cols)
        return ColumnVector(T.FLOAT64, jnp.sqrt(var),
                            (n > 0) & (denom > 0))

    def pandas_spec(self):
        return "std"


class StddevPop(_MomentAgg):
    ddof = 0

    def evaluate_tpu(self, state_cols, n_groups):
        n, denom, var = self._moments(state_cols)
        return ColumnVector(T.FLOAT64, jnp.sqrt(var), (n > 0))

    def pandas_spec(self):
        return ("std", 0)


# ---------------------------------------------------------------------------
# Custom segmented aggregates: functions whose per-group result cannot be a
# fixed-width mergeable state (collect_list/set, min_by/max_by, percentile).
# Reference: aggregateFunctions.scala GpuCollectList/GpuCollectSet/
# GpuMinBy/GpuMaxBy, GpuPercentile.scala, GpuApproximatePercentile.scala.
#
# TPU-first: these run in COMPLETE mode only (the planner exchanges RAW
# rows by group key first — `no_partial`), where the sort-based aggregator
# hands them the group-sorted row order; each computes its final column in
# one traced pass with segment reductions / one extra in-group sort.
# ---------------------------------------------------------------------------


class SegmentedAgg(AggFunction):
    """Base for complete-mode custom aggregates."""

    no_partial = True

    def state_schema(self):
        return [("result", self.result_type())]

    def update_ops(self):
        return [("custom", 0)]

    def merge_ops(self):
        # never reached: no_partial plans run a single update pass
        raise NotImplementedError(
            f"{type(self).__name__} has no mergeable partial state")

    def evaluate_tpu(self, state_cols, n_groups):
        return state_cols[0]

    def segmented_eval_tpu(self, inputs, perm, seg_ids, seg_cap, live,
                           num_rows) -> ColumnVector:
        raise NotImplementedError

    def eval_cpu_groups(self, inputs, gid, n_groups):
        raise NotImplementedError


def _valid_under(col: ColumnVector, live):
    return live if col.validity is None else (col.validity & live)


def _cpu_leaf_converter(dt):
    """CPU-tier element values arrive as raw numpy scalars; arrow nested
    builders need real python Decimals for decimal children (the device
    path already converts via _leaf_to_py)."""
    if isinstance(dt, T.DecimalType):
        import decimal
        scale = dt.scale
        return lambda v: decimal.Decimal(int(v)).scaleb(-scale)
    return lambda v: v


def _pack_valid_front(src: ColumnVector, perm, keep_sorted, cap):
    """Scatter the kept sorted rows to the front (stable): returns
    (child ColumnVector, dest positions of kept rows)."""
    from spark_rapids_tpu.ops import kernels as K
    dest = jnp.cumsum(keep_sorted.astype(jnp.int32)) - keep_sorted
    src_idx = jnp.full(cap, -1, jnp.int32).at[
        jnp.where(keep_sorted, dest, cap)].set(perm, mode="drop")
    return K.gather_column(src, src_idx, cap), dest


class CollectList(SegmentedAgg):
    """collect_list: group values in stable input order, nulls dropped."""

    def result_type(self):
        return T.ArrayType(self.children[0].data_type(), contains_null=False)

    def segmented_eval_tpu(self, inputs, perm, seg_ids, seg_cap, live,
                           num_rows):
        import jax
        src = inputs[0]
        cap = perm.shape[0]
        keep = _valid_under(src, live)[perm]
        child, _ = _pack_valid_front(src, perm, keep, cap)
        counts = jax.ops.segment_sum(keep.astype(jnp.int32), seg_ids,
                                     num_segments=seg_cap)
        offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(counts).astype(jnp.int32)])
        return ColumnVector(self.result_type(),
                            {"offsets": offsets, "child": child}, None)

    def eval_cpu_groups(self, inputs, gid, n_groups):
        src = inputs[0]
        conv = _cpu_leaf_converter(self.children[0].data_type())
        out = [[] for _ in range(n_groups)]
        for g, v, ok in zip(gid, src.values, src.valid):
            if ok and v is not None:
                out[g].append(conv(v))
        vals = np.empty(n_groups, object)
        vals[:] = out
        return CpuCol(self.result_type(), vals, np.ones(n_groups, np.bool_))


class CollectSet(SegmentedAgg):
    """collect_set: distinct group values. Spark leaves element order
    unspecified; both backends emit ascending value order (deterministic,
    and any order is conformant).

    Dict-encoded strings with a unique vocabulary dedup EXACTLY by code.
    Flat/non-unique string dedup rides the 64-bit double-hash equality of
    normalize_key: two distinct strings colliding (odds ~2^-64 per pair)
    would merge into one set element — same documented incompat as the
    string join path (ops/join.py), gated by the same
    ``spark.rapids.sql.incompatibleOps.enabled`` conf."""

    def result_type(self):
        return T.ArrayType(self.children[0].data_type(), contains_null=False)

    def segmented_eval_tpu(self, inputs, perm, seg_ids, seg_cap, live,
                           num_rows):
        import jax
        from jax import lax
        from spark_rapids_tpu.ops import kernels as K
        src = inputs[0]
        cap = perm.shape[0]
        keep = _valid_under(src, live)[perm]
        if src.is_dict and src.dict_unique:
            # unique-vocab dict strings: the CODE is an exact equality
            # key — no hash-collision exposure at all (VERDICT r3 weak
            # #8); flat strings keep the documented 64-bit hash incompat
            vkey = src.data["codes"].astype(jnp.uint64)
        else:
            vkey, _ = K.normalize_key(src, num_rows, live=live)
        vkey_s = vkey[perm]
        iota = jnp.arange(cap, dtype=jnp.int32)
        # re-sort within groups by value (invalid rows last) to expose
        # duplicates as adjacent runs
        _, _, _, idx2 = lax.sort(
            (seg_ids, (~keep).astype(jnp.uint8), vkey_s, iota),
            num_keys=3, is_stable=True)
        seg2 = seg_ids[idx2]
        vk2 = vkey_s[idx2]
        keep2 = keep[idx2]
        first = jnp.concatenate([
            jnp.ones(1, jnp.bool_),
            (seg2[1:] != seg2[:-1]) | (vk2[1:] != vk2[:-1])])
        keep2 = keep2 & first
        perm2 = perm[idx2]
        child, _ = _pack_valid_front(src, perm2, keep2, cap)
        counts = jax.ops.segment_sum(keep2.astype(jnp.int32), seg2,
                                     num_segments=seg_cap)
        offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(counts).astype(jnp.int32)])
        return ColumnVector(self.result_type(),
                            {"offsets": offsets, "child": child}, None)

    def eval_cpu_groups(self, inputs, gid, n_groups):
        src = inputs[0]
        conv = _cpu_leaf_converter(self.children[0].data_type())
        seen = [dict() for _ in range(n_groups)]
        for g, v, ok in zip(gid, src.values, src.valid):
            if ok and v is not None:
                v = conv(v)
                # NaN is ONE distinct set member (Spark semantics); python
                # dict keying by the value itself would keep every NaN
                key = "__nan__" if isinstance(v, float) and v != v else v
                seen[g].setdefault(key, v)
        def skey(x):
            return (2, 0) if isinstance(x, float) and x != x else (1, x)
        out = [sorted(s.values(), key=skey) for s in seen]
        vals = np.empty(n_groups, object)
        vals[:] = out
        return CpuCol(self.result_type(), vals, np.ones(n_groups, np.bool_))


class _MinMaxBy(SegmentedAgg):
    """min_by/max_by(value, ordering): value at the extreme ordering. Rows
    with null ordering are ignored; ties break to the earliest row in
    group-sorted (stable input) order."""

    is_min = True

    def result_type(self):
        return self.children[0].data_type()

    def segmented_eval_tpu(self, inputs, perm, seg_ids, seg_cap, live,
                           num_rows):
        import jax
        from spark_rapids_tpu.ops import kernels as K
        val, ordc = inputs
        cap = perm.shape[0]
        ok = _valid_under(ordc, live)
        okey, _ = K.normalize_key(ordc, num_rows, live=live)
        if not self.is_min:
            okey = ~okey
        key_s = jnp.where(ok, okey, jnp.uint64(0xFFFFFFFFFFFFFFFF))[perm]
        gmin = jax.ops.segment_min(key_s, seg_ids, num_segments=seg_cap)
        iota = jnp.arange(cap, dtype=jnp.int32)
        hit = ok[perm] & (key_s == gmin[seg_ids])
        pos = jnp.where(hit, iota, cap)
        sel = jax.ops.segment_min(pos, seg_ids, num_segments=seg_cap)
        has = sel < cap
        src_idx = jnp.where(has, perm[jnp.clip(sel, 0, cap - 1)], -1)
        return K.gather_column(val, src_idx, cap)

    def eval_cpu_groups(self, inputs, gid, n_groups):
        from spark_rapids_tpu.exec.cpu_backend import _norm_key_np
        val, ordc = inputs
        okey, onull = _norm_key_np(ordc)
        if not self.is_min:
            okey = ~okey
        best = {}
        for i, g in enumerate(gid):
            if onull[i]:
                continue
            if g not in best or okey[i] < okey[best[g]]:
                best[g] = i
        rt = self.result_type()
        is_obj = isinstance(rt, (T.StringType, T.ArrayType, T.StructType,
                                 T.MapType))
        vals = np.empty(n_groups, object) if is_obj \
            else np.zeros(n_groups, rt.np_dtype)
        ok = np.zeros(n_groups, np.bool_)
        for g, i in best.items():
            if val.valid[i]:
                vals[g] = val.values[i]
                ok[g] = True
        return CpuCol(rt, vals, ok)


class MinBy(_MinMaxBy):
    is_min = True


class MaxBy(_MinMaxBy):
    is_min = False


class Percentile(SegmentedAgg):
    """percentile(col, p): exact percentile with linear interpolation
    (reference GpuPercentile.scala). approx_percentile shares this path —
    the exact answer satisfies any accuracy parameter, so on TPU the
    approximate form is simply... exact (reference uses t-digest because
    cuDF has one; a sorted segmented batch gives exactness for free)."""

    def __init__(self, child, percentage: float):
        super().__init__(child)
        self.percentage = float(percentage)
        if not (0.0 <= self.percentage <= 1.0):
            from spark_rapids_tpu.expr.core import SparkException
            raise SparkException(
                f"percentage must be in [0, 1], got {percentage}")

    def fingerprint(self):
        return f"{type(self).__name__}({self.percentage};" + \
            ",".join(c.fingerprint() for c in self.children) + ")"

    def transform(self, fn):
        return type(self)(self.children[0].transform(fn), self.percentage)

    def result_type(self):
        return T.FLOAT64

    def segmented_eval_tpu(self, inputs, perm, seg_ids, seg_cap, live,
                           num_rows):
        import jax
        from jax import lax
        src = inputs[0]
        cap = perm.shape[0]
        keep = _valid_under(src, live)[perm]
        v = src.data.astype(jnp.float64)[perm]
        cdt = self.children[0].data_type()
        if isinstance(cdt, T.DecimalType):
            # unscaled int64 state -> real value (mirrors Average)
            v = v / (10.0 ** cdt.scale)
        iota = jnp.arange(cap, dtype=jnp.int32)
        # kept rows pack to the FRONT globally (invalid/dead rows would
        # otherwise sit inside their segment and shift every later
        # segment's offsets), segment-major, values ascending
        _, _, _, idx2 = lax.sort(
            ((~keep).astype(jnp.uint8), seg_ids, v, iota),
            num_keys=3, is_stable=True)
        v2 = v[idx2]
        m = jax.ops.segment_sum(keep.astype(jnp.int32), seg_ids,
                                num_segments=seg_cap)
        starts = jnp.cumsum(m) - m
        rank = self.percentage * jnp.maximum(m - 1, 0).astype(jnp.float64)
        lo = jnp.floor(rank).astype(jnp.int32)
        hi = jnp.ceil(rank).astype(jnp.int32)
        frac = rank - lo.astype(jnp.float64)
        vlo = v2[jnp.clip(starts + lo, 0, cap - 1)]
        vhi = v2[jnp.clip(starts + hi, 0, cap - 1)]
        res = vlo + (vhi - vlo) * frac
        return ColumnVector(T.FLOAT64, res, m > 0)

    def eval_cpu_groups(self, inputs, gid, n_groups):
        src = inputs[0]
        cdt = self.children[0].data_type()
        descale = (10.0 ** cdt.scale) if isinstance(cdt, T.DecimalType) else 1.0
        buckets = [[] for _ in range(n_groups)]
        for g, v, ok in zip(gid, src.values, src.valid):
            if ok:
                buckets[g].append(float(v) / descale)
        vals = np.zeros(n_groups, np.float64)
        okm = np.zeros(n_groups, np.bool_)
        for g, b in enumerate(buckets):
            if not b:
                continue
            b.sort()
            rank = self.percentage * (len(b) - 1)
            lo, hi = int(np.floor(rank)), int(np.ceil(rank))
            vals[g] = b[lo] + (b[hi] - b[lo]) * (rank - lo)
            okm[g] = True
        return CpuCol(T.FLOAT64, vals, okm)


class ApproxPercentile(Percentile):
    """approx_percentile(col, p[, accuracy]): exact on this engine (see
    Percentile) — any accuracy parameter is trivially satisfied."""

    def __init__(self, child, percentage: float, accuracy: int = 10000):
        super().__init__(child, percentage)
        self.accuracy = accuracy


class GroupingMarker(AggFunction):
    """grouping(col) / grouping_id(): pseudo-aggregates valid only under
    ROLLUP/CUBE/GROUPING SETS. GroupedData.agg resolves them to bit
    reads of the Expand-introduced __grouping_id key (the same rewrite
    Catalyst applies before the reference sees the plan; reference
    GpuExpandExec consumes the already-lowered form). They never reach
    the aggregation kernels."""

    def __init__(self, *children: Expression):
        super().__init__(*children)

    def state_schema(self):
        raise SparkException(
            "grouping()/grouping_id() is only valid with "
            "ROLLUP/CUBE/GROUPING SETS")


class Grouping(GroupingMarker):
    """grouping(col): 1 when the key is aggregated away in this output
    row, else 0 (Spark ByteType)."""

    def result_type(self):
        return T.INT8


class GroupingID(GroupingMarker):
    """grouping_id(): the full bitmask over the group-by keys
    (Spark LongType)."""

    def result_type(self):
        return T.INT64
