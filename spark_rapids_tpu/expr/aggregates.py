"""Aggregate function descriptors.

Reference parity: aggregate/aggregateFunctions.scala (GpuSum, GpuCount,
GpuMin, GpuMax, GpuAverage, GpuFirst/Last, M2/stddev/variance) and the
update/merge/evaluate phase structure of GpuAggregateExec.

An AggFunction declares, like the reference's CudfAggregate pairs:
- state_schema: the partial-aggregation buffer columns
- update ops: segmented reductions applied to input rows per group
- merge ops: segmented reductions combining partial states per group
- evaluate: final projection from state columns to the result column

The exec layer (exec/aggregate.py) drives these through the sort-based
segmented kernels in ops/groupby.py. The CPU differential path uses pandas
groupby -- an independent implementation.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector
from spark_rapids_tpu.expr.core import Expression


class AggFunction:
    """Base; children are input expressions evaluated before aggregation."""

    def __init__(self, *children: Expression):
        self.children = list(children)

    def result_type(self) -> T.DataType:
        raise NotImplementedError

    def state_schema(self) -> List[Tuple[str, T.DataType]]:
        raise NotImplementedError

    def update_ops(self) -> List[Tuple[str, int]]:
        """[(segmented_op, input_index)] producing each state column."""
        raise NotImplementedError

    def merge_ops(self) -> List[str]:
        """Segmented op per state column for combining partials."""
        raise NotImplementedError

    def evaluate_tpu(self, state_cols: List[ColumnVector], n_groups: int) -> ColumnVector:
        raise NotImplementedError

    def pandas_spec(self):
        """(colname_fn, agg) description for the CPU pandas path; see
        exec/cpu_exec.py."""
        raise NotImplementedError

    def fingerprint(self) -> str:
        kids = ",".join(c.fingerprint() for c in self.children)
        return f"{type(self).__name__}({kids})"

    def over(self, spec):
        """agg OVER window-spec -> WindowExpr (pyspark F.sum(c).over(w))."""
        from spark_rapids_tpu.expr.window import over as _over
        return _over(self, spec)

    def transform(self, fn):
        clone = type(self)(*[c.transform(fn) for c in self.children])
        return clone

    def alias(self, name):
        return NamedAgg(self, name)

    def __repr__(self):
        return self.fingerprint()


class NamedAgg:
    def __init__(self, fn: AggFunction, name: str):
        self.fn = fn
        self.name = name

    def transform(self, f):
        return NamedAgg(self.fn.transform(f), self.name)


class Sum(AggFunction):
    """Spark sum: int inputs -> long; float -> double; null if all null."""

    def result_type(self):
        dt = self.children[0].data_type()
        if dt.is_integral:
            return T.INT64
        if isinstance(dt, T.DecimalType):
            return T.DecimalType(min(dt.precision + 10, 18), dt.scale)
        return T.FLOAT64

    def state_schema(self):
        return [("sum", self.result_type())]

    def update_ops(self):
        return [("sum", 0)]

    def merge_ops(self):
        return ["sum"]

    def evaluate_tpu(self, state_cols, n_groups):
        return state_cols[0]

    def pandas_spec(self):
        return "sum"


class Count(AggFunction):
    def result_type(self):
        return T.INT64

    def state_schema(self):
        return [("count", T.INT64)]

    def update_ops(self):
        return [("count", 0)]

    def merge_ops(self):
        return ["sum"]

    def evaluate_tpu(self, state_cols, n_groups):
        c = state_cols[0]
        return ColumnVector(T.INT64, c.data, None)

    def pandas_spec(self):
        return "count"


class CountAll(AggFunction):
    """count(*) / count(1)."""

    def __init__(self):
        super().__init__()

    def result_type(self):
        return T.INT64

    def state_schema(self):
        return [("count", T.INT64)]

    def update_ops(self):
        return [("count_all", -1)]  # -1: no input column needed

    def merge_ops(self):
        return ["sum"]

    def evaluate_tpu(self, state_cols, n_groups):
        return ColumnVector(T.INT64, state_cols[0].data, None)

    def pandas_spec(self):
        return "size"

    def transform(self, fn):
        return self


class Min(AggFunction):
    def result_type(self):
        return self.children[0].data_type()

    def state_schema(self):
        return [("min", self.result_type())]

    def update_ops(self):
        return [("min", 0)]

    def merge_ops(self):
        return ["min"]

    def evaluate_tpu(self, state_cols, n_groups):
        return state_cols[0]

    def pandas_spec(self):
        return "min"


class Max(AggFunction):
    def result_type(self):
        return self.children[0].data_type()

    def state_schema(self):
        return [("max", self.result_type())]

    def update_ops(self):
        return [("max", 0)]

    def merge_ops(self):
        return ["max"]

    def evaluate_tpu(self, state_cols, n_groups):
        return state_cols[0]

    def pandas_spec(self):
        return "max"


class Average(AggFunction):
    """avg: state (sum: double, count: long); result double.
    (Decimal avg via double in round 1, documented incompat.)"""

    def result_type(self):
        return T.FLOAT64

    def state_schema(self):
        return [("sum", T.FLOAT64), ("count", T.INT64)]

    def update_ops(self):
        return [("sum", 0), ("count", 0)]

    def merge_ops(self):
        return ["sum", "sum"]

    def evaluate_tpu(self, state_cols, n_groups):
        s, c = state_cols
        cnt = c.data.astype(jnp.float64)
        val = s.data.astype(jnp.float64) / jnp.where(cnt == 0, 1.0, cnt)
        return ColumnVector(T.FLOAT64, val, (c.data > 0))

    def pandas_spec(self):
        return "mean"


class First(AggFunction):
    """first(expr, ignoreNulls=True) -- our batch-sorted implementation picks
    the first non-null in group-sorted order; with ignore_nulls=False Spark's
    result is non-deterministic anyway."""

    op = "first"

    def result_type(self):
        return self.children[0].data_type()

    def state_schema(self):
        return [("val", self.result_type())]

    def update_ops(self):
        return [(self.op, 0)]

    def merge_ops(self):
        return [self.op]

    def evaluate_tpu(self, state_cols, n_groups):
        return state_cols[0]

    def pandas_spec(self):
        return "first"


class Last(First):
    op = "last"

    def pandas_spec(self):
        return "last"


class _MomentAgg(AggFunction):
    """Shared machinery for variance/stddev via (n, sum, sum_sq) states with
    the final moment computed as m2 = sumsq - sum^2/n. The reference uses
    cudf M2 merging; sum-of-squares is algebraically identical with double
    precision and our deterministic sorted-order summation keeps it stable
    enough for SQL parity tests."""

    ddof = 1  # 1 = sample, 0 = population

    def result_type(self):
        return T.FLOAT64

    def state_schema(self):
        return [("n", T.INT64), ("sum", T.FLOAT64), ("sumsq", T.FLOAT64)]

    def update_ops(self):
        return [("count", 0), ("sum", 0), ("sumsq", 0)]

    def merge_ops(self):
        return ["sum", "sum", "sum"]

    def _moments(self, state_cols):
        n = state_cols[0].data.astype(jnp.float64)
        s = state_cols[1].data.astype(jnp.float64)
        ss = state_cols[2].data.astype(jnp.float64)
        denom = n - self.ddof
        m2 = ss - (s * s) / jnp.where(n == 0, 1.0, n)
        m2 = jnp.maximum(m2, 0.0)
        var = m2 / jnp.where(denom <= 0, 1.0, denom)
        return n, denom, var


class VarianceSamp(_MomentAgg):
    ddof = 1

    def evaluate_tpu(self, state_cols, n_groups):
        n, denom, var = self._moments(state_cols)
        return ColumnVector(T.FLOAT64, jnp.where(denom <= 0, jnp.nan, var),
                            (n > 0))

    def pandas_spec(self):
        return "var"


class VariancePop(_MomentAgg):
    ddof = 0

    def evaluate_tpu(self, state_cols, n_groups):
        n, denom, var = self._moments(state_cols)
        return ColumnVector(T.FLOAT64, var, (n > 0))

    def pandas_spec(self):
        return ("var", 0)


class StddevSamp(_MomentAgg):
    ddof = 1

    def evaluate_tpu(self, state_cols, n_groups):
        n, denom, var = self._moments(state_cols)
        return ColumnVector(T.FLOAT64,
                            jnp.where(denom <= 0, jnp.nan, jnp.sqrt(var)), (n > 0))

    def pandas_spec(self):
        return "std"


class StddevPop(_MomentAgg):
    ddof = 0

    def evaluate_tpu(self, state_cols, n_groups):
        n, denom, var = self._moments(state_cols)
        return ColumnVector(T.FLOAT64, jnp.sqrt(var), (n > 0))

    def pandas_spec(self):
        return ("std", 0)
