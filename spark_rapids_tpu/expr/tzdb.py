"""Timezone transition database for device-side timestamp localization.

Reference parity: sql-plugin TimeZoneDB.scala + the JNI GpuTimeZoneDB,
which load IANA rules into a device table so non-UTC sessions keep
datetime expressions on the GPU. The TPU-first shape of the same idea:

- HOST, once per zone: parse the binary TZif file (RFC 8536) straight
  from the system zoneinfo directory into (transition instants, UTC
  offsets) arrays. Zones have a few hundred transitions; the table is
  bytes, not megabytes.
- DEVICE, per batch: ``searchsorted`` of the timestamp plane against the
  transition instants (a log2(~300)-step branchless binary search over a
  VMEM-resident table) + one gather for the offset. Future transitions
  beyond the TZif data use the POSIX footer rule approximated by the
  last recorded offset pair — correct for all zones whose current DST
  rules match their final recorded year (the reference's table has the
  same horizon discipline).

Local->UTC (``to_utc_timestamp``) resolves through a LOCAL-wall-time
boundary table (local_boundaries): DST gaps take the pre-gap offset and
overlaps the earlier offset — java.time's fold=0 resolution, matching
this module's zoneinfo-based CPU twin exactly.
"""
from __future__ import annotations

import os
import struct
from functools import lru_cache
from typing import Tuple

import numpy as np

#: microseconds per second (Spark timestamps are int64 micros)
_US = 1_000_000

_TZPATHS = ("/usr/share/zoneinfo", "/usr/lib/zoneinfo",
            "/usr/share/lib/zoneinfo", "/etc/zoneinfo")


class UnknownTimeZone(ValueError):
    pass


def _read_tzif(zone: str) -> bytes:
    if not zone or zone in (".", "..") or "//" in zone or "\0" in zone:
        raise UnknownTimeZone(zone)
    for base in _TZPATHS:
        p = os.path.join(base, *zone.split("/"))
        if os.path.isfile(p) and os.path.realpath(p).startswith(
                os.path.realpath(base)):
            with open(p, "rb") as f:
                return f.read()
    raise UnknownTimeZone(zone)


def _parse_block(data: bytes, pos: int, time_size: int):
    """One TZif data block; returns (transitions, offsets_sec, next_pos)."""
    hdr = struct.unpack(">4s c 15x 6I", data[pos: pos + 44])
    magic, _ver, isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = hdr
    if magic != b"TZif":
        raise ValueError("not a TZif file")
    pos += 44
    tfmt = ">%d%s" % (timecnt, "q" if time_size == 8 else "l")
    trans = struct.unpack_from(tfmt, data, pos)
    pos += timecnt * time_size
    idx = struct.unpack_from(">%dB" % timecnt, data, pos)
    pos += timecnt
    types = []
    for _ in range(typecnt):
        utoff, isdst, abbrind = struct.unpack_from(">lBB", data, pos)
        types.append(utoff)
        pos += 6
    pos += charcnt
    pos += leapcnt * (time_size + 4)
    pos += isstdcnt + isutcnt
    offsets = [types[i] for i in idx]
    #: offset BEFORE the first transition: the first non-dst type, else
    #: type 0 (RFC 8536 §3.2 guidance)
    base = types[0] if types else 0
    return np.asarray(trans, np.int64), np.asarray(offsets, np.int64), \
        np.int64(base), pos


@lru_cache(maxsize=256)
def zone_table(zone: str) -> Tuple[np.ndarray, np.ndarray]:
    """(transitions_us int64[n], offsets_us int64[n+1]) for a zone.
    offsets_us[i] applies to instants < transitions_us[i] (offsets_us[0]
    before all transitions); offsets_us[n] after the last."""
    data = _read_tzif(zone)
    trans, offs, base, pos = _parse_block(data, 0, 4)
    if data[4:5] in (b"2", b"3"):
        # v2+: a second block with 64-bit times supersedes the v1 data
        trans, offs, base, _ = _parse_block(data, pos, 8)
    if len(trans) == 0:
        fixed = np.asarray([base * _US], np.int64)
        return np.zeros(0, np.int64), fixed
    offsets = np.concatenate([[base], offs]) * _US
    return trans * _US, offsets


def utc_offset_us(zone: str, ts_us: np.ndarray) -> np.ndarray:
    """Host-side: UTC offset (us) in effect at each UTC instant."""
    trans, offsets = zone_table(zone)
    if len(trans) == 0:
        return np.full(ts_us.shape, offsets[0], np.int64)
    idx = np.searchsorted(trans, ts_us, side="right")
    return offsets[idx]


def from_utc_us(zone: str, ts_us: np.ndarray) -> np.ndarray:
    return ts_us + utc_offset_us(zone, ts_us)


@lru_cache(maxsize=256)
def local_boundaries(zone: str) -> Tuple[np.ndarray, np.ndarray]:
    """(boundaries_us int64[n], offsets_us int64[n+1]) in LOCAL wall time
    with java.time fold=0 resolution: the pre-transition offset applies
    to every local instant below boundary[i] = trans[i] +
    max(offset_before, offset_after) — which resolves DST gaps to the
    pre-gap offset and overlaps to the earlier offset, both matching
    ZonedDateTime.ofLocal/zoneinfo fold=0."""
    trans, offsets = zone_table(zone)
    if len(trans) == 0:
        return trans, offsets
    b = trans + np.maximum(offsets[:-1], offsets[1:])
    # pathological zones (day-skip offset jumps) could locally unsort the
    # boundaries; enforce monotonicity so searchsorted stays valid
    b = np.maximum.accumulate(b)
    return b, offsets


def local_offset_us(zone: str, local_us: np.ndarray) -> np.ndarray:
    """Host-side: UTC offset for LOCAL wall-clock instants (fold=0)."""
    b, offsets = local_boundaries(zone)
    if len(b) == 0:
        return np.full(local_us.shape, offsets[0], np.int64)
    idx = np.searchsorted(b, local_us, side="right")
    return offsets[idx]


def to_utc_us(zone: str, local_us: np.ndarray) -> np.ndarray:
    """local->UTC with fold=0 (earlier-offset) resolution."""
    return local_us - local_offset_us(zone, local_us)


def is_valid_zone(zone: str) -> bool:
    try:
        zone_table(zone)
        return True
    except (UnknownTimeZone, ValueError, OSError):
        return False
