"""Misc expressions: rand, sequence, parse_url, raise_error, hive hash.

Reference parity: GpuRandomExpressions.scala, GpuSequenceUtil,
GpuParseUrl.scala (JNI ParseURI), RaiseError, HashFunctions.scala hive
hash (jni.Hash.hiveHash).
"""
from __future__ import annotations

from typing import List, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector
from spark_rapids_tpu.expr.core import (
    CPU_EVAL_CTX, CpuCol, EvalCtx, Expression, SparkException, _valid_of,
)
from spark_rapids_tpu.expr.cpu_functions import CpuRowFunction


class Rand(Expression):
    """rand([seed]): uniform [0,1) doubles, deterministic per
    (seed, partition, row index) via splitmix64. NOTE: the value STREAM
    differs from Spark's XORShiftRandom (documented divergence — Spark
    itself calls the function non-deterministic); the distribution and
    determinism contract match, and both backends here agree exactly."""

    def __init__(self, seed: int = 0):
        self.children = []
        self.seed = int(seed)

    def data_type(self):
        return T.FLOAT64

    def _params(self):
        return str(self.seed)

    def with_children(self, children):
        return self

    @staticmethod
    def _mix64_np(x):
        M = np.uint64
        x = (x + M(0x9E3779B97F4A7C15)) & M(0xFFFFFFFFFFFFFFFF)
        x = ((x ^ (x >> M(30))) * M(0xBF58476D1CE4E5B9)) & M(0xFFFFFFFFFFFFFFFF)
        x = ((x ^ (x >> M(27))) * M(0x94D049BB133111EB)) & M(0xFFFFFFFFFFFFFFFF)
        return x ^ (x >> M(31))

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        idx = jnp.cumsum(ctx.row_mask.astype(jnp.int64)) - 1
        pos = (jnp.asarray(ctx.row_base, jnp.int64) + idx).astype(jnp.uint64)
        pid = jnp.asarray(ctx.partition_id, jnp.int64).astype(jnp.uint64)
        x = pos + (pid << jnp.uint64(40)) + jnp.uint64(self.seed & (2**64 - 1))
        x = x + jnp.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
        x = x ^ (x >> jnp.uint64(31))
        v = (x >> jnp.uint64(11)).astype(jnp.float64) / np.float64(1 << 53)
        return ColumnVector(T.FLOAT64, v, None)

    def eval_cpu(self, cols, ansi=False):
        n = len(cols[0].values) if cols else 0
        M = np.uint64
        pos = (np.uint64(CPU_EVAL_CTX.row_base) + np.arange(n, dtype=np.uint64))
        x = pos + (M(CPU_EVAL_CTX.partition_id) << M(40)) \
            + M(self.seed & (2**64 - 1))
        x = self._mix64_np(x)
        v = (x >> M(11)).astype(np.float64) / np.float64(1 << 53)
        return CpuCol(T.FLOAT64, v, np.ones(n, np.bool_))


class Sequence(CpuRowFunction):
    """sequence(start, stop[, step]) -> array<long> (host tier; the
    variable-length output needs a count-then-build device pass that lands
    with device sequence support)."""

    name = "sequence"

    def __init__(self, *children, params=()):
        super().__init__(*children, params=params)
        self.result = T.ArrayType(T.INT64, contains_null=False)

    def row_fn(self, *vals):
        if len(vals) == 3:
            start, stop, step = int(vals[0]), int(vals[1]), int(vals[2])
        else:
            start, stop = int(vals[0]), int(vals[1])
            step = 1 if stop >= start else -1
        if step == 0:
            raise SparkException("sequence step must not be zero")
        if (stop - start) * step < 0:
            return []
        n = (stop - start) // step + 1
        if n > 10_000_000:
            raise SparkException("sequence too long")
        return list(range(start, start + n * step, step))

    def eval_cpu(self, cols, ansi=False):
        ins = [c.eval_cpu(cols, ansi) for c in self.children]
        n = len(ins[0].values)
        out, ok = [], []
        for i in range(n):
            if all(c.valid[i] for c in ins):
                out.append(self.row_fn(*(c.values[i] for c in ins)))
                ok.append(True)
            else:
                out.append(None)
                ok.append(False)
        vals = np.empty(n, object)
        vals[:] = out
        return CpuCol(self.result, vals, np.asarray(ok, np.bool_))


class ParseUrl(CpuRowFunction):
    """parse_url(url, part[, key]) (host tier; reference JNI ParseURI)."""

    name = "parse_url"
    result = T.STRING
    PARTS = ("HOST", "PATH", "QUERY", "REF", "PROTOCOL", "FILE",
             "AUTHORITY", "USERINFO")

    def __init__(self, *children, params=()):
        super().__init__(*children, params=params)
        part = (params[0] or "").upper()
        if part not in self.PARTS:
            raise SparkException(f"parse_url: unknown part {params[0]!r}")
        self.part = part
        self.key = params[1] if len(params) > 1 else None

    def row_fn(self, url):
        try:
            u = urlparse(url)
        except ValueError:
            return None
        if self.part == "HOST":
            return u.hostname
        if self.part == "PATH":
            return u.path or None if u.scheme else None
        if self.part == "QUERY":
            if self.key is not None:
                q = parse_qs(u.query)
                v = q.get(self.key)
                return v[0] if v else None
            return u.query or None
        if self.part == "REF":
            return u.fragment or None
        if self.part == "PROTOCOL":
            return u.scheme or None
        if self.part == "FILE":
            return (u.path + ("?" + u.query if u.query else "")) or None
        if self.part == "AUTHORITY":
            return u.netloc or None
        if self.part == "USERINFO":
            if u.username is None:
                return None
            return u.username + (":" + u.password if u.password else "")
        return None


class RaiseError(CpuRowFunction):
    """raise_error(msg): fails the query when evaluated on any live row."""

    name = "raise_error"
    result = T.NULL

    def row_fn(self, msg):
        raise SparkException(str(msg))


class HiveHash(Expression):
    """hive hash over columns (reference jni.Hash hiveHash): per-column
    hive hashCode chained as h = h*31 + colHash; nulls hash to 0. Device
    kernel for fixed-width + string columns."""

    def __init__(self, children: List[Expression]):
        self.children = list(children)

    def data_type(self):
        return T.INT32

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        from spark_rapids_tpu.ops import kernels as K
        h = jnp.zeros(ctx.capacity, jnp.int32)
        for c in self.children:
            col = c.eval_tpu(ctx)
            ch = _hive_hash_col_tpu(col, ctx)
            valid = _valid_of(col, ctx)
            ch = jnp.where(valid, ch, 0)
            h = h * np.int32(31) + ch
        return ColumnVector(T.INT32, h, None)

    def eval_cpu(self, cols, ansi=False):
        n = len(cols[0].values) if cols else 0
        h = np.zeros(n, np.int32)
        for c in self.children:
            cc = c.eval_cpu(cols, ansi)
            ch = _hive_hash_col_np(cc)
            ch = np.where(cc.valid, ch, 0).astype(np.int32)
            with np.errstate(over="ignore"):
                h = (h.astype(np.int64) * 31 + ch).astype(np.int32)
        return CpuCol(T.INT32, h, np.ones(n, np.bool_))


def _hive_hash_col_tpu(col: ColumnVector, ctx) -> jax.Array:
    from jax import lax
    from spark_rapids_tpu.ops.kernels import _bitcast_f64_u64
    d = col.dtype
    if isinstance(d, T.StringType):
        if col.is_dict:
            voc_h = _hive_string_hash(col.data["dict_offsets"],
                                      col.data["dict_bytes"])
            return voc_h[col.data["codes"]]
        return _hive_string_hash(col.data["offsets"], col.data["bytes"])
    if isinstance(d, T.BooleanType):
        return jnp.where(col.data, jnp.int32(1), jnp.int32(0))
    if isinstance(d, (T.Int8Type, T.Int16Type, T.Int32Type, T.DateType)):
        return col.data.astype(jnp.int32)
    if isinstance(d, T.Float32Type):
        v = jnp.where(col.data == 0.0, jnp.zeros_like(col.data), col.data)
        return lax.bitcast_convert_type(v, jnp.int32)
    if isinstance(d, T.Float64Type):
        v = jnp.where(col.data == 0.0, jnp.zeros_like(col.data), col.data)
        bits = _bitcast_f64_u64(v)
        return ((bits ^ (bits >> jnp.uint64(32))) & jnp.uint64(0xFFFFFFFF)) \
            .astype(jnp.int32)
    # int64 / timestamp
    v = col.data.astype(jnp.int64).astype(jnp.uint64)
    return ((v ^ (v >> jnp.uint64(32))) & jnp.uint64(0xFFFFFFFF)) \
        .astype(jnp.int32)


def _hive_string_hash(offsets, raw) -> jax.Array:
    """Java String.hashCode over byte slices: h = 31*h + b (signed)."""
    from jax import lax
    starts = offsets[:-1].astype(jnp.int32)
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.int32)
    nbytes = raw.shape[0]

    def body(state):
        i, h = state
        pos = jnp.clip(starts + i, 0, nbytes - 1)
        b = raw[pos].astype(jnp.int8).astype(jnp.int32)
        nh = h * np.int32(31) + b
        return i + 1, jnp.where(i < lens, nh, h)

    def cond(state):
        return state[0] < jnp.max(lens)

    _, h = lax.while_loop(cond, body,
                          (jnp.int32(0),
                           jnp.zeros(starts.shape[0], jnp.int32)))
    return h


def _hive_hash_col_np(c: CpuCol) -> np.ndarray:
    d = c.dtype
    with np.errstate(over="ignore"):
        if isinstance(d, T.StringType):
            out = np.zeros(len(c.values), np.int32)
            for i, v in enumerate(c.values):
                if isinstance(v, str):
                    h = 0
                    for b in v.encode("utf-8"):
                        h = (h * 31 + (b if b < 128 else b - 256)) & 0xFFFFFFFF
                    out[i] = np.uint32(h).astype(np.int32)
            return out
        if isinstance(d, T.BooleanType):
            return c.values.astype(np.int32)
        if isinstance(d, (T.Int8Type, T.Int16Type, T.Int32Type, T.DateType)):
            return c.values.astype(np.int32)
        if isinstance(d, T.Float32Type):
            v = np.where(c.values == 0.0, 0.0, c.values).astype(np.float32)
            return v.view(np.int32)
        if isinstance(d, T.Float64Type):
            v = np.where(c.values == 0.0, 0.0, c.values).astype(np.float64)
            bits = v.view(np.uint64)
            return ((bits ^ (bits >> np.uint64(32)))
                    & np.uint64(0xFFFFFFFF)).astype(np.uint32).astype(np.int32)
        v = c.values.astype(np.int64).view(np.uint64)
        return ((v ^ (v >> np.uint64(32)))
                & np.uint64(0xFFFFFFFF)).astype(np.uint32).astype(np.int32)


MISC_CPU_FUNCTIONS = [Sequence, ParseUrl, RaiseError]


# ---------------------------------------------------------------------------
# Hash breadth: crc32 + xxhash64 expressions (reference jni.Hash)
# ---------------------------------------------------------------------------

def _crc32_table():
    t = np.zeros(256, np.uint32)
    for i in range(256):
        c = np.uint32(i)
        for _ in range(8):
            c = np.uint32(0xEDB88320) ^ (c >> np.uint32(1)) \
                if c & np.uint32(1) else c >> np.uint32(1)
        t[i] = c
    return t


_CRC32_TABLE = _crc32_table()


class Crc32(Expression):
    """crc32(str|binary) -> bigint. Device: table-gather per byte inside
    the usual max-length lockstep loop (same shape as murmur3_bytes)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT64

    def with_children(self, children):
        return Crc32(children[0])

    def eval_tpu(self, ctx):
        import jax
        from jax import lax
        from spark_rapids_tpu.expr.strings import _lift_unary
        c = self.children[0].eval_tpu(ctx)
        table = jnp.asarray(_CRC32_TABLE)

        def compute(flat, cap):
            off = flat.data["offsets"][: cap + 1].astype(jnp.int32)
            raw = flat.data["bytes"]
            starts = off[:-1]
            lens = off[1:] - off[:-1]
            nbytes = int(raw.shape[0])

            def body(i, crc):
                idx = jnp.clip(starts + i, 0, nbytes - 1)
                byte = raw[idx].astype(jnp.uint32)
                nxt = table[((crc ^ byte) & jnp.uint32(0xFF)).astype(jnp.int32)] \
                    ^ (crc >> jnp.uint32(8))
                return jnp.where(i < lens, nxt, crc)

            crc0 = jnp.full(cap, 0xFFFFFFFF, jnp.uint32)
            crc = lax.fori_loop(0, jnp.max(lens), body, crc0)
            out = (~crc).astype(jnp.uint32).astype(jnp.int64)
            return ColumnVector(T.INT64, out, None)

        out = _lift_unary(ctx, c, compute)
        return ColumnVector(T.INT64, out.data, _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        import zlib
        c = self.children[0].eval_cpu(cols, ansi)
        vals = np.array([zlib.crc32(s.encode() if isinstance(s, str) else
                                    (s or b"")) for s in c.values], np.int64)
        return CpuCol(T.INT64, vals, c.valid)


class XxHash64(Expression):
    """xxhash64(cols..., seed 42): Spark-compatible chained xxhash64 over
    fixed-width columns — <=4-byte types go through XXH64.hashInt, 8-byte
    through hashLong, exactly as Spark's XxHash64Function dispatches; each
    row's hash seeds the next column's. String columns fall back to CPU
    (no bytes kernel yet); null fields pass the running seed through."""

    def __init__(self, children):
        self.children = list(children)

    def data_type(self):
        return T.INT64

    def with_children(self, children):
        return XxHash64(children)

    def supported_on_tpu(self):
        for c in self.children:
            dt = c.data_type()
            if isinstance(dt, (T.StringType, T.ArrayType, T.MapType,
                               T.StructType)):
                return False
        return True

    @staticmethod
    def _norm(col):
        """(plane, is_int32) per Spark's per-type hash dispatch."""
        import jax.lax as lax
        d = col.dtype
        if isinstance(d, T.Float32Type):
            v = jnp.where(col.data == 0.0, jnp.zeros_like(col.data),
                          col.data)
            v = jnp.where(jnp.isnan(v), jnp.float32(np.nan), v)
            return lax.bitcast_convert_type(v, jnp.int32), True
        if isinstance(d, T.Float64Type):
            from spark_rapids_tpu.ops.kernels import _bitcast_f64_u64
            v = jnp.where(col.data == 0.0, jnp.zeros_like(col.data),
                          col.data)
            v = jnp.where(jnp.isnan(v), jnp.float64(np.nan), v)
            return _bitcast_f64_u64(v).astype(jnp.int64), False
        if isinstance(d, (T.BooleanType, T.Int8Type, T.Int16Type,
                          T.Int32Type, T.DateType)):
            return col.data.astype(jnp.int32), True
        return col.data.astype(jnp.int64), False

    def eval_tpu(self, ctx):
        from spark_rapids_tpu.ops import kernels as K
        cols = [c.eval_tpu(ctx) for c in self.children]
        cap = ctx.capacity
        h = jnp.full(cap, np.uint64(42), jnp.uint64)
        for c in cols:
            v, is32 = self._norm(c)
            valid = c.validity_or_default(ctx.num_rows) & ctx.row_mask
            h2 = (K.xxhash64_int32(v, h) if is32
                  else K.xxhash64_int64(v, h)).astype(jnp.uint64)
            h = jnp.where(valid, h2, h)
        return ColumnVector(T.INT64, h.astype(jnp.int64), None)

    def eval_cpu(self, cols, ansi=False):
        M = (1 << 64) - 1
        P1, P2, P3 = 0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F, \
            0x165667B19E3779F9
        P4, P5 = 0x85EBCA77C2B2AE63, 0x27D4EB2F165667C5

        def rotl(x, r):
            return ((x << r) | (x >> (64 - r))) & M

        def avalanche(h):
            h = ((h ^ (h >> 33)) * P2) & M
            h = ((h ^ (h >> 29)) * P3) & M
            return h ^ (h >> 32)

        def hash_long(v, seed):
            h = (seed + P5 + 8) & M
            k1 = (rotl((v * P2) & M, 31) * P1) & M
            h = h ^ k1
            h = (rotl(h, 27) * P1 + P4) & M
            return avalanche(h)

        def hash_int(v, seed):
            h = (seed + P5 + 4) & M
            h = h ^ ((v & 0xFFFFFFFF) * P1) & M
            h = (rotl(h & M, 23) * P2 + P3) & M
            return avalanche(h)

        ins = [c.eval_cpu(cols, ansi) for c in self.children]
        n = len(ins[0].values) if ins else 0
        out = np.zeros(n, np.int64)
        for i in range(n):
            h = 42
            for c in ins:
                if not c.valid[i]:
                    continue
                d = c.dtype
                v = c.values[i]
                if isinstance(d, T.Float32Type):
                    f = np.float32(0.0 if v == 0 else v)
                    h = hash_int(int(f.view(np.int32)) & 0xFFFFFFFF, h)
                elif isinstance(d, T.Float64Type):
                    f = np.float64(0.0 if v == 0 else v)
                    h = hash_long(int(f.view(np.uint64)), h)
                elif isinstance(d, (T.BooleanType, T.Int8Type, T.Int16Type,
                                    T.Int32Type, T.DateType)):
                    h = hash_int(int(np.int32(v)) & 0xFFFFFFFF, h)
                else:
                    h = hash_long(int(np.int64(v).view(np.uint64)), h)
            out[i] = np.uint64(h).astype(np.int64)
        return CpuCol(T.INT64, out, np.ones(n, np.bool_))
