"""String expressions on device byte planes.

Reference parity: org/apache/spark/sql/rapids/stringFunctions.scala and the
string pieces of GpuCast.scala (CastStrings JNI).

Device representation is offsets(int32[cap+1]) + bytes(uint8). Kernels are
branch-free over byte planes; per-row variable length is handled with
searchsorted row mapping (same trick as kernels.gather) or bounded
while_loops over the batch max length. Ops we cannot (yet) express
efficiently on device report supported_on_tpu() = False and the planner
falls the enclosing exec back to CPU -- the reference's per-op fallback
discipline.
"""
from __future__ import annotations

from typing import List

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector, round_capacity
from spark_rapids_tpu.expr.core import (
    CpuCol, EvalCtx, Expression, SparkException, _valid_of,
)


def _lens(col: ColumnVector) -> jax.Array:
    if col.is_dict:
        o = col.data["dict_offsets"]
        return (o[1:] - o[:-1])[col.data["codes"]]
    o = col.data["offsets"]
    return o[1:] - o[:-1]


def _starts(col: ColumnVector) -> jax.Array:
    return col.data["offsets"][:-1]


def _flat_view(c: ColumnVector) -> ColumnVector:
    """The vocab of a dict column viewed as a small flat string column."""
    return ColumnVector(T.STRING, {"offsets": c.data["dict_offsets"],
                                   "bytes": c.data["dict_bytes"]}, None)


def _flatten(c: ColumnVector, ctx) -> ColumnVector:
    if not c.is_dict:
        return c
    from spark_rapids_tpu.ops.kernels import flatten_dict_column
    return flatten_dict_column(c, ctx.num_rows)


def _lift_unary(ctx, c: ColumnVector, compute) -> ColumnVector:
    """Evaluate a unary string op. compute(flat_col, row_cap) returns a
    ColumnVector over the flat row space (validity ignored). Dict-encoded
    children evaluate over the VOCAB — O(vocab) instead of O(rows) — and
    map back by code; string-valued results stay dict-encoded with a new
    vocab (zero per-row byte work)."""
    valid = _valid_of(c, ctx)
    if c.is_dict:
        flat = _flat_view(c)
        res = compute(flat, flat.capacity)
        codes = c.data["codes"]
        if res.is_string:
            # transformed vocab may contain duplicates (upper('a')==
            # upper('A')) — mark codes non-unique so bucket-by-code
            # grouping falls back to content-hash grouping
            return ColumnVector(T.STRING, {
                "codes": codes,
                "dict_offsets": res.data["offsets"],
                "dict_bytes": res.data["bytes"]}, c.validity,
                dict_unique=False)
        return ColumnVector(res.dtype, res.data[codes], valid)
    res = compute(c, c.capacity)
    return ColumnVector(res.dtype, res.data, valid)


class StringLength(Expression):
    """length(): number of UTF-8 characters (not bytes), like Spark."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return StringLength(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)

        def compute(flat, cap):
            raw = flat.data["bytes"]
            o = flat.data["offsets"]
            # count non-continuation bytes per row: prefix-sum over bytes
            is_start = (raw & 0xC0) != 0x80
            csum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                    jnp.cumsum(is_start.astype(jnp.int32))])
            nchars = csum[o[1:]] - csum[o[:-1]]
            return ColumnVector(T.INT32, nchars.astype(jnp.int32), None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        vals = np.array([len(s) if isinstance(s, str) else 0 for s in c.values], np.int32)
        return CpuCol(T.INT32, vals, c.valid)


class _CaseMap(Expression):
    """ASCII upper/lower; rows containing non-ASCII map byte-wise only for
    ASCII letters (Spark does full Unicode -- non-ASCII batches should be
    tagged off-device by the planner via contains_non_ascii stats; round 1
    applies ASCII mapping and documents the incompat)."""

    upper: bool = True

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.STRING

    def with_children(self, children):
        return type(self)(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)

        def compute(flat, cap):
            raw = flat.data["bytes"]
            from spark_rapids_tpu.ops import pallas_kernels as PK
            if PK.enabled() and raw.shape[0] % 4096 == 0:
                shifted = PK.ascii_case_map_pallas(raw, self.upper)
            elif self.upper:
                shifted = jnp.where((raw >= 97) & (raw <= 122), raw - 32, raw)
            else:
                shifted = jnp.where((raw >= 65) & (raw <= 90), raw + 32, raw)
            return ColumnVector(T.STRING, {"offsets": flat.data["offsets"],
                                           "bytes": shifted}, None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        f = str.upper if self.upper else str.lower
        vals = np.array([f(s) if isinstance(s, str) else s for s in c.values], object)
        return CpuCol(T.STRING, vals, c.valid)


class Upper(_CaseMap):
    upper = True


class Lower(_CaseMap):
    upper = False


class Substring(Expression):
    """substring(str, pos, len): 1-based pos, negative counts from end;
    character (not byte) positions, like Spark."""

    def __init__(self, child, pos: int, length: int = 1 << 30):
        self.children = [child]
        self.pos = pos
        self.length = length

    def data_type(self):
        return T.STRING

    def _params(self):
        return f"{self.pos},{self.length}"

    def with_children(self, children):
        return Substring(children[0], self.pos, self.length)

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        return _lift_unary(ctx, c, self._compute)

    def _compute(self, flat, cap):
        o = flat.data["offsets"]
        raw = flat.data["bytes"]
        is_start = ((raw & 0xC0) != 0x80).astype(jnp.int32)
        char_csum = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(is_start)])
        nchars = char_csum[o[1:]] - char_csum[o[:-1]]
        # resolve 1-based/negative start to 0-based char index
        if self.pos > 0:
            start_char = jnp.minimum(self.pos - 1, nchars)
        elif self.pos == 0:
            start_char = jnp.zeros_like(nchars)
        else:
            start_char = jnp.maximum(nchars + self.pos, 0)
        take = max(self.length, 0)
        end_char = jnp.minimum(start_char + take, nchars)
        # char index -> byte offset: byte b is the k-th char start where
        # k = char_csum[b] - char_csum[row_start]. Build per-row byte offsets
        # by searching the cumulative char counts.
        # byte position of char t in a row = last byte index whose prefix
        # char-count equals csum[row_start]+t (side='right'-1 lands past any
        # UTF-8 continuation bytes onto the next char-start byte).
        target_start = char_csum[o[:-1]] + start_char
        target_end = char_csum[o[:-1]] + end_char
        byte_start = jnp.searchsorted(char_csum, target_start, side="right").astype(jnp.int32) - 1
        byte_end = jnp.searchsorted(char_csum, target_end, side="right").astype(jnp.int32) - 1
        byte_start = jnp.clip(byte_start, o[:-1], o[1:])
        byte_end = jnp.clip(byte_end, byte_start, o[1:])
        out_lens = byte_end - byte_start
        new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(out_lens).astype(jnp.int32)])
        nb = raw.shape[0]
        b = jnp.arange(nb, dtype=jnp.int32)
        row = jnp.clip(jnp.searchsorted(new_off, b, side="right").astype(jnp.int32) - 1,
                       0, nchars.shape[0] - 1)
        src = jnp.clip(byte_start[row] + (b - new_off[row]), 0, nb - 1)
        out_bytes = jnp.where(b < new_off[-1], raw[src], 0).astype(jnp.uint8)
        return ColumnVector(T.STRING, {"offsets": new_off, "bytes": out_bytes}, None)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        out = []
        for s in c.values:
            if not isinstance(s, str):
                out.append(s)
                continue
            if self.pos > 0:
                start = self.pos - 1
            elif self.pos == 0:
                start = 0
            else:
                start = max(len(s) + self.pos, 0)
            out.append(s[start: start + max(self.length, 0)])
        return CpuCol(T.STRING, np.array(out, object), c.valid)


class ConcatStrings(Expression):
    """concat(s1, s2, ...): null if any input null (Spark concat)."""

    def __init__(self, *children):
        self.children = list(children)

    def data_type(self):
        return T.STRING

    def with_children(self, children):
        return ConcatStrings(*children)

    def eval_tpu(self, ctx):
        parts = [_flatten(c.eval_tpu(ctx), ctx) for c in self.children]
        valid = _valid_of(parts[0], ctx)
        for p in parts[1:]:
            valid = valid & _valid_of(p, ctx)
        lens = sum(_lens(p) for p in parts)
        lens = jnp.where(valid, lens, 0)
        new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(lens).astype(jnp.int32)])
        total_cap = round_capacity(int(sum(int(p.data["bytes"].shape[0]) for p in parts)))
        b = jnp.arange(total_cap, dtype=jnp.int32)
        row = jnp.clip(jnp.searchsorted(new_off, b, side="right").astype(jnp.int32) - 1,
                       0, ctx.capacity - 1)
        pos = b - new_off[row]  # position within the concatenated row
        out = jnp.zeros(total_cap, jnp.uint8)
        acc = jnp.zeros(ctx.capacity, jnp.int32)  # running char offset per row
        for p in parts:
            pl = _lens(p)
            in_part = (pos >= acc[row]) & (pos < acc[row] + pl[row])
            src = jnp.clip(_starts(p)[row] + (pos - acc[row]), 0,
                           p.data["bytes"].shape[0] - 1)
            out = jnp.where(in_part, p.data["bytes"][src], out)
            acc = acc + pl
        out = jnp.where(b < new_off[-1], out, 0).astype(jnp.uint8)
        return ColumnVector(T.STRING, {"offsets": new_off, "bytes": out}, valid)

    def eval_cpu(self, cols, ansi=False):
        parts = [c.eval_cpu(cols, ansi) for c in self.children]
        valid = parts[0].valid.copy()
        for p in parts[1:]:
            valid = valid & p.valid
        out = []
        for i in range(len(valid)):
            if valid[i]:
                out.append("".join(str(p.values[i]) for p in parts))
            else:
                out.append(None)
        return CpuCol(T.STRING, np.array(out, object), valid)


class _LiteralMatch(Expression):
    """startswith/endswith/contains with a literal pattern: sliding fixed
    window compare over the byte plane."""

    mode = "starts"  # starts | ends | contains

    def __init__(self, child, pattern: str):
        self.children = [child]
        self.pattern = pattern

    def data_type(self):
        return T.BOOLEAN

    def _params(self):
        return repr(self.pattern)

    def with_children(self, children):
        return type(self)(children[0], self.pattern)

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        return _lift_unary(ctx, c, self._compute)

    def _compute(self, flat, cap):
        raw = flat.data["bytes"]
        o = flat.data["offsets"]
        lens = o[1:] - o[:-1]
        pat = np.frombuffer(self.pattern.encode("utf-8"), np.uint8)
        m = len(pat)
        if m == 0:
            return ColumnVector(T.BOOLEAN, jnp.ones(cap, jnp.bool_), None)
        nb = raw.shape[0]

        def window_eq(base):
            eq = jnp.ones(base.shape, jnp.bool_)
            for k in range(m):
                idx = jnp.clip(base + k, 0, nb - 1)
                eq = eq & (raw[idx] == pat[k])
            return eq

        fits = lens >= m
        if self.mode == "starts":
            res = fits & window_eq(o[:-1])
        elif self.mode == "ends":
            res = fits & window_eq(o[1:] - m)
        else:  # contains: match at any byte start position
            base = jnp.arange(nb, dtype=jnp.int32)
            w = window_eq(base)
            # map each byte position to its row; position must leave room
            rowidx = jnp.searchsorted(o, base, side="right").astype(jnp.int32) - 1
            rowidx = jnp.clip(rowidx, 0, cap - 1)
            in_row = (base + m) <= o[rowidx + 1]
            hit = w & in_row
            per_row = jnp.zeros(cap, jnp.int32).at[rowidx].add(
                hit.astype(jnp.int32), mode="drop")
            res = fits & (per_row > 0)
        return ColumnVector(T.BOOLEAN, res, None)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        f = {"starts": str.startswith, "ends": str.endswith,
             "contains": str.__contains__}[self.mode]
        vals = np.array([bool(f(s, self.pattern)) if isinstance(s, str) else False
                         for s in c.values], np.bool_)
        return CpuCol(T.BOOLEAN, vals, c.valid)


class StartsWith(_LiteralMatch):
    mode = "starts"


class EndsWith(_LiteralMatch):
    mode = "ends"


class Contains(_LiteralMatch):
    mode = "contains"


class Like(Expression):
    """SQL LIKE. Patterns reducible to starts/ends/contains/equality compile
    to device kernels (the reference's regex-transpile-or-reject strategy,
    RegexParser.scala); general patterns run on CPU via fnmatch-style
    matching and mark the expression unsupported on device."""

    def __init__(self, child, pattern: str, escape: str = "\\"):
        self.children = [child]
        self.pattern = pattern
        self.escape = escape

    def data_type(self):
        return T.BOOLEAN

    def _params(self):
        return repr(self.pattern)

    def with_children(self, children):
        return Like(children[0], self.pattern, self.escape)

    def _transpile(self):
        """Return an equivalent device expression, or None."""
        p = self.pattern
        esc = self.escape
        # tokenize
        literal = []
        tokens: List[str] = []
        i = 0
        while i < len(p):
            ch = p[i]
            if ch == esc and i + 1 < len(p):
                literal.append(p[i + 1])
                tokens.append("LIT")
                i += 2
            elif ch == "%":
                tokens.append("%")
                literal.append("")
                i += 1
            elif ch == "_":
                tokens.append("_")
                literal.append("")
                i += 1
            else:
                tokens.append("LIT")
                literal.append(ch)
                i += 1
        if "_" in tokens:
            return None
        # split literal runs by %
        runs: List[str] = []
        cur = ""
        for tk, li in zip(tokens, literal):
            if tk == "%":
                runs.append(cur)
                cur = ""
            else:
                cur += li
        runs.append(cur)
        child = self.children[0]
        if len(runs) == 1:
            return _StringEquals(child, runs[0])
        if len(runs) == 2:
            a, b = runs
            if a == "" and b == "":
                return None  # trivially true; handled below
            if a == "":
                return EndsWith(child, b)
            if b == "":
                return StartsWith(child, a)
            return _AndExpr(StartsWith(child, a), EndsWith(child, b), min_len=len(a) + len(b))
        if len(runs) == 3 and runs[0] == "" and runs[2] == "" and runs[1]:
            return Contains(child, runs[1])
        return None

    def _nfa(self):
        from spark_rapids_tpu.expr import regex as RX
        if not hasattr(self, "_nfa_cache"):
            try:
                # LIKE wildcards match newlines too (CPU path uses
                # re.DOTALL): translate via (.|\n), not bare `.`
                out = []
                i = 0
                p, esc = self.pattern, self.escape
                while i < len(p):
                    ch = p[i]
                    if ch == esc and i + 1 < len(p):
                        ch = p[i + 1]
                        i += 2
                    elif ch == "%":
                        out.append("(.|\n)*")
                        i += 1
                        continue
                    elif ch == "_":
                        out.append("(.|\n)")
                        i += 1
                        continue
                    else:
                        i += 1
                    out.append("\\" + ch if ch in ".^$*+?()[]{}|\\/-" else ch)
                self._nfa_cache = RX.compile_pattern("".join(out), mode="match")
            except RX.RegexUnsupported:
                self._nfa_cache = None
        return self._nfa_cache

    def supported_on_tpu(self):
        return (self._transpile() is not None
                or self.pattern.replace("%", "") == ""
                or self._nfa() is not None)

    def eval_tpu(self, ctx):
        t = self._transpile()
        if t is not None:
            return t.eval_tpu(ctx)
        if self.pattern.replace("%", "") == "":
            c = self.children[0].eval_tpu(ctx)
            return ColumnVector(T.BOOLEAN, jnp.ones(ctx.capacity, jnp.bool_),
                                _valid_of(c, ctx))
        # general LIKE (e.g. '_' wildcards): full-match device NFA
        from spark_rapids_tpu.expr import regex as RX
        nfa = self._nfa()
        if nfa is None:
            raise NotImplementedError(f"LIKE pattern {self.pattern!r} on device")
        c = self.children[0].eval_tpu(ctx)

        def compute(flat, cap):
            res = RX.nfa_eval(nfa, flat.data["offsets"], flat.data["bytes"], None)
            return ColumnVector(T.BOOLEAN, res, None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        import re
        c = self.children[0].eval_cpu(cols, ansi)
        rx = _like_to_regex(self.pattern, self.escape)
        prog = re.compile(rx, re.DOTALL)
        vals = np.array([bool(prog.fullmatch(s)) if isinstance(s, str) else False
                         for s in c.values], np.bool_)
        return CpuCol(T.BOOLEAN, vals, c.valid)


def _like_to_regex(pattern: str, esc: str) -> str:
    import re
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == esc and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
        elif ch == "%":
            out.append(".*")
            i += 1
        elif ch == "_":
            out.append(".")
            i += 1
        else:
            out.append(re.escape(ch))
            i += 1
    return "".join(out)


class RLike(Expression):
    """Spark RLIKE: Java regex, match-anywhere. Patterns inside the device
    subset run as a bit-parallel NFA over byte planes (expr/regex.py);
    others fall back to CPU `re` — the reference's RegexParser
    transpile-or-reject contract."""

    def __init__(self, child, pattern: str):
        self.children = [child]
        self.pattern = pattern
        self._nfa = None
        self._nfa_err = None

    def data_type(self):
        return T.BOOLEAN

    def _params(self):
        return repr(self.pattern)

    def with_children(self, children):
        return RLike(children[0], self.pattern)

    def _compiled(self):
        from spark_rapids_tpu.expr import regex as RX
        if self._nfa is None and self._nfa_err is None:
            try:
                self._nfa = RX.compile_pattern(self.pattern, mode="find")
            except RX.RegexUnsupported as e:
                self._nfa_err = str(e)
        return self._nfa

    def supported_on_tpu(self):
        return self._compiled() is not None

    def eval_tpu(self, ctx):
        from spark_rapids_tpu.expr import regex as RX
        nfa = self._compiled()
        if nfa is None:
            raise NotImplementedError(
                f"regex {self.pattern!r} on device: {self._nfa_err}")
        c = self.children[0].eval_tpu(ctx)

        def compute(flat, cap):
            res = RX.nfa_eval(nfa, flat.data["offsets"], flat.data["bytes"],
                              None)
            return ColumnVector(T.BOOLEAN, res, None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        import re
        c = self.children[0].eval_cpu(cols, ansi)
        prog = re.compile(self.pattern)
        vals = np.array([bool(prog.search(s)) if isinstance(s, str) else False
                         for s in c.values], np.bool_)
        return CpuCol(T.BOOLEAN, vals, c.valid)


class _RegexCpuBase(Expression):
    """regexp_extract / regexp_replace: capture-group semantics need a
    backtracking engine — CPU-only (tagged unsupported on device so the
    enclosing exec falls back, reference behavior for unsupported regex)."""

    def data_type(self):
        return T.STRING

    def supported_on_tpu(self):
        return False

    def eval_tpu(self, ctx):
        raise NotImplementedError("capture-group regex runs on CPU")


class RegexpExtract(_RegexCpuBase):
    """regexp_extract: capture-group extraction. Alternation-free
    patterns within the tagged-NFA subset run ON DEVICE (expr/regex.py
    compile_extract — the reference transpiles to the cudf regex engine
    the same transpile-or-reject way, RegexParser.scala); everything
    else falls back to the CPU tier."""

    def __init__(self, child, pattern: str, group: int = 1):
        self.children = [child]
        self.pattern = pattern
        self.group = group
        from spark_rapids_tpu.expr.regex import (
            RegexUnsupported, compile_extract)
        try:
            self._tagged = compile_extract(pattern, group)
            self._nfa_err = None
        except RegexUnsupported as e:
            self._tagged = None
            self._nfa_err = str(e)

    def _params(self):
        return f"{self.pattern!r},{self.group}"

    def with_children(self, children):
        return RegexpExtract(children[0], self.pattern, self.group)

    def supported_on_tpu(self):
        return self._tagged is not None

    def eval_tpu(self, ctx):
        from spark_rapids_tpu.expr.regex import nfa_extract
        c = self.children[0].eval_tpu(ctx)
        t = self._tagged

        def compute(flat, cap):
            off = flat.data["offsets"][: cap + 1].astype(jnp.int32)
            raw = flat.data["bytes"]
            has, g0, g1 = nfa_extract(t, off, raw)
            lens = jnp.where(has, g1 - g0, 0)
            new_off = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(lens).astype(jnp.int32)])
            bcap = int(raw.shape[0])
            b = jnp.arange(bcap, dtype=jnp.int32)
            row = jnp.clip(
                jnp.searchsorted(new_off, b, side="right").astype(jnp.int32)
                - 1, 0, cap - 1)
            src = jnp.clip(off[row] + g0[row] + (b - new_off[row]),
                           0, bcap - 1)
            out_bytes = jnp.where(b < new_off[-1], raw[src],
                                  0).astype(jnp.uint8)
            return ColumnVector(T.STRING, {"offsets": new_off,
                                           "bytes": out_bytes}, None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        import re
        c = self.children[0].eval_cpu(cols, ansi)
        prog = re.compile(self.pattern)
        if self.group > prog.groups or self.group < 0:
            raise ValueError(
                f"regexp_extract group {self.group} out of range for "
                f"{self.pattern!r} ({prog.groups} groups)")
        out = []
        for s in c.values:
            if not isinstance(s, str):
                out.append(None)
                continue
            m = prog.search(s)
            # Spark: "" for no match AND for a non-participating group
            out.append((m.group(self.group) or "") if m else "")
        return CpuCol(T.STRING, np.array(out, object), c.valid)


class RegexpReplace(_RegexCpuBase):
    """regexp_replace: replace-all. Patterns in the tagged-NFA subset
    with a LITERAL replacement (<= 8 bytes, no $n backrefs) run ON
    DEVICE: one match-span scan (expr/regex.py nfa_match_spans) plus a
    byte-plane splice — the transpile-or-reject discipline of the
    reference's RegexParser.scala. Backrefs and everything outside the
    subset fall back to the CPU tier."""

    _MAX_DEVICE_REPL = 8

    def __init__(self, child, pattern: str, replacement: str):
        self.children = [child]
        self.pattern = pattern
        self.replacement = replacement
        self._tagged = None
        self._nfa_err = None
        import re as _re
        if _re.search(r"\$\d", replacement):
            self._nfa_err = "backref in replacement"
        elif len(replacement.encode()) > self._MAX_DEVICE_REPL:
            self._nfa_err = "replacement too long for device splice"
        else:
            from spark_rapids_tpu.expr.regex import (
                RegexUnsupported, compile_replace)
            try:
                self._tagged = compile_replace(pattern)
            except RegexUnsupported as e:
                self._nfa_err = str(e)

    def _params(self):
        return f"{self.pattern!r},{self.replacement!r}"

    def with_children(self, children):
        return RegexpReplace(children[0], self.pattern, self.replacement)

    def supported_on_tpu(self):
        return self._tagged is not None

    def eval_tpu(self, ctx):
        from spark_rapids_tpu.expr.regex import nfa_match_spans
        if self._tagged is None:
            raise NotImplementedError(
                f"regexp_replace {self.pattern!r} on device: "
                f"{self._nfa_err}")
        c = self.children[0].eval_tpu(ctx)
        t = self._tagged
        rep = np.frombuffer(self.replacement.encode(), np.uint8)
        R = int(rep.shape[0])

        def compute(flat, cap):
            off = flat.data["offsets"][: cap + 1].astype(jnp.int32)
            raw = flat.data["bytes"]
            nbytes = int(raw.shape[0])
            flags, slen = nfa_match_spans(t, off, raw)
            fi = flags.astype(jnp.int32)
            # in-match mask via the range-delta trick (spans never
            # cross row boundaries)
            delta = jnp.zeros(nbytes + 1, jnp.int32)
            b_idx = jnp.arange(nbytes, dtype=jnp.int32)
            delta = delta.at[jnp.where(flags, b_idx, nbytes)].add(fi)
            delta = delta.at[jnp.where(flags, b_idx + slen, nbytes)].add(
                -fi)
            inm = jnp.cumsum(delta[:nbytes]) > 0
            keep = ~inm & (b_idx < off[cap])
            # output layout: per byte, kept-bytes-so-far and
            # matches-so-far (exclusive prefix sums)
            kept_x = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                      jnp.cumsum(keep.astype(jnp.int32))])
            m_x = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(fi)])
            new_off = kept_x[off] + R * m_x[off]
            new_off = new_off - new_off[0]
            out_cap = max(int(nbytes) * max(1, R), 8)
            row = jnp.clip(jnp.searchsorted(
                off, b_idx, side="right").astype(jnp.int32) - 1,
                0, cap - 1)
            out_base = new_off[row] + (kept_x[b_idx] - kept_x[off[row]]) \
                + R * (m_x[b_idx] - m_x[off[row]])
            out = jnp.zeros(out_cap, jnp.uint8)
            out = out.at[jnp.where(keep, out_base, out_cap)].set(
                raw, mode="drop")
            for j in range(R):
                out = out.at[jnp.where(flags, out_base + j, out_cap)].set(
                    jnp.uint8(rep[j]), mode="drop")
            return ColumnVector(T.STRING,
                                {"offsets": new_off.astype(jnp.int32),
                                 "bytes": out}, None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        import re
        c = self.children[0].eval_cpu(cols, ansi)
        prog = re.compile(self.pattern)
        # Java $1 -> python \1 backrefs
        repl = re.sub(r"\$(\d)", r"\\\1", self.replacement)
        vals = np.array([prog.sub(repl, s) if isinstance(s, str) else s
                         for s in c.values], object)
        return CpuCol(T.STRING, vals, c.valid)


class _StringEquals(Expression):
    def __init__(self, child, value: str):
        self.children = [child]
        self.value = value

    def data_type(self):
        return T.BOOLEAN

    def with_children(self, children):
        return _StringEquals(children[0], self.value)

    def eval_tpu(self, ctx):
        from spark_rapids_tpu.expr.core import EqualTo, Literal
        return EqualTo(self.children[0], Literal(self.value, T.STRING)).eval_tpu(ctx)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        vals = np.array([s == self.value if isinstance(s, str) else False
                         for s in c.values], np.bool_)
        return CpuCol(T.BOOLEAN, vals, c.valid)


class _AndExpr(Expression):
    def __init__(self, a, b, min_len=0):
        self.children = [a, b]
        self.min_len = min_len

    def data_type(self):
        return T.BOOLEAN

    def with_children(self, children):
        return _AndExpr(children[0], children[1], self.min_len)

    def eval_tpu(self, ctx):
        a = self.children[0].eval_tpu(ctx)
        b = self.children[1].eval_tpu(ctx)
        res = a.data & b.data
        if self.min_len:
            src = self.children[0].children[0].eval_tpu(ctx)
            res = res & ((_lens(src)) >= self.min_len)
        return ColumnVector(T.BOOLEAN, res, _valid_of(a, ctx) & _valid_of(b, ctx))

    def eval_cpu(self, cols, ansi=False):
        a = self.children[0].eval_cpu(cols, ansi)
        b = self.children[1].eval_cpu(cols, ansi)
        res = a.values & b.values
        if self.min_len:
            src = self.children[0].children[0].eval_cpu(cols, ansi)
            lens = np.array([len(s) if isinstance(s, str) else 0 for s in src.values])
            res = res & (lens >= self.min_len)
        return CpuCol(T.BOOLEAN, res, a.valid & b.valid)


# ---------------------------------------------------------------------------
# Casts involving strings (reference GpuCast string paths / CastStrings JNI)
# ---------------------------------------------------------------------------

_DIGITS = np.frombuffer(b"0123456789", np.uint8)


def _render_int64_tpu(values: jax.Array, valid: jax.Array) -> ColumnVector:
    """int64 -> decimal string rendering on device: compute per-row digit
    count, then scatter digits (branch-free, fixed 20-byte max per row)."""
    cap = values.shape[0]
    neg = values < 0
    # abs in uint64 to handle INT64_MIN
    mag = jnp.where(neg, (~values.astype(jnp.uint64)) + jnp.uint64(1),
                    values.astype(jnp.uint64))
    # digit count via comparisons (max 20 digits for uint64)
    ndig = jnp.ones(cap, jnp.int32)
    p = jnp.uint64(10)
    for k in range(1, 20):
        ndig = ndig + (mag >= p).astype(jnp.int32)
        p = p * jnp.uint64(10)
    lens = ndig + neg.astype(jnp.int32)
    lens = jnp.where(valid, lens, 0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    total = new_off[-1]
    bcap = cap * 20  # static upper bound
    b = jnp.arange(bcap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off, b, side="right").astype(jnp.int32) - 1,
                   0, cap - 1)
    pos = b - new_off[row]  # position within the rendered number
    is_sign = neg[row] & (pos == 0)
    # digit index from the right: ndig-1-(pos - has_sign)
    di = ndig[row] - 1 - (pos - neg[row].astype(jnp.int32))
    di = jnp.clip(di, 0, 19)
    # extract digit di (from least significant) of mag[row]
    mrow = mag[row]
    div = jnp.power(jnp.full(bcap, 10, jnp.uint64), di.astype(jnp.uint64))
    digit = ((mrow // div) % jnp.uint64(10)).astype(jnp.int32)
    ch = jnp.where(is_sign, np.uint8(45), (digit + 48).astype(jnp.uint8))
    out = jnp.where(b < total, ch, 0).astype(jnp.uint8)
    return ColumnVector(T.STRING, {"offsets": new_off, "bytes": out}, valid)


def _parse_int64_tpu(col: ColumnVector, valid: jax.Array, ctx: EvalCtx):
    """string -> int64: optional sign + digits, leading/trailing spaces
    trimmed, anything else -> null (non-ANSI Spark)."""
    o = col.data["offsets"]
    raw = col.data["bytes"]
    starts = o[:-1]
    ends = o[1:]
    nb = raw.shape[0]

    def at(pos):
        return raw[jnp.clip(pos, 0, nb - 1)]

    # trim spaces
    def trim(state):
        s, e = state
        lead = (s < e) & (at(s) == 32)
        tail = (e > s) & (at(e - 1) == 32)
        return jnp.where(lead, s + 1, s), jnp.where(tail, e - 1, e)

    def trim_cond(state):
        s, e = state
        lead = (s < e) & (at(s) == 32)
        tail = (e > s) & (at(e - 1) == 32)
        return jnp.any(lead | tail)

    s, e = lax.while_loop(trim_cond, trim, (starts, ends))
    first = at(s)
    has_sign = (first == 45) | (first == 43)
    neg = first == 45
    ds = s + has_sign.astype(jnp.int32)
    ok = (e > ds)

    def body(state):
        i, acc, good, done = state
        pos = ds + i
        active = (pos < e) & ~done
        byte = at(pos)
        is_digit = (byte >= 48) & (byte <= 57)
        acc2 = acc * 10 + (byte - 48).astype(jnp.int64)
        acc = jnp.where(active & is_digit, acc2, acc)
        good = good & (~active | is_digit)
        done = done | (pos >= e)
        return i + 1, acc, good, done

    def cond(state):
        i, _, _, done = state
        return ~jnp.all(done)

    n = starts.shape[0]
    init = (jnp.int32(0), jnp.zeros(n, jnp.int64), ok,
            jnp.zeros(n, jnp.bool_))
    _, acc, good, _ = lax.while_loop(cond, body, init)
    value = jnp.where(neg, -acc, acc)
    out_valid = valid & good
    if ctx is not None and ctx.ansi:
        ctx.add_error("CAST_INVALID_INPUT", valid & ~good)
    return value, out_valid


def cast_string_tpu(c: ColumnVector, dst: T.DataType, ctx: EvalCtx) -> ColumnVector:
    valid = _valid_of(c, ctx)
    if isinstance(dst, T.StringType):
        src = c.dtype
        if isinstance(src, T.BooleanType):
            from spark_rapids_tpu.expr.core import If, Literal, _RawCol
            return If(_RawCol(ColumnVector(T.BOOLEAN, c.data, valid)),
                      Literal("true", T.STRING),
                      Literal("false", T.STRING)).eval_tpu(ctx)
        if isinstance(src, T.DateType):
            from spark_rapids_tpu.expr import cast_kernels as CK
            return CK.render_date(c.data, valid)
        if isinstance(src, T.TimestampType):
            from spark_rapids_tpu.expr import cast_kernels as CK
            return CK.render_timestamp(c.data, valid)
        if src.is_integral:
            return _render_int64_tpu(c.data.astype(jnp.int64), valid)
        raise NotImplementedError(f"cast {src!r} -> string on device")
    if isinstance(c.dtype, T.StringType):
        if dst.is_integral:
            if c.is_dict:
                # parse the vocab once, gather values/validity by code
                flat = _flat_view(c)
                k = flat.capacity
                vv, vok = _parse_int64_tpu(flat, jnp.ones(k, jnp.bool_),
                                           ctx if not ctx.ansi else None)
                codes = c.data["codes"]
                out_valid = valid & vok[codes]
                if ctx.ansi:
                    ctx.add_error("CAST_INVALID_INPUT", valid & ~vok[codes])
                return ColumnVector(dst, vv[codes].astype(dst.np_dtype), out_valid)
            v64, out_valid = _parse_int64_tpu(c, valid, ctx)
            return ColumnVector(dst, v64.astype(dst.np_dtype), out_valid)
        if isinstance(dst, (T.Float32Type, T.Float64Type, T.DateType,
                            T.TimestampType)):
            from spark_rapids_tpu.expr import cast_kernels as CK
            if isinstance(dst, (T.Float32Type, T.Float64Type)):
                parse = CK.parse_f64
            elif isinstance(dst, T.DateType):
                parse = CK.parse_date
            else:
                parse = CK.parse_timestamp
            if c.is_dict:
                flat = _flat_view(c)
                vv, vok = parse(flat)
                codes = c.data["codes"]
                okc = vok[codes]
                out_valid = valid & okc
                if ctx.ansi:
                    ctx.add_error("CAST_INVALID_INPUT", valid & ~okc)
                vals = vv[codes]
            else:
                vals, vok = parse(c)
                out_valid = valid & vok
                if ctx.ansi:
                    ctx.add_error("CAST_INVALID_INPUT", valid & ~vok)
            return ColumnVector(dst, vals.astype(dst.np_dtype), out_valid)
        raise NotImplementedError(f"cast string -> {dst!r} on device")
    raise NotImplementedError


def cast_string_cpu(c: CpuCol, dst: T.DataType, ansi: bool) -> CpuCol:
    if isinstance(dst, T.StringType):
        src = c.dtype
        out = []
        for i, v in enumerate(c.values):
            if not c.valid[i]:
                out.append(None)
            elif isinstance(src, T.BooleanType):
                out.append("true" if v else "false")
            elif isinstance(src, (T.Float32Type, T.Float64Type)):
                out.append(_spark_float_str(float(v)))
            elif isinstance(src, T.DateType):
                import datetime
                out.append(str(datetime.date(1970, 1, 1)
                               + datetime.timedelta(days=int(v))))
            elif isinstance(src, T.TimestampType):
                import datetime
                dt = (datetime.datetime(1970, 1, 1)
                      + datetime.timedelta(microseconds=int(v)))
                s_iso = dt.isoformat(sep=" ")
                if "." in s_iso:  # Spark trims trailing fraction zeros
                    s_iso = s_iso.rstrip("0").rstrip(".")
                out.append(s_iso)
            elif isinstance(src, T.DecimalType):
                import decimal
                out.append(str(decimal.Decimal(int(v)).scaleb(-src.scale)))
            else:
                out.append(str(int(v)))
        return CpuCol(T.STRING, np.array(out, object),
                      c.valid.copy())
    # string -> X
    n = len(c.values)
    valid = c.valid.copy()
    if dst.is_integral:
        vals = np.zeros(n, np.int64)
        for i, s in enumerate(c.values):
            if not valid[i]:
                continue
            t = s.strip() if isinstance(s, str) else ""
            try:
                vals[i] = int(t)
            except ValueError:
                if ansi:
                    raise SparkException(f"[CAST_INVALID_INPUT] '{s}' to int")
                valid[i] = False
        return CpuCol(dst, vals.astype(dst.np_dtype), valid)
    if isinstance(dst, (T.Float32Type, T.Float64Type)):
        import re
        # Spark castToDouble = UTF8String.trim + Java Double.parseDouble:
        # case-SENSITIVE Infinity/NaN, no underscores, no bare 'inf'
        # (python float() is more lenient — do NOT use it directly)
        num_re = re.compile(
            r"[+-]?((\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?|Infinity|NaN)")
        vals = np.zeros(n, np.float64)
        for i, s in enumerate(c.values):
            if not valid[i]:
                continue
            t = _java_trim(s) if isinstance(s, str) else ""
            if num_re.fullmatch(t):
                vals[i] = float(t.replace("Infinity", "inf"))
            else:
                if ansi:
                    raise SparkException(f"[CAST_INVALID_INPUT] '{s}' to float")
                valid[i] = False
        return CpuCol(dst, vals.astype(dst.np_dtype), valid)
    if isinstance(dst, T.BooleanType):
        vals = np.zeros(n, np.bool_)
        for i, s in enumerate(c.values):
            if not valid[i]:
                continue
            t = (s.strip().lower() if isinstance(s, str) else "")
            if t in ("true", "t", "yes", "y", "1"):
                vals[i] = True
            elif t in ("false", "f", "no", "n", "0"):
                vals[i] = False
            else:
                if ansi:
                    raise SparkException(f"[CAST_INVALID_INPUT] '{s}' to boolean")
                valid[i] = False
        return CpuCol(dst, vals, valid)
    if isinstance(dst, (T.DateType, T.TimestampType)):
        vals = np.zeros(n, np.int64)
        for i, s in enumerate(c.values):
            if not valid[i]:
                continue
            r = _parse_dt_py(s, with_time=isinstance(dst, T.TimestampType))
            if r is None:
                if ansi:
                    raise SparkException(
                        f"[CAST_INVALID_INPUT] '{s}' to {dst!r}")
                valid[i] = False
            else:
                vals[i] = r
        np_dt = np.int32 if isinstance(dst, T.DateType) else np.int64
        return CpuCol(dst, vals.astype(np_dt), valid)
    raise NotImplementedError(f"cast string -> {dst!r}")


_JAVA_WS = "".join(chr(c) for c in range(33))


def _java_trim(s: str) -> str:
    """Java String/UTF8String trim: strip chars <= 0x20 on both ends."""
    return s.strip(_JAVA_WS)


def _parse_dt_py(s, with_time: bool):
    """Spark stringToDate/stringToTimestamp subset, matching the device
    kernel (cast_kernels._parse_ymd_hms): yyyy[-m[-d]] and
    yyyy-m-d[ |T]H:M:S[.ffffff], UTC."""
    import re
    import datetime
    if not isinstance(s, str):
        return None
    t = _java_trim(s)
    date_re = r"(\d{1,7})(?:-(\d{1,2})(?:-(\d{1,2}))?)?"
    time_re = r"(?:[ T](\d{1,2}):(\d{1,2}):(\d{1,2})(?:\.(\d+))?)?"
    m = re.fullmatch(date_re + (time_re if with_time else ""), t)
    if m is None:
        return None
    g = m.groups()
    y, mo, d = int(g[0]), int(g[1] or 1), int(g[2] or 1)
    try:
        date = datetime.date(y, mo, d)
    except ValueError:
        return None
    days = (date - datetime.date(1970, 1, 1)).days
    if not with_time:
        return days
    us = 0
    if g[3] is not None:
        H, Mi, S = int(g[3]), int(g[4]), int(g[5])
        if H > 23 or Mi > 59 or S > 59:
            return None
        frac = (g[6] or "")[:6].ljust(6, "0") if g[6] else "0"
        us = H * 3_600_000_000 + Mi * 60_000_000 + S * 1_000_000 + int(frac)
    return days * 86_400_000_000 + us


def _spark_float_str(v: float) -> str:
    """Java Double.toString-ish rendering (Spark cast double->string)."""
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "Infinity"
    if v == float("-inf"):
        return "-Infinity"
    if v == int(v) and abs(v) < 1e16:
        return f"{int(v)}.0"
    return repr(v)


# ---------------------------------------------------------------------------
# String function breadth (reference stringFunctions.scala): all unary ops
# ride the vocab lift, so dict-encoded columns pay O(vocab) byte work.
# ---------------------------------------------------------------------------

def _row_of_byte(offsets, nbytes, cap):
    b = jnp.arange(nbytes, dtype=jnp.int32)
    return jnp.clip(jnp.searchsorted(offsets, b, side="right").astype(jnp.int32) - 1,
                    0, cap - 1)


def _slice_rows(raw, new_start, lens, cap):
    """Assemble a string column taking lens[i] bytes from new_start[i]."""
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    nb = raw.shape[0]
    b = jnp.arange(nb, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off, b, side="right").astype(jnp.int32) - 1,
                   0, cap - 1)
    src = jnp.clip(new_start[row] + (b - new_off[row]), 0, nb - 1)
    out = jnp.where(b < new_off[-1], raw[src], 0).astype(jnp.uint8)
    return {"offsets": new_off, "bytes": out}


class _TrimBase(Expression):
    """trim/ltrim/rtrim of ASCII spaces (Spark default trims ' ')."""

    lead = True
    tail = True

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.STRING

    def with_children(self, children):
        return type(self)(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)

        def compute(flat, cap):
            o = flat.data["offsets"]
            raw = flat.data["bytes"]
            nb = raw.shape[0]
            row = _row_of_byte(o, nb, cap)
            pos = jnp.arange(nb, dtype=jnp.int32)
            in_row = (pos >= o[row]) & (pos < o[row + 1])
            nonspace = in_row & (raw != 32)
            first_ns = jax.ops.segment_min(
                jnp.where(nonspace, pos, nb), row, num_segments=cap)
            last_ns = jax.ops.segment_max(
                jnp.where(nonspace, pos, -1), row, num_segments=cap)
            has = last_ns >= 0
            start = jnp.where(self.lead, jnp.where(has, first_ns, o[1:]),
                              o[:-1]).astype(jnp.int32)
            end = jnp.where(self.tail, jnp.where(has, last_ns + 1, start),
                            o[1:]).astype(jnp.int32)
            end = jnp.maximum(end, start)
            return ColumnVector(T.STRING,
                                _slice_rows(raw, start, end - start, cap), None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        def f(s):
            if self.lead and self.tail:
                return s.strip(" ")
            return s.lstrip(" ") if self.lead else s.rstrip(" ")
        vals = np.array([f(s) if isinstance(s, str) else s for s in c.values],
                        object)
        return CpuCol(T.STRING, vals, c.valid)


class Trim(_TrimBase):
    lead = tail = True


class LTrim(_TrimBase):
    lead, tail = True, False


class RTrim(_TrimBase):
    lead, tail = False, True


class InitCap(Expression):
    """initcap: uppercase after a space / row start, lowercase elsewhere
    (ASCII mapping; reference documents the same non-ASCII incompat)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.STRING

    def with_children(self, children):
        return InitCap(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)

        def compute(flat, cap):
            o = flat.data["offsets"]
            raw = flat.data["bytes"]
            nb = raw.shape[0]
            row = _row_of_byte(o, nb, cap)
            pos = jnp.arange(nb, dtype=jnp.int32)
            at_start = pos == o[row]
            prev = jnp.where(at_start, jnp.uint8(32), jnp.roll(raw, 1))
            after_sep = prev == 32
            lower = jnp.where((raw >= 65) & (raw <= 90), raw + 32, raw)
            upper = jnp.where((raw >= 97) & (raw <= 122), raw - 32, raw)
            out = jnp.where(after_sep, upper, lower)
            return ColumnVector(T.STRING, {"offsets": o, "bytes": out}, None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)

        def f(s):
            return " ".join(w[:1].upper() + w[1:].lower() for w in s.split(" "))

        vals = np.array([f(s) if isinstance(s, str) else s for s in c.values],
                        object)
        return CpuCol(T.STRING, vals, c.valid)


class Ascii(Expression):
    """ascii(s): code of the first character (ASCII subset on device)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return Ascii(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)

        def compute(flat, cap):
            o = flat.data["offsets"]
            raw = flat.data["bytes"]
            nb = raw.shape[0]
            first = raw[jnp.clip(o[:-1], 0, nb - 1)].astype(jnp.int32)
            lens = o[1:] - o[:-1]
            return ColumnVector(T.INT32, jnp.where(lens > 0, first, 0), None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        vals = np.array([ord(s[0]) if isinstance(s, str) and s else 0
                         for s in c.values], np.int32)
        return CpuCol(T.INT32, vals, c.valid)


class InStr(Expression):
    """instr(str, substr-literal): 1-based CHAR position of the first
    occurrence, 0 if absent."""

    def __init__(self, child, substr: str):
        self.children = [child]
        self.substr = substr

    def _params(self):
        return repr(self.substr)

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return InStr(children[0], self.substr)

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        pat = np.frombuffer(self.substr.encode("utf-8"), np.uint8)
        m = len(pat)

        def compute(flat, cap):
            o = flat.data["offsets"]
            raw = flat.data["bytes"]
            nb = raw.shape[0]
            if m == 0:
                return ColumnVector(T.INT32, jnp.ones(cap, jnp.int32), None)
            pos = jnp.arange(nb, dtype=jnp.int32)
            row = _row_of_byte(o, nb, cap)
            eq = jnp.ones(nb, jnp.bool_)
            for k in range(m):
                eq = eq & (raw[jnp.clip(pos + k, 0, nb - 1)] == pat[k])
            fits = (pos + m) <= o[row + 1]
            hit = eq & fits
            first_hit = jax.ops.segment_min(jnp.where(hit, pos, nb), row,
                                            num_segments=cap)
            found = first_hit < nb
            # byte position -> 1-based char index
            is_start = ((raw & 0xC0) != 0x80).astype(jnp.int32)
            csum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                    jnp.cumsum(is_start)])
            char_idx = csum[jnp.clip(first_hit, 0, nb)] - csum[o[:-1]] + 1
            return ColumnVector(T.INT32,
                                jnp.where(found, char_idx, 0).astype(jnp.int32),
                                None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        vals = np.array([s.find(self.substr) + 1 if isinstance(s, str) else 0
                         for s in c.values], np.int32)
        return CpuCol(T.INT32, vals, c.valid)


class StringRepeat(Expression):
    """repeat(str, n-literal)."""

    def __init__(self, child, n: int):
        self.children = [child]
        self.n = max(int(n), 0)

    def _params(self):
        return str(self.n)

    def data_type(self):
        return T.STRING

    def with_children(self, children):
        return StringRepeat(children[0], self.n)

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        n = self.n

        def compute(flat, cap):
            o = flat.data["offsets"]
            raw = flat.data["bytes"]
            nb = int(raw.shape[0])
            lens = o[1:] - o[:-1]
            out_lens = lens * n
            new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                       jnp.cumsum(out_lens).astype(jnp.int32)])
            out_cap = nb * max(n, 1)
            b = jnp.arange(out_cap, dtype=jnp.int32)
            row = jnp.clip(jnp.searchsorted(new_off, b, side="right")
                           .astype(jnp.int32) - 1, 0, cap - 1)
            off_in = b - new_off[row]
            src = jnp.clip(o[row] + jnp.mod(off_in, jnp.maximum(lens[row], 1)),
                           0, nb - 1)
            out = jnp.where(b < new_off[-1], raw[src], 0).astype(jnp.uint8)
            return ColumnVector(T.STRING, {"offsets": new_off, "bytes": out},
                                None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        vals = np.array([s * self.n if isinstance(s, str) else s
                         for s in c.values], object)
        return CpuCol(T.STRING, vals, c.valid)


# ---------------------------------------------------------------------------
# String breadth second tier: device-trivial length/slice family
# ---------------------------------------------------------------------------

class OctetLength(Expression):
    """octet_length(): UTF-8 byte count."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return OctetLength(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)

        def compute(flat, cap):
            off = flat.data["offsets"]
            lens = (off[1: cap + 1] - off[:cap]).astype(jnp.int32)
            return ColumnVector(T.INT32, lens, None)

        return _lift_unary(ctx, c, compute)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        vals = np.array([len(s.encode()) if isinstance(s, str) else 0
                         for s in c.values], np.int32)
        return CpuCol(T.INT32, vals, c.valid)


class BitLength(OctetLength):
    """bit_length(): 8 * octet_length."""

    def with_children(self, children):
        return BitLength(children[0])

    def eval_tpu(self, ctx):
        base = super().eval_tpu(ctx)
        return ColumnVector(T.INT32, base.data * 8, base.validity)

    def eval_cpu(self, cols, ansi=False):
        base = super().eval_cpu(cols, ansi)
        return CpuCol(T.INT32, base.values * 8, base.valid)


class Left(Substring):
    """left(s, n) = substring(s, 1, n); n < 0 yields ''."""

    def __init__(self, child, n: int):
        super().__init__(child, 1, max(int(n), 0))

    def with_children(self, children):
        return Left(children[0], self.length)


class Right(Expression):
    """right(s, n): last n characters ('' for n <= 0)."""

    def __init__(self, child, n: int):
        self.children = [child]
        self.n = int(n)

    def _params(self):
        return str(self.n)

    def with_children(self, children):
        return Right(children[0], self.n)

    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        if self.n <= 0:
            inner = Substring(self.children[0], 1, 0)
        else:
            inner = Substring(self.children[0], -self.n, self.n)
        inner = inner.with_children([self.children[0]])
        return inner.eval_tpu(ctx)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        n = self.n
        vals = np.array([s[-n:] if isinstance(s, str) and n > 0 else
                         ("" if isinstance(s, str) else None)
                         for s in c.values], object)
        return CpuCol(T.STRING, vals, c.valid)


class Chr(Expression):
    """chr(n): the character with code n % 256 for positive n in Latin-1
    range (Spark semantics: n <= 0 -> '', 256-multiples -> '\\0' etc.)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.STRING

    def with_children(self, children):
        return Chr(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        v = c.data.astype(jnp.int64)
        code = jnp.where(v < 0, jnp.int64(0), v % 256)
        # UTF-8: codes < 128 are one byte; 128..255 encode as two bytes.
        # Spark: only NEGATIVE n gives ''; chr(0) and chr(256) are '\\x00'
        two = code >= 128
        lens = jnp.where(c.validity_or_default(ctx.num_rows) & (v >= 0),
                         jnp.where(two, 2, 1), 0).astype(jnp.int32)
        off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
        cap = ctx.capacity
        bcap = 2 * cap
        b = jnp.arange(bcap, dtype=jnp.int32)
        row = jnp.clip(jnp.searchsorted(off, b, side="right").astype(jnp.int32)
                       - 1, 0, cap - 1)
        in_r = b < off[-1]
        second = b - off[row] == 1
        cd = code[row]
        byte1 = jnp.where(cd < 128, cd, 0xC0 | (cd >> 6))
        byte2 = 0x80 | (cd & 0x3F)
        ob = jnp.where(second, byte2, byte1)
        out_bytes = jnp.where(in_r, ob, 0).astype(jnp.uint8)
        return ColumnVector(T.STRING, {"offsets": off, "bytes": out_bytes},
                            _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        out = []
        for v, ok in zip(c.values, c.valid):
            if not ok:
                out.append(None)
                continue
            n = int(v)
            out.append("" if n < 0 else chr(n % 256))
        return CpuCol(T.STRING, np.array(out, object), c.valid)
