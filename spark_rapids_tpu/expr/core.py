"""Expression tree with dual evaluation paths.

Reference parity: the expression library surveyed in SURVEY.md §2.5
(arithmetic.scala, predicates.scala, conditionalExpressions.scala,
nullExpressions.scala, GpuCast.scala) and the `columnarEval` contract of
GpuExpression.

TPU-first difference from the reference: cuDF evaluates one kernel per
expression node over materialized columns; here `eval_tpu` builds jnp ops
inside a trace, so an entire projection/filter stage fuses into ONE jitted
XLA computation (see exec/compiled.py). The CPU path (`eval_cpu`, numpy on
(values, mask) pairs) is an independent implementation used as the
differential-testing baseline, playing the role CPU Spark plays for the
reference's integration tests.

Null semantics follow Spark SQL: null-propagating arithmetic/comparison,
Kleene AND/OR, null-safe equality, CASE/IF lazy-ish branches (both branches
computed, selected by mask -- fine because expressions are pure), non-ANSI
division-by-zero yields null, ANSI mode raises.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector


class SparkException(Exception):
    """Raised for ANSI-mode arithmetic/cast errors (host-side, after the
    jitted stage reports error flags)."""


class _CpuEvalContext(threading.local):
    """Partition context for the CPU interpreter (spark_partition_id,
    monotonically_increasing_id). CpuFallbackExec collapses its input to a
    single partition, so the defaults describe that execution; a caller
    evaluating per-partition must set these to match the TPU path's
    (pid << 33) + idx layout."""
    partition_id = 0
    row_base = 0


CPU_EVAL_CTX = _CpuEvalContext()


@dataclasses.dataclass
class CpuCol:
    """CPU evaluation currency: numpy values + bool validity (True=valid).
    Strings are object ndarrays of python str."""
    dtype: T.DataType
    values: np.ndarray
    valid: np.ndarray

    @staticmethod
    def of(dtype, values, valid=None):
        values = np.asarray(values) if not isinstance(values, np.ndarray) else values
        if valid is None:
            valid = np.ones(len(values), np.bool_)
        return CpuCol(dtype, values, valid)


class EvalCtx:
    """Context for one traced stage: input columns + row-count scalar.

    num_rows is a traced int32 scalar so changing row counts inside a
    capacity bucket does NOT recompile. `row_mask` gives in-range rows.
    ANSI errors accumulate as (code, bool-plane) pairs checked on the host
    after stage execution.
    """

    def __init__(self, columns: Sequence[ColumnVector], num_rows, capacity: int,
                 ansi: bool = False, live=None, partition_id=0, row_base=0):
        self.columns = list(columns)
        self.num_rows = num_rows
        self.capacity = capacity
        self.ansi = ansi
        self.live = live  # selection mask; dead rows never raise ANSI errors
        #: traced scalars for partition-aware expressions
        #: (spark_partition_id, monotonically_increasing_id)
        self.partition_id = partition_id
        self.row_base = row_base
        self.errors: List[Tuple[str, jax.Array]] = []

    @property
    def row_mask(self) -> jax.Array:
        if self.live is not None:
            return self.live
        return jnp.arange(self.capacity) < self.num_rows

    def add_error(self, code: str, mask: jax.Array) -> None:
        self.errors.append((code, mask & self.row_mask))


class Expression:
    children: List["Expression"] = []

    def data_type(self) -> T.DataType:
        raise NotImplementedError

    @property
    def nullable(self) -> bool:
        return True

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        raise NotImplementedError

    def eval_cpu(self, cols: Sequence[CpuCol], ansi: bool = False) -> CpuCol:
        raise NotImplementedError

    def fingerprint(self) -> str:
        params = self._params()
        kids = ",".join(c.fingerprint() for c in self.children)
        return f"{type(self).__name__}({params};{kids})"

    def static_range(self):
        """Optional (lo, hi) int bounds of this expression's values,
        derivable from the expression alone (e.g. ``x % 1000``). Lets the
        radix groupby/sort paths skip the per-batch device min/max probe
        (one host sync per batch). None = unknown."""
        return None

    def _params(self) -> str:
        return ""

    def transform(self, fn) -> "Expression":
        """Bottom-up rewrite (used by the analyzer to bind names)."""
        new = self.with_children([c.transform(fn) for c in self.children])
        return fn(new)

    def with_children(self, children: List["Expression"]) -> "Expression":
        if not self.children and not children:
            return self
        clone = dataclasses.replace(self) if dataclasses.is_dataclass(self) else self
        clone.children = children
        return clone

    def references(self) -> set:
        out = set()
        if isinstance(self, Col):
            out.add(self.name)
        for c in self.children:
            out |= c.references()
        return out

    def __repr__(self):
        return self.fingerprint()

    # Operator sugar so tests/DataFrame code read like Spark Column exprs.
    def __add__(self, o): return Add(self, _wrap(o))
    def __radd__(self, o): return Add(_wrap(o), self)
    def __sub__(self, o): return Subtract(self, _wrap(o))
    def __rsub__(self, o): return Subtract(_wrap(o), self)
    def __mul__(self, o): return Multiply(self, _wrap(o))
    def __rmul__(self, o): return Multiply(_wrap(o), self)
    def __truediv__(self, o): return Divide(self, _wrap(o))
    def __mod__(self, o): return Remainder(self, _wrap(o))
    def __neg__(self): return UnaryMinus(self)
    def __eq__(self, o): return EqualTo(self, _wrap(o))  # type: ignore[override]
    def __ne__(self, o): return Not(EqualTo(self, _wrap(o)))  # type: ignore[override]
    def __lt__(self, o): return LessThan(self, _wrap(o))
    def __le__(self, o): return LessThanOrEqual(self, _wrap(o))
    def __gt__(self, o): return GreaterThan(self, _wrap(o))
    def __ge__(self, o): return GreaterThanOrEqual(self, _wrap(o))
    def __and__(self, o): return And(self, _wrap(o))
    def __or__(self, o): return Or(self, _wrap(o))
    def __invert__(self): return Not(self)
    def __hash__(self):
        return hash(self.fingerprint())

    def is_null(self): return IsNull(self)
    def is_not_null(self): return IsNotNull(self)
    def alias(self, name): return Alias(self, name)
    def cast(self, dtype): return Cast(self, dtype)
    def isin(self, *vals): return In(self, [_wrap(v) for v in vals])

    def substr(self, pos, length):
        """pyspark Column.substr (1-based)."""
        from spark_rapids_tpu.expr.strings import Substring
        return Substring(self, pos, length)

    # Complex-type sugar (Spark Column.getItem/getField).
    def get_item(self, key):
        from spark_rapids_tpu.expr import complex as CX
        if isinstance(key, str):
            return CX.GetMapValue(self, _wrap(key))
        return CX.GetArrayItem(self, _wrap(key))

    getItem = get_item

    def get_field(self, name: str):
        from spark_rapids_tpu.expr import complex as CX
        return CX.GetStructField(self, name)

    getField = get_field

    # Sort-order sugar (Spark Column.asc/desc family).
    def _order(self, ascending, nulls_first=None):
        from spark_rapids_tpu.plan.nodes import SortOrder
        return SortOrder(self, ascending, nulls_first)

    def asc(self): return self._order(True)
    def desc(self): return self._order(False)
    def asc_nulls_first(self): return self._order(True, True)
    def asc_nulls_last(self): return self._order(True, False)
    def desc_nulls_first(self): return self._order(False, True)
    def desc_nulls_last(self): return self._order(False, False)


def _wrap(v) -> Expression:
    return v if isinstance(v, Expression) else Literal.infer(v)


def col(name: str) -> "Col":
    return Col(name)


def lit(v) -> "Literal":
    return Literal.infer(v)


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class Col(Expression):
    """Unresolved attribute; the analyzer rewrites to BoundRef."""

    def __init__(self, name: str):
        self.name = name
        self.children = []

    def data_type(self):
        raise RuntimeError(f"unresolved column {self.name!r}")

    def _params(self):
        return self.name

    def with_children(self, children):
        return self


class BoundRef(Expression):
    def __init__(self, index: int, dtype: T.DataType, name: str = ""):
        self.index = index
        self.dtype = dtype
        self.name = name
        self.children = []

    def data_type(self):
        return self.dtype

    def _params(self):
        return f"{self.index}:{self.dtype!r}"

    def with_children(self, children):
        return self

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        return ctx.columns[self.index]

    def eval_cpu(self, cols, ansi=False) -> CpuCol:
        return cols[self.index]


class Literal(Expression):
    def __init__(self, value, dtype: T.DataType):
        self.value = value
        self.dtype = dtype
        self.children = []

    @staticmethod
    def infer(v) -> "Literal":
        import datetime
        import decimal
        if v is None:
            return Literal(None, T.NULL)
        if isinstance(v, bool):
            return Literal(v, T.BOOLEAN)
        if isinstance(v, int):
            return Literal(v, T.INT32 if -(2**31) <= v < 2**31 else T.INT64)
        if isinstance(v, float):
            return Literal(v, T.FLOAT64)
        if isinstance(v, str):
            return Literal(v, T.STRING)
        if isinstance(v, decimal.Decimal):
            sign, digits, exp = v.as_tuple()
            scale = max(0, -exp)
            return Literal(v, T.DecimalType(max(len(digits), scale + 1), scale))
        if isinstance(v, datetime.datetime):
            return Literal(v, T.TIMESTAMP)
        if isinstance(v, datetime.date):
            return Literal(v, T.DATE)
        raise TypeError(f"cannot infer literal type for {v!r}")

    def data_type(self):
        return self.dtype

    def static_range(self):
        if isinstance(self.dtype, (T.Int8Type, T.Int16Type, T.Int32Type,
                                   T.Int64Type)) and self.value is not None:
            return (int(self.value), int(self.value))
        return None

    @property
    def nullable(self):
        return self.value is None

    def _params(self):
        return f"{self.value!r}:{self.dtype!r}"

    def with_children(self, children):
        return self

    def _scalar(self):
        import datetime
        v = self.value
        if isinstance(self.dtype, T.DateType) and isinstance(v, datetime.date):
            return (v - datetime.date(1970, 1, 1)).days
        if isinstance(self.dtype, T.TimestampType) and isinstance(v, datetime.datetime):
            epoch = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
            if v.tzinfo is None:
                v = v.replace(tzinfo=datetime.timezone.utc)
            return int((v - epoch).total_seconds() * 1_000_000)
        if isinstance(self.dtype, T.DecimalType):
            import decimal
            return int(decimal.Decimal(v).scaleb(self.dtype.scale).to_integral_value())
        return v

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        cap = ctx.capacity
        if self.value is None:
            dt = self.dtype if self.dtype != T.NULL else T.NULL
            np_dt = dt.np_dtype if dt.np_dtype is not None else np.int8
            if isinstance(dt, T.StringType):
                data = {"offsets": jnp.zeros(cap + 1, jnp.int32),
                        "bytes": jnp.zeros(8, jnp.uint8)}
            else:
                data = jnp.zeros(cap, np_dt)
            return ColumnVector(dt, data, jnp.zeros(cap, jnp.bool_))
        if isinstance(self.dtype, T.StringType):
            from spark_rapids_tpu.columnar.batch import round_capacity
            bs = np.frombuffer(self.value.encode("utf-8"), np.uint8)
            blen = len(bs)
            rep = np.tile(bs, cap) if blen else np.zeros(0, np.uint8)
            buf = np.zeros(round_capacity(max(len(rep), 1)), np.uint8)
            buf[: len(rep)] = rep
            offsets = jnp.asarray((np.arange(cap + 1) * blen).astype(np.int32))
            return ColumnVector(self.dtype, {"offsets": offsets,
                                             "bytes": jnp.asarray(buf)},
                                jnp.ones(cap, jnp.bool_))
        val = self._scalar()
        data = jnp.full(cap, val, self.dtype.np_dtype)
        return ColumnVector(self.dtype, data, jnp.ones(cap, jnp.bool_))

    def eval_cpu(self, cols, ansi=False) -> CpuCol:
        n = len(cols[0].values) if cols else 0
        if self.value is None:
            np_dt = self.dtype.np_dtype if self.dtype.np_dtype is not None else np.int8
            vals = np.zeros(n, object if isinstance(self.dtype, T.StringType) else np_dt)
            return CpuCol(self.dtype, vals, np.zeros(n, np.bool_))
        if isinstance(self.dtype, T.StringType):
            return CpuCol(self.dtype, np.array([self.value] * n, object),
                          np.ones(n, np.bool_))
        return CpuCol(self.dtype, np.full(n, self._scalar(), self.dtype.np_dtype),
                      np.ones(n, np.bool_))


class SparkPartitionID(Expression):
    """spark_partition_id() (reference GpuSparkPartitionID)."""

    def __init__(self):
        self.children = []

    def data_type(self):
        return T.INT32

    def with_children(self, children):
        return self

    def eval_tpu(self, ctx):
        v = jnp.full(ctx.capacity, 0, jnp.int32) + jnp.asarray(
            ctx.partition_id, jnp.int32)
        return ColumnVector(T.INT32, v, None)

    def eval_cpu(self, cols, ansi=False):
        n = len(cols[0].values) if cols else 0
        pid = CPU_EVAL_CTX.partition_id
        return CpuCol(T.INT32, np.full(n, pid, np.int32), np.ones(n, np.bool_))


class MonotonicallyIncreasingID(Expression):
    """monotonically_increasing_id(): (partition_id << 33) + row index
    within the partition (reference GpuMonotonicallyIncreasingID; same
    layout as Spark's)."""

    def __init__(self):
        self.children = []

    def data_type(self):
        return T.INT64

    def with_children(self, children):
        return self

    def eval_tpu(self, ctx):
        base = (jnp.asarray(ctx.partition_id, jnp.int64) << jnp.int64(33)) \
            + jnp.asarray(ctx.row_base, jnp.int64)
        # ids count LIVE rows (dead rows get garbage, masked downstream)
        idx = jnp.cumsum(ctx.row_mask.astype(jnp.int64)) - 1
        return ColumnVector(T.INT64, base + idx, None)

    def eval_cpu(self, cols, ansi=False):
        n = len(cols[0].values) if cols else 0
        base = (np.int64(CPU_EVAL_CTX.partition_id) << np.int64(33)) \
            + np.int64(CPU_EVAL_CTX.row_base)
        return CpuCol(T.INT64, base + np.arange(n, dtype=np.int64),
                      np.ones(n, np.bool_))


class NullOf(Expression):
    """An all-null column with the (post-binding) type of its child — used
    by rewrites like nullif that need a typed null before names resolve."""

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self) -> T.DataType:
        return self.children[0].data_type()

    def with_children(self, children):
        return NullOf(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        return ColumnVector(c.dtype, c.data, jnp.zeros(ctx.capacity, jnp.bool_))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        return CpuCol(c.dtype, c.values, np.zeros(len(c.values), np.bool_))


class Alias(Expression):
    def __init__(self, child: Expression, name: str):
        self.children = [child]
        self.name = name

    def data_type(self):
        return self.children[0].data_type()

    def static_range(self):
        return self.children[0].static_range()

    @property
    def nullable(self):
        return self.children[0].nullable

    def _params(self):
        return self.name

    def with_children(self, children):
        return Alias(children[0], self.name)

    def eval_tpu(self, ctx):
        return self.children[0].eval_tpu(ctx)

    def eval_cpu(self, cols, ansi=False):
        return self.children[0].eval_cpu(cols, ansi)


# ---------------------------------------------------------------------------
# Helpers for null-propagating binary/unary ops
# ---------------------------------------------------------------------------

def _valid_of(col: ColumnVector, ctx: EvalCtx) -> jax.Array:
    # validity None means "valid wherever the row is live" — the live mask
    # (selection vector) is the floor, NOT arange<num_rows, because masked
    # batches have live rows at arbitrary positions.
    if col.validity is not None:
        return col.validity
    return ctx.row_mask


def _dec_shift(src: T.DataType, out: "T.DecimalType") -> int:
    """Power-of-ten rescale bringing src's unscaled values to out's scale
    (integrals are decimals of scale 0)."""
    src_scale = src.scale if isinstance(src, T.DecimalType) else 0
    return out.scale - src_scale


def _promote(l: ColumnVector, r: ColumnVector, out: T.DataType):
    if isinstance(out, T.DecimalType):
        def conv(c):
            d = c.data.astype(jnp.int64)
            sh = _dec_shift(c.dtype, out)
            return d * (10 ** sh) if sh else d
        return conv(l), conv(r)
    def conv(c):
        d = c.data if c.dtype == out else c.data.astype(out.np_dtype)
        if isinstance(c.dtype, T.DecimalType) and not isinstance(
                out, T.DecimalType):
            # decimal joining a fractional op: promote the VALUE, not the
            # unscaled integer
            d = d / np.float64(10.0 ** c.dtype.scale)
        return d
    return conv(l), conv(r)


def _promote_cpu(l: CpuCol, r: CpuCol, out: T.DataType):
    if isinstance(out, T.DecimalType):
        def conv(c):
            d = c.values.astype(np.int64)
            sh = _dec_shift(c.dtype, out)
            return d * (10 ** sh) if sh else d
        return conv(l), conv(r)
    def conv(c):
        d = c.values.astype(out.np_dtype, copy=False)
        if isinstance(c.dtype, T.DecimalType) and not isinstance(
                out, T.DecimalType):
            d = d / np.float64(10.0 ** c.dtype.scale)
        return d
    return conv(l), conv(r)


class BinaryExpression(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def with_children(self, children):
        return type(self)(children[0], children[1])


class BinaryArithmetic(BinaryExpression):
    """Null-propagating arithmetic with Spark type promotion."""

    op_tpu: Callable = None
    op_cpu: Callable = None

    def data_type(self):
        return T.common_type(self.left.data_type(), self.right.data_type())

    def eval_tpu(self, ctx):
        l = self.left.eval_tpu(ctx)
        r = self.right.eval_tpu(ctx)
        out = self.data_type()
        ld, rd = _promote(l, r, out)
        valid = _valid_of(l, ctx) & _valid_of(r, ctx)
        data = type(self).op_tpu(ld, rd)
        return ColumnVector(out, data, valid)

    def eval_cpu(self, cols, ansi=False):
        l = self.left.eval_cpu(cols, ansi)
        r = self.right.eval_cpu(cols, ansi)
        out = self.data_type()
        ld, rd = _promote_cpu(l, r, out)
        with np.errstate(all="ignore"):
            data = type(self).op_cpu(ld, rd)
        return CpuCol(out, data.astype(out.np_dtype, copy=False), l.valid & r.valid)


class Add(BinaryArithmetic):
    op_tpu = staticmethod(lambda a, b: a + b)
    op_cpu = staticmethod(lambda a, b: a + b)


class Subtract(BinaryArithmetic):
    op_tpu = staticmethod(lambda a, b: a - b)
    op_cpu = staticmethod(lambda a, b: a - b)


class Multiply(BinaryArithmetic):
    op_tpu = staticmethod(lambda a, b: a * b)
    op_cpu = staticmethod(lambda a, b: a * b)

    def data_type(self):
        lt, rt = self.left.data_type(), self.right.data_type()
        if isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType):
            if isinstance(lt, T.DecimalType) and isinstance(rt, T.DecimalType):
                # Spark: precision p1+p2+1, scale s1+s2. Beyond this
                # engine's 18-digit decimal the product computes as DOUBLE
                # (value-correct, reduced precision — documented) instead
                # of silently mis-scaling.
                if lt.scale + rt.scale > 18 \
                        or lt.precision + rt.precision + 1 > 18:
                    return T.FLOAT64
                return T.DecimalType(lt.precision + rt.precision + 1,
                                     lt.scale + rt.scale)
            dec = lt if isinstance(lt, T.DecimalType) else rt
            other = rt if dec is lt else lt
            if other.is_integral:
                # decimal x integral: scale unchanged. Mirror the
                # decimal-x-decimal overflow guard: when the integral
                # operand's digits could push the unscaled product past 18
                # digits (int64 wrap territory), compute as DOUBLE instead
                # of risking a silently wrong wrapped decimal.
                int_prec = {1: 3, 2: 5, 4: 10, 8: 19}.get(
                    np.dtype(other.np_dtype).itemsize, 19)
                if dec.precision + int_prec > 18:
                    return T.FLOAT64
                return T.DecimalType(18, dec.scale)
            return T.FLOAT64
        return T.common_type(lt, rt)

    def eval_tpu(self, ctx):
        out = self.data_type()
        if not isinstance(out, T.DecimalType):
            return super().eval_tpu(ctx)
        # decimal product: unscaled values multiply DIRECTLY (scales add)
        l = self.left.eval_tpu(ctx)
        r = self.right.eval_tpu(ctx)
        data = l.data.astype(jnp.int64) * r.data.astype(jnp.int64)
        return ColumnVector(out, data, _valid_of(l, ctx) & _valid_of(r, ctx))

    def eval_cpu(self, cols, ansi=False):
        out = self.data_type()
        if not isinstance(out, T.DecimalType):
            return super().eval_cpu(cols, ansi)
        l = self.left.eval_cpu(cols, ansi)
        r = self.right.eval_cpu(cols, ansi)
        with np.errstate(all="ignore"):
            data = l.values.astype(np.int64) * r.values.astype(np.int64)
        return CpuCol(out, data, l.valid & r.valid)


class Divide(BinaryExpression):
    """Spark `/`: result is double (fractional); div-by-zero -> null
    (non-ANSI) or error (ANSI). Reference: arithmetic.scala GpuDivide."""

    def data_type(self):
        lt, rt = self.left.data_type(), self.right.data_type()
        if isinstance(lt, T.DecimalType) or isinstance(rt, T.DecimalType):
            return T.FLOAT64  # round-1: decimal division via double
        return T.FLOAT64

    def eval_tpu(self, ctx):
        l = self.left.eval_tpu(ctx)
        r = self.right.eval_tpu(ctx)
        ld = l.data.astype(np.float64)
        rd = r.data.astype(np.float64)
        if isinstance(l.dtype, T.DecimalType):
            ld = ld / (10.0 ** l.dtype.scale)
        if isinstance(r.dtype, T.DecimalType):
            rd = rd / (10.0 ** r.dtype.scale)
        zero = rd == 0.0
        valid = _valid_of(l, ctx) & _valid_of(r, ctx)
        if ctx.ansi:
            ctx.add_error("DIVIDE_BY_ZERO", zero & valid)
        data = ld / jnp.where(zero, 1.0, rd)
        return ColumnVector(T.FLOAT64, jnp.where(zero, 0.0, data), valid & ~zero)

    def eval_cpu(self, cols, ansi=False):
        l = self.left.eval_cpu(cols, ansi)
        r = self.right.eval_cpu(cols, ansi)
        ld = l.values.astype(np.float64)
        rd = r.values.astype(np.float64)
        if isinstance(l.dtype, T.DecimalType):
            ld = ld / (10.0 ** l.dtype.scale)
        if isinstance(r.dtype, T.DecimalType):
            rd = rd / (10.0 ** r.dtype.scale)
        zero = rd == 0.0
        valid = l.valid & r.valid
        if ansi and bool((zero & valid).any()):
            raise SparkException("[DIVIDE_BY_ZERO] Division by zero")
        with np.errstate(all="ignore"):
            data = np.where(zero, 0.0, ld / np.where(zero, 1.0, rd))
        return CpuCol(T.FLOAT64, data, valid & ~zero)


class IntegralDivide(BinaryExpression):
    """Spark `div`: long division; div-by-zero -> null (non-ANSI)."""

    def data_type(self):
        return T.INT64

    def eval_tpu(self, ctx):
        l = self.left.eval_tpu(ctx)
        r = self.right.eval_tpu(ctx)
        ld = l.data.astype(np.int64)
        rd = r.data.astype(np.int64)
        zero = rd == 0
        valid = _valid_of(l, ctx) & _valid_of(r, ctx)
        if ctx.ansi:
            ctx.add_error("DIVIDE_BY_ZERO", zero & valid)
        q = _java_int_div(ld, jnp.where(zero, 1, rd))
        return ColumnVector(T.INT64, jnp.where(zero, 0, q), valid & ~zero)

    def eval_cpu(self, cols, ansi=False):
        l = self.left.eval_cpu(cols, ansi)
        r = self.right.eval_cpu(cols, ansi)
        ld = l.values.astype(np.int64)
        rd = r.values.astype(np.int64)
        zero = rd == 0
        valid = l.valid & r.valid
        if ansi and bool((zero & valid).any()):
            raise SparkException("[DIVIDE_BY_ZERO] Division by zero")
        safe = np.where(zero, 1, rd)
        with np.errstate(all="ignore"):
            q = ld // safe
            rem = ld - q * safe
            # numpy floors; Java truncates toward zero
            q = np.where((rem != 0) & ((ld < 0) != (safe < 0)), q + 1, q)
        return CpuCol(T.INT64, np.where(zero, 0, q), valid & ~zero)


def _java_int_div(a, b):
    """Truncated (toward-zero) integer division, Java semantics."""
    q = a // b
    rem = a - q * b
    fix = (rem != 0) & ((a < 0) != (b < 0))
    return jnp.where(fix, q + 1, q)


class Remainder(BinaryExpression):
    """Spark `%`: sign follows dividend (Java %); zero divisor -> null."""

    def data_type(self):
        return T.common_type(self.left.data_type(), self.right.data_type())

    def static_range(self):
        r = self.right.static_range()
        if r is None or not isinstance(self.data_type(),
                                       (T.Int8Type, T.Int16Type, T.Int32Type,
                                        T.Int64Type)):
            return None
        m = max(abs(r[0]), abs(r[1]))
        if m == 0:
            return None
        lr = self.left.static_range()
        lo = 0 if (lr is not None and lr[0] >= 0) else -(m - 1)
        return (lo, m - 1)

    def eval_tpu(self, ctx):
        l = self.left.eval_tpu(ctx)
        r = self.right.eval_tpu(ctx)
        out = self.data_type()
        ld, rd = _promote(l, r, out)
        valid = _valid_of(l, ctx) & _valid_of(r, ctx)
        if out.is_integral:
            zero = rd == 0
            if ctx.ansi:
                ctx.add_error("DIVIDE_BY_ZERO", zero & valid)
            safe = jnp.where(zero, 1, rd)
            q = _java_int_div(ld, safe)
            rem = ld - q * safe
            return ColumnVector(out, jnp.where(zero, 0, rem), valid & ~zero)
        rem = jnp.where(rd == 0, jnp.nan, ld - rd * lax.div(ld, rd).astype(ld.dtype) if False else jnp.fmod(ld, rd))
        return ColumnVector(out, rem, valid)

    def eval_cpu(self, cols, ansi=False):
        l = self.left.eval_cpu(cols, ansi)
        r = self.right.eval_cpu(cols, ansi)
        out = self.data_type()
        ld, rd = _promote_cpu(l, r, out)
        valid = l.valid & r.valid
        with np.errstate(all="ignore"):
            if out.is_integral:
                zero = rd == 0
                if ansi and bool((zero & valid).any()):
                    raise SparkException("[DIVIDE_BY_ZERO] Division by zero")
                rem = np.fmod(ld, np.where(zero, 1, rd))
                return CpuCol(out, np.where(zero, 0, rem), valid & ~zero)
            return CpuCol(out, np.fmod(ld, rd), valid)


class UnaryMinus(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return self.children[0].data_type()

    def with_children(self, children):
        return UnaryMinus(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        return ColumnVector(c.dtype, -c.data, _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        with np.errstate(all="ignore"):
            return CpuCol(c.dtype, -c.values, c.valid)


class Abs(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return self.children[0].data_type()

    def with_children(self, children):
        return Abs(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        return ColumnVector(c.dtype, jnp.abs(c.data), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        with np.errstate(all="ignore"):
            return CpuCol(c.dtype, np.abs(c.values), c.valid)


# ---------------------------------------------------------------------------
# Comparisons
# ---------------------------------------------------------------------------

def _string_eq_tpu(l: ColumnVector, r: ColumnVector) -> jax.Array:
    """Exact per-row string equality: lengths equal AND bytes equal, computed
    with a bounded while_loop over 8-byte strides. Dict-encoded pairs with
    a shared vocab short-circuit to integer code equality."""
    from spark_rapids_tpu.ops.kernels import flatten_dict_column
    if l.is_dict and r.is_dict and \
            l.data["dict_offsets"] is r.data["dict_offsets"] and \
            l.data["dict_bytes"] is r.data["dict_bytes"]:
        return l.data["codes"] == r.data["codes"]
    if l.is_dict:
        l = flatten_dict_column(l, 0)
    if r.is_dict:
        r = flatten_dict_column(r, 0)
    lo, lb = l.data["offsets"], l.data["bytes"]
    ro, rb = r.data["offsets"], r.data["bytes"]
    ll = lo[1:] - lo[:-1]
    rl = ro[1:] - ro[:-1]
    same_len = ll == rl
    maxlen = jnp.maximum(jnp.max(jnp.where(same_len, ll, 0)), 0)

    def body(state):
        i, eq = state
        p = i * 8

        def get8(raw, off):
            vals = []
            for k in range(8):
                idx = jnp.clip(off + p + k, 0, raw.shape[0] - 1)
                vals.append(jnp.where(p + k < ll, raw[idx], 0).astype(jnp.uint64) << jnp.uint64(8 * k))
            out = vals[0]
            for v in vals[1:]:
                out = out | v
            return out
        lw = get8(lb, lo[:-1])
        rw = get8(rb, ro[:-1])
        active = p < ll
        eq = eq & (~active | (lw == rw))
        return i + 1, eq

    def cond(state):
        i, _ = state
        return i * 8 < maxlen

    _, eq = lax.while_loop(cond, body, (jnp.int32(0), same_len))
    return eq


class BinaryComparison(BinaryExpression):
    op_tpu: Callable = None
    op_cpu: Callable = None

    def data_type(self):
        return T.BOOLEAN

    def _compare_tpu(self, ctx):
        l = self.left.eval_tpu(ctx)
        r = self.right.eval_tpu(ctx)
        if isinstance(l.dtype, T.StringType):
            if type(self) in (EqualTo, EqualNullSafe):
                return l, r, _string_eq_tpu(l, r)
            raise NotImplementedError("string ordering comparison on device")
        out = T.common_type(l.dtype, r.dtype)
        ld, rd = _promote(l, r, out)
        return l, r, type(self).op_tpu(ld, rd)

    def eval_tpu(self, ctx):
        l, r, cmp = self._compare_tpu(ctx)
        valid = _valid_of(l, ctx) & _valid_of(r, ctx)
        return ColumnVector(T.BOOLEAN, cmp, valid)

    def _compare_cpu(self, l: CpuCol, r: CpuCol):
        if isinstance(l.dtype, T.StringType):
            return type(self).op_cpu(l.values, r.values)
        out = T.common_type(l.dtype, r.dtype)
        ld, rd = _promote_cpu(l, r, out)
        with np.errstate(all="ignore"):
            return type(self).op_cpu(ld, rd)

    def eval_cpu(self, cols, ansi=False):
        l = self.left.eval_cpu(cols, ansi)
        r = self.right.eval_cpu(cols, ansi)
        return CpuCol(T.BOOLEAN, self._compare_cpu(l, r), l.valid & r.valid)


class EqualTo(BinaryComparison):
    op_tpu = staticmethod(lambda a, b: a == b)
    op_cpu = staticmethod(lambda a, b: a == b)


class LessThan(BinaryComparison):
    op_tpu = staticmethod(lambda a, b: a < b)
    op_cpu = staticmethod(lambda a, b: a < b)


class LessThanOrEqual(BinaryComparison):
    op_tpu = staticmethod(lambda a, b: a <= b)
    op_cpu = staticmethod(lambda a, b: a <= b)


class GreaterThan(BinaryComparison):
    op_tpu = staticmethod(lambda a, b: a > b)
    op_cpu = staticmethod(lambda a, b: a > b)


class GreaterThanOrEqual(BinaryComparison):
    op_tpu = staticmethod(lambda a, b: a >= b)
    op_cpu = staticmethod(lambda a, b: a >= b)


class EqualNullSafe(BinaryComparison):
    """<=>: null<=>null is true, never returns null."""
    op_tpu = staticmethod(lambda a, b: a == b)
    op_cpu = staticmethod(lambda a, b: a == b)

    def eval_tpu(self, ctx):
        l, r, cmp = self._compare_tpu(ctx)
        lv, rv = _valid_of(l, ctx), _valid_of(r, ctx)
        val = jnp.where(lv & rv, cmp, (~lv) & (~rv))
        return ColumnVector(T.BOOLEAN, val, jnp.ones(ctx.capacity, jnp.bool_))

    def eval_cpu(self, cols, ansi=False):
        l = self.left.eval_cpu(cols, ansi)
        r = self.right.eval_cpu(cols, ansi)
        cmp = self._compare_cpu(l, r)
        val = np.where(l.valid & r.valid, cmp, (~l.valid) & (~r.valid))
        return CpuCol(T.BOOLEAN, val, np.ones(len(val), np.bool_))


# ---------------------------------------------------------------------------
# Boolean logic (Kleene three-valued)
# ---------------------------------------------------------------------------

class And(BinaryExpression):
    def data_type(self):
        return T.BOOLEAN

    def eval_tpu(self, ctx):
        l = self.left.eval_tpu(ctx)
        r = self.right.eval_tpu(ctx)
        lv, rv = _valid_of(l, ctx), _valid_of(r, ctx)
        ld = l.data.astype(jnp.bool_)
        rd = r.data.astype(jnp.bool_)
        lfalse = lv & ~ld
        rfalse = rv & ~rd
        value = ld & rd
        valid = (lv & rv) | lfalse | rfalse
        return ColumnVector(T.BOOLEAN, value & lv & rv, valid)

    def eval_cpu(self, cols, ansi=False):
        l = self.left.eval_cpu(cols, ansi)
        r = self.right.eval_cpu(cols, ansi)
        ld = l.values.astype(np.bool_)
        rd = r.values.astype(np.bool_)
        lfalse = l.valid & ~ld
        rfalse = r.valid & ~rd
        valid = (l.valid & r.valid) | lfalse | rfalse
        return CpuCol(T.BOOLEAN, ld & rd & l.valid & r.valid, valid)


class Or(BinaryExpression):
    def data_type(self):
        return T.BOOLEAN

    def eval_tpu(self, ctx):
        l = self.left.eval_tpu(ctx)
        r = self.right.eval_tpu(ctx)
        lv, rv = _valid_of(l, ctx), _valid_of(r, ctx)
        ld = l.data.astype(jnp.bool_) & lv
        rd = r.data.astype(jnp.bool_) & rv
        valid = (lv & rv) | ld | rd
        return ColumnVector(T.BOOLEAN, ld | rd, valid)

    def eval_cpu(self, cols, ansi=False):
        l = self.left.eval_cpu(cols, ansi)
        r = self.right.eval_cpu(cols, ansi)
        ld = l.values.astype(np.bool_) & l.valid
        rd = r.values.astype(np.bool_) & r.valid
        valid = (l.valid & r.valid) | ld | rd
        return CpuCol(T.BOOLEAN, ld | rd, valid)


class Not(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.BOOLEAN

    def with_children(self, children):
        return Not(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        return ColumnVector(T.BOOLEAN, ~c.data.astype(jnp.bool_), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        return CpuCol(T.BOOLEAN, ~c.values.astype(np.bool_), c.valid)


# ---------------------------------------------------------------------------
# Null predicates / conditionals
# ---------------------------------------------------------------------------

class IsNull(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return IsNull(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        return ColumnVector(T.BOOLEAN, ~_valid_of(c, ctx), jnp.ones(ctx.capacity, jnp.bool_))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        return CpuCol(T.BOOLEAN, ~c.valid, np.ones(len(c.valid), np.bool_))


class IsNotNull(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return IsNotNull(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        return ColumnVector(T.BOOLEAN, _valid_of(c, ctx), jnp.ones(ctx.capacity, jnp.bool_))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        return CpuCol(T.BOOLEAN, c.valid.copy(), np.ones(len(c.valid), np.bool_))


class IsNaN(Expression):
    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return T.BOOLEAN

    def with_children(self, children):
        return IsNaN(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        return ColumnVector(T.BOOLEAN, jnp.isnan(c.data), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        return CpuCol(T.BOOLEAN, np.isnan(c.values.astype(np.float64)), c.valid)


class In(Expression):
    """IN list of literals (reference GpuInSet)."""

    def __init__(self, child, values: List[Expression]):
        self.children = [child] + list(values)

    def data_type(self):
        return T.BOOLEAN

    def with_children(self, children):
        return In(children[0], children[1:])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        acc = None
        for v in self.children[1:]:
            eq = EqualTo(_RawCol(c), v).eval_tpu(ctx)
            acc = eq if acc is None else Or(_RawCol(acc), _RawCol(eq)).eval_tpu(ctx)
        return acc

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        acc = None
        for v in self.children[1:]:
            eq = EqualTo(_RawCpu(c), v).eval_cpu(cols, ansi)
            acc = eq if acc is None else Or(_RawCpu(acc), _RawCpu(eq)).eval_cpu(cols, ansi)
        return acc


class _RawCol(Expression):
    """Internal: wraps an already-evaluated device column as an expression."""

    def __init__(self, col: ColumnVector):
        self.col = col
        self.children = []

    def data_type(self):
        return self.col.dtype

    def with_children(self, children):
        return self

    def eval_tpu(self, ctx):
        return self.col


class _RawCpu(Expression):
    def __init__(self, col: CpuCol):
        self.col = col
        self.children = []

    def data_type(self):
        return self.col.dtype

    def with_children(self, children):
        return self

    def eval_cpu(self, cols, ansi=False):
        return self.col


class If(Expression):
    def __init__(self, pred, then, otherwise):
        self.children = [pred, then, otherwise]

    def data_type(self):
        return T.common_type(self.children[1].data_type(), self.children[2].data_type())

    def with_children(self, children):
        return If(children[0], children[1], children[2])

    def eval_tpu(self, ctx):
        p = self.children[0].eval_tpu(ctx)
        t = self.children[1].eval_tpu(ctx)
        f = self.children[2].eval_tpu(ctx)
        out = self.data_type()
        take_then = p.data.astype(jnp.bool_) & _valid_of(p, ctx)
        if isinstance(out, T.StringType):
            return _select_strings_tpu(take_then, t, f, _valid_of(t, ctx), _valid_of(f, ctx))
        td, fd = _promote(t, f, out)
        data = jnp.where(take_then, td, fd)
        valid = jnp.where(take_then, _valid_of(t, ctx), _valid_of(f, ctx))
        return ColumnVector(out, data, valid)

    def eval_cpu(self, cols, ansi=False):
        p = self.children[0].eval_cpu(cols, ansi)
        t = self.children[1].eval_cpu(cols, ansi)
        f = self.children[2].eval_cpu(cols, ansi)
        out = self.data_type()
        take_then = p.values.astype(np.bool_) & p.valid
        if isinstance(out, T.StringType):
            vals = np.where(take_then, t.values, f.values)
        else:
            td, fd = _promote_cpu(t, f, out)
            vals = np.where(take_then, td, fd)
        valid = np.where(take_then, t.valid, f.valid)
        return CpuCol(out, vals, valid)


def _select_strings_tpu(mask, t: ColumnVector, f: ColumnVector, tv, fv) -> ColumnVector:
    """Per-row select between two string columns: build new offsets from the
    chosen lengths, then gather bytes from the chosen source."""
    from spark_rapids_tpu.ops.kernels import flatten_dict_column
    if t.is_dict:
        t = flatten_dict_column(t, 0)
    if f.is_dict:
        f = flatten_dict_column(f, 0)
    to_, tb = t.data["offsets"], t.data["bytes"]
    fo, fb = f.data["offsets"], f.data["bytes"]
    tl = to_[1:] - to_[:-1]
    fl = fo[1:] - fo[:-1]
    lens = jnp.where(mask, tl, fl)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(lens).astype(jnp.int32)])
    out_cap = max(tb.shape[0], fb.shape[0])
    b = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off, b, side="right").astype(jnp.int32) - 1,
                   0, mask.shape[0] - 1)
    off_in_row = b - new_off[row]
    tsrc = jnp.clip(to_[row] + off_in_row, 0, tb.shape[0] - 1)
    fsrc = jnp.clip(fo[row] + off_in_row, 0, fb.shape[0] - 1)
    out_b = jnp.where(mask[row], tb[tsrc], fb[fsrc])
    out_b = jnp.where(b < new_off[-1], out_b, 0).astype(jnp.uint8)
    valid = jnp.where(mask, tv, fv)
    return ColumnVector(T.STRING, {"offsets": new_off, "bytes": out_b}, valid)


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 ... ELSE e END, folded as nested If."""

    def __init__(self, branches: List[Tuple[Expression, Expression]],
                 otherwise: Optional[Expression] = None):
        self.branches = branches
        self.otherwise_expr = otherwise or Literal(None, branches[0][1].data_type()
                                                   if _resolved(branches[0][1]) else T.NULL)
        self.children = [e for b in branches for e in b] + [self.otherwise_expr]

    def _fold(self) -> Expression:
        out = self.otherwise_expr
        for p, v in reversed(self.branches):
            out = If(p, v, out)
        return out

    def data_type(self):
        return self._fold().data_type()

    def with_children(self, children):
        nb = len(self.branches)
        branches = [(children[2 * i], children[2 * i + 1]) for i in range(nb)]
        return CaseWhen(branches, children[-1])

    def eval_tpu(self, ctx):
        return self._fold().eval_tpu(ctx)

    def eval_cpu(self, cols, ansi=False):
        return self._fold().eval_cpu(cols, ansi)


def _resolved(e: Expression) -> bool:
    try:
        e.data_type()
        return True
    except Exception:
        return False


class KnownNotNull(Expression):
    """Catalyst's null-introspection wrapper (reference registers it as
    a pass-through): asserts the optimizer proved the child non-null."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return self.children[0].data_type()

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return KnownNotNull(children[0])

    def eval_tpu(self, ctx):
        return self.children[0].eval_tpu(ctx)

    def eval_cpu(self, cols, ansi=False):
        return self.children[0].eval_cpu(cols, ansi)


class KnownFloatingPointNormalized(KnownNotNull):
    """Pass-through marker: the child's NaN/-0.0 are already canonical."""

    @property
    def nullable(self):
        return self.children[0].nullable

    def with_children(self, children):
        return KnownFloatingPointNormalized(children[0])


class NormalizeNaNAndZero(Expression):
    """Canonicalize floats for grouping/join keys: -0.0 -> 0.0 and any
    NaN bit pattern -> the canonical NaN (reference
    normalizeNansAndZeros in GpuOverrides; Catalyst inserts it under
    First/aggregation keys)."""

    def __init__(self, child):
        self.children = [child]

    def data_type(self):
        return self.children[0].data_type()

    def with_children(self, children):
        return NormalizeNaNAndZero(children[0])

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        v = c.data
        # explicit compare: XLA folds v + 0.0 back to v, keeping -0.0
        v = jnp.where(v == 0, jnp.zeros((), v.dtype), v)
        v = jnp.where(jnp.isnan(v), jnp.nan, v)
        return ColumnVector(c.dtype, v, _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        v = c.values
        with np.errstate(all="ignore"):
            v = np.where(v == 0, np.zeros((), v.dtype), v)
            v = np.where(np.isnan(v), np.nan, v)
        return CpuCol(c.dtype, v, c.valid)


class AtLeastNNonNulls(Expression):
    """Catalyst's dropna predicate: true when >= n of the children are
    non-null (and, for floats, non-NaN — Spark counts NaN as missing
    here)."""

    def __init__(self, n: int, *children):
        self.n = int(n)
        self.children = list(children)

    def _params(self):
        return str(self.n)

    def data_type(self):
        return T.BOOLEAN

    @property
    def nullable(self):
        return False

    def with_children(self, children):
        return AtLeastNNonNulls(self.n, *children)

    def eval_tpu(self, ctx):
        cnt = None
        for c in self.children:
            cc = c.eval_tpu(ctx)
            ok = _valid_of(cc, ctx)
            if isinstance(cc.dtype, (T.Float32Type, T.Float64Type)):
                ok = ok & ~jnp.isnan(cc.data)
            one = ok.astype(jnp.int32)
            cnt = one if cnt is None else cnt + one
        return ColumnVector(T.BOOLEAN, cnt >= self.n,
                            jnp.ones(cnt.shape[0], jnp.bool_))

    def eval_cpu(self, cols, ansi=False):
        cnt = None
        for c in self.children:
            cc = c.eval_cpu(cols, ansi)
            ok = cc.valid
            if isinstance(cc.dtype, (T.Float32Type, T.Float64Type)):
                with np.errstate(all="ignore"):
                    ok = ok & ~np.isnan(cc.values)
            cnt = ok.astype(np.int32) if cnt is None \
                else cnt + ok.astype(np.int32)
        return CpuCol(T.BOOLEAN, cnt >= self.n,
                      np.ones(len(cnt), np.bool_))


class Coalesce(Expression):
    def __init__(self, *exprs):
        self.children = list(exprs)

    def data_type(self):
        dt = self.children[0].data_type()
        for c in self.children[1:]:
            dt = T.common_type(dt, c.data_type())
        return dt

    def with_children(self, children):
        return Coalesce(*children)

    def eval_tpu(self, ctx):
        out = self.data_type()
        acc = self.children[0].eval_tpu(ctx)
        acc_valid = _valid_of(acc, ctx)
        if not isinstance(out, T.StringType) and acc.dtype != out:
            acc = ColumnVector(out, acc.data.astype(out.np_dtype), acc_valid)
        for c in self.children[1:]:
            nxt = c.eval_tpu(ctx)
            nxt_valid = _valid_of(nxt, ctx)
            if isinstance(out, T.StringType):
                acc = _select_strings_tpu(acc_valid, acc, nxt, acc_valid, nxt_valid)
            else:
                nd = nxt.data.astype(out.np_dtype)
                acc = ColumnVector(out, jnp.where(acc_valid, acc.data, nd),
                                   acc_valid | nxt_valid)
            acc_valid = acc.validity
        return acc

    def eval_cpu(self, cols, ansi=False):
        out = self.data_type()
        acc = self.children[0].eval_cpu(cols, ansi)
        vals = acc.values if isinstance(out, T.StringType) else acc.values.astype(out.np_dtype)
        valid = acc.valid.copy()
        for c in self.children[1:]:
            nxt = c.eval_cpu(cols, ansi)
            nvals = nxt.values if isinstance(out, T.StringType) else nxt.values.astype(out.np_dtype)
            vals = np.where(valid, vals, nvals)
            valid = valid | nxt.valid
        return CpuCol(out, vals, valid)


# ---------------------------------------------------------------------------
# Cast (reference GpuCast.scala; numeric matrix for round 1, string casts in
# expr/strings.py where byte-plane rendering lives)
# ---------------------------------------------------------------------------

_INT_BOUNDS = {
    np.dtype(np.int8): (-(2 ** 7), 2 ** 7 - 1),
    np.dtype(np.int16): (-(2 ** 15), 2 ** 15 - 1),
    np.dtype(np.int32): (-(2 ** 31), 2 ** 31 - 1),
    np.dtype(np.int64): (-(2 ** 63), 2 ** 63 - 1),
}


class Cast(Expression):
    def __init__(self, child: Expression, to: T.DataType):
        self.children = [child]
        self.to = to

    def data_type(self):
        return self.to

    def _params(self):
        return repr(self.to)

    def with_children(self, children):
        return Cast(children[0], self.to)

    def eval_tpu(self, ctx):
        c = self.children[0].eval_tpu(ctx)
        src, dst = c.dtype, self.to
        valid = _valid_of(c, ctx)
        if src == dst:
            return c
        if isinstance(dst, T.StringType) or isinstance(src, T.StringType):
            from spark_rapids_tpu.expr import strings as S
            return S.cast_string_tpu(c, dst, ctx)
        if isinstance(src, T.BooleanType):
            data = c.data.astype(dst.np_dtype)
            return ColumnVector(dst, data, valid)
        if isinstance(dst, T.BooleanType):
            return ColumnVector(dst, c.data != 0, valid)
        if isinstance(dst, (T.Float32Type, T.Float64Type)):
            data = c.data.astype(dst.np_dtype)
            if isinstance(src, T.DecimalType):
                data = data / np.float64(10.0 ** src.scale)
            return ColumnVector(dst, data.astype(dst.np_dtype), valid)
        if isinstance(dst, T.DecimalType):
            return self._to_decimal_tpu(c, dst, ctx, valid)
        if isinstance(src, (T.Float32Type, T.Float64Type)) and dst.is_integral:
            lo, hi = _INT_BOUNDS[np.dtype(dst.np_dtype)]
            v = c.data.astype(np.float64)
            if ctx.ansi:
                bad = (jnp.isnan(v) | (v < lo) | (v > hi)) & valid
                ctx.add_error("CAST_OVERFLOW", bad)
            clamped = jnp.clip(jnp.where(jnp.isnan(v), 0.0, v), lo, hi)
            data = jnp.trunc(clamped).astype(dst.np_dtype)
            return ColumnVector(dst, data, valid)
        if isinstance(src, T.DecimalType) and dst.is_integral:
            v = _java_int_div(c.data, jnp.int64(10 ** src.scale))
            return ColumnVector(dst, v.astype(dst.np_dtype), valid)
        # integral/date/timestamp -> integral: Java narrowing (bit truncation)
        data = c.data.astype(np.int64)
        if isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
            days = _java_floor_div(data, 86_400_000_000)
            return ColumnVector(dst, days.astype(np.int32), valid)
        if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
            return ColumnVector(dst, data * 86_400_000_000, valid)
        if isinstance(src, T.TimestampType) and dst.is_integral:
            data = _java_floor_div(data, 1_000_000)  # ts -> seconds
        if isinstance(dst, T.TimestampType) and src.is_integral:
            return ColumnVector(dst, data * 1_000_000, valid)
        if ctx.ansi and dst.is_integral:
            lo, hi = _INT_BOUNDS[np.dtype(dst.np_dtype)]
            ctx.add_error("CAST_OVERFLOW", ((data < lo) | (data > hi)) & valid)
        return ColumnVector(dst, data.astype(dst.np_dtype), valid)

    def _to_decimal_tpu(self, c, dst, ctx, valid):
        if isinstance(c.dtype, T.DecimalType):
            shift = dst.scale - c.dtype.scale
            if shift >= 0:
                data = c.data * (10 ** shift)
            else:
                data = _round_half_up_div(c.data, 10 ** (-shift))
        elif c.dtype.is_integral:
            data = c.data.astype(np.int64) * (10 ** dst.scale)
        else:
            scaled = c.data.astype(np.float64) * (10.0 ** dst.scale)
            data = jnp.round(scaled).astype(np.int64)
        bound = 10 ** min(dst.precision, 18)
        overflow = (data <= -bound) | (data >= bound)
        if ctx.ansi:
            ctx.add_error("CAST_OVERFLOW", overflow & valid)
        return ColumnVector(dst, jnp.where(overflow, 0, data), valid & ~overflow)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        src, dst = c.dtype, self.to
        valid = c.valid
        if src == dst:
            return c
        if isinstance(dst, T.StringType) or isinstance(src, T.StringType):
            from spark_rapids_tpu.expr import strings as S
            return S.cast_string_cpu(c, dst, ansi)
        with np.errstate(all="ignore"):
            if isinstance(src, T.BooleanType):
                return CpuCol(dst, c.values.astype(dst.np_dtype), valid)
            if isinstance(dst, T.BooleanType):
                return CpuCol(dst, c.values != 0, valid)
            if isinstance(dst, (T.Float32Type, T.Float64Type)):
                vals = c.values.astype(np.float64)
                if isinstance(src, T.DecimalType):
                    vals = vals / (10.0 ** src.scale)
                return CpuCol(dst, vals.astype(dst.np_dtype), valid)
            if isinstance(dst, T.DecimalType):
                if isinstance(src, T.DecimalType):
                    shift = dst.scale - src.scale
                    if shift >= 0:
                        vals = c.values * (10 ** shift)
                    else:
                        vals = _round_half_up_div_np(c.values, 10 ** (-shift))
                elif src.is_integral:
                    vals = c.values.astype(np.int64) * (10 ** dst.scale)
                else:
                    vals = np.round(c.values.astype(np.float64) * (10.0 ** dst.scale)).astype(np.int64)
                bound = 10 ** min(dst.precision, 18)
                overflow = (vals <= -bound) | (vals >= bound)
                if ansi and bool((overflow & valid).any()):
                    raise SparkException("[CAST_OVERFLOW]")
                return CpuCol(dst, np.where(overflow, 0, vals), valid & ~overflow)
            if isinstance(src, (T.Float32Type, T.Float64Type)) and dst.is_integral:
                lo, hi = _INT_BOUNDS[np.dtype(dst.np_dtype)]
                v = c.values.astype(np.float64)
                if ansi and bool(((np.isnan(v) | (v < lo) | (v > hi)) & valid).any()):
                    raise SparkException("[CAST_OVERFLOW]")
                clamped = np.clip(np.where(np.isnan(v), 0.0, v), lo, hi)
                return CpuCol(dst, np.trunc(clamped).astype(dst.np_dtype), valid)
            if isinstance(src, T.DecimalType) and dst.is_integral:
                q = (np.abs(c.values) // (10 ** src.scale)) * np.sign(c.values)
                return CpuCol(dst, q.astype(dst.np_dtype), valid)
            data = c.values.astype(np.int64)
            if isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
                return CpuCol(dst, np.floor_divide(data, 86_400_000_000).astype(np.int32), valid)
            if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
                return CpuCol(dst, data * 86_400_000_000, valid)
            if isinstance(src, T.TimestampType) and dst.is_integral:
                data = np.floor_divide(data, 1_000_000)
            if isinstance(dst, T.TimestampType) and src.is_integral:
                return CpuCol(dst, data * 1_000_000, valid)
            if ansi and dst.is_integral:
                lo, hi = _INT_BOUNDS[np.dtype(dst.np_dtype)]
                if bool((((data < lo) | (data > hi)) & valid).any()):
                    raise SparkException("[CAST_OVERFLOW]")
            return CpuCol(dst, data.astype(dst.np_dtype), valid)


def _java_floor_div(a, b):
    return jnp.floor_divide(a, b)


def _round_half_up_div(v, d):
    """Decimal scale-down with HALF_UP rounding (Spark decimal semantics)."""
    sign = jnp.sign(v)
    av = jnp.abs(v)
    return sign * ((av + d // 2) // d)


def _round_half_up_div_np(v, d):
    sign = np.sign(v)
    av = np.abs(v)
    return sign * ((av + d // 2) // d)
