"""CPU-only expressions: functions without device kernels yet.

Reference parity: the reference's per-operator fallback keeps queries
running when an expression has no GPU implementation (RapidsMeta tagging
-> CPU operator). These expressions declare supported_on_tpu() = False so
the enclosing exec falls back to the CPU interpreter; each is a
row-function over python values. Device implementations graduate out of
this module as kernels land (the reference moved ops from CPU to cuDF the
same way, version by version).
"""
from __future__ import annotations

import datetime as _dt
import hashlib
import re as _re
from urllib.parse import quote_plus as _quote_plus, \
    unquote_plus as _unquote_plus
from typing import Callable, List, Optional

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import CpuCol, Expression, SparkException


class CpuRowFunction(Expression):
    """An expression evaluated row-wise on host (CPU backend only)."""

    #: subclasses set these
    name = "cpu_fn"
    result = T.STRING

    def __init__(self, *children, params=()):
        self.children = list(children)
        self.params = tuple(params)

    def data_type(self):
        return self.result

    def _params(self):
        return repr(self.params)

    def with_children(self, children):
        return type(self)(*children, params=self.params)

    def supported_on_tpu(self):
        return False

    def eval_tpu(self, ctx):
        raise NotImplementedError(f"{self.name} has no device kernel yet")

    def row_fn(self, *vals):
        raise NotImplementedError

    def eval_cpu(self, cols, ansi=False):
        ins = [c.eval_cpu(cols, ansi) for c in self.children]
        n = len(ins[0].values)
        valid = np.ones(n, np.bool_)
        for c in ins:
            valid = valid & c.valid
        out: List = []
        out_valid = valid.copy()
        for i in range(n):
            if not valid[i]:
                out.append(None)
                continue
            r = self.row_fn(*(c.values[i] for c in ins))
            if r is None:
                out_valid[i] = False
            out.append(r)
        if isinstance(self.result, T.StringType):
            vals = np.array(out, object)
        else:
            vals = np.array([0 if v is None else v for v in out]
                            ).astype(self.result.np_dtype)
        return CpuCol(self.result, vals, out_valid)


class Reverse(CpuRowFunction):
    name = "reverse"
    result = T.STRING

    def row_fn(self, s):
        return s[::-1] if isinstance(s, str) else s


class ConcatWs(CpuRowFunction):
    """concat_ws(sep, ...): null inputs are SKIPPED (unlike concat)."""

    name = "concat_ws"
    result = T.STRING

    def eval_cpu(self, cols, ansi=False):
        from spark_rapids_tpu.expr.strings import cast_string_cpu
        sep = self.params[0]
        ins = []
        for c in self.children:
            cc = c.eval_cpu(cols, ansi)
            if not isinstance(cc.dtype, T.StringType):
                # Spark-faithful rendering (true/false, float formatting)
                cc = cast_string_cpu(cc, T.STRING, ansi)
            ins.append(cc)
        n = len(ins[0].values)
        out = []
        for i in range(n):
            parts = [c.values[i] for c in ins
                     if c.valid[i] and c.values[i] is not None]
            out.append(sep.join(parts))
        return CpuCol(T.STRING, np.array(out, object), np.ones(n, np.bool_))


class LPad(CpuRowFunction):
    name = "lpad"
    result = T.STRING

    def row_fn(self, s):
        ln, pad = self.params
        if not isinstance(s, str):
            return s
        if ln <= 0:
            return ""  # Spark: non-positive length pads to empty
        if len(s) >= ln:
            return s[:ln]
        fill = (pad * ln)[: ln - len(s)]
        return fill + s


class RPad(LPad):
    name = "rpad"

    def row_fn(self, s):
        ln, pad = self.params
        if not isinstance(s, str):
            return s
        if ln <= 0:
            return ""
        if len(s) >= ln:
            return s[:ln]
        return s + (pad * ln)[: ln - len(s)]


class Translate(CpuRowFunction):
    name = "translate"
    result = T.STRING

    def row_fn(self, s):
        if not hasattr(self, "_table"):
            src, dst = self.params
            self._table = {ord(a): (dst[i] if i < len(dst) else None)
                           for i, a in enumerate(src)}
        return s.translate(self._table) if isinstance(s, str) else s


class SubstringIndex(CpuRowFunction):
    """substring_index(str, delim, count) (reference
    GpuSubstringIndexUtils JNI)."""

    name = "substring_index"
    result = T.STRING

    def row_fn(self, s):
        delim, count = self.params
        if not isinstance(s, str) or not delim:
            return ""
        parts = s.split(delim)
        if count > 0:
            return delim.join(parts[:count])
        if count < 0:
            return delim.join(parts[count:])
        return ""


class Md5(CpuRowFunction):
    name = "md5"
    result = T.STRING

    def row_fn(self, s):
        b = s.encode() if isinstance(s, str) else bytes(s)
        return hashlib.md5(b).hexdigest()


class Sha2(CpuRowFunction):
    name = "sha2"
    result = T.STRING

    _ALGOS = {0: hashlib.sha256, 224: hashlib.sha224, 256: hashlib.sha256,
              384: hashlib.sha384, 512: hashlib.sha512}

    def row_fn(self, s):
        algo = self._ALGOS.get(self.params[0])
        if algo is None:
            return None  # Spark: NULL for unsupported bit lengths
        b = s.encode() if isinstance(s, str) else bytes(s)
        return algo(b).hexdigest()


def _java_fmt_to_py(pattern: str) -> str:
    """Transpile the supported Java datetime-pattern subset to strftime,
    rejecting anything unhandled (the transpile-or-reject contract the
    regex layer uses): a pattern like 'd/M/yyyy' or 'EEE' must raise, not
    silently emit literal 'd/M/2024'."""
    tokens = [("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"),
              ("HH", "%H"), ("mm", "%M"), ("ss", "%S")]
    out = []
    i = 0
    while i < len(pattern):
        for j, p in tokens:
            if pattern.startswith(j, i):
                out.append(p)
                i += len(j)
                break
        else:
            ch = pattern[i]
            if ch.isalpha() or ch in "%'":
                raise SparkException(
                    f"unsupported datetime pattern {pattern!r}: "
                    f"unhandled character {ch!r}")
            out.append(ch)
            i += 1
    return "".join(out)


class DateFormat(CpuRowFunction):
    """date_format(date/ts, java-pattern-subset)."""

    name = "date_format"
    result = T.STRING

    def __init__(self, *children, params=()):
        super().__init__(*children, params=params)
        if params:
            _java_fmt_to_py(params[0])  # reject bad patterns at construction

    def _py_fmt(self):
        if not hasattr(self, "_py"):
            self._py = _java_fmt_to_py(self.params[0])
        return self._py

    def row_fn(self, v):
        src = self.children[0].data_type()
        if isinstance(src, T.TimestampType):
            d = _dt.datetime(1970, 1, 1) + _dt.timedelta(microseconds=int(v))
        else:
            d = _dt.datetime(1970, 1, 1) + _dt.timedelta(days=int(v))
        return d.strftime(self._py_fmt())


class ToDateFmt(CpuRowFunction):
    """to_date(str, fmt): parse failures yield null (non-ANSI Spark)."""

    name = "to_date"
    result = T.DATE

    def __init__(self, *children, params=()):
        super().__init__(*children, params=params)
        if params:
            _java_fmt_to_py(params[0])

    def row_fn(self, s):
        if not hasattr(self, "_py"):
            self._py = _java_fmt_to_py(self.params[0])
        try:
            d = _dt.datetime.strptime(s, self._py).date()
        except (ValueError, TypeError):
            return None
        return (d - _dt.date(1970, 1, 1)).days


class FromUnixtime(CpuRowFunction):
    name = "from_unixtime"
    result = T.STRING

    def __init__(self, *children, params=()):
        super().__init__(*children, params=params)
        if params:
            _java_fmt_to_py(params[0])

    def row_fn(self, v):
        if not hasattr(self, "_py"):
            self._py = _java_fmt_to_py(
                self.params[0] if self.params else "yyyy-MM-dd HH:mm:ss")
        return (_dt.datetime(1970, 1, 1)
                + _dt.timedelta(seconds=int(v))).strftime(self._py)


class FormatNumber(CpuRowFunction):
    name = "format_number"
    result = T.STRING

    def row_fn(self, v):
        d = self.params[0]
        return f"{float(v):,.{d}f}"


ALL_CPU_FUNCTIONS = [Reverse, ConcatWs, LPad, RPad, Translate,
                     SubstringIndex, Md5, Sha2, DateFormat, ToDateFmt,
                     FromUnixtime, FormatNumber]


# ---------------------------------------------------------------------------
# String breadth second tier (CPU rows; device kernels graduate later)
# ---------------------------------------------------------------------------

class FindInSet(CpuRowFunction):
    """find_in_set(s, csv): 1-based index of s within the comma list."""

    name = "find_in_set"
    result = T.INT32

    def row_fn(self, s, csv):
        if not isinstance(s, str) or not isinstance(csv, str):
            return None
        if "," in s:
            return 0
        parts = csv.split(",")
        try:
            return parts.index(s) + 1
        except ValueError:
            return 0


class Levenshtein(CpuRowFunction):
    name = "levenshtein"
    result = T.INT32

    def row_fn(self, a, b):
        if not isinstance(a, str) or not isinstance(b, str):
            return None
        if len(a) < len(b):
            a, b = b, a
        prev = list(range(len(b) + 1))
        for i, ca in enumerate(a, 1):
            cur = [i]
            for j, cb in enumerate(b, 1):
                cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                               prev[j - 1] + (ca != cb)))
            prev = cur
        return prev[-1]


class Base64Encode(CpuRowFunction):
    name = "base64"
    result = T.STRING

    def row_fn(self, s):
        import base64
        if isinstance(s, bytes):
            return base64.b64encode(s).decode()
        if isinstance(s, str):
            return base64.b64encode(s.encode()).decode()
        return None


class UnBase64(CpuRowFunction):
    name = "unbase64"
    result = T.STRING

    def row_fn(self, s):
        import base64
        if not isinstance(s, str):
            return None
        try:
            return base64.b64decode(s).decode("utf-8", "replace")
        except Exception:  # noqa: BLE001 - Spark: invalid input -> error/null
            return None


class FormatString(CpuRowFunction):
    """format_string(fmt, args...): java.lang.String.format subset via
    Python %-interpolation of the common conversions."""

    name = "format_string"
    result = T.STRING

    def eval_cpu(self, cols, ansi=False):
        fmt = self.params[0]
        ins = [c.eval_cpu(cols, ansi) for c in self.children]
        n = len(ins[0].values) if ins else 0
        out, ok = [], []
        for i in range(n):
            # java.util.Formatter renders null arguments as "null"
            args = tuple(
                "null" if not c.valid[i] else
                (c.values[i].item() if isinstance(c.values[i], np.generic)
                 else c.values[i]) for c in ins)
            try:
                out.append(fmt % args)
                ok.append(True)
            except (TypeError, ValueError):
                out.append(None)
                ok.append(False)
        return CpuCol(T.STRING, np.array(out, object),
                      np.asarray(ok, np.bool_))


class Elt(CpuRowFunction):
    """elt(n, s1, s2, ...): the n-th argument string (1-based); null when
    out of range (ANSI: error)."""

    name = "elt"
    result = T.STRING

    def eval_cpu(self, cols, ansi=False):
        ins = [c.eval_cpu(cols, ansi) for c in self.children]
        idx = ins[0]
        n = len(idx.values)
        out, ok = [], []
        for i in range(n):
            if not idx.valid[i]:
                out.append(None)
                ok.append(False)
                continue
            k = int(idx.values[i])
            if 1 <= k < len(ins):
                c = ins[k]
                out.append(c.values[i] if c.valid[i] else None)
                ok.append(bool(c.valid[i]))
            else:
                if ansi:
                    raise SparkException(f"elt index {k} out of range")
                out.append(None)
                ok.append(False)
        return CpuCol(T.STRING, np.array(out, object),
                      np.asarray(ok, np.bool_))


class Soundex(CpuRowFunction):
    name = "soundex"
    result = T.STRING

    _CODE = {**{c: "1" for c in "BFPV"}, **{c: "2" for c in "CGJKQSXZ"},
             **{c: "3" for c in "DT"}, "L": "4",
             **{c: "5" for c in "MN"}, "R": "6"}

    def row_fn(self, s):
        if not isinstance(s, str):
            return None
        if not s or not s[0].isalpha():
            return s
        u = s.upper()
        out = [u[0]]
        prev = self._CODE.get(u[0], "")
        for ch in u[1:]:
            code = self._CODE.get(ch, "")
            if code and code != prev:
                out.append(code)
                if len(out) == 4:
                    break
            if ch not in "HW":
                prev = code
        return "".join(out).ljust(4, "0")


class JsonTuple(CpuRowFunction):
    """json_tuple is a generator in Spark; this expression form returns
    the ARRAY of extracted fields (the DataFrame layer explodes it into
    columns). Reference GpuJsonTuple.scala."""

    name = "json_tuple"

    @property
    def result(self):
        return T.ArrayType(T.STRING)

    def data_type(self):
        return T.ArrayType(T.STRING)

    def eval_cpu(self, cols, ansi=False):
        import json
        c = self.children[0].eval_cpu(cols, ansi)
        fields = self.params
        out, ok = [], []
        for s, v in zip(c.values, c.valid):
            if not v or not isinstance(s, str):
                out.append(None)
                ok.append(False)
                continue
            try:
                obj = json.loads(s)
            except ValueError:
                obj = None
            row = []
            for f in fields:
                x = obj.get(f) if isinstance(obj, dict) else None
                if x is None:
                    row.append(None)
                elif isinstance(x, (dict, list)):
                    row.append(json.dumps(x, separators=(",", ":")))
                elif isinstance(x, bool):
                    row.append("true" if x else "false")
                else:
                    row.append(str(x))
            out.append(row)
            ok.append(True)
        return CpuCol(self.result, np.array(out, object),
                      np.asarray(ok, np.bool_))


# ---------------------------------------------------------------------------
# Binary/codec breadth tier (reference stringFunctions.scala GpuSha1/
# GpuHex family semantics, NumberConverter for conv)
# ---------------------------------------------------------------------------

class Sha1(CpuRowFunction):
    name = "sha1"
    result = T.STRING

    def row_fn(self, s):
        b = s.encode() if isinstance(s, str) else bytes(s)
        return hashlib.sha1(b).hexdigest()


class HexStr(CpuRowFunction):
    """hex(): integers render as unsigned-64 uppercase hex, strings as
    the hex of their utf-8 bytes (Spark Hex)."""

    name = "hex"
    result = T.STRING

    def row_fn(self, v):
        if isinstance(v, str):
            return v.encode().hex().upper()
        if isinstance(v, (bytes, bytearray)):
            return bytes(v).hex().upper()
        return format(int(v) & 0xFFFFFFFFFFFFFFFF, "X")


class Unhex(CpuRowFunction):
    """unhex(): odd-length input gets a leading zero nibble; any
    non-hex character makes the row NULL (Spark Unhex). The decoded
    bytes surface as a latin-1 string (the engine's binary carrier)."""

    name = "unhex"
    result = T.STRING

    def row_fn(self, s):
        if not isinstance(s, str):
            return None
        if len(s) % 2:
            s = "0" + s
        try:
            return bytes.fromhex(s).decode("latin-1")
        except ValueError:
            return None


class Bin(CpuRowFunction):
    """bin(): Long.toBinaryString — the unsigned-64 binary rendering."""

    name = "bin"
    result = T.STRING

    def row_fn(self, v):
        return format(int(v) & 0xFFFFFFFFFFFFFFFF, "b")


_CONV_DIGITS = "0123456789abcdefghijklmnopqrstuvwxyz"


class Conv(CpuRowFunction):
    """conv(num, from_base, to_base): Java NumberConverter semantics —
    case-insensitive digits, the longest valid prefix parses (empty
    prefix is NULL), overflow CLAMPS to the unsigned-64 max (Hive's
    converter, which Spark inherits), and a negative to_base renders
    the SIGNED interpretation."""

    name = "conv"
    result = T.STRING

    def row_fn(self, s):
        fb, tb = self.params
        # only TO_base may be negative (NumberConverter: fromBase must
        # be a plain radix in [2, 36])
        if not isinstance(s, str) or not (2 <= fb <= 36) \
                or not (2 <= abs(tb) <= 36):
            return None
        s = s.strip().lower()
        neg = s.startswith("-")
        if neg:
            s = s[1:]
        v, seen, umax = 0, False, (1 << 64) - 1
        for ch in s:
            d = _CONV_DIGITS.find(ch)
            if d < 0 or d >= fb:
                break
            v = min(v * fb + d, umax)
            seen = True
        if not seen:
            return None
        if neg:
            v = (-v) & 0xFFFFFFFFFFFFFFFF
        out_neg = False
        if tb < 0 and v >= 1 << 63:  # signed rendering
            v = (1 << 64) - v
            out_neg = True
        base = abs(tb)
        digits = []
        while True:
            v, r = divmod(v, base)
            digits.append(_CONV_DIGITS[r])
            if v == 0:
                break
        return ("-" if out_neg else "") + "".join(reversed(digits)).upper()


_BAD_ESCAPE = _re.compile(r"%(?![0-9a-fA-F]{2})")


class UrlEncode(CpuRowFunction):
    """url_encode(): java.net.URLEncoder form encoding (space -> '+';
    '~' IS escaped, unlike python's quote which hardcodes it safe)."""

    name = "url_encode"
    result = T.STRING

    def row_fn(self, s):
        if not isinstance(s, str):
            return None
        return _quote_plus(s, safe="*-._").replace("~", "%7E")


class UrlDecode(CpuRowFunction):
    """url_decode(): inverse form decoding; malformed percent escapes
    are an error in Spark — raised here too."""

    name = "url_decode"
    result = T.STRING

    def row_fn(self, s):
        if not isinstance(s, str):
            return None
        if _BAD_ESCAPE.search(s):
            raise SparkException(f"invalid URL escape in {s!r}")
        return _unquote_plus(s)


class RegexpExtractAll(CpuRowFunction):
    """regexp_extract_all(s, pattern, group) -> array<string> (reference
    GpuRegExpExtractAll). Invalid group index raises like Spark."""

    name = "regexp_extract_all"

    @property
    def result(self):
        from spark_rapids_tpu import types as _T
        return _T.ArrayType(_T.STRING)

    def data_type(self):
        return self.result

    def row_fn(self, s):
        import re
        pattern, idx = self.params
        if not hasattr(self, "_prog"):
            self._prog = re.compile(pattern)
            if idx < 0 or idx > self._prog.groups:
                raise SparkException(
                    f"regexp_extract_all: group {idx} out of range")
        if not isinstance(s, str):
            return None
        out = []
        for m in self._prog.finditer(s):
            g = m.group(idx)
            out.append(g if g is not None else "")
        return out

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        n = len(c.values)
        vals = np.empty(n, object)
        valid = c.valid.copy()
        for i in range(n):
            r = self.row_fn(c.values[i]) if valid[i] else None
            if r is None:
                valid[i] = False
            vals[i] = r
        return CpuCol(self.result, vals, valid)


class StructsToJson(CpuRowFunction):
    """to_json(struct|map|array) (reference GpuStructsToJson). NULL
    fields are omitted, Spark's default JacksonGenerator behavior. The
    engine carries MAP values as [key, value] pair-lists; the declared
    column type (not the python shape) picks the object rendering, so
    a map renders as a JSON object, recursively."""

    name = "to_json"
    result = T.STRING

    def row_fn(self, v):
        if v is None:
            return None
        return self._enc_typed(v, self.children[0].data_type())

    def _enc_typed(self, v, dt):
        import json
        if v is None:
            return "null"
        if isinstance(dt, T.MapType):
            items = [(k, self._enc_typed(x, dt.value)) for k, x in v
                     if x is not None]
            return "{" + ",".join(f"{json.dumps(str(k))}:{x}"
                                  for k, x in items) + "}"
        if isinstance(dt, T.ArrayType):
            return "[" + ",".join(self._enc_typed(x, dt.element)
                                  for x in v) + "]"
        if isinstance(dt, T.StructType) and isinstance(v, dict):
            fields = {f.name: f.dtype for f in dt.fields}
            items = [(k, self._enc_typed(x, fields.get(k)))
                     for k, x in v.items() if x is not None]
            return "{" + ",".join(f"{json.dumps(str(k))}:{x}"
                                  for k, x in items) + "}"
        return self._enc(v)

    def _enc(self, v):
        import json
        if isinstance(v, dict):
            items = [(k, self._enc(x)) for k, x in v.items()
                     if x is not None]
            return "{" + ",".join(f"{json.dumps(str(k))}:{x}"
                                  for k, x in items) + "}"
        if isinstance(v, (list, tuple)):
            return "[" + ",".join(
                "null" if x is None else self._enc(x) for x in v) + "]"
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, _dt.datetime):
            return json.dumps(v.isoformat())
        if isinstance(v, _dt.date):
            return json.dumps(v.isoformat())
        return json.dumps(v)


class Luhncheck(CpuRowFunction):
    """luhn_check(str): credit-card checksum validity (Spark 3.5)."""

    name = "luhn_check"
    result = T.BOOLEAN

    def row_fn(self, s):
        if not isinstance(s, str) or not s \
                or not (s.isascii() and s.isdigit()):
            return False  # ASCII digits only (Spark rejects U+0660 etc)
        total = 0
        for i, ch in enumerate(reversed(s)):
            d = ord(ch) - 48
            if i % 2:
                d *= 2
                if d > 9:
                    d -= 9
            total += d
        return total % 10 == 0
