"""Array collection operations (non-lambda).

Reference parity: sql-plugin collectionOperations.scala (GpuArrayMin/Max,
GpuSortArray, GpuSlice, GpuFlattenArray, GpuArraysOverlap, GpuArrayRemove,
GpuArrayDistinct? — the reference covers this family via cudf list ops),
GpuElementAt relatives live in expr/complex.py.

TPU-first design: every per-row set/sort operation is ONE global pass over
the flattened element plane — a lexicographic sort by (owning row, element
key) turns per-row multiset questions (distinct, membership, min/max,
sort) into segmented scans, the same count-then-compact discipline the
join uses. String elements ride the 64-bit equality-faithful normalize_key
(documented hash-collision incompat, as joins); ORDER-sensitive ops
(sort_array, array_min/max) handle fixed-width keys on device and fall
back to CPU for strings.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector
from spark_rapids_tpu.expr.core import (
    CpuCol, EvalCtx, Expression, SparkException, _valid_of, _wrap,
)
from spark_rapids_tpu.expr.complex import (
    _element_segments, _leaf_cpu_col, _cmp_child_to_row,
)


def _offsets(col: ColumnVector):
    cap = col.capacity
    off = col.data["offsets"]
    return off[:cap], off[1: cap + 1] - off[:cap]


def _elem_layout(arr: ColumnVector):
    """(child, seg, e, in_range, start) for an array column."""
    cap = arr.capacity
    off = arr.data["offsets"]
    child = arr.data["child"]
    child_cap = child.capacity
    seg = _element_segments(off[: cap + 1], cap, child_cap)
    e = jnp.arange(child_cap, dtype=jnp.int32)
    in_range = e < off[cap]
    return child, seg, e, in_range, off[:cap]


def _compact_elements(arr: ColumnVector, keep: jax.Array,
                      out_dtype: Optional[T.DataType] = None) -> ColumnVector:
    """New array column keeping elements where `keep` (stable within each
    row); offsets recomputed, child gathered (shared with hof.ArrayFilter
    semantics)."""
    from spark_rapids_tpu.ops import kernels as K
    child, seg, e, in_range, start = _elem_layout(arr)
    child_cap = child.capacity
    keep = keep & in_range
    kpre = jnp.cumsum(keep.astype(jnp.int32))
    ex = kpre - keep.astype(jnp.int32)
    kept_per_row = jax.ops.segment_sum(keep.astype(jnp.int32), seg,
                                       num_segments=arr.capacity)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(kept_per_row).astype(jnp.int32)])
    base = ex[jnp.clip(start[seg], 0, child_cap - 1)]
    dest = jnp.where(keep, new_off[seg] + (ex - base), child_cap)
    src = jnp.full(child_cap + 1, -1, jnp.int32) \
        .at[dest].set(e, mode="drop")[:child_cap]
    out_child = K.gather_column(child, src, child_cap)
    return ColumnVector(out_dtype or arr.dtype,
                        {"offsets": new_off, "child": out_child},
                        arr.validity)


def _elem_eq_key(child: ColumnVector, in_range, num_rows):
    """64-bit equality key per element + null flag (normalize_key)."""
    from spark_rapids_tpu.ops import kernels as K
    k, nulls = K.normalize_key(child, num_rows)
    return k, nulls


def _group_first_flags(seg, key64, is_null, in_range, cap, child_cap):
    """Per element: is it the FIRST occurrence of its (row, value) among
    in-range elements? Nulls form their own value group per row. One
    3-operand sort + boundary scan + scatter back to element order."""
    segK = jnp.where(in_range, seg, cap).astype(jnp.int32)
    # fold the null flag into the key (nulls sort together, distinct from
    # any value's hash with overwhelming probability is NOT enough — use a
    # separate operand so null != value exactly)
    nullk = is_null.astype(jnp.int32)
    e = jnp.arange(child_cap, dtype=jnp.int32)
    ss, nn, kk, si = jax.lax.sort((segK, nullk, key64, e), num_keys=3)
    first_sorted = jnp.concatenate([
        jnp.ones(1, jnp.bool_),
        (ss[1:] != ss[:-1]) | (nn[1:] != nn[:-1]) | (kk[1:] != kk[:-1])])
    # group id in sorted order; min element index per group = the
    # original position that "wins" (order of first occurrence)
    gid = jnp.cumsum(first_sorted.astype(jnp.int32)) - 1
    winner = jnp.full(child_cap + 1, child_cap, jnp.int32) \
        .at[jnp.where(ss < cap, gid, child_cap)].min(si, mode="drop")
    first_of_group = winner[gid]  # per sorted row
    keep_sorted = si == first_of_group
    keep = jnp.zeros(child_cap, jnp.bool_).at[si].set(keep_sorted,
                                                      mode="drop")
    return keep & in_range, (segK, nullk, key64)


def _membership_flags(a: ColumnVector, b: ColumnVector, num_rows):
    """For each element of a: does an equal element exist in the SAME ROW
    of b? Returns (present bool plane over a's elements, a_layout,
    b_has_null per row, a null-flag plane). One sort over the union."""
    a_child, a_seg, a_e, a_in, _ = _elem_layout(a)
    b_child, b_seg, b_e, b_in, _ = _elem_layout(b)
    cap = a.capacity
    ak, anull = _elem_eq_key(a_child, a_in, num_rows)
    bk, bnull = _elem_eq_key(b_child, b_in, num_rows)
    na, nb = a_child.capacity, b_child.capacity
    seg_u = jnp.concatenate([jnp.where(a_in, a_seg, cap),
                             jnp.where(b_in, b_seg, cap)]).astype(jnp.int32)
    null_u = jnp.concatenate([anull, bnull]).astype(jnp.int32)
    key_u = jnp.concatenate([ak, bk])
    side_u = jnp.concatenate([jnp.zeros(na, jnp.int32),
                              jnp.ones(nb, jnp.int32)])
    iota = jnp.arange(na + nb, dtype=jnp.int32)
    ss, nn, kk, sd, si = jax.lax.sort((seg_u, null_u, key_u, side_u, iota),
                                      num_keys=4)
    first = jnp.concatenate([
        jnp.ones(1, jnp.bool_),
        (ss[1:] != ss[:-1]) | (nn[1:] != nn[:-1]) | (kk[1:] != kk[:-1])])
    gid = jnp.cumsum(first.astype(jnp.int32)) - 1
    ngroups = na + nb
    has_b = jnp.zeros(ngroups + 1, jnp.bool_).at[
        jnp.where(ss < cap, gid, ngroups)].max(sd == 1, mode="drop")
    present_sorted = has_b[gid]
    present_u = jnp.zeros(na + nb, jnp.bool_).at[si].set(present_sorted,
                                                         mode="drop")
    b_has_null = jnp.zeros(cap, jnp.bool_).at[
        jnp.where(b_in, b_seg, cap)].max(bnull, mode="drop")
    return present_u[:na], (a_child, a_seg, a_e, a_in), b_has_null, anull


class ArrayMin(Expression):
    """array_min(arr): least non-null element (NaN > any number)."""

    _op = "min"

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self):
        return self.children[0].data_type().element

    def supported_on_tpu(self):
        et = self.children[0].data_type().element
        return not isinstance(et, (T.StringType, T.ArrayType, T.MapType,
                                   T.StructType))

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr = self.children[0].eval_tpu(ctx)
        child, seg, e, in_range, _ = _elem_layout(arr)
        cap = arr.capacity
        cv = (child.validity if child.validity is not None
              else jnp.ones(child.capacity, jnp.bool_))
        ok = in_range & cv
        et = self.data_type()
        from spark_rapids_tpu.ops import radix as R
        d = np.dtype(et.np_dtype)
        if d in (np.dtype(np.float64), np.dtype(np.float32)):
            o = R._f64_order_i64(child.data.astype(jnp.float64))
        else:
            o = child.data.astype(jnp.int64)
        init = np.iinfo(np.int64).max if self._op == "min" \
            else np.iinfo(np.int64).min
        o = jnp.where(ok, o, jnp.int64(init))
        red = (lambda t, s, v: t.at[s].min(v, mode="drop")) \
            if self._op == "min" else \
            (lambda t, s, v: t.at[s].max(v, mode="drop"))
        w = red(jnp.full(cap + 1, init, jnp.int64),
                jnp.where(ok, seg, cap), o)[:cap]
        some = jnp.zeros(cap, jnp.bool_).at[jnp.where(ok, seg, cap)].max(
            True, mode="drop")
        if d in (np.dtype(np.float64), np.dtype(np.float32)):
            vals = R._i64_order_f64(w).astype(et.np_dtype)
        else:
            vals = w.astype(et.np_dtype)
        return ColumnVector(et, vals, _valid_of(arr, ctx) & some)

    def eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        out_v, out_ok = [], []
        pick = min if self._op == "min" else max
        for v, ok in zip(arr.values, arr.valid):
            vals = [x for x in (v or []) if x is not None] \
                if ok and v is not None else []
            if not ok or v is None or not vals:
                out_v.append(None)
                out_ok.append(False)
                continue
            if any(isinstance(x, float) and np.isnan(x) for x in vals):
                nonnan = [x for x in vals if not (isinstance(x, float)
                                                  and np.isnan(x))]
                if self._op == "max" or not nonnan:
                    out_v.append(float("nan"))
                else:
                    out_v.append(pick(nonnan))
            else:
                out_v.append(pick(vals))
            out_ok.append(True)
        return _leaf_cpu_col(self.data_type(), out_v, out_ok)


class ArrayMax(ArrayMin):
    """array_max(arr)."""

    _op = "max"


class ArrayPosition(Expression):
    """array_position(arr, v): 1-based index of first match, 0 if absent,
    null if arr or v is null."""

    def __init__(self, child: Expression, value: Expression):
        self.children = [child, _wrap(value)]

    def data_type(self):
        return T.INT64

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr = self.children[0].eval_tpu(ctx)
        val = self.children[1].eval_tpu(ctx)
        child, seg, e, in_range, start = _elem_layout(arr)
        eq, both = _cmp_child_to_row(child, val, seg, ctx)
        match = eq & both & in_range
        cap = arr.capacity
        first = jnp.full(cap + 1, np.iinfo(np.int32).max, jnp.int32).at[
            jnp.where(match, seg, cap)].min(e, mode="drop")[:cap]
        found = first < np.iinfo(np.int32).max
        pos = jnp.where(found, first - start + 1, 0).astype(jnp.int64)
        valid = _valid_of(arr, ctx) & _valid_of(val, ctx)
        return ColumnVector(T.INT64, pos, valid)

    def eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        val = self.children[1].eval_cpu(cols, ansi)
        out_v, out_ok = [], []
        for (v, ok), (x, xok) in zip(zip(arr.values, arr.valid),
                                     zip(val.values, val.valid)):
            if not ok or v is None or not xok:
                out_v.append(0)
                out_ok.append(False)
                continue
            pos = 0
            for i, el in enumerate(v):
                if el is not None and el == x:
                    pos = i + 1
                    break
            out_v.append(pos)
            out_ok.append(True)
        return CpuCol(T.INT64, np.asarray(out_v, np.int64),
                      np.asarray(out_ok, np.bool_))


class ArrayRemove(Expression):
    """array_remove(arr, v): drop elements equal to v (nulls kept)."""

    def __init__(self, child: Expression, value: Expression):
        self.children = [child, _wrap(value)]

    def data_type(self):
        return self.children[0].data_type()

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr = self.children[0].eval_tpu(ctx)
        val = self.children[1].eval_tpu(ctx)
        child, seg, e, in_range, _ = _elem_layout(arr)
        eq, both = _cmp_child_to_row(child, val, seg, ctx)
        keep = ~(eq & both)
        out = _compact_elements(arr, keep & in_range)
        return ColumnVector(out.dtype, out.data,
                            _valid_of(arr, ctx) & _valid_of(val, ctx))

    def eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        val = self.children[1].eval_cpu(cols, ansi)
        out_v, out_ok = [], []
        for (v, ok), (x, xok) in zip(zip(arr.values, arr.valid),
                                     zip(val.values, val.valid)):
            if not ok or v is None or not xok:
                out_v.append(None)
                out_ok.append(False)
                continue
            out_v.append([el for el in v if el is None or el != x])
            out_ok.append(True)
        return CpuCol(self.data_type(), np.array(out_v, object),
                      np.asarray(out_ok, np.bool_))


class Slice(Expression):
    """slice(arr, start, length): 1-based; negative start counts from the
    end; start=0 errors; negative length errors."""

    def __init__(self, child: Expression, start: Expression,
                 length: Expression):
        self.children = [child, _wrap(start), _wrap(length)]

    def data_type(self):
        return self.children[0].data_type()

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        from spark_rapids_tpu.ops import kernels as K
        arr = self.children[0].eval_tpu(ctx)
        st = self.children[1].eval_tpu(ctx)
        ln = self.children[2].eval_tpu(ctx)
        child, seg, e, in_range, start = _elem_layout(arr)
        cap = arr.capacity
        _, lens = _offsets(arr)
        valid = (_valid_of(arr, ctx) & _valid_of(st, ctx)
                 & _valid_of(ln, ctx))
        s = st.data.astype(jnp.int32)
        l = ln.data.astype(jnp.int32)
        ctx.add_error("SliceStartZero", valid & (s == 0))
        ctx.add_error("SliceNegativeLength", valid & (l < 0))
        begin = jnp.where(s > 0, s - 1, lens + s)  # 0-based
        begin_c = jnp.clip(begin, 0, lens)
        out_len = jnp.clip(jnp.minimum(l, lens - begin_c), 0, None)
        out_len = jnp.where(valid & (begin >= 0) & (begin < lens),
                            out_len, 0)
        new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(out_len).astype(jnp.int32)])
        child_cap = child.capacity
        oe = jnp.arange(child_cap, dtype=jnp.int32)
        oseg = jnp.clip(jnp.searchsorted(new_off, oe, side="right")
                        .astype(jnp.int32) - 1, 0, cap - 1)
        o_in = oe < new_off[cap]
        src = jnp.where(
            o_in, start[oseg] + begin_c[oseg] + (oe - new_off[oseg]), -1)
        out_child = K.gather_column(child, src, child_cap)
        return ColumnVector(self.data_type(),
                            {"offsets": new_off, "child": out_child}, valid)

    def eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        st = self.children[1].eval_cpu(cols, ansi)
        ln = self.children[2].eval_cpu(cols, ansi)
        out_v, out_ok = [], []
        for (v, ok), (s, sok), (l, lok) in zip(
                zip(arr.values, arr.valid), zip(st.values, st.valid),
                zip(ln.values, ln.valid)):
            if not ok or v is None or not sok or not lok:
                out_v.append(None)
                out_ok.append(False)
                continue
            s, l = int(s), int(l)
            if s == 0:
                raise SparkException("Unexpected value for start in slice: "
                                     "SQL array indices start at 1")
            if l < 0:
                raise SparkException(
                    f"Unexpected value for length in slice: {l}")
            b = s - 1 if s > 0 else len(v) + s
            out_v.append(v[b: b + l] if b >= 0 else [])
            out_ok.append(True)
        return CpuCol(self.data_type(), np.array(out_v, object),
                      np.asarray(out_ok, np.bool_))


class SortArray(Expression):
    """sort_array(arr, asc): nulls first when ascending, last when
    descending (Spark semantics)."""

    def __init__(self, child: Expression, asc: bool = True):
        self.children = [child]
        self.asc = bool(asc)

    def _params(self):
        return str(self.asc)

    def with_children(self, children):
        return SortArray(children[0], self.asc)

    def data_type(self):
        return self.children[0].data_type()

    def supported_on_tpu(self):
        et = self.children[0].data_type().element
        return not isinstance(et, (T.StringType, T.ArrayType, T.MapType,
                                   T.StructType))

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        from spark_rapids_tpu.ops import kernels as K
        from spark_rapids_tpu.ops import radix as R
        arr = self.children[0].eval_tpu(ctx)
        child, seg, e, in_range, start = _elem_layout(arr)
        child_cap = child.capacity
        cap = arr.capacity
        et = self.data_type().element
        d = np.dtype(et.np_dtype)
        if d in (np.dtype(np.float64), np.dtype(np.float32)):
            o = R._f64_order_i64(child.data.astype(jnp.float64))
        else:
            o = child.data.astype(jnp.int64)
        if not self.asc:
            o = ~o  # descending: monotone bitwise reversal (no overflow)
        cv = (child.validity if child.validity is not None
              else jnp.ones(child_cap, jnp.bool_))
        # Spark puts nulls FIRST ascending, LAST descending: in the
        # ascending sort of the (possibly reversed) key that is -inf for
        # asc and +inf for desc
        null_key = jnp.int64(np.iinfo(np.int64).min if self.asc
                             else np.iinfo(np.int64).max)
        o = jnp.where(cv, o, null_key)
        segK = jnp.where(in_range, seg, cap).astype(jnp.int32)
        iota = jnp.arange(child_cap, dtype=jnp.int32)
        ss, oo, si = jax.lax.sort((segK, o, iota), num_keys=2)
        # sorted elements land back contiguously: position i of the sorted
        # union IS the destination (rows are contiguous in both layouts)
        out_child = K.gather_column(child, jnp.where(ss < cap, si, -1),
                                    child_cap)
        return ColumnVector(self.data_type(),
                            {"offsets": arr.data["offsets"],
                             "child": out_child}, arr.validity)

    def eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        out_v = []
        for v, ok in zip(arr.values, arr.valid):
            if not ok or v is None:
                out_v.append(None)
                continue
            nn = [x for x in v if x is not None]
            nulls = [None] * (len(v) - len(nn))
            key = (lambda x: (np.isnan(x), x)) \
                if nn and isinstance(nn[0], float) else (lambda x: x)
            nn.sort(key=key, reverse=not self.asc)
            out_v.append(nulls + nn if self.asc else nn + nulls)
        return CpuCol(self.data_type(), np.array(out_v, object),
                      arr.valid.copy())


class Flatten(Expression):
    """flatten(arr<arr<T>>): null if the outer row or ANY inner array is
    null."""

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self):
        return self.children[0].data_type().element

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr = self.children[0].eval_tpu(ctx)
        inner = arr.data["child"]  # array<T> column over mid elements
        cap = arr.capacity
        off = arr.data["offsets"]
        start = off[:cap]
        end = off[1: cap + 1]
        ioff = inner.data["offsets"]
        mid_cap = inner.capacity
        # out offsets: inner_off at each outer boundary
        new_off = ioff[jnp.clip(off[: cap + 1], 0, mid_cap)]
        new_off = new_off - new_off[0]
        mid_valid = (inner.validity if inner.validity is not None
                     else jnp.ones(mid_cap, jnp.bool_))
        seg = _element_segments(off[: cap + 1], cap, mid_cap)
        m = jnp.arange(mid_cap, dtype=jnp.int32)
        m_in = m < off[cap]
        has_null_inner = jnp.zeros(cap, jnp.bool_).at[
            jnp.where(m_in, seg, cap)].max(~mid_valid, mode="drop")
        valid = _valid_of(arr, ctx) & ~has_null_inner
        return ColumnVector(self.data_type(),
                            {"offsets": new_off.astype(jnp.int32),
                             "child": inner.data["child"]}, valid)

    def eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        out_v, out_ok = [], []
        for v, ok in zip(arr.values, arr.valid):
            if not ok or v is None or any(x is None for x in v):
                out_v.append(None)
                out_ok.append(False)
                continue
            out_v.append([el for sub in v for el in sub])
            out_ok.append(True)
        return CpuCol(self.data_type(), np.array(out_v, object),
                      np.asarray(out_ok, np.bool_))


class ArrayDistinct(Expression):
    """array_distinct(arr): first-occurrence order; at most one null kept.
    String elements use the 64-bit equality hash (documented incompat)."""

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self):
        return self.children[0].data_type()

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr = self.children[0].eval_tpu(ctx)
        child, seg, e, in_range, _ = _elem_layout(arr)
        k, nulls = _elem_eq_key(child, in_range, ctx.num_rows)
        keep, _ = _group_first_flags(seg, k, nulls, in_range, arr.capacity,
                                     child.capacity)
        return _compact_elements(arr, keep)

    def eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        out_v = []
        for v, ok in zip(arr.values, arr.valid):
            if not ok or v is None:
                out_v.append(None)
                continue
            seen, row = set(), []
            saw_null = False
            for el in v:
                if el is None:
                    if not saw_null:
                        saw_null = True
                        row.append(None)
                elif el not in seen:
                    seen.add(el)
                    row.append(el)
            out_v.append(row)
        return CpuCol(self.data_type(), np.array(out_v, object),
                      arr.valid.copy())


class _ArraySetBase(Expression):
    """Shared union/intersect/except machinery."""

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    def data_type(self):
        lt = self.children[0].data_type()
        rt = self.children[1].data_type()
        return T.ArrayType(T.common_type(lt.element, rt.element))

    def _cpu_rows(self, cols, ansi):
        a = self.children[0].eval_cpu(cols, ansi)
        b = self.children[1].eval_cpu(cols, ansi)
        out_v, out_ok = [], []
        for (av, aok), (bv, bok) in zip(zip(a.values, a.valid),
                                        zip(b.values, b.valid)):
            if not aok or av is None or not bok or bv is None:
                out_v.append(None)
                out_ok.append(False)
                continue
            out_v.append(self._combine(av, bv))
            out_ok.append(True)
        return CpuCol(self.data_type(), np.array(out_v, object),
                      np.asarray(out_ok, np.bool_))

    eval_cpu = _cpu_rows

    @staticmethod
    def _dedup(vals):
        seen, out, saw_null = set(), [], False
        for el in vals:
            if el is None:
                if not saw_null:
                    saw_null = True
                    out.append(None)
            elif el not in seen:
                seen.add(el)
                out.append(el)
        return out


class ArrayUnion(_ArraySetBase):
    """array_union(a, b): distinct elements of a then b."""

    def _combine(self, av, bv):
        return self._dedup(list(av) + list(bv))

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        from spark_rapids_tpu.ops import kernels as K
        a = self.children[0].eval_tpu(ctx)
        b = self.children[1].eval_tpu(ctx)
        # concat per row, then distinct: build the concatenated array
        # column (a's elements then b's within each row), reusing concat
        # offsets arithmetic.
        cat = _concat_arrays_tpu(a, b, ctx, self.data_type())
        child, seg, e, in_range, _ = _elem_layout(cat)
        k, nulls = _elem_eq_key(child, in_range, ctx.num_rows)
        keep, _ = _group_first_flags(seg, k, nulls, in_range, cat.capacity,
                                     child.capacity)
        out = _compact_elements(cat, keep, self.data_type())
        valid = _valid_of(a, ctx) & _valid_of(b, ctx)
        return ColumnVector(out.dtype, out.data, valid)


class ArrayIntersect(_ArraySetBase):
    """array_intersect(a, b): distinct elements of a present in b."""

    def _combine(self, av, bv):
        bs = set(x for x in bv if x is not None)
        bnull = any(x is None for x in bv)
        return self._dedup([x for x in av
                            if (x is None and bnull)
                            or (x is not None and x in bs)])

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        a = self.children[0].eval_tpu(ctx)
        b = self.children[1].eval_tpu(ctx)
        present, (a_child, a_seg, a_e, a_in), b_has_null, anull = \
            _membership_flags(a, b, ctx.num_rows)
        k, nulls = _elem_eq_key(a_child, a_in, ctx.num_rows)
        first, _ = _group_first_flags(a_seg, k, nulls, a_in, a.capacity,
                                      a_child.capacity)
        keep = first & jnp.where(nulls, b_has_null[jnp.clip(
            a_seg, 0, a.capacity - 1)], present)
        out = _compact_elements(a, keep, self.data_type())
        valid = _valid_of(a, ctx) & _valid_of(b, ctx)
        return ColumnVector(out.dtype, out.data, valid)


class ArrayExcept(_ArraySetBase):
    """array_except(a, b): distinct elements of a NOT present in b."""

    def _combine(self, av, bv):
        bs = set(x for x in bv if x is not None)
        bnull = any(x is None for x in bv)
        return self._dedup([x for x in av
                            if (x is None and not bnull)
                            or (x is not None and x not in bs)])

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        a = self.children[0].eval_tpu(ctx)
        b = self.children[1].eval_tpu(ctx)
        present, (a_child, a_seg, a_e, a_in), b_has_null, anull = \
            _membership_flags(a, b, ctx.num_rows)
        k, nulls = _elem_eq_key(a_child, a_in, ctx.num_rows)
        first, _ = _group_first_flags(a_seg, k, nulls, a_in, a.capacity,
                                      a_child.capacity)
        keep = first & jnp.where(nulls, ~b_has_null[jnp.clip(
            a_seg, 0, a.capacity - 1)], ~present)
        out = _compact_elements(a, keep, self.data_type())
        valid = _valid_of(a, ctx) & _valid_of(b, ctx)
        return ColumnVector(out.dtype, out.data, valid)


class ArraysOverlap(Expression):
    """arrays_overlap(a, b): true if a common non-null element exists;
    otherwise null if either side has a null element (and both non-empty);
    else false."""

    def __init__(self, left: Expression, right: Expression):
        self.children = [left, right]

    def data_type(self):
        return T.BOOLEAN

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        a = self.children[0].eval_tpu(ctx)
        b = self.children[1].eval_tpu(ctx)
        present, (a_child, a_seg, a_e, a_in), b_has_null, anull = \
            _membership_flags(a, b, ctx.num_rows)
        cap = a.capacity
        segc = jnp.where(a_in, a_seg, cap)
        common = jnp.zeros(cap, jnp.bool_).at[segc].max(
            present & ~anull, mode="drop")
        a_has_null = jnp.zeros(cap, jnp.bool_).at[segc].max(anull,
                                                            mode="drop")
        _, alens = _offsets(a)
        _, blens = _offsets(b)
        nonempty = (alens > 0) & (blens > 0)
        unknown = nonempty & (a_has_null | b_has_null) & ~common
        valid = _valid_of(a, ctx) & _valid_of(b, ctx) & ~unknown
        return ColumnVector(T.BOOLEAN, common, valid)

    def eval_cpu(self, cols, ansi=False):
        a = self.children[0].eval_cpu(cols, ansi)
        b = self.children[1].eval_cpu(cols, ansi)
        out_v, out_ok = [], []
        for (av, aok), (bv, bok) in zip(zip(a.values, a.valid),
                                        zip(b.values, b.valid)):
            if not aok or av is None or not bok or bv is None:
                out_v.append(False)
                out_ok.append(False)
                continue
            bs = set(x for x in bv if x is not None)
            common = any(x is not None and x in bs for x in av)
            has_null = (any(x is None for x in av)
                        or any(x is None for x in bv))
            unknown = (len(av) > 0 and len(bv) > 0 and has_null
                       and not common)
            out_v.append(common)
            out_ok.append(not unknown)
        return CpuCol(T.BOOLEAN, np.asarray(out_v, np.bool_),
                      np.asarray(out_ok, np.bool_))


def _concat_arrays_tpu(a: ColumnVector, b: ColumnVector, ctx,
                       out_t: T.DataType) -> ColumnVector:
    """Row-wise array concat: a's elements then b's. Child capacity is the
    sum of both child planes (static)."""
    from spark_rapids_tpu.ops import kernels as K
    cap = a.capacity
    _, alens = _offsets(a)
    _, blens = _offsets(b)
    astart = a.data["offsets"][:cap]
    bstart = b.data["offsets"][:cap]
    olen = alens + blens
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(olen).astype(jnp.int32)])
    a_child, b_child = a.data["child"], b.data["child"]
    out_cap = a_child.capacity + b_child.capacity
    e = jnp.arange(out_cap, dtype=jnp.int32)
    seg = jnp.clip(jnp.searchsorted(new_off, e, side="right")
                   .astype(jnp.int32) - 1, 0, cap - 1)
    o_in = e < new_off[cap]
    j = e - new_off[seg]
    from_a = j < alens[seg]
    a_idx = jnp.where(o_in & from_a, astart[seg] + j, -1)
    b_idx = jnp.where(o_in & ~from_a, bstart[seg] + (j - alens[seg]), -1)
    av = K.gather_column(a_child, a_idx, a_child.capacity)
    bv = K.gather_column(b_child, b_idx, b_child.capacity)
    et = out_t.element
    data = jnp.where(from_a, av.data.astype(et.np_dtype),
                     bv.data.astype(et.np_dtype)) \
        if not a_child.is_string else None
    if data is None:
        raise NotImplementedError("string array concat on device")
    va = av.validity if av.validity is not None else o_in
    vb = bv.validity if bv.validity is not None else o_in
    valid = jnp.where(from_a, va, vb) & o_in
    child = ColumnVector(et, data, valid)
    return ColumnVector(out_t, {"offsets": new_off, "child": child}, None)


# ---------------------------------------------------------------------------
# CPU-tier collection constructors (device kernels graduate later; the
# reference keeps these on the JNI list-ops surface)
# ---------------------------------------------------------------------------

def _obj_array(rows):
    """Object ndarray that NEVER collapses equal-length rows into a 2-D
    array (both np.array(rows, object) and arr[:] = rows do when row
    lengths happen to match)."""
    arr = np.empty(len(rows), object)
    for i, r in enumerate(rows):
        arr[i] = r
    return arr


class _CpuCollection(Expression):
    def supported_on_tpu(self):
        return False

    def eval_tpu(self, ctx):
        raise NotImplementedError(f"{type(self).__name__} runs on CPU")


class ArrayRepeat(_CpuCollection):
    """array_repeat(v, n)."""

    def __init__(self, value: Expression, count: Expression):
        self.children = [_wrap(value), _wrap(count)]

    def data_type(self):
        return T.ArrayType(self.children[0].data_type())

    def eval_cpu(self, cols, ansi=False):
        v = self.children[0].eval_cpu(cols, ansi)
        n = self.children[1].eval_cpu(cols, ansi)
        out, ok = [], []
        for (val, vok), (cnt, cok) in zip(zip(v.values, v.valid),
                                          zip(n.values, n.valid)):
            if not cok:
                out.append(None)
                ok.append(False)
                continue
            c = max(int(cnt), 0)
            val = val.item() if isinstance(val, np.generic) else val
            out.append([val if vok else None] * c)
            ok.append(True)
        return CpuCol(self.data_type(), _obj_array(out),
                      np.asarray(ok, np.bool_))


class ArrayJoin(_CpuCollection):
    """array_join(arr, sep[, nullReplacement])."""

    def __init__(self, child: Expression, sep: str,
                 null_replacement: Optional[str] = None):
        self.children = [child]
        self.sep = sep
        self.null_replacement = null_replacement

    def _params(self):
        return f"{self.sep!r},{self.null_replacement!r}"

    def with_children(self, children):
        return ArrayJoin(children[0], self.sep, self.null_replacement)

    def data_type(self):
        return T.STRING

    def eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        out = []
        for v, ok in zip(arr.values, arr.valid):
            if not ok or v is None:
                out.append(None)
                continue
            parts = []
            for el in v:
                if el is None:
                    if self.null_replacement is not None:
                        parts.append(self.null_replacement)
                else:
                    parts.append(el if isinstance(el, str) else str(el))
            out.append(self.sep.join(parts))
        return CpuCol(T.STRING, np.array(out, object), arr.valid.copy())


class ArraysZip(_CpuCollection):
    """arrays_zip(a, b, ...) -> array<struct<...>> (None-padded)."""

    def __init__(self, children, names=None):
        self.children = list(children)
        self.names = list(names) if names else \
            [str(i) for i in range(len(self.children))]

    def _params(self):
        return ",".join(self.names)

    def with_children(self, children):
        return ArraysZip(children, self.names)

    def data_type(self):
        fields = tuple(
            T.StructField(n, c.data_type().element)
            for n, c in zip(self.names, self.children))
        return T.ArrayType(T.StructType(fields))

    def eval_cpu(self, cols, ansi=False):
        ins = [c.eval_cpu(cols, ansi) for c in self.children]
        n = len(ins[0].values)
        out, ok = [], []
        for i in range(n):
            if not all(c.valid[i] and c.values[i] is not None for c in ins):
                out.append(None)
                ok.append(False)
                continue
            rows = [c.values[i] for c in ins]
            ln = max(len(r) for r in rows) if rows else 0
            out.append([{nm: (r[j] if j < len(r) else None)
                         for nm, r in zip(self.names, rows)}
                        for j in range(ln)])
            ok.append(True)
        return CpuCol(self.data_type(), _obj_array(out),
                      np.asarray(ok, np.bool_))


class MapEntries(Expression):
    """map_entries(m) -> array<struct<key,value>> — device: the map's
    planes ARE the answer (offsets + key/value children re-labelled)."""

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self):
        mt = self.children[0].data_type()
        return T.ArrayType(T.StructType((
            T.StructField("key", mt.key, False),
            T.StructField("value", mt.value))))

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        m = self.children[0].eval_tpu(ctx)
        st = self.data_type().element
        child = ColumnVector(st, {"children": [m.data["keys"],
                                               m.data["values"]]}, None)
        return ColumnVector(self.data_type(),
                            {"offsets": m.data["offsets"], "child": child},
                            m.validity)

    def eval_cpu(self, cols, ansi=False):
        m = self.children[0].eval_cpu(cols, ansi)
        out = [None if (not ok or v is None)
               else [{"key": k, "value": vv} for k, vv in v]
               for v, ok in zip(m.values, m.valid)]
        return CpuCol(self.data_type(), _obj_array(out),
                      m.valid.copy())


class MapConcat(_CpuCollection):
    """map_concat(m1, m2, ...): last-wins duplicate handling is an
    EXCEPTION in Spark's default policy — mirrored here."""

    def __init__(self, children):
        self.children = list(children)

    def with_children(self, children):
        return MapConcat(children)

    def data_type(self):
        return self.children[0].data_type()

    def eval_cpu(self, cols, ansi=False):
        ins = [c.eval_cpu(cols, ansi) for c in self.children]
        n = len(ins[0].values)
        out, ok = [], []
        for i in range(n):
            if not all(c.valid[i] and c.values[i] is not None for c in ins):
                out.append(None)
                ok.append(False)
                continue
            seen = set()
            entries = []
            for c in ins:
                for k, v in c.values[i]:
                    if k in seen:
                        raise SparkException(f"Duplicate map key {k}")
                    seen.add(k)
                    entries.append((k, v))
            out.append(entries)
            ok.append(True)
        return CpuCol(self.data_type(), _obj_array(out),
                      np.asarray(ok, np.bool_))


class MapFromArrays(_CpuCollection):
    """map_from_arrays(keys, values)."""

    def __init__(self, keys: Expression, values: Expression):
        self.children = [keys, values]

    def data_type(self):
        return T.MapType(self.children[0].data_type().element,
                         self.children[1].data_type().element)

    def eval_cpu(self, cols, ansi=False):
        ks = self.children[0].eval_cpu(cols, ansi)
        vs = self.children[1].eval_cpu(cols, ansi)
        out, ok = [], []
        for (k, kok), (v, vok) in zip(zip(ks.values, ks.valid),
                                      zip(vs.values, vs.valid)):
            if not kok or k is None or not vok or v is None:
                out.append(None)
                ok.append(False)
                continue
            if len(k) != len(v):
                raise SparkException(
                    "map_from_arrays: key and value arrays differ in length")
            if any(x is None for x in k):
                raise SparkException("Cannot use null as map key")
            seen = set()
            for x in k:
                xx = x.item() if isinstance(x, np.generic) else x
                if xx in seen:
                    raise SparkException(f"Duplicate map key {xx}")
                seen.add(xx)
            out.append(list(zip(k, v)))
            ok.append(True)
        return CpuCol(self.data_type(), _obj_array(out),
                      np.asarray(ok, np.bool_))


class StrToMap(_CpuCollection):
    """str_to_map(s, pairDelim, keyValueDelim)."""

    def __init__(self, child: Expression, pair_delim: str = ",",
                 kv_delim: str = ":"):
        self.children = [child]
        self.pair_delim = pair_delim
        self.kv_delim = kv_delim

    def _params(self):
        return f"{self.pair_delim!r},{self.kv_delim!r}"

    def with_children(self, children):
        return StrToMap(children[0], self.pair_delim, self.kv_delim)

    def data_type(self):
        return T.MapType(T.STRING, T.STRING)

    def eval_cpu(self, cols, ansi=False):
        import re
        c = self.children[0].eval_cpu(cols, ansi)
        pd = re.compile(self.pair_delim)
        kd = re.compile(self.kv_delim)
        out = []
        for s, ok in zip(c.values, c.valid):
            if not ok or not isinstance(s, str):
                out.append(None)
                continue
            entries = []
            seen = set()
            # Spark treats both delimiters as REGEXES
            for pair in pd.split(s):
                kv = kd.split(pair, maxsplit=1)
                k = kv[0]
                v = kv[1] if len(kv) > 1 else None
                if k in seen:
                    raise SparkException(f"Duplicate map key {k!r}")
                seen.add(k)
                entries.append((k, v))
            out.append(entries)
        return CpuCol(self.data_type(), _obj_array(out),
                      c.valid.copy())
