"""Complex-type expressions: arrays, structs, maps.

Reference parity: sql-plugin complexTypeExtractors.scala (GetArrayItem,
GetStructField, GetMapValue, ElementAt), complexTypeCreator.scala
(CreateArray), collectionOperations.scala (Size, ArrayContains,
SortArray...), GpuGenerateExec.scala expressions (Explode/PosExplode
markers live here; the exec is exec/tpu_nodes.GenerateExec).

TPU-first design: nested columns are offsets+child-plane pytrees
(columnar/batch.py). Extraction ops are segment gathers over static
capacities; per-row element reductions (contains, map lookup) are
scatter-min/any over an element->row segment map — no per-row loops, no
dynamic shapes.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector
from spark_rapids_tpu.expr.core import (
    CpuCol, EvalCtx, Expression, Literal, SparkException, _valid_of, _wrap,
)


def _offsets_view(col: ColumnVector):
    cap = col.capacity
    off = col.data["offsets"]
    return off[:cap], off[1: cap + 1] - off[:cap]


def _element_segments(off: jax.Array, cap: int, child_cap: int) -> jax.Array:
    """Element index -> owning row index (elements past the last offset
    clip to the final row; callers mask them via an in-range check)."""
    e = jnp.arange(child_cap, dtype=jnp.int32)
    seg = jnp.searchsorted(off, e, side="right").astype(jnp.int32) - 1
    return jnp.clip(seg, 0, cap - 1)


def _gather_child(child: ColumnVector, pos: jax.Array) -> ColumnVector:
    from spark_rapids_tpu.ops import kernels as K
    return K.gather_column(child, pos, child.capacity)


def _cmp_child_to_row(child: ColumnVector, row_col: ColumnVector,
                      seg: jax.Array, ctx: EvalCtx):
    """Per-element equality between child[e] and row_col[seg[e]].
    Returns (eq bool plane, both-valid bool plane) over child capacity."""
    from spark_rapids_tpu.ops import kernels as K
    row_at_e = K.gather_column(row_col, seg, row_col.capacity)
    cv = (child.validity if child.validity is not None
          else jnp.ones(child.capacity, jnp.bool_))
    rv = (row_at_e.validity if row_at_e.validity is not None
          else jnp.ones(child.capacity, jnp.bool_))
    if isinstance(child.dtype, T.StringType):
        from spark_rapids_tpu.expr.core import _string_eq_tpu
        eq = _string_eq_tpu(child, row_at_e)
    else:
        l = child.data
        r = row_at_e.data
        out = T.common_type(child.dtype, row_at_e.dtype)
        eq = (l.astype(out.np_dtype) == r.astype(out.np_dtype))
    return eq, cv & rv


class Size(Expression):
    """size(array|map). Modern Spark semantics (legacySizeOfNull=false):
    null input -> null."""

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self):
        return T.INT32

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        c = self.children[0].eval_tpu(ctx)
        _, lens = _offsets_view(c)
        return ColumnVector(T.INT32, lens.astype(jnp.int32), _valid_of(c, ctx))

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        vals = np.array([len(v) if ok and v is not None else 0
                         for v, ok in zip(c.values, c.valid)], np.int32)
        return CpuCol(T.INT32, vals, c.valid.copy())


class GetArrayItem(Expression):
    """arr[i]: 0-based; null when out of bounds (ANSI: error)."""

    def __init__(self, child: Expression, ordinal: Expression):
        self.children = [child, _wrap(ordinal)]

    def data_type(self):
        return self.children[0].data_type().element

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr = self.children[0].eval_tpu(ctx)
        idx = self.children[1].eval_tpu(ctx)
        start, lens = _offsets_view(arr)
        child = arr.data["child"]
        i = idx.data.astype(jnp.int32)
        both = _valid_of(arr, ctx) & _valid_of(idx, ctx)
        in_b = (i >= 0) & (i < lens)
        if ctx.ansi:
            ctx.add_error("ArrayIndexOutOfBounds", both & ~in_b)
        ok = both & in_b
        pos = jnp.where(ok, jnp.clip(start + i, 0, child.capacity - 1), -1)
        out = _gather_child(child, pos)
        return ColumnVector(out.dtype, out.data, out.validity,
                            dict_unique=out.dict_unique)

    def eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        idx = self.children[1].eval_cpu(cols, ansi)
        return _extract_cpu(self.data_type(), arr, idx, base=0, ansi=ansi)


class ElementAt(Expression):
    """element_at(array, i): 1-based, negative counts from the end, index 0
    always errors. element_at(map, key): value or null."""

    def __init__(self, child: Expression, key: Expression):
        self.children = [child, _wrap(key)]

    def data_type(self):
        dt = self.children[0].data_type()
        if isinstance(dt, T.MapType):
            return dt.value
        return dt.element

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        c = self.children[0].eval_tpu(ctx)
        if isinstance(c.dtype, T.MapType):
            return _map_lookup_tpu(c, self.children[1].eval_tpu(ctx), ctx)
        idx = self.children[1].eval_tpu(ctx)
        start, lens = _offsets_view(c)
        child = c.data["child"]
        i = idx.data.astype(jnp.int32)
        both = _valid_of(c, ctx) & _valid_of(idx, ctx)
        ctx.add_error("ElementAtIndexZero", both & (i == 0))
        eff = jnp.where(i > 0, i - 1, lens + i)
        in_b = (eff >= 0) & (eff < lens)
        if ctx.ansi:
            ctx.add_error("ArrayIndexOutOfBounds", both & (i != 0) & ~in_b)
        ok = both & in_b & (i != 0)
        pos = jnp.where(ok, jnp.clip(start + eff, 0, child.capacity - 1), -1)
        return _gather_child(child, pos)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        k = self.children[1].eval_cpu(cols, ansi)
        if isinstance(self.children[0].data_type(), T.MapType):
            return _map_lookup_cpu(self.data_type(), c, k)
        out_v, out_ok = [], []
        for (v, ok), (i, iok) in zip(zip(c.values, c.valid),
                                     zip(k.values, k.valid)):
            if not ok or not iok or v is None:
                out_v.append(None)
                out_ok.append(False)
                continue
            i = int(i)
            if i == 0:
                raise SparkException("SQL array indices start at 1")
            eff = i - 1 if i > 0 else len(v) + i
            if 0 <= eff < len(v):
                out_v.append(v[eff])
                out_ok.append(v[eff] is not None)
            else:
                if ansi:
                    raise SparkException(
                        f"Index {i} out of bounds for array of {len(v)}")
                out_v.append(None)
                out_ok.append(False)
        return _leaf_cpu_col(self.data_type(), out_v, out_ok)


def _extract_cpu(rt, arr: CpuCol, idx: CpuCol, base: int, ansi: bool):
    out_v, out_ok = [], []
    for (v, ok), (i, iok) in zip(zip(arr.values, arr.valid),
                                 zip(idx.values, idx.valid)):
        if not ok or not iok or v is None:
            out_v.append(None)
            out_ok.append(False)
            continue
        i = int(i) - base if base else int(i)
        if 0 <= i < len(v):
            out_v.append(v[i])
            out_ok.append(v[i] is not None)
        else:
            if ansi:
                raise SparkException(
                    f"Index {i} out of bounds for array of {len(v)}")
            out_v.append(None)
            out_ok.append(False)
    return _leaf_cpu_col(rt, out_v, out_ok)


def _leaf_cpu_col(rt: T.DataType, vals: list, ok: list) -> CpuCol:
    valid = np.asarray(ok, np.bool_)
    if isinstance(rt, (T.StringType, T.ArrayType, T.StructType, T.MapType)):
        return CpuCol(rt, np.array(vals, object), valid)
    np_vals = np.array([0 if (v is None or not o) else v
                        for v, o in zip(vals, ok)], rt.np_dtype)
    return CpuCol(rt, np_vals, valid)


def _map_lookup_tpu(m: ColumnVector, key: ColumnVector, ctx: EvalCtx
                    ) -> ColumnVector:
    keys, values = m.data["keys"], m.data["values"]
    cap = m.capacity
    off = m.data["offsets"]
    child_cap = keys.capacity
    seg = _element_segments(off[: cap + 1], cap, child_cap)
    eq, both = _cmp_child_to_row(keys, key, seg, ctx)
    e = jnp.arange(child_cap, dtype=jnp.int32)
    in_range = e < off[cap]
    match = eq & both & in_range
    first = jnp.full(cap, child_cap, jnp.int32).at[seg].min(
        jnp.where(match, e, child_cap))
    row_ok = _valid_of(m, ctx) & _valid_of(key, ctx) & (first < child_cap)
    pos = jnp.where(row_ok, jnp.clip(first, 0, child_cap - 1), -1)
    return _gather_child(values, pos)


def _map_lookup_cpu(rt, m: CpuCol, k: CpuCol) -> CpuCol:
    out_v, out_ok = [], []
    for (v, ok), (key, kok) in zip(zip(m.values, m.valid),
                                   zip(k.values, k.valid)):
        hit = None
        if ok and kok and v is not None:
            for kk, vv in v:
                if kk == key:
                    hit = vv
                    break
        out_v.append(hit)
        out_ok.append(hit is not None)
    return _leaf_cpu_col(rt, out_v, out_ok)


class GetMapValue(ElementAt):
    """map[key] — same as element_at(map, key)."""


class GetStructField(Expression):
    def __init__(self, child: Expression, name: str):
        self.children = [child]
        self.field_name = name

    def _field_index(self):
        st = self.children[0].data_type()
        for i, f in enumerate(st.fields):
            if f.name == self.field_name:
                return i
        raise SparkException(f"No such struct field {self.field_name} in "
                             f"{st!r}")

    def data_type(self):
        st = self.children[0].data_type()
        return st.fields[self._field_index()].dtype

    def _params(self):
        return self.field_name

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        c = self.children[0].eval_tpu(ctx)
        kid = c.data["children"][self._field_index()]
        valid = _valid_of(c, ctx)
        kv = kid.validity if kid.validity is not None else ctx.row_mask
        return ColumnVector(kid.dtype, kid.data, kv & valid,
                            dict_unique=kid.dict_unique)

    def eval_cpu(self, cols, ansi=False):
        c = self.children[0].eval_cpu(cols, ansi)
        name = self.field_name
        vals = [None if (not ok or v is None) else v.get(name)
                for v, ok in zip(c.values, c.valid)]
        ok = [v is not None for v in vals]
        return _leaf_cpu_col(self.data_type(), vals, ok)


class ArrayContains(Expression):
    """array_contains(arr, v). Spark null semantics: null if arr is null or
    v is null; true when found; null when not found but the array has a
    null element; false otherwise."""

    def __init__(self, child: Expression, value: Expression):
        self.children = [child, _wrap(value)]

    def data_type(self):
        return T.BOOLEAN

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr = self.children[0].eval_tpu(ctx)
        val = self.children[1].eval_tpu(ctx)
        cap = arr.capacity
        off = arr.data["offsets"]
        child = arr.data["child"]
        child_cap = child.capacity
        seg = _element_segments(off[: cap + 1], cap, child_cap)
        eq, both = _cmp_child_to_row(child, val, seg, ctx)
        e = jnp.arange(child_cap, dtype=jnp.int32)
        in_range = e < off[cap]
        found = jnp.zeros(cap, jnp.bool_).at[seg].max(eq & both & in_range)
        cv = (child.validity if child.validity is not None
              else jnp.ones(child_cap, jnp.bool_))
        has_null = jnp.zeros(cap, jnp.bool_).at[seg].max(~cv & in_range)
        inputs_ok = _valid_of(arr, ctx) & _valid_of(val, ctx)
        validity = inputs_ok & (found | ~has_null)
        return ColumnVector(T.BOOLEAN, found, validity)

    def eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        val = self.children[1].eval_cpu(cols, ansi)
        out_v, out_ok = [], []
        for (v, ok), (x, xok) in zip(zip(arr.values, arr.valid),
                                     zip(val.values, val.valid)):
            if not ok or v is None or not xok:
                out_v.append(False)
                out_ok.append(False)
                continue
            found = any(el is not None and el == x for el in v)
            has_null = any(el is None for el in v)
            out_v.append(found)
            out_ok.append(found or not has_null)
        return CpuCol(T.BOOLEAN, np.asarray(out_v, np.bool_),
                      np.asarray(out_ok, np.bool_))


class CreateArray(Expression):
    """array(e1, e2, ...) — fixed-width elements interleave into child
    planes on device; strings build on CPU."""

    def __init__(self, children: List[Expression]):
        self.children = [_wrap(c) for c in children]

    def data_type(self):
        if not self.children:
            return T.ArrayType(T.NULL)
        dt = self.children[0].data_type()
        for c in self.children[1:]:
            dt = T.common_type(dt, c.data_type())
        return T.ArrayType(dt)

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        elem_t = self.data_type().element
        cols = [c.eval_tpu(ctx) for c in self.children]
        k = len(cols)
        cap = ctx.capacity
        datas = [c.data.astype(elem_t.np_dtype) for c in cols]
        valids = [_valid_of(c, ctx) for c in cols]
        child_data = jnp.stack(datas, axis=1).reshape(-1)
        child_valid = jnp.stack(valids, axis=1).reshape(-1)
        offsets = (jnp.arange(cap + 1, dtype=jnp.int32) * k)
        child = ColumnVector(elem_t, child_data, child_valid)
        return ColumnVector(self.data_type(),
                            {"offsets": offsets, "child": child}, None)

    def eval_cpu(self, cols, ansi=False):
        elem_t = self.data_type().element
        parts = [c.eval_cpu(cols, ansi) for c in self.children]
        n = len(parts[0].values) if parts else 0
        out = []
        for i in range(n):
            row = []
            for p in parts:
                if not p.valid[i]:
                    row.append(None)
                else:
                    v = p.values[i]
                    v = v.item() if isinstance(v, np.generic) else v
                    if elem_t.np_dtype is not None and v is not None \
                            and not isinstance(elem_t, T.StringType):
                        v = np.dtype(elem_t.np_dtype).type(v).item()
                    row.append(v)
            out.append(row)
        return CpuCol(self.data_type(), np.array(out, object),
                      np.ones(n, np.bool_))


class MapKeys(Expression):
    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self):
        return T.ArrayType(self.children[0].data_type().key,
                           contains_null=False)

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        m = self.children[0].eval_tpu(ctx)
        data = {"offsets": m.data["offsets"], "child": m.data["keys"]}
        return ColumnVector(self.data_type(), data, m.validity)

    def eval_cpu(self, cols, ansi=False):
        m = self.children[0].eval_cpu(cols, ansi)
        vals = [None if (not ok or v is None) else [kk for kk, _ in v]
                for v, ok in zip(m.values, m.valid)]
        return CpuCol(self.data_type(), np.array(vals, object),
                      m.valid.copy())


class MapValues(Expression):
    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self):
        return T.ArrayType(self.children[0].data_type().value)

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        m = self.children[0].eval_tpu(ctx)
        data = {"offsets": m.data["offsets"], "child": m.data["values"]}
        return ColumnVector(self.data_type(), data, m.validity)

    def eval_cpu(self, cols, ansi=False):
        m = self.children[0].eval_cpu(cols, ansi)
        vals = [None if (not ok or v is None) else [vv for _, vv in v]
                for v, ok in zip(m.values, m.valid)]
        return CpuCol(self.data_type(), np.array(vals, object),
                      m.valid.copy())


# ---------------------------------------------------------------------------
# Generator expressions (plan-level markers; the work happens in
# exec/tpu_nodes.GenerateExec — reference GpuGenerateExec.scala)
# ---------------------------------------------------------------------------

class Explode(Expression):
    """explode(array|map) / explode_outer. Only valid as a top-level select
    expression; the DataFrame layer rewrites it into a Generate node."""

    outer = False

    def __init__(self, child: Expression):
        self.children = [child]

    def data_type(self):
        dt = self.children[0].data_type()
        if isinstance(dt, T.MapType):
            return T.StructType((T.StructField("key", dt.key, False),
                                 T.StructField("value", dt.value)))
        return dt.element

    def output_fields(self, alias: Optional[str] = None):
        dt = self.children[0].data_type()
        if not isinstance(dt, (T.ArrayType, T.MapType)):
            raise SparkException(
                f"explode() requires an array or map input, got {dt!r}")
        if isinstance(dt, T.MapType):
            return [("key", dt.key), ("value", dt.value)]
        return [(alias or "col", dt.element)]


class ExplodeOuter(Explode):
    outer = True


class PosExplode(Explode):
    position = True

    def output_fields(self, alias: Optional[str] = None):
        return [("pos", T.INT32)] + super().output_fields(alias)


class PosExplodeOuter(PosExplode):
    outer = True


class Stack(Expression):
    """stack(n, e1..ek): n output rows per input row, ceil(k/n) columns
    named col0..col{m-1}, short rows NULL-filled (reference GpuStack in
    GpuOverrides.scala:3547 lowers to GpuGenerateExec). The engine
    lowers it in DataFrame.select as a UNION of n row-projections —
    columnar-friendly (no row expansion kernel) and exactly the
    generator's multiset of rows."""

    def __init__(self, n: int, *exprs):
        if n <= 0:
            raise SparkException("stack(): row count must be positive")
        if not exprs:
            raise SparkException("stack() needs at least one value")
        self.n = int(n)
        self.children = list(exprs)

    def _params(self):
        return str(self.n)

    def with_children(self, children):
        return Stack(self.n, *children)

    @property
    def ncols(self):
        return -(-len(self.children) // self.n)

    def output_fields(self):
        cols = []
        for j in range(self.ncols):
            dt = self.children[j].data_type()
            for r in range(1, self.n):
                i = r * self.ncols + j
                if i < len(self.children):
                    other = self.children[i].data_type()
                    if other != dt and not isinstance(dt, T.NullType):
                        if isinstance(other, T.NullType):
                            continue
                        raise SparkException(
                            f"stack(): column {j} mixes {dt!r} and "
                            f"{other!r}")
                    if isinstance(dt, T.NullType):
                        dt = other
            cols.append((f"col{j}", dt))
        return cols

    def row_exprs(self):
        """The n per-row projections (typed-NULL padded)."""
        fields = self.output_fields()
        rows = []
        for r in range(self.n):
            row = []
            for j, (_, dt) in enumerate(fields):
                i = r * self.ncols + j
                if i >= len(self.children):
                    row.append(Literal(None, dt))
                    continue
                c = self.children[i]
                if isinstance(c.data_type(), T.NullType):
                    # retype explicit NULLs to the merged column type:
                    # Expand derives the schema from projection 0 alone
                    c = Literal(None, dt)
                row.append(c)
            rows.append(row)
        return rows

    def data_type(self):
        raise SparkException("stack() is only valid in select()")

    def eval_tpu(self, ctx):
        raise SparkException("stack() is only valid in select()")

    eval_cpu = eval_tpu
