"""Device cast kernels: string <-> float/date/timestamp.

Reference parity: jni CastStrings + GpuCast.scala string conversions.
All kernels are branch-free byte-walks (lax.while_loop over the batch max
length) over offsets+bytes planes; dictionary columns parse the (small)
vocab once and gather by code at the call site.

Documented divergences (same class as the reference's CastStrings notes):
- string->double parses via int64 mantissa + pow10 scaling: results can
  differ from correctly-rounded strtod by ~1-2 ulp.
- date/timestamp rendering covers years 0..9999 (fixed-width digits);
  values outside render as null.
- timestamp parsing accepts `yyyy-MM-dd[ |T]HH:mm:ss[.ffffff]` (UTC
  engine; no zone suffixes).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector, round_capacity


def _walker(col: ColumnVector):
    o = col.data["offsets"]
    raw = col.data["bytes"]
    starts = o[:-1].astype(jnp.int32)
    ends = o[1:].astype(jnp.int32)
    nb = raw.shape[0]

    def at(pos):
        return raw[jnp.clip(pos, 0, nb - 1)].astype(jnp.int32)

    return starts, ends, at


def _trim(starts, ends, at):
    """Java UTF8String.trim semantics: strip bytes <= 0x20 on both ends
    (space AND control chars) — what Spark's string casts use."""
    def step(state):
        s, e = state
        lead = (s < e) & (at(s) <= 32)
        tail = (e > s) & (at(e - 1) <= 32)
        return jnp.where(lead, s + 1, s), jnp.where(tail, e - 1, e)

    def cond(state):
        s, e = state
        return jnp.any(((s < e) & (at(s) <= 32)) | ((e > s) & (at(e - 1) <= 32)))

    return lax.while_loop(cond, step, (starts, ends))


def _match_lit(at, s, e, text: bytes):
    """Rows whose [s,e) slice equals `text` exactly."""
    ok = (e - s) == len(text)
    for j, ch in enumerate(text):
        ok = ok & (at(s + j) == ch)
    return ok


def parse_f64(col: ColumnVector):
    """(values f64, parsed_ok bool) — optional sign, digits, '.', digits,
    [eE][+-]digits; 'Infinity'/'NaN' specials; spaces trimmed."""
    starts, ends, at = _walker(col)
    s, e = _trim(starts, ends, at)
    n = s.shape[0]
    first = at(s)
    has_sign = (first == 45) | (first == 43)
    neg = first == 45
    ds = s + has_sign.astype(jnp.int32)

    inf = _match_lit(at, ds, e, b"Infinity")
    nan = _match_lit(at, ds, e, b"NaN")  # Java: Sign_opt NaN

    # phases: 0 = integer digits, 1 = fraction digits, 2 = exponent
    def body(state):
        (i, acc, scale, ndig, exp, esign, ednig, phase, good, done) = state
        pos = ds + i
        active = (pos < e) & ~done
        b = at(pos)
        prev = at(pos - 1)
        is_digit = (b >= 48) & (b <= 57)
        dv = (b - 48).astype(jnp.int64)
        # mantissa digit (phase 0/1): accumulate up to 18 digits; integer
        # digits beyond 18 inflate the scale, fraction overflow is dropped
        mant = active & is_digit & (phase < 2)
        room = ndig < 18
        acc = jnp.where(mant & room, acc * 10 + dv, acc)
        scale = jnp.where(mant & room & (phase == 1), scale + 1, scale)
        scale = jnp.where(mant & ~room & (phase == 0), scale - 1, scale)
        ndig = jnp.where(mant, ndig + 1, ndig)
        # exponent digit (phase 2)
        ed = active & is_digit & (phase == 2)
        exp = jnp.where(ed, jnp.minimum(exp * 10 + dv.astype(jnp.int32),
                                        9999), exp)
        ednig = jnp.where(ed, ednig + 1, ednig)
        # '.' -> fraction (once, from phase 0 only)
        dot = active & (b == 46) & (phase == 0)
        bad_dot = active & (b == 46) & (phase != 0)
        phase = jnp.where(dot, 1, phase)
        # e/E -> exponent (needs a mantissa digit first)
        ee = active & ((b == 101) | (b == 69)) & (phase < 2) & (ndig > 0)
        bad_ee = active & ((b == 101) | (b == 69)) & ~ee
        phase = jnp.where(ee, 2, phase)
        # exponent sign: only the byte immediately after e/E
        exp_sign = active & ((b == 45) | (b == 43)) & (phase == 2) \
            & ((prev == 101) | (prev == 69)) & (ednig == 0)
        esign = jnp.where(exp_sign & (b == 45), -1, esign)
        recognized = mant | ed | dot | ee | exp_sign
        good = good & (~active | recognized) & ~bad_dot & ~bad_ee
        done = done | (pos >= e)
        return (i + 1, acc, scale, ndig, exp, esign, ednig, phase, good,
                done)

    def cond(state):
        return ~jnp.all(state[-1])

    good0 = (e > ds) & ~inf & ~nan
    init = (jnp.int32(0), jnp.zeros(n, jnp.int64), jnp.zeros(n, jnp.int32),
            jnp.zeros(n, jnp.int32), jnp.zeros(n, jnp.int32),
            jnp.ones(n, jnp.int32), jnp.zeros(n, jnp.int32),
            jnp.zeros(n, jnp.int32), good0, inf | nan | (s >= e))
    (_, acc, scale, ndig, exp, esign, ednig, phase, good, _) = \
        lax.while_loop(cond, body, init)
    good = good & (ndig > 0) & ((phase < 2) | (ednig > 0))
    p = (exp * esign - scale).astype(jnp.float64)
    p = jnp.clip(p, -400.0, 400.0)
    v = acc.astype(jnp.float64) * jnp.power(np.float64(10.0), p)
    v = jnp.where(neg, -v, v)
    v = jnp.where(inf, jnp.where(neg, -jnp.inf, jnp.inf), v)
    v = jnp.where(nan, jnp.nan, v)
    ok = (good | inf | nan) & (s < e)
    return v, ok


# civil-calendar conversions shared with the datetime expression layer —
# ONE Hinnant implementation for extraction and casting alike
from spark_rapids_tpu.expr.datetime import (  # noqa: E402
    _civil_from_days, _days_from_civil,
)


def _parse_ymd_hms(col: ColumnVector, with_time: bool):
    """Shared date/timestamp parser. Returns (days, us_of_day, ok)."""
    starts, ends, at = _walker(col)
    s, e = _trim(starts, ends, at)
    n = s.shape[0]

    # phases: 0 y, 1 m, 2 d, 3 H, 4 M, 5 S, 6 frac
    NP = 7

    def body(state):
        i, pos, accs, digs, phase, good, done = state
        active = (pos < e) & ~done
        b = at(pos)
        is_digit = (b >= 48) & (b <= 57)
        d = (b - 48).astype(jnp.int64)
        ph1 = jax.nn.one_hot(phase, NP, dtype=jnp.int64)
        add = jnp.where((active & is_digit)[:, None], ph1, 0)
        accs = accs * jnp.where(add > 0, 10, 1) + add * d[:, None]
        digs = digs + add.astype(jnp.int32)
        sep_dash = active & (b == 45) & (phase < 2)
        sep_sp = active & ((b == 32) | (b == 84)) & (phase == 2) & with_time
        sep_col = active & (b == 58) & ((phase == 3) | (phase == 4))
        sep_dot = active & (b == 46) & (phase == 5) & with_time
        sep = sep_dash | sep_sp | sep_col | sep_dot
        phase = jnp.where(sep, phase + 1, phase)
        good = good & (~active | is_digit | sep)
        done = done | (pos >= e)
        return i + 1, pos + 1, accs, digs, phase, good, done

    def cond(state):
        return ~jnp.all(state[-1])

    init = (jnp.int32(0), s, jnp.zeros((n, NP), jnp.int64),
            jnp.zeros((n, NP), jnp.int32), jnp.zeros(n, jnp.int32),
            s < e, s >= e)
    _, _, accs, digs, phase, good, _ = lax.while_loop(cond, body, init)
    y = accs[:, 0]
    m = jnp.where(digs[:, 1] > 0, accs[:, 1], 1)
    d = jnp.where(digs[:, 2] > 0, accs[:, 2], 1)
    # year range matches the host oracle (datetime): 1..9999
    good = good & (digs[:, 0] >= 1) & (digs[:, 0] <= 7) \
        & (y >= 1) & (y <= 9999)
    good = good & ((digs[:, 1] == 0) | (digs[:, 1] <= 2))
    good = good & ((digs[:, 2] == 0) | (digs[:, 2] <= 2))
    good = good & (m >= 1) & (m <= 12) & (d >= 1)
    # day-in-month bound incl. leap years
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    mdays = jnp.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                      jnp.int64)[jnp.clip(m - 1, 0, 11)]
    mdays = jnp.where((m == 2) & leap, 29, mdays)
    good = good & (d <= mdays)
    # started-but-empty segments ("2020-", "2020-01-") are invalid
    good = good & ~((phase >= 1) & (phase <= 2) & (digs[:, 1] == 0))
    good = good & ~((phase == 2) & (digs[:, 2] == 0))
    days = _days_from_civil(y, m, d).astype(jnp.int64)
    if not with_time:
        good = good & (phase <= 2)
        return days, jnp.zeros(n, jnp.int64), good
    H, Mi, S = accs[:, 3], accs[:, 4], accs[:, 5]
    good = good & ((phase <= 2) | (phase >= 5))  # time needs H:M:S at least
    has_time = phase >= 3
    good = good & (~has_time | ((digs[:, 3] >= 1) & (digs[:, 3] <= 2)
                                & (digs[:, 4] >= 1) & (digs[:, 4] <= 2)
                                & (digs[:, 5] >= 1) & (digs[:, 5] <= 2)
                                & (H < 24) & (Mi < 60) & (S < 60)))
    frac = accs[:, 6]
    fd = digs[:, 6]
    good = good & ((phase < 6) | (fd >= 1))
    us = jnp.where(fd > 0,
                   frac * (10 ** jnp.clip(6 - fd, 0, 6)), 0)
    us = jnp.where(fd > 6, frac // (10 ** jnp.clip(fd - 6, 0, 12)), us)
    usod = H * 3_600_000_000 + Mi * 60_000_000 + S * 1_000_000 + us
    return days, jnp.where(has_time, usod, 0), good


def parse_date(col: ColumnVector):
    days, _, ok = _parse_ymd_hms(col, with_time=False)
    return days.astype(jnp.int32), ok


def parse_timestamp(col: ColumnVector):
    days, usod, ok = _parse_ymd_hms(col, with_time=True)
    return days * 86_400_000_000 + usod, ok


def _digits(val, count):
    """val -> `count` ASCII digit planes, most significant first."""
    out = []
    for i in range(count - 1, -1, -1):
        out.append((val // (10 ** i)) % 10 + 48)
    return out


def render_date(days: jax.Array, valid: jax.Array):
    """int32 days -> flat 'yyyy-MM-dd' string planes; years outside
    0..9999 render null."""
    y, m, d = _civil_from_days(days.astype(jnp.int64))
    ok = valid & (y >= 0) & (y <= 9999)
    n = days.shape[0]
    cols = _digits(y, 4) + [jnp.full(n, 45)] + _digits(m, 2) \
        + [jnp.full(n, 45)] + _digits(d, 2)
    mat = jnp.stack([c.astype(jnp.uint8) for c in cols], axis=1)
    lens = jnp.where(ok, 10, 0).astype(jnp.int32)
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    bcap = round_capacity(max(n * 10, 8))
    flat = jnp.zeros(bcap, jnp.uint8)
    rowpos = jnp.repeat(offsets[:-1], 10).reshape(n, 10) \
        + jnp.arange(10, dtype=jnp.int32)[None, :]
    dest = jnp.where(ok[:, None], rowpos, bcap)
    flat = flat.at[dest.reshape(-1)].set(mat.reshape(-1), mode="drop")
    return ColumnVector(T.STRING, {"offsets": offsets, "bytes": flat}, ok)


def render_timestamp(us: jax.Array, valid: jax.Array):
    """int64 micros -> 'yyyy-MM-dd HH:mm:ss[.ffffff]' (trailing zeros of
    the fraction trimmed; whole-second values render without fraction)."""
    days = jnp.floor_divide(us, 86_400_000_000)
    usod = us - days * 86_400_000_000
    y, m, d = _civil_from_days(days)
    ok = valid & (y >= 0) & (y <= 9999)
    H = usod // 3_600_000_000
    Mi = (usod // 60_000_000) % 60
    S = (usod // 1_000_000) % 60
    frac = usod % 1_000_000
    # fraction length = smallest k with frac divisible by 10^(6-k)
    # (trailing zeros trimmed; 0 when the fraction is zero)
    flen = jnp.where(frac == 0, 0, 6)
    for k in range(5, 0, -1):
        flen = jnp.where((frac != 0) & (frac % (10 ** (6 - k)) == 0), k, flen)
    n = us.shape[0]
    base = _digits(y, 4) + [jnp.full(n, 45)] + _digits(m, 2) \
        + [jnp.full(n, 45)] + _digits(d, 2) + [jnp.full(n, 32)] \
        + _digits(H, 2) + [jnp.full(n, 58)] + _digits(Mi, 2) \
        + [jnp.full(n, 58)] + _digits(S, 2) + [jnp.full(n, 46)] \
        + _digits(frac, 6)
    W = 26
    mat = jnp.stack([c.astype(jnp.uint8) for c in base], axis=1)
    lens = jnp.where(ok, jnp.where(flen > 0, 20 + flen, 19), 0) \
        .astype(jnp.int32)
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    bcap = round_capacity(max(n * W, 8))
    flat = jnp.zeros(bcap, jnp.uint8)
    within = jnp.arange(W, dtype=jnp.int32)[None, :] < lens[:, None]
    rowpos = offsets[:-1][:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    dest = jnp.where(within & ok[:, None], rowpos, bcap)
    flat = flat.at[dest.reshape(-1)].set(mat.reshape(-1), mode="drop")
    return ColumnVector(T.STRING, {"offsets": offsets, "bytes": flat}, ok)
