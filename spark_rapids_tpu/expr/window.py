"""Window expressions.

Reference parity: sql-plugin window/ (GpuWindowExec family,
GpuWindowExpression.scala:198 — rank/dense_rank/row_number/lead/lag and
windowed aggregations over ROWS/RANGE frames; SURVEY.md §2.4 "Window").

Model: a WindowExpr pairs a window function with a WindowSpec
(partition-by, order-by, frame). The planner splits projections containing
WindowExprs into a Window plan node; the exec sorts once per partition
spec and evaluates every window function as segmented scans in ONE fused
kernel (the TPU answer to the reference's batched running/bounded window
iterators).

Frames: (kind, lower, upper) with kind in {"rows", "range"}; None bounds
mean UNBOUNDED, 0 means CURRENT ROW, ints are offsets. Spark defaults:
ordered specs get ("range", None, 0) — running with ties; unordered specs
get ("rows", None, None) — whole partition.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import AggFunction
from spark_rapids_tpu.expr.core import Expression


@dataclasses.dataclass(frozen=True)
class Frame:
    kind: str = "range"          # "rows" | "range"
    lower: Optional[int] = None  # None = UNBOUNDED PRECEDING
    upper: Optional[int] = 0     # None = UNBOUNDED FOLLOWING; 0 = CURRENT

    def fingerprint(self) -> str:
        return f"{self.kind}[{self.lower},{self.upper}]"


class WindowSpec:
    """Builder: Window.partition_by(...).order_by(...).rows_between(a, b)."""

    def __init__(self, partition_by=None, order_by=None, frame: Optional[Frame] = None):
        self.partition_exprs: List[Expression] = list(partition_by or [])
        self.order_specs = list(order_by or [])  # list[plan.SortOrder]
        self.frame = frame

    def partition_by(self, *exprs) -> "WindowSpec":
        from spark_rapids_tpu.expr.core import col
        es = [col(e) if isinstance(e, str) else e for e in exprs]
        return WindowSpec(es, self.order_specs, self.frame)

    def order_by(self, *orders) -> "WindowSpec":
        from spark_rapids_tpu.plan.nodes import SortOrder
        from spark_rapids_tpu.expr.core import col
        os = []
        for o in orders:
            if isinstance(o, SortOrder):
                os.append(o)
            else:
                os.append(SortOrder(col(o) if isinstance(o, str) else o))
        return WindowSpec(self.partition_exprs, os, self.frame)

    def rows_between(self, lower, upper) -> "WindowSpec":
        return WindowSpec(self.partition_exprs, self.order_specs,
                          Frame("rows", lower, upper))

    def resolved_frame(self) -> Frame:
        if self.frame is not None:
            return self.frame
        if self.order_specs:
            return Frame("range", None, 0)
        return Frame("rows", None, None)

    def fingerprint(self) -> str:
        ps = ",".join(e.fingerprint() for e in self.partition_exprs)
        os = ",".join(f"{o.expr.fingerprint()}:{o.ascending}:"
                      f"{o.resolved_nulls_first()}" for o in self.order_specs)
        return f"spec({ps}|{os}|{self.resolved_frame().fingerprint()})"


class Window:
    """Entry points mirroring pyspark.sql.Window."""

    #: frame bound sentinels
    unboundedPreceding = None
    unboundedFollowing = None
    currentRow = 0

    @staticmethod
    def partition_by(*exprs) -> WindowSpec:
        return WindowSpec().partition_by(*exprs)

    partitionBy = partition_by

    @staticmethod
    def order_by(*orders) -> WindowSpec:
        return WindowSpec().order_by(*orders)

    orderBy = order_by


class WindowFunction:
    """Base for pure window functions (rank family, lead/lag)."""

    children: List[Expression] = []
    needs_order = True

    def result_type(self) -> T.DataType:
        raise NotImplementedError

    def fingerprint(self) -> str:
        kids = ",".join(c.fingerprint() for c in self.children)
        return f"{type(self).__name__}({kids};{self._params()})"

    def _params(self) -> str:
        return ""

    def transform(self, fn):
        return self

    def over(self, spec: WindowSpec) -> "WindowExpr":
        return WindowExpr(self, spec)


class RowNumber(WindowFunction):
    def result_type(self):
        return T.INT32


class Rank(WindowFunction):
    def result_type(self):
        return T.INT32


class DenseRank(WindowFunction):
    def result_type(self):
        return T.INT32


class NTile(WindowFunction):
    def __init__(self, n: int):
        self.n = n

    def _params(self):
        return str(self.n)

    def result_type(self):
        return T.INT32


class LeadLag(WindowFunction):
    is_lead = True

    def __init__(self, child: Expression, offset: int = 1, default=None):
        self.children = [child]
        self.offset = offset
        self.default = default

    def _params(self):
        return f"{self.offset},{self.default!r}"

    def result_type(self):
        return self.children[0].data_type()

    def transform(self, fn):
        out = type(self)(self.children[0].transform(fn), self.offset, self.default)
        return out


class Lead(LeadLag):
    is_lead = True


class Lag(LeadLag):
    is_lead = False


class PercentRank(WindowFunction):
    """(rank - 1) / (partition rows - 1); 0.0 for single-row partitions."""

    def result_type(self):
        return T.FLOAT64


class CumeDist(WindowFunction):
    """rows ordering <= current (peers included) / partition rows."""

    def result_type(self):
        return T.FLOAT64


class NthValue(WindowFunction):
    """nth_value(col, n): the partition's nth value once the frame has
    reached it, null before (Spark default-frame semantics)."""

    def __init__(self, child: Expression, n: int):
        if n < 1:
            from spark_rapids_tpu.expr.core import SparkException
            raise SparkException("nth_value offset must be >= 1")
        self.children = [child]
        self.n = n

    def _params(self):
        return str(self.n)

    def result_type(self):
        return self.children[0].data_type()

    def transform(self, fn):
        return NthValue(self.children[0].transform(fn), self.n)


class FirstValue(WindowFunction):
    def __init__(self, child: Expression):
        self.children = [child]

    def result_type(self):
        return self.children[0].data_type()

    def transform(self, fn):
        return FirstValue(self.children[0].transform(fn))


class LastValue(WindowFunction):
    """last_value over the FRAME — with Spark's default frame (unbounded
    preceding to current row) this is the current peer group's last row,
    the famously surprising behavior the reference reproduces too."""

    def __init__(self, child: Expression):
        self.children = [child]

    def result_type(self):
        return self.children[0].data_type()

    def transform(self, fn):
        return LastValue(self.children[0].transform(fn))


class WindowAgg(WindowFunction):
    """An aggregate function evaluated over a window frame."""

    needs_order = False

    def __init__(self, fn: AggFunction):
        self.fn = fn
        self.children = list(fn.children)

    def _params(self):
        return type(self.fn).__name__

    def result_type(self):
        return self.fn.result_type()

    def transform(self, tf):
        return WindowAgg(self.fn.transform(lambda e: e.transform(tf)))


class WindowExpr(Expression):
    """function OVER spec — appears in projection lists; the planner hoists
    it into a Window plan node."""

    def __init__(self, fn: WindowFunction, spec: WindowSpec):
        self.fn = fn
        self.spec = spec
        self.children = []

    def data_type(self) -> T.DataType:
        return self.fn.result_type()

    def fingerprint(self) -> str:
        return f"winexpr({self.fn.fingerprint()} over {self.spec.fingerprint()})"

    def transform(self, tf):
        out = tf(self)
        return out if out is not self else self


def over(fn_or_agg, spec: WindowSpec) -> WindowExpr:
    if isinstance(fn_or_agg, AggFunction):
        fn_or_agg = WindowAgg(fn_or_agg)
    if not isinstance(fn_or_agg, WindowFunction):
        raise TypeError(f"not a window function: {fn_or_agg!r}")
    return WindowExpr(fn_or_agg, spec)
