"""Higher-order functions over arrays and maps (lambda expressions).

Reference parity: sql-plugin higherOrderFunctions.scala (GpuArrayTransform,
GpuArrayExists, GpuArrayFilter, GpuTransformKeys, GpuTransformValues,
GpuMapFilter, GpuNamedLambdaVariable/GpuLambdaFunction) plus ArrayForAll,
ArrayAggregate and ZipWith from Spark's higherOrderFunctions.

TPU-first design: the lambda body is an ordinary expression tree that
evaluates ONCE over the flattened ELEMENT plane (child column of the
array), not per row — a nested column is already a contiguous plane, so a
lambda over N rows of K-element arrays is one fused elementwise pass over
N*K lanes. Lambda variables bind to element-plane columns through the
EvalCtx; outer row references are gathered to element positions by the
row-ownership segment map (one searchsorted per stage, shared).

aggregate()/reduce() is a sequential per-row fold with an arbitrary merge
lambda — inherently order-dependent, so it runs on the CPU tier
(supported_on_tpu=False), mirroring the reference's unsupported-op
fallback discipline.
"""
from __future__ import annotations

import itertools
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector
from spark_rapids_tpu.expr.core import (
    CpuCol, EvalCtx, Expression, SparkException, _valid_of, _wrap,
)
from spark_rapids_tpu.expr.complex import _element_segments, _leaf_cpu_col

_ids = itertools.count()

#: lambda-variable bindings for the CPU tier (TPU bindings ride on the
#: EvalCtx). Thread-local: partitions evaluate concurrently.
_tls = threading.local()


def _cpu_bindings() -> dict:
    if not hasattr(_tls, "b"):
        _tls.b = {}
    return _tls.b


class _bound_cpu:
    """Scoped CPU-tier lambda bindings: mutates the live thread-local
    dict in place (never swaps the object — nested folds re-fetch it)."""

    def __init__(self, bindings: dict):
        self.bindings = bindings

    def __enter__(self):
        b = _cpu_bindings()
        self.saved = {k: b.get(k, _MISSING) for k in self.bindings}
        b.update(self.bindings)

    def __exit__(self, *exc):
        b = _cpu_bindings()
        for k, v in self.saved.items():
            if v is _MISSING:
                b.pop(k, None)
            else:
                b[k] = v


_MISSING = object()


class LambdaVar(Expression):
    """A named lambda parameter (reference GpuNamedLambdaVariable): a leaf
    that resolves to whatever column the enclosing HOF bound it to."""

    def __init__(self, dtype: T.DataType, name: str):
        self.children = []
        self.dtype = dtype
        self.name = name
        self.var_id = next(_ids)

    def data_type(self):
        return self.dtype

    def _params(self):
        # the id is deliberately NOT part of the fingerprint: two lambdas
        # with the same structure must share a compiled kernel. Shadowing
        # is disambiguated by the name + nesting depth at build time.
        return f"{self.name}:{self.dtype!r}"

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        binding = getattr(ctx, "lambda_bindings", {}).get(self.var_id)
        if binding is None:
            raise SparkException(f"unbound lambda variable {self.name}")
        return binding

    def eval_cpu(self, cols, ansi=False):
        binding = _cpu_bindings().get(self.var_id)
        if binding is None:
            raise SparkException(f"unbound lambda variable {self.name}")
        return binding


def make_lambda(fn: Callable, arg_types: Sequence[T.DataType],
                names: Sequence[str]) -> tuple:
    """Build (body, vars) from a Python callable over Expression args."""
    vs = [LambdaVar(dt, nm) for dt, nm in zip(arg_types, names)]
    body = _wrap(fn(*vs))
    return body, vs


def _element_ctx(ctx: EvalCtx, arr: ColumnVector, bindings: dict):
    """EvalCtx over the element plane of `arr`, with outer refs gathered
    and `bindings` (var_id -> element ColumnVector) installed. Returns
    (ectx, seg, in_range, start)."""
    cap = arr.capacity
    off = arr.data["offsets"]
    first_child = (arr.data.get("child") or arr.data.get("keys"))
    child_cap = first_child.capacity
    seg = _element_segments(off[: cap + 1], cap, child_cap)
    e = jnp.arange(child_cap, dtype=jnp.int32)
    row_live = ctx.row_mask & _valid_of(arr, ctx)
    from spark_rapids_tpu.ops import kernels as K
    live_at_e = K.gather_column(
        ColumnVector(T.BOOLEAN, row_live, None), seg, cap).data
    in_range = (e < off[cap]) & live_at_e.astype(jnp.bool_)
    from spark_rapids_tpu.ops import kernels as K
    ectx = EvalCtx([], jnp.sum(in_range.astype(jnp.int32)), child_cap,
                   ctx.ansi, live=in_range,
                   partition_id=ctx.partition_id, row_base=ctx.row_base)
    # lazily-gathering column view AFTER init (EvalCtx list()s its arg)
    ectx.columns = K.LazyGatheredCols(
        ctx.columns, jnp.where(in_range, seg, -1), ctx.num_rows)
    ectx.lambda_bindings = dict(getattr(ctx, "lambda_bindings", {}))
    ectx.lambda_bindings.update(bindings)
    return ectx, seg, in_range, off[:cap]


def _index_col(seg, start, in_range) -> ColumnVector:
    e = jnp.arange(seg.shape[0], dtype=jnp.int32)
    idx = jnp.where(in_range, e - start[seg], 0)
    return ColumnVector(T.INT32, idx, in_range)


class _HofBase(Expression):
    """Shared plumbing: children[0] is the collection, `body` the lambda
    body, `vars` its parameters. Lambda-parameter dtypes resolve lazily
    (the collection's element type is unknown until the analyzer binds
    column refs), so every dtype-dependent entry point calls
    _bind_types() first."""

    def __init__(self, child: Expression, body: Expression,
                 vars: List[LambdaVar]):
        self.children = [child, body]
        self.vars = vars

    def _bind_types(self) -> None:
        dt = self.children[0].data_type()
        if isinstance(dt, T.MapType):
            if len(self.vars) > 0:
                self.vars[0].dtype = dt.key
            if len(self.vars) > 1:
                self.vars[1].dtype = dt.value
        elif isinstance(dt, T.ArrayType):
            self.vars[0].dtype = dt.element
            if len(self.vars) > 1:
                self.vars[1].dtype = T.INT32

    def data_type(self):
        self._bind_types()
        return self._result_type()

    def _result_type(self):
        raise NotImplementedError

    def eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        self._bind_types()
        return self._eval_tpu(ctx)

    def eval_cpu(self, cols, ansi=False):
        self._bind_types()
        return self._eval_cpu(cols, ansi)

    @property
    def body(self):
        return self.children[1]

    def _params(self):
        return ",".join(v._params() for v in self.vars)

    def with_children(self, children):
        clone = type(self).__new__(type(self))
        clone.children = list(children)
        clone.vars = self.vars
        return clone

    # -- CPU helpers --------------------------------------------------------
    def _cpu_rows(self, cols, ansi):
        return self.children[0].eval_cpu(cols, ansi)

    def _cpu_eval_body(self, elem_cols_by_var: dict, outer: Sequence[CpuCol],
                       n_elems: int, ansi: bool) -> CpuCol:
        with _bound_cpu(elem_cols_by_var):
            return self.body.eval_cpu(outer, ansi)

    @staticmethod
    def _flatten_cpu(arr_col: CpuCol, elem_t: T.DataType):
        """(flat element CpuCol, per-row lengths, row validity)."""
        lens, flat, flat_ok = [], [], []
        for v, ok in zip(arr_col.values, arr_col.valid):
            if not ok or v is None:
                lens.append(0)
                continue
            lens.append(len(v))
            for el in v:
                flat.append(el)
                flat_ok.append(el is not None)
        return (_leaf_cpu_col(elem_t, flat, flat_ok),
                np.asarray(lens, np.int64), arr_col.valid)

    @staticmethod
    def _outer_repeat(outer: Sequence[CpuCol], lens) -> List[CpuCol]:
        out = []
        for c in outer:
            vals = np.repeat(c.values, lens)
            valid = np.repeat(c.valid, lens)
            out.append(CpuCol(c.dtype, vals, valid))
        return out


class ArrayTransform(_HofBase):
    """transform(arr, x -> expr) / transform(arr, (x, i) -> expr)."""

    def _result_type(self):
        return T.ArrayType(self.body.data_type())

    def _eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr = self.children[0].eval_tpu(ctx)
        child = arr.data["child"]
        bindings = {self.vars[0].var_id: child}
        ectx, seg, in_range, start = _element_ctx(ctx, arr, bindings)
        if len(self.vars) > 1:
            ectx.lambda_bindings[self.vars[1].var_id] = \
                _index_col(seg, start, in_range)
        out_child = self.body.eval_tpu(ectx)
        ctx.errors.extend(ectx.errors)
        return ColumnVector(self.data_type(),
                            {"offsets": arr.data["offsets"],
                             "child": out_child},
                            arr.validity)

    def _eval_cpu(self, cols, ansi=False):
        arr = self._cpu_rows(cols, ansi)
        elem_t = self.children[0].data_type().element
        flat, lens, row_ok = self._flatten_cpu(arr, elem_t)
        bind = {self.vars[0].var_id: flat}
        if len(self.vars) > 1:
            idx = np.concatenate([np.arange(n) for n in lens]) \
                if lens.sum() else np.zeros(0, np.int64)
            bind[self.vars[1].var_id] = CpuCol(
                T.INT32, idx.astype(np.int32),
                np.ones(len(idx), np.bool_))
        outer = self._outer_repeat(cols, lens)
        res = self._cpu_eval_body(bind, outer, int(lens.sum()), ansi)
        out, pos = [], 0
        for n, ok in zip(lens, row_ok):
            if not ok:
                out.append(None)
                continue
            row = [res.values[pos + j] if res.valid[pos + j] else None
                   for j in range(n)]
            vals = [v.item() if isinstance(v, np.generic) else v for v in row]
            out.append(vals)
            pos += n
        return CpuCol(self.data_type(), np.array(out, object),
                      np.asarray(row_ok, np.bool_))


class ArrayFilter(_HofBase):
    """filter(arr, x -> bool)."""

    def _result_type(self):
        return self.children[0].data_type()

    def _eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr = self.children[0].eval_tpu(ctx)
        child = arr.data["child"]
        child_cap = child.capacity
        bindings = {self.vars[0].var_id: child}
        ectx, seg, in_range, start = _element_ctx(ctx, arr, bindings)
        if len(self.vars) > 1:
            ectx.lambda_bindings[self.vars[1].var_id] = \
                _index_col(seg, start, in_range)
        pred = self.body.eval_tpu(ectx)
        ctx.errors.extend(ectx.errors)
        keep = pred.data.astype(jnp.bool_) & in_range
        if pred.validity is not None:
            keep = keep & pred.validity
        # stable compaction of kept elements within each row
        kpre = jnp.cumsum(keep.astype(jnp.int32))
        ex = kpre - keep.astype(jnp.int32)  # exclusive prefix
        kept_per_row = jax.ops.segment_sum(
            keep.astype(jnp.int32), seg, num_segments=arr.capacity)
        new_off = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(kept_per_row).astype(jnp.int32)])
        base = ex[jnp.clip(start[seg], 0, child_cap - 1)]
        dest = jnp.where(keep, new_off[seg] + (ex - base), child_cap)
        e = jnp.arange(child_cap, dtype=jnp.int32)
        src = jnp.full(child_cap + 1, -1, jnp.int32) \
            .at[dest].set(e, mode="drop")[:child_cap]
        from spark_rapids_tpu.ops import kernels as K
        out_child = K.gather_column(child, src, child_cap)
        return ColumnVector(self.data_type(),
                            {"offsets": new_off, "child": out_child},
                            arr.validity)

    def _eval_cpu(self, cols, ansi=False):
        arr = self._cpu_rows(cols, ansi)
        elem_t = self.children[0].data_type().element
        flat, lens, row_ok = self._flatten_cpu(arr, elem_t)
        bind = {self.vars[0].var_id: flat}
        if len(self.vars) > 1:
            idx = np.concatenate([np.arange(n) for n in lens]) \
                if lens.sum() else np.zeros(0, np.int64)
            bind[self.vars[1].var_id] = CpuCol(
                T.INT32, idx.astype(np.int32), np.ones(len(idx), np.bool_))
        outer = self._outer_repeat(cols, lens)
        pred = self._cpu_eval_body(bind, outer, int(lens.sum()), ansi)
        out, pos = [], 0
        for n, ok in zip(lens, row_ok):
            if not ok:
                out.append(None)
                continue
            row = []
            for j in range(n):
                if pred.valid[pos + j] and bool(pred.values[pos + j]):
                    v = flat.values[pos + j]
                    row.append(None if not flat.valid[pos + j]
                               else (v.item() if isinstance(v, np.generic)
                                     else v))
            out.append(row)
            pos += n
        return CpuCol(self.data_type(), np.array(out, object),
                      np.asarray(row_ok, np.bool_))


class _ArrayPredicateBase(_HofBase):
    """Shared exists/forall: per-row tri-state reduction over the lambda
    predicate (Spark three-valued logic)."""

    def _result_type(self):
        return T.BOOLEAN

    def _tpu_tristate(self, ctx):
        arr = self.children[0].eval_tpu(ctx)
        child = arr.data["child"]
        bindings = {self.vars[0].var_id: child}
        ectx, seg, in_range, _ = _element_ctx(ctx, arr, bindings)
        pred = self.body.eval_tpu(ectx)
        ctx.errors.extend(ectx.errors)
        pv = pred.data.astype(jnp.bool_)
        pok = (pred.validity if pred.validity is not None
               else jnp.ones(child.capacity, jnp.bool_))
        cap = arr.capacity
        any_true = jnp.zeros(cap, jnp.bool_).at[seg].max(
            pv & pok & in_range, mode="drop")
        any_false = jnp.zeros(cap, jnp.bool_).at[seg].max(
            ~pv & pok & in_range, mode="drop")
        any_null = jnp.zeros(cap, jnp.bool_).at[seg].max(
            ~pok & in_range, mode="drop")
        return arr, any_true, any_false, any_null

    def _cpu_tristate(self, cols, ansi):
        arr = self._cpu_rows(cols, ansi)
        elem_t = self.children[0].data_type().element
        flat, lens, row_ok = self._flatten_cpu(arr, elem_t)
        outer = self._outer_repeat(cols, lens)
        pred = self._cpu_eval_body({self.vars[0].var_id: flat}, outer,
                                   int(lens.sum()), ansi)
        at, af, an = [], [], []
        pos = 0
        for n in lens:
            t = f = nl = False
            for j in range(n):
                if not pred.valid[pos + j]:
                    nl = True
                elif bool(pred.values[pos + j]):
                    t = True
                else:
                    f = True
            at.append(t)
            af.append(f)
            an.append(nl)
            pos += n
        return (arr, np.asarray(at, np.bool_), np.asarray(af, np.bool_),
                np.asarray(an, np.bool_), np.asarray(row_ok, np.bool_))


class ArrayExists(_ArrayPredicateBase):
    """exists(arr, p): true if any true, else null if any null-pred, else
    false (Spark 3 three-valued semantics)."""

    def _eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr, any_true, any_false, any_null = self._tpu_tristate(ctx)
        valid = _valid_of(arr, ctx) & (any_true | ~any_null)
        return ColumnVector(T.BOOLEAN, any_true, valid)

    def _eval_cpu(self, cols, ansi=False):
        arr, at, af, an, row_ok = self._cpu_tristate(cols, ansi)
        return CpuCol(T.BOOLEAN, at, row_ok & (at | ~an))


class ArrayForAll(_ArrayPredicateBase):
    """forall(arr, p): false if any false, else null if any null-pred,
    else true."""

    def _eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        arr, any_true, any_false, any_null = self._tpu_tristate(ctx)
        valid = _valid_of(arr, ctx) & (any_false | ~any_null)
        return ColumnVector(T.BOOLEAN, ~any_false, valid)

    def _eval_cpu(self, cols, ansi=False):
        arr, at, af, an, row_ok = self._cpu_tristate(cols, ansi)
        return CpuCol(T.BOOLEAN, ~af, row_ok & (af | ~an))


class TransformValues(_HofBase):
    """transform_values(map, (k, v) -> expr)."""

    def _result_type(self):
        mt = self.children[0].data_type()
        return T.MapType(mt.key, self.body.data_type())

    def _eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        m = self.children[0].eval_tpu(ctx)
        keys, values = m.data["keys"], m.data["values"]
        bindings = {self.vars[0].var_id: keys,
                    self.vars[1].var_id: values}
        ectx, _, _, _ = _element_ctx(ctx, m, bindings)
        out_vals = self.body.eval_tpu(ectx)
        ctx.errors.extend(ectx.errors)
        return ColumnVector(self.data_type(),
                            {"offsets": m.data["offsets"], "keys": keys,
                             "values": out_vals}, m.validity)

    def _eval_cpu(self, cols, ansi=False):
        return _map_transform_cpu(self, cols, ansi, transform_key=False)


class TransformKeys(_HofBase):
    """transform_keys(map, (k, v) -> expr). Spark default dedup policy is
    EXCEPTION: duplicate produced keys raise."""

    def _result_type(self):
        mt = self.children[0].data_type()
        return T.MapType(self.body.data_type(), mt.value)

    def _eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        m = self.children[0].eval_tpu(ctx)
        keys, values = m.data["keys"], m.data["values"]
        bindings = {self.vars[0].var_id: keys,
                    self.vars[1].var_id: values}
        ectx, seg, in_range, _ = _element_ctx(ctx, m, bindings)
        out_keys = self.body.eval_tpu(ectx)
        ctx.errors.extend(ectx.errors)
        if out_keys.validity is not None:
            ctx.add_error("NullMapKey",
                          jnp.zeros(m.capacity, jnp.bool_).at[seg].max(
                              ~out_keys.validity & in_range, mode="drop"))
        # duplicate detection: sort (seg, key64) and compare neighbours
        from spark_rapids_tpu.ops import kernels as K
        k64, knull = K.normalize_key(out_keys, ectx.num_rows, live=in_range)
        child_cap = k64.shape[0]
        segK = jnp.where(in_range, seg, m.capacity)
        order = jnp.lexsort((k64, segK))
        ss, kk = segK[order], k64[order]
        dup = (ss[1:] == ss[:-1]) & (kk[1:] == kk[:-1]) \
            & (ss[1:] < m.capacity)
        dup_row = jnp.zeros(m.capacity + 1, jnp.bool_).at[
            jnp.where(dup, ss[1:], m.capacity)].max(True, mode="drop")
        ctx.add_error("DuplicateMapKey", dup_row[:m.capacity])
        return ColumnVector(self.data_type(),
                            {"offsets": m.data["offsets"], "keys": out_keys,
                             "values": values}, m.validity)

    def _eval_cpu(self, cols, ansi=False):
        return _map_transform_cpu(self, cols, ansi, transform_key=True)


def _map_transform_cpu(node: _HofBase, cols, ansi, transform_key: bool):
    m = node.children[0].eval_cpu(cols, ansi)
    mt = node.children[0].data_type()
    lens, fk, fv = [], [], []
    for v, ok in zip(m.values, m.valid):
        if not ok or v is None:
            lens.append(0)
            continue
        lens.append(len(v))
        for kk, vv in v:
            fk.append(kk)
            fv.append(vv)
    lens = np.asarray(lens, np.int64)
    kc = _leaf_cpu_col(mt.key, fk, [k is not None for k in fk])
    vc = _leaf_cpu_col(mt.value, fv, [x is not None for x in fv])
    outer = node._outer_repeat(cols, lens)
    res = node._cpu_eval_body(
        {node.vars[0].var_id: kc, node.vars[1].var_id: vc}, outer,
        int(lens.sum()), ansi)
    out, pos = [], 0
    for n, ok in zip(lens, m.valid):
        if not ok:
            out.append(None)
            continue
        entries = []
        seen = set()
        for j in range(n):
            r = res.values[pos + j] if res.valid[pos + j] else None
            r = r.item() if isinstance(r, np.generic) else r
            if transform_key:
                if r is None:
                    raise SparkException("Cannot use null as map key")
                if r in seen:
                    raise SparkException(f"Duplicate map key {r}")
                seen.add(r)
                entries.append((r, fv[pos + j] if pos + j < len(fv) else None))
            else:
                entries.append((fk[pos + j], r))
        out.append(entries)
        pos += n
    return CpuCol(node.data_type(), np.array(out, object),
                  np.asarray(m.valid, np.bool_))


class MapFilter(_HofBase):
    """map_filter(map, (k, v) -> bool)."""

    def _result_type(self):
        return self.children[0].data_type()

    def _eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        m = self.children[0].eval_tpu(ctx)
        keys, values = m.data["keys"], m.data["values"]
        child_cap = keys.capacity
        bindings = {self.vars[0].var_id: keys, self.vars[1].var_id: values}
        ectx, seg, in_range, start = _element_ctx(ctx, m, bindings)
        pred = self.body.eval_tpu(ectx)
        ctx.errors.extend(ectx.errors)
        keep = pred.data.astype(jnp.bool_) & in_range
        if pred.validity is not None:
            keep = keep & pred.validity
        kpre = jnp.cumsum(keep.astype(jnp.int32))
        ex = kpre - keep.astype(jnp.int32)
        kept_per_row = jax.ops.segment_sum(
            keep.astype(jnp.int32), seg, num_segments=m.capacity)
        new_off = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(kept_per_row).astype(jnp.int32)])
        base = ex[jnp.clip(start[seg], 0, child_cap - 1)]
        dest = jnp.where(keep, new_off[seg] + (ex - base), child_cap)
        e = jnp.arange(child_cap, dtype=jnp.int32)
        src = jnp.full(child_cap + 1, -1, jnp.int32) \
            .at[dest].set(e, mode="drop")[:child_cap]
        from spark_rapids_tpu.ops import kernels as K
        return ColumnVector(self.data_type(),
                            {"offsets": new_off,
                             "keys": K.gather_column(keys, src, child_cap),
                             "values": K.gather_column(values, src,
                                                       child_cap)},
                            m.validity)

    def _eval_cpu(self, cols, ansi=False):
        m = self.children[0].eval_cpu(cols, ansi)
        mt = self.children[0].data_type()
        lens, fk, fv = [], [], []
        for v, ok in zip(m.values, m.valid):
            if not ok or v is None:
                lens.append(0)
                continue
            lens.append(len(v))
            for kk, vv in v:
                fk.append(kk)
                fv.append(vv)
        lens = np.asarray(lens, np.int64)
        kc = _leaf_cpu_col(mt.key, fk, [k is not None for k in fk])
        vc = _leaf_cpu_col(mt.value, fv, [x is not None for x in fv])
        outer = self._outer_repeat(cols, lens)
        pred = self._cpu_eval_body(
            {self.vars[0].var_id: kc, self.vars[1].var_id: vc}, outer,
            int(lens.sum()), ansi)
        out, pos = [], 0
        for n, ok in zip(lens, m.valid):
            if not ok:
                out.append(None)
                continue
            out.append([(fk[pos + j], fv[pos + j]) for j in range(n)
                        if pred.valid[pos + j]
                        and bool(pred.values[pos + j])])
            pos += n
        return CpuCol(self.data_type(), np.array(out, object),
                      np.asarray(m.valid, np.bool_))


class ZipWith(_HofBase):
    """zip_with(a, b, (x, y) -> expr): element-wise over both arrays,
    padding the shorter with nulls."""

    def __init__(self, left: Expression, right: Expression,
                 body: Expression, vars: List[LambdaVar]):
        self.children = [left, body, right]
        self.vars = vars

    def with_children(self, children):
        clone = type(self).__new__(type(self))
        clone.children = list(children)
        clone.vars = self.vars
        return clone

    def _bind_types(self) -> None:
        lt = self.children[0].data_type()
        rt = self.children[2].data_type()
        if isinstance(lt, T.ArrayType):
            self.vars[0].dtype = lt.element
        if isinstance(rt, T.ArrayType):
            self.vars[1].dtype = rt.element

    def _result_type(self):
        return T.ArrayType(self.body.data_type())

    def _eval_tpu(self, ctx: EvalCtx) -> ColumnVector:
        from spark_rapids_tpu.ops import kernels as K
        a = self.children[0].eval_tpu(ctx)
        b = self.children[2].eval_tpu(ctx)
        cap = a.capacity
        aoff, boff = a.data["offsets"], b.data["offsets"]
        alen = aoff[1: cap + 1] - aoff[:cap]
        blen = boff[1: cap + 1] - boff[:cap]
        row_ok = ctx.row_mask & _valid_of(a, ctx) & _valid_of(b, ctx)
        olen = jnp.where(row_ok, jnp.maximum(alen, blen), 0)
        new_off = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(olen).astype(jnp.int32)])
        out_cap = a.data["child"].capacity + b.data["child"].capacity
        e = jnp.arange(out_cap, dtype=jnp.int32)
        seg = jnp.clip(
            jnp.searchsorted(new_off, e, side="right").astype(jnp.int32) - 1,
            0, cap - 1)
        in_range = e < new_off[cap]
        j = e - new_off[seg]  # position within the output row
        a_ok = in_range & (j < alen[seg])
        b_ok = in_range & (j < blen[seg])
        a_idx = jnp.where(a_ok, aoff[seg] + j, -1)
        b_idx = jnp.where(b_ok, boff[seg] + j, -1)
        av = K.gather_column(a.data["child"], a_idx,
                             a.data["child"].capacity)
        bv = K.gather_column(b.data["child"], b_idx,
                             b.data["child"].capacity)
        from spark_rapids_tpu.ops import kernels as K
        ectx = EvalCtx([], jnp.sum(in_range.astype(jnp.int32)), out_cap,
                       ctx.ansi, live=in_range,
                       partition_id=ctx.partition_id, row_base=ctx.row_base)
        ectx.columns = K.LazyGatheredCols(
            ctx.columns, jnp.where(in_range, seg, -1), ctx.num_rows)
        ectx.lambda_bindings = dict(getattr(ctx, "lambda_bindings", {}))
        ectx.lambda_bindings[self.vars[0].var_id] = av
        ectx.lambda_bindings[self.vars[1].var_id] = bv
        out_child = self.body.eval_tpu(ectx)
        ctx.errors.extend(ectx.errors)
        return ColumnVector(self.data_type(),
                            {"offsets": new_off, "child": out_child},
                            row_ok)

    def _eval_cpu(self, cols, ansi=False):
        a = self.children[0].eval_cpu(cols, ansi)
        b = self.children[2].eval_cpu(cols, ansi)
        at = self.children[0].data_type().element
        bt = self.children[2].data_type().element
        lens, fa, fb = [], [], []
        row_ok = []
        for (av, aok), (bv, bok) in zip(zip(a.values, a.valid),
                                        zip(b.values, b.valid)):
            ok = aok and bok and av is not None and bv is not None
            row_ok.append(ok)
            if not ok:
                lens.append(0)
                continue
            n = max(len(av), len(bv))
            lens.append(n)
            for j in range(n):
                fa.append(av[j] if j < len(av) else None)
                fb.append(bv[j] if j < len(bv) else None)
        lens = np.asarray(lens, np.int64)
        ac = _leaf_cpu_col(at, fa, [v is not None for v in fa])
        bc = _leaf_cpu_col(bt, fb, [v is not None for v in fb])
        outer = self._outer_repeat(cols, lens)
        res = self._cpu_eval_body(
            {self.vars[0].var_id: ac, self.vars[1].var_id: bc}, outer,
            int(lens.sum()), ansi)
        out, pos = [], 0
        for n, ok in zip(lens, row_ok):
            if not ok:
                out.append(None)
                continue
            row = [res.values[pos + j] if res.valid[pos + j] else None
                   for j in range(n)]
            out.append([v.item() if isinstance(v, np.generic) else v
                        for v in row])
            pos += n
        return CpuCol(self.data_type(), np.array(out, object),
                      np.asarray(row_ok, np.bool_))


class ArrayAggregate(_HofBase):
    """aggregate(arr, zero, (acc, x) -> merge[, acc -> finish]): a
    sequential per-row fold — order-dependent with an arbitrary merge
    lambda, so it runs on the CPU tier (the reference rejects it to CPU
    the same way for unsupported shapes)."""

    def __init__(self, child: Expression, zero: Expression,
                 merge_body: Expression, merge_vars: List[LambdaVar],
                 finish_body: Optional[Expression] = None,
                 finish_vars: Optional[List[LambdaVar]] = None):
        self.children = [child, merge_body, _wrap(zero)] + \
            ([finish_body] if finish_body is not None else [])
        self.vars = merge_vars
        self.finish_vars = finish_vars or []

    def with_children(self, children):
        clone = type(self).__new__(type(self))
        clone.children = list(children)
        clone.vars = self.vars
        clone.finish_vars = self.finish_vars
        return clone

    @property
    def merge_body(self):
        return self.children[1]

    @property
    def finish_body(self):
        return self.children[3] if len(self.children) > 3 else None

    def _result_type(self):
        fb = self.finish_body
        return fb.data_type() if fb is not None else \
            self.merge_body.data_type()

    def supported_on_tpu(self):
        return False

    def _bind_types(self) -> None:
        dt = self.children[0].data_type()
        if isinstance(dt, T.ArrayType):
            self.vars[1].dtype = dt.element
        self.vars[0].dtype = self.children[2].data_type()
        if self.finish_vars:
            self.finish_vars[0].dtype = self.merge_body.data_type()

    def _eval_tpu(self, ctx):
        raise NotImplementedError("aggregate() folds run on CPU")

    def _eval_cpu(self, cols, ansi=False):
        arr = self.children[0].eval_cpu(cols, ansi)
        zero = self.children[2].eval_cpu(cols, ansi)
        elem_t = self.children[0].data_type().element
        acc_t = self.merge_body.data_type()
        n = len(arr.values)
        acc_vals = list(zero.values)
        acc_ok = list(zero.valid)
        max_len = max((len(v) for v, ok in zip(arr.values, arr.valid)
                       if ok and v is not None), default=0)
        for step in range(max_len):
            xs, xok, active = [], [], []
            for i in range(n):
                v, ok = arr.values[i], arr.valid[i]
                if ok and v is not None and step < len(v):
                    active.append(i)
                    xs.append(v[step])
                    xok.append(v[step] is not None)
            if not active:
                break
            sub_acc = _leaf_cpu_col(acc_t, [acc_vals[i] for i in active],
                                    [acc_ok[i] for i in active])
            sub_x = _leaf_cpu_col(elem_t, xs, xok)
            with _bound_cpu({self.vars[0].var_id: sub_acc,
                             self.vars[1].var_id: sub_x}):
                outer = [CpuCol(c.dtype, c.values[active],
                                c.valid[active]) for c in cols]
                res = self.merge_body.eval_cpu(outer, ansi)
            for j, i in enumerate(active):
                acc_vals[i] = res.values[j]
                acc_ok[i] = bool(res.valid[j])
        out_ok = [bool(a and o) for a, o in zip(arr.valid, acc_ok)]
        if self.finish_body is not None:
            acc = _leaf_cpu_col(acc_t, acc_vals, acc_ok)
            with _bound_cpu({self.finish_vars[0].var_id: acc}):
                res = self.finish_body.eval_cpu(cols, ansi)
            return CpuCol(self.data_type(), res.values,
                          res.valid & np.asarray(arr.valid, np.bool_))
        return _leaf_cpu_col(self.data_type(),
                             [v if ok else None
                              for v, ok in zip(acc_vals, out_ok)], out_ok)
