"""Adaptive query execution on the MEASURED cost model (the AQE layer).

Reference parity: GpuQueryStagePrepOverrides / the AQE shims +
CostBasedOptimizer — Spark replans between stages from runtime
statistics. Here the statistics are better than Spark's: the compact
exchange already fetches exact per-partition row counts (one offsets
D2H per batch), and the kernel cost auditor (analysis/kernel_audit.py)
writes per-digest roofline verdicts into the query history store. This
module turns both into runtime decisions:

- ``AdaptiveShuffledHashJoinExec``: materialize the build-side exchange
  FIRST; when its measured bytes land under
  ``spark.rapids.sql.adaptive.broadcastThresholdBytes``, the probe-side
  exchange is never dispatched — the join replans as a broadcast hash
  join over the raw probe partitions (shuffle-hash -> broadcast
  conversion, the dispatch-storm killer).
- skew accounting for ``ExchangeExec``: partitions whose row count
  exceeds ``skewFactor`` x median split into bounded sub-dispatches
  (the split itself lives in tpu_nodes; the policy math is here).
- a cross-query broadcast-build cache keyed by build-plan digest +
  table registration version, next to the compile cache in spirit:
  entries die on any temp-view re-registration and never outlive the
  anchor relation's materialization.
- the decision RECORDER: every decision emits an ``aqeDecision`` trace
  instant, a ``rapids_aqe_decisions_total{kind}`` counter, an EXPLAIN
  ANALYZE "adaptive" section and an ``aqe`` field in the history
  record. A replan that cannot be seen did not happen.

The measured cost PASS (pick partition counts / fusion boundaries /
coalesce thresholds from per-digest history) lives in plan/cost.py;
its decisions are recorded through this module so all four pieces
share one observable surface.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec import tpu_nodes as X
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import trace as TR

# ---------------------------------------------------------------------------
# decision recorder (the observable surface every AQE piece reports to)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
#: the open query's decision list (collect depth 0 opens it; None
#: between queries — decisions made with no open query still trace and
#: count, they just have no history record to land in)
_CUR: Optional[List[dict]] = None

#: decision kinds (the rapids_aqe_decisions_total label values)
BROADCAST_CONVERSION = "broadcast_conversion"
SKEW_SPLIT = "skew_split"
BUILD_REUSE = "build_reuse"
MEASURED_COST = "measured_cost"


def enabled(conf) -> bool:
    return bool(conf.get(C.ADAPTIVE_ENABLED))


def on_query_start(conf=None) -> None:
    """Open the active query's decision list (collect depth 0). Cheap
    enough to run unconditionally: the disabled path pays one lock and
    one list allocation per ACTION, not per batch."""
    global _CUR
    with _LOCK:
        _CUR = []


def record(kind: str, *, dispatches_saved: int = 0, **detail: Any) -> None:
    """One adaptive decision, made first-class: appended to the open
    query's list (-> EXPLAIN ANALYZE + history), traced as an
    ``aqeDecision`` instant, and counted in the process registry."""
    d: Dict[str, Any] = {"kind": kind}
    d.update(detail)
    if dispatches_saved:
        d["dispatches_saved"] = int(dispatches_saved)
    with _LOCK:
        if _CUR is not None:
            _CUR.append(d)
    try:
        TR.instant("aqeDecision", cat="adaptive", args=d,
                   level=TR.ESSENTIAL)
    except Exception:  # noqa: BLE001 - a marker failure must not fail
        pass  # the query the decision just sped up
    try:
        from spark_rapids_tpu.runtime import obs as OBS
        st = OBS.state()
        if st is not None:
            st.registry.counter(
                "rapids_aqe_decisions_total",
                "Adaptive execution decisions by kind (aqeDecision "
                "instants; spark.rapids.sql.adaptive.*).",
                labels={"kind": kind}).inc()
            if dispatches_saved:
                st.registry.counter(
                    "rapids_aqe_dispatches_saved_total",
                    "Device dispatches adaptive execution avoided "
                    "(broadcast conversions skipping probe-side "
                    "exchanges, reused broadcast builds).").inc(
                        int(dispatches_saved))
    except Exception:  # noqa: BLE001 - observability never fails a query
        pass


def finish_query() -> Optional[dict]:
    """Close the active query's decision list into the ``aqe`` doc the
    session threads into EXPLAIN ANALYZE and the history record. None
    when the query made no adaptive decision."""
    global _CUR
    with _LOCK:
        cur, _CUR = _CUR, None
    if not cur:
        return None
    counts: Dict[str, int] = {}
    saved = 0
    for d in cur:
        counts[d["kind"]] = counts.get(d["kind"], 0) + 1
        saved += int(d.get("dispatches_saved", 0))
    return {"decisions": cur, "counts": counts, "dispatches_saved": saved}


def render_text(doc: Optional[dict]) -> List[str]:
    """EXPLAIN ANALYZE "adaptive" section (the render_text pattern of
    attribution / kernel_audit)."""
    if not doc:
        return []
    n = sum(doc.get("counts", {}).values())
    lines = [f"-- adaptive ({n} decision{'s' if n != 1 else ''}, "
             f"{doc.get('dispatches_saved', 0)} dispatches saved) --"]
    for d in doc.get("decisions", []):
        detail = ", ".join(f"{k}={v}" for k, v in d.items()
                           if k != "kind")
        lines.append(f"  {d['kind']}" + (f": {detail}" if detail else ""))
    return lines


# ---------------------------------------------------------------------------
# cross-query broadcast-build cache (digest + table version keyed)
# ---------------------------------------------------------------------------

#: bumped by every temp-view (re-)registration: a key minted under an
#: older epoch can never hit again, so a re-registered table invalidates
#: every cached build that might have read the replaced data
_TABLE_EPOCH = 0
_BUILD_CACHE: "OrderedDict[tuple, dict]" = OrderedDict()
_BUILD_CACHE_CAP = 8


def table_epoch() -> int:
    with _LOCK:
        return _TABLE_EPOCH


def bump_table_version() -> None:
    """A temp view was (re-)registered: invalidate the whole digest
    cache. Coarse on purpose — the digest cannot tell which relation a
    name now resolves to, and stale entries would pin replaced HBM."""
    global _TABLE_EPOCH
    with _LOCK:
        _TABLE_EPOCH += 1
        _BUILD_CACHE.clear()


def _build_cache_key(build_plan, skey) -> Optional[tuple]:
    try:
        from spark_rapids_tpu.runtime.obs.history import plan_digest
        digest = plan_digest(build_plan)
    except Exception:  # noqa: BLE001 - an undigestable build just
        return None  # doesn't participate in cross-query reuse
    with _LOCK:
        epoch = _TABLE_EPOCH
    return (digest, skey, epoch)


def build_cache_get(conf, build_plan, skey, anchor) -> Optional[dict]:
    """Look up a materialized broadcast build for this build-plan digest.
    The digest normalizes CachedRelation state out (two same-shaped
    relations collide), so a hit is only trusted when the entry's anchor
    AND its materialization are identity-identical to the live ones."""
    if anchor is None or not enabled(conf) \
            or not conf.get(C.ADAPTIVE_BUILD_REUSE):
        return None
    key = _build_cache_key(build_plan, skey)
    if key is None:
        return None
    with _LOCK:
        entry = _BUILD_CACHE.get(key)
        if entry is None:
            return None
        if entry.get("anchor") is not anchor \
                or entry["mat"] is not anchor.materialized:
            del _BUILD_CACHE[key]  # stale: stop pinning old batches
            return None
        _BUILD_CACHE.move_to_end(key)
    return entry


def build_cache_put(conf, build_plan, skey, anchor, entry: dict) -> None:
    if anchor is None or not enabled(conf) \
            or not conf.get(C.ADAPTIVE_BUILD_REUSE):
        return
    key = _build_cache_key(build_plan, skey)
    if key is None:
        return
    e = dict(entry)
    e["anchor"] = anchor
    with _LOCK:
        while len(_BUILD_CACHE) >= _BUILD_CACHE_CAP:
            _BUILD_CACHE.popitem(last=False)
        _BUILD_CACHE[key] = e


# ---------------------------------------------------------------------------
# skew policy (the split mechanics live on ExchangeExec)
# ---------------------------------------------------------------------------

def skew_threshold(conf, totals: List[Optional[int]]
                   ) -> Optional[Tuple[int, int]]:
    """(threshold_rows, median_rows) for a materialized exchange's
    per-partition row totals, or None when splitting must not engage:
    adaptive off, factor <= 0, fewer than 2 partitions with known
    counts, or nothing exceeds the threshold anyway. ``None`` totals
    (lazy/masked counts that would sync) are excluded from the median
    and their partitions never split."""
    if not enabled(conf):
        return None
    factor = float(conf.get(C.ADAPTIVE_SKEW_FACTOR))
    if factor <= 0:
        return None
    known = sorted(t for t in totals if t is not None)
    if len(known) < 2:
        return None
    mid = len(known) // 2
    median = known[mid] if len(known) % 2 else (
        (known[mid - 1] + known[mid]) // 2)
    threshold = int(factor * max(median, 1))
    if known[-1] <= threshold:
        return None
    return threshold, max(int(median), 1)


# ---------------------------------------------------------------------------
# shuffle-hash -> broadcast conversion
# ---------------------------------------------------------------------------

class AdaptiveShuffledHashJoinExec(X.TpuExec):
    """The planned-as-shuffled join that measures before dispatching
    (tentpole piece (a); reference GpuCustomShuffleReaderExec reading a
    materialized stage + the AQE broadcast demotion): the BUILD side's
    exchange materializes first — its per-partition row counts are
    already exact host ints from the compact offsets fetch — and when
    the measured device bytes land at or under
    spark.rapids.sql.adaptive.broadcastThresholdBytes the probe-side
    exchange is never built: the join replans as a broadcast hash join
    over the RAW probe partitions, eliminating the probe partitioning
    kernels, their offsets fetches, and the per-sub-batch dispatch
    storm downstream. Over the threshold (or when measuring would sync
    a lazy count) the already-materialized exchange feeds the shuffled
    join unchanged — the measurement is never wasted work.

    Differs from AdaptiveJoinExec (the est-unknown planner fallback):
    this node exists where the planner DID estimate the build side as
    big; the conversion catches estimates that were wrong at runtime,
    and the build side is measured THROUGH its exchange so the shuffled
    path never re-executes the child."""

    def __init__(self, plan, children, conf, part_keys):
        super().__init__(plan, children, conf)
        self.part_keys = part_keys
        self._lock = threading.Lock()
        self._chosen: Optional[X.TpuExec] = None

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    @staticmethod
    def _measure(parts) -> Optional[Tuple[int, int, int]]:
        """(device_bytes, rows, batches) across a materialized
        exchange's output, or None when any count would sync (masked
        sub-batches, lazily-deserialized shuffle blobs) — the decision
        must stay free, exactly like _coalesce_tiny's."""
        nbytes = nrows = nbatches = 0
        for part in parts:
            for b in part:
                if not isinstance(b, ColumnarBatch) \
                        or b.row_mask is not None \
                        or not isinstance(b.num_rows, int):
                    return None
                nrows += b.num_rows
                nbytes += int(b.device_memory_size())
                nbatches += 1
        return nbytes, nrows, nbatches

    def _choose(self) -> X.TpuExec:
        with self._lock:
            if self._chosen is not None:
                return self._chosen
            left, right = self.children
            lkeys, rkeys = self.part_keys
            n_out = left.num_partitions
            rex = X.ShuffleExchangeExec(self.plan, [right], self.conf,
                                        rkeys, n_out)
            threshold = int(self.conf.get(C.ADAPTIVE_BROADCAST_BYTES))
            measured = None
            if threshold > 0 and enabled(self.conf) \
                    and self.plan.how not in ("right", "full"):
                # right/full track probe-side matches across the whole
                # build: they need the single-probe-partition collect
                # plan, so they keep the shuffled path here
                parts = rex._materialize()
                measured = self._measure(parts)
            if measured is not None and measured[0] <= threshold:
                nbytes, nrows, nbatches = measured
                batches = [b for part in parts for b in part]
                src = X._MaterializedExec(self.plan.children[1], batches,
                                          self.conf)
                self._chosen = X.BroadcastHashJoinExec(
                    self.plan, [left, src], self.conf)
                # the avoided work: the probe-side partitioning kernels
                # + offsets fetches the exchange we never built would
                # have dispatched. The build side's own tally is the
                # best same-shaped estimate available without running
                # the probe.
                saved = int(
                    rex.metrics.metric(M.PARTITION_DISPATCHES).value
                    + rex.metrics.metric(M.PARTITION_HOST_FETCHES).value)
                record(BROADCAST_CONVERSION, build_bytes=nbytes,
                       build_rows=nrows, build_batches=nbatches,
                       threshold_bytes=threshold, n_out=n_out,
                       dispatches_saved=max(saved, 1))
            else:
                lex = X.ShuffleExchangeExec(self.plan, [left], self.conf,
                                            lkeys, n_out)
                self._chosen = X.ShuffledHashJoinExec(
                    self.plan, [lex, rex], self.conf,
                    part_keys=self.part_keys)
            return self._chosen

    def execute_partition(self, ctx, pidx):
        yield from self._choose().execute_partition(ctx, pidx)


# ---------------------------------------------------------------------------
# test hook
# ---------------------------------------------------------------------------

def reset_for_tests() -> None:
    """Drop all process-global adaptive state (tests/conftest.py's
    _reset_runtime): the open decision list, the build cache, and the
    table epoch."""
    global _CUR, _TABLE_EPOCH
    with _LOCK:
        _CUR = None
        _TABLE_EPOCH = 0
        _BUILD_CACHE.clear()
