"""Whole-stage compilation of expression lists.

The TPU-idiomatic replacement for cuDF's kernel-per-expression model
(reference GpuProjectExec/GpuFilterExec calling one cudf kernel per op,
basicPhysicalOperators.scala): an entire projection/filter expression list
is traced once into a single jitted XLA computation per (expression
fingerprint, batch capacity bucket, column layout). XLA fuses the whole
stage; num_rows is a traced scalar so row-count changes don't recompile.

ANSI errors surface as per-code boolean planes returned from the jitted fn;
the host raises SparkException if any fire (data-dependent raising cannot
happen inside a trace).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector, ColumnarBatch
from spark_rapids_tpu.expr.core import EvalCtx, Expression, SparkException
from spark_rapids_tpu.runtime import compile_cache as _cc


def _planes_of(col: ColumnVector):
    if isinstance(col.data, dict):
        out = dict(col.data)
        out["validity"] = col.validity
        return out
    return {"data": col.data, "validity": col.validity}


def _col_from_planes(planes, dtype: T.DataType) -> ColumnVector:
    planes = dict(planes)
    validity = planes.pop("validity")
    if "data" in planes:
        return ColumnVector(dtype, planes["data"], validity)
    return ColumnVector(dtype, planes, validity)


def _layout_key(col: ColumnVector):
    if isinstance(col.data, dict):
        kind = ("dict" if "codes" in col.data else
                "arr" if "child" in col.data else
                "map" if "keys" in col.data else
                "struct" if "children" in col.data else "str")
        parts = []
        for k in sorted(col.data):
            v = col.data[k]
            if isinstance(v, ColumnVector):
                parts.append((k, _layout_key(v)))
            elif isinstance(v, list):
                parts.append((k, tuple(_layout_key(x) for x in v)))
            else:
                parts.append((k, v.shape))
        return (kind,) + tuple(parts) + (col.validity is None,)
    return (str(col.data.dtype), col.data.shape, col.validity is None)


def run_stage(exprs: Sequence[Expression], batch: ColumnarBatch,
              ansi: bool = False) -> List[ColumnVector]:
    """Evaluate expressions over a batch as one jitted stage."""
    # shape discipline (runtime/shapes.py): capacities arriving here are
    # bucketed BY CONSTRUCTION — every capacity decision in the engine
    # routes through round_capacity, which delegates to the bucket
    # ladder — so the capacity in the cache key below ranges over a
    # small set and traces share across batches and queries. (Padding
    # in-place here would be unsound: callers hold the ORIGINAL batch's
    # planes and combine them with these outputs — see
    # shapes.ensure_bucketed for the ingestion-side canonicalizer.
    # The one deliberate off-ladder source, masked concat's
    # sum-of-capacities, is bounded by its input buckets.)
    fp = tuple(e.fingerprint() for e in exprs)
    layout = tuple(_layout_key(c) for c in batch.columns)
    key = (fp, layout, batch.capacity, ansi)
    in_dtypes = [c.dtype for c in batch.columns]
    out_dtypes = [e.data_type() for e in exprs]
    cap = batch.capacity  # capture the int, NOT the batch (a closure
    # holding the batch would pin its device planes in the stage cache)

    def build():
        def stage(col_planes, num_rows, live):
            cols = [_col_from_planes(p, dt) for p, dt in zip(col_planes, in_dtypes)]
            ctx = EvalCtx(cols, num_rows, cap, ansi, live=live)
            outs = [e.eval_tpu(ctx) for e in exprs]
            out_planes = [_planes_of(c) for c in outs]
            err = {code: mask for code, mask in ctx.errors}
            return out_planes, err
        return stage

    # the sanctioned compile choke point (runtime/compile_cache.py):
    # storage, hit/miss stats, first-call compile attribution
    fn = _cc.get("run_stage", key, build)

    from spark_rapids_tpu.columnar.batch import traced_rows
    from spark_rapids_tpu.exec import fuse
    from spark_rapids_tpu.runtime import lifecycle as _lc
    from spark_rapids_tpu.runtime import trace as TR
    _lc.check_current()  # run_stage is the OTHER per-batch dispatch path
    fuse.notify_dispatch(("run_stage", fp))  # dispatch-budget hook
    col_planes = [_planes_of(c) for c in batch.columns]
    with TR.span("compiled.run_stage", cat="dispatch", level=TR.DEBUG,
                 args={"exprs": len(exprs)}):
        out_planes, err = fn(col_planes,
                             jnp.asarray(traced_rows(batch.num_rows),
                                         jnp.int32),
                             batch.live_mask())
    raise_errors(err)
    outs = [_col_from_planes(p, dt) for p, dt in zip(out_planes, out_dtypes)]
    carry_bounds(exprs, batch.columns, outs)
    return outs


def carry_bounds(exprs, in_cols, out_cols) -> None:
    """Carry column-stat bounds (host metadata, not pytree leaves) across
    a jit boundary for passthrough column references."""
    from spark_rapids_tpu.expr.core import Alias, BoundRef
    for e, o in zip(exprs, out_cols):
        inner = e.children[0] if isinstance(e, Alias) else e
        if isinstance(inner, BoundRef) and inner.index < len(in_cols):
            o.bounds = in_cols[inner.index].bounds


def raise_errors(err: Dict[str, jax.Array]) -> None:
    """Check ANSI error planes from a fused stage. Only synchronizes when
    the stage ran in ANSI mode and produced error masks."""
    if err:
        for code, mask in err.items():
            if bool(jnp.any(mask)):
                raise SparkException(f"[{code}] ANSI mode error in stage")


def run_projection(exprs: Sequence[Expression], batch: ColumnarBatch,
                   ansi: bool = False) -> ColumnarBatch:
    cols = run_stage(exprs, batch, ansi)
    return ColumnarBatch(cols, batch.num_rows, batch.row_mask)


def can_compile(e: Expression) -> Tuple[bool, str]:
    """Best-effort static check that an expression will trace on device;
    the overrides engine uses this plus the registry checks."""
    sup = getattr(e, "supported_on_tpu", None)
    if sup is not None and not sup():
        return False, f"{type(e).__name__} not supported on TPU"
    for c in e.children:
        ok, why = can_compile(c)
        if not ok:
            return False, why
    return True, ""
