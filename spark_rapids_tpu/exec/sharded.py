"""Sharded stage execution: one SPMD dispatch per batch-WAVE over the mesh.

Whole-stage fusion (exec/stage_fusion.py) already collapsed each pipeline
stage to one dispatch per batch — but a 16-partition query still issues 16
independent single-device programs per wave of input, and every one of
them pays the full host->device round trip. Under
``spark.rapids.sql.multichip.enabled`` this pass goes one level up: it
rewrites eligible ``FusedStageExec`` nodes into ``ShardedStageExec``,
which packs one batch per partition into a single set of
``[n_shards * capacity]`` planes, lays them across the ``part`` axis of
the device mesh, and runs the SAME composed member-body chain per-shard
inside ``shard_map`` — one XLA dispatch per wave instead of one per
partition, with aggregate HBM bandwidth scaling with the mesh.

Eligibility (the v1 restriction set; everything else falls back per-shard
to the single-device fused path through the tagging tree):

- every member body is carry-free and non-exhausting (a LIMIT budget or
  row_base carry is per-partition loop state that cannot live inside one
  SPMD program);
- the stage's input and output schemas are fixed-width (flat string /
  nested planes are per-batch ragged — their byte-plane shapes differ per
  shard, so they cannot pack into one uniform SPMD operand). Dict-encoded
  shuffle keys still cross the mesh: they ride ShuffleExchangeExec's ICI
  all-to-all, which aligns vocabs host-side before the collective;
- a chain rooted at DeviceDecodeScanExec is excluded for the same
  raggedness reason (encoded vocab planes vary per batch).

The planner records WHY a stage stayed single-device on the node
(``_shard_fallback_reason``) so plan dumps can show it. Runtime failures
(a trace that won't compose under shard_map) degrade the same way the
fused path degrades to the unfused chain: per-slot replay through a fresh
single-device FusedStageExec over the already-pulled batches.

Dispatches ride the ordinary fuse.fused choke point — lifecycle
checkpoints, the device.dispatch fault site, the watchdog, the
dispatch-budget hook, and the compile cache's mesh-fingerprinted keys all
apply unchanged. Per-wave shard row counts feed the kernel cost auditor
(kernel_audit.note_shards) so shard skew shows up as a column in the
roofline table and EXPLAIN ANALYZE.
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (ColumnVector, ColumnarBatch,
                                             traced_rows)
from spark_rapids_tpu.exec import compiled, fuse
from spark_rapids_tpu.exec.stage_fusion import (_ReplaySourceExec,
                                                fused_stage_cls)
from spark_rapids_tpu.parallel import mesh as MESH
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import trace as TR

log = logging.getLogger("spark_rapids_tpu")

#: column dtypes whose device planes are per-batch ragged: they cannot
#: pack into one uniform SPMD operand (see module header)
_WIDE_TYPES = (T.StringType, T.ArrayType, T.StructType, T.MapType)


class _NotShardable(Exception):
    """Runtime layout guard: a wave's batches cannot pack (dict/encoded
    planes slipped past the static schema check). Triggers the per-slot
    single-device fallback, never an error."""


def _exec_base():
    from spark_rapids_tpu.exec import tpu_nodes as X
    return X


def make_sharded_stage_exec():
    X = _exec_base()

    class ShardedStageExec(X.TpuExec):
        """A fused stage executed per-shard inside shard_map: one SPMD
        dispatch per wave of (up to) n_shards partition batches. Members
        keep their plan nodes and metrics exactly as under FusedStageExec;
        only the dispatch granularity changes."""

        def __init__(self, plan, children, conf, members, stage_id=0,
                     n_shards=1):
            super().__init__(plan, children, conf)
            self.members = members
            self.stage_id = stage_id
            self.n_shards = int(n_shards)
            self.bodies = [m.stage_body() for m in members]
            self._key_bodies = tuple(b.key for b in self.bodies)
            self._mesh = None  # built lazily at first materialization
            self._failed = False
            self._out: Optional[List[list]] = None
            import threading
            self._lock = threading.Lock()

        @property
        def schema(self):
            return self.members[-1].schema

        def name(self) -> str:
            ops = "+".join(type(m).__name__.replace("Exec", "")
                           for m in reversed(self.members))
            return f"ShardedStageExec({ops})x{self.n_shards}"

        def tree_string(self, indent: int = 0) -> str:
            pad = "  " * indent
            sid = self.stage_id
            lines = [f"{pad}*({sid}) {self.name()} "
                     f"[sharded n={self.n_shards}]"]
            for m in reversed(self.members):
                lines.append(f"{pad}  *({sid}) {type(m).__name__} "
                             f"<- {m.plan.describe()} [sharded]")
            lines.append(self.children[0].tree_string(indent + 1))
            return "\n".join(lines)

        # -- dispatch ----------------------------------------------------

        def _build(self, in_dtypes):
            bodies = self.bodies
            mesh = self._mesh
            spec = P(MESH.PART_AXIS)

            def build():
                fns = [b.builder() for b in bodies]

                def shard_fn(col_planes, live, nrows, pid):
                    cols = [ColumnVector(dt, p["data"], p["validity"])
                            for p, dt in zip(col_planes, in_dtypes)]
                    batch = ColumnarBatch(cols, nrows[0], live)
                    errs_all, rows = [], []
                    for f, b in zip(fns, bodies):
                        batch, errs, _ = f(batch, pid[0], b.init_carry())
                        errs_all.append(errs)
                        rows.append(jnp.sum(
                            batch.live_mask().astype(jnp.int64)
                        ).reshape(1))
                    out_planes = [compiled._planes_of(c)
                                  for c in batch.columns]
                    return (out_planes, batch.live_mask(),
                            tuple(errs_all), tuple(rows))

                return shard_map(shard_fn, mesh=mesh,
                                 in_specs=(spec, spec, spec, spec),
                                 out_specs=(spec, spec, spec, spec))
            return build

        def _pack(self, slots, in_dtypes, pids, cap):
            """Concatenate one (possibly absent) batch per shard slot into
            [m*cap] planes. Dead slots pack as all-dead zero planes, so
            every wave dispatches the full mesh shape."""
            m = self.n_shards
            n_cols = len(in_dtypes)
            col_data = [[] for _ in range(n_cols)]
            col_val = [[] for _ in range(n_cols)]
            live_parts, nr_parts, bounds = [], [], []
            for b in slots:
                if b is None:
                    for j, dt in enumerate(in_dtypes):
                        col_data[j].append(jnp.zeros(cap, dt.np_dtype))
                        col_val[j].append(jnp.zeros(cap, jnp.bool_))
                    live_parts.append(jnp.zeros(cap, jnp.bool_))
                    nr_parts.append(jnp.int32(0))
                    bounds.append(None)
                    continue
                bcap = b.capacity
                pad = cap - bcap
                live = b.live_mask()
                if pad:
                    live = jnp.concatenate(
                        [live, jnp.zeros(pad, jnp.bool_)])
                live_parts.append(live)
                nr_parts.append(jnp.asarray(traced_rows(b.num_rows),
                                            jnp.int32))
                bounds.append([c.bounds for c in b.columns])
                for j, c in enumerate(b.columns):
                    d = c.data
                    if isinstance(d, dict):
                        raise _NotShardable(
                            f"column {j} has ragged dict planes")
                    if pad:
                        d = jnp.concatenate(
                            [d, jnp.zeros(pad, d.dtype)])
                    v = c.validity
                    if v is None:
                        v = jnp.ones(bcap, jnp.bool_)
                    if pad:
                        v = jnp.concatenate(
                            [v, jnp.zeros(pad, jnp.bool_)])
                    col_data[j].append(d)
                    col_val[j].append(v)
            planes = [{"data": jnp.concatenate(col_data[j]),
                       "validity": jnp.concatenate(col_val[j])}
                      for j in range(n_cols)]
            live = jnp.concatenate(live_parts)
            nrs = jnp.stack(nr_parts)
            pid_arr = jnp.asarray(
                [pids[i] if i < len(pids) else 0 for i in range(m)],
                jnp.int32)
            return planes, live, nrs, pid_arr, bounds

        def _coalesce(self, batches):
            """Concatenate one partition's pulled batches host-side into
            ONE batch, so a group dispatches one wave per STAGE instead
            of one per upstream batch. Post-exchange partitions hold one
            batch per SENDER (the aggregate merge's unique-key contract
            at the exchange edge), which would otherwise cost n_senders
            waves per stage. Members here are carry-free row-local ops
            (the eligibility set), so batch boundaries within a
            partition carry no semantics for this stage. Numpy concat
            is a memcpy; the packed planes device_put once per wave.
            The stage holds a whole group's partitions at once either
            way, so this does not change the peak-memory order."""
            if len(batches) <= 1:
                return batches
            if any(isinstance(c.data, dict)
                   for b in batches for c in b.columns):
                return batches  # ragged dict planes: per-batch waves
            live = np.concatenate(
                [np.asarray(b.live_mask()) for b in batches])
            cols = []
            for j in range(len(batches[0].columns)):
                parts = [b.columns[j] for b in batches]
                data = np.concatenate(
                    [np.asarray(c.data) for c in parts])
                validity = np.concatenate(
                    [np.ones(c.capacity, np.bool_) if c.validity is None
                     else np.asarray(c.validity) for c in parts])
                cols.append(ColumnVector(parts[0].dtype, data, validity))
            return [ColumnarBatch(cols, int(live.sum()), live)]

        def _out_bounds(self, in_bounds, out_cols):
            if in_bounds is None:
                return
            bounds = in_bounds
            for b in self.bodies:
                if b.bounds_map is None:
                    return
                bounds = b.bounds_map(bounds)
            for c, bd in zip(out_cols, bounds):
                if bd is not None:
                    c.bounds = bd

        # -- fallbacks ---------------------------------------------------

        def _single_delegate(self, source):
            """A single-device FusedStageExec over `source`, sharing this
            node's metrics registry so fallback rows still land under the
            sharded stage in last_metrics/explain."""
            cls = fused_stage_cls()
            d = cls(self.plan, [source], self.conf, self.members,
                    stage_id=self.stage_id)
            d.metrics = self.metrics
            return d

        # -- the wave loop -----------------------------------------------

        def _materialize(self, ctx):
            child = self.children[0]
            nparts = child.num_partitions
            m = self.n_shards
            outs: List[list] = [[] for _ in range(nparts)]
            in_dtypes = [f.dtype for f in child.schema.fields]
            out_dtypes = [f.dtype for f in self.schema.fields]
            out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
            in_batches = self.metrics.metric(M.NUM_INPUT_BATCHES)
            disp = self.metrics.metric(M.STAGE_DISPATCHES)
            waves = self.metrics.metric(M.SHARD_WAVES)
            member_t = [mb.metrics.metric(M.OP_TIME)
                        for mb in self.members]
            member_rows = [mb.metrics.metric(M.NUM_OUTPUT_ROWS)
                           for mb in self.members]
            from spark_rapids_tpu.analysis import kernel_audit as KA
            from spark_rapids_tpu.expr.core import SparkException
            from spark_rapids_tpu.runtime.lifecycle import \
                QueryCancelledError
            from spark_rapids_tpu.runtime.retry import with_retry_no_split
            if self._mesh is None:
                self._mesh = MESH.make_mesh(
                    m, dp=1, axis_names=(MESH.PART_AXIS,))
            sharding = NamedSharding(self._mesh, P(MESH.PART_AXIS))

            for g0 in range(0, nparts, m):
                slot_pids = list(range(g0, min(g0 + m, nparts)))
                if self._failed:
                    for pidx in slot_pids:
                        outs[pidx] = list(self._single_delegate(
                            child).execute_partition(ctx, pidx))
                    continue
                queues = [self._coalesce(list(
                    child.execute_partition(ctx, p)))
                    for p in slot_pids]
                for w in range(max((len(q) for q in queues), default=0)):
                    slots: List[Optional[ColumnarBatch]] = [
                        q[w] if w < len(q) else None for q in queues]
                    n_live = sum(1 for b in slots if b is not None)
                    if n_live == 0:
                        break
                    slots.extend([None] * (m - len(slots)))
                    cap = max(b.capacity for b in slots
                              if b is not None)
                    self._acquire(ctx)
                    MESH.check_mesh_devices(self._mesh)
                    in_batches.add(n_live)
                    t0 = time.perf_counter_ns()
                    try:
                        planes, live, nrs, pid_arr, bounds = self._pack(
                            slots, in_dtypes, slot_pids, cap)
                        key = ("sharded_stage", self._key_bodies, m, cap,
                               tuple(str(dt.np_dtype)
                                     for dt in in_dtypes))
                        fn = fuse.fused(key, self._build(in_dtypes))
                        args = jax.device_put(
                            (planes, live, nrs, pid_arr), sharding)
                        # retry-on-OOM wraps the wave exactly as the
                        # single-device fused dispatch is wrapped: a
                        # device OOM replays the SAME wave (no split —
                        # the pack is already capacity-bucketed), and
                        # only a non-OOM trace failure degrades to the
                        # per-slot fallback below
                        out_planes, out_live, errs_all, rows = \
                            with_retry_no_split(lambda: fn(*args))
                    except (SparkException, MESH.MeshDeviceError,
                            QueryCancelledError):
                        # typed errors (incl. a cooperative cancel at
                        # the compile/dispatch checkpoints) propagate:
                        # the fallback is for shard-map trace failures,
                        # not for resurrecting cancelled work
                        raise
                    except Exception:
                        # per-slot replay through the single-device fused
                        # path: the already-pulled batches must not
                        # re-execute the source (stage_fusion fallback
                        # discipline, lifted one level)
                        self._failed = True
                        log.warning(
                            "sharded stage trace failed for %s; falling "
                            "back to the single-device fused path",
                            self.name(), exc_info=True)
                        for i, pidx in enumerate(slot_pids):
                            rest = queues[i][w:]
                            if not rest:
                                continue
                            src = _ReplaySourceExec(
                                child.schema, rest, iter(()))
                            outs[pidx].extend(self._single_delegate(
                                src).execute_partition(ctx, pidx))
                        break
                    dt_ns = time.perf_counter_ns() - t0
                    if TR.active() is not None:
                        TR.emit_span(self.name(), t0, dt_ns, cat="exec",
                                     args={"stage_id": self.stage_id,
                                           "n_shards": m,
                                           "live_slots": n_live})
                        TR.instant("shardedDispatch", cat="dispatch",
                                   args={"stage_id": self.stage_id})
                    for errs in errs_all:
                        compiled.raise_errors(errs)
                    disp.add(1)
                    waves.add(1)
                    # ONE host assembly per wave, then numpy slicing.
                    # Eager ops on the sharded outputs (a slice, a sum)
                    # each run the full GSPMD partitioner — measured
                    # 20-40x a single-device op on the CPU mesh, and a
                    # sharded jnp.sum even launches a cross-device
                    # all-reduce. device_get only gathers the local
                    # shards (no XLA program). The emitted batches keep
                    # the host numpy planes: every consumer either
                    # feeds them back into a jitted kernel (which
                    # accepts numpy) or packs them for the next wave /
                    # exchange, and per-slice device re-uploads here
                    # measured ~0.15ms x n_slots x n_planes per wave.
                    out_planes, out_live, rows = jax.device_get(
                        (out_planes, out_live, rows))
                    share = dt_ns // len(self.members)
                    for mt, mr, r in zip(member_t, member_rows, rows):
                        mt.add(share)
                        mr.add(int(r.sum()))
                    KA.note_shards(m, rows[-1])
                    cap_out = int(out_live.shape[0]) // m
                    for i, pidx in enumerate(slot_pids):
                        if slots[i] is None:
                            continue
                        lo, hi = i * cap_out, (i + 1) * cap_out
                        mask = out_live[lo:hi]

                        def _slice(x, lo=lo, hi=hi):
                            return None if x is None else x[lo:hi]
                        cols = [compiled._col_from_planes(
                            {k: _slice(v) for k, v in p.items()}, dt)
                            for p, dt in zip(out_planes, out_dtypes)]
                        self._out_bounds(bounds[i], cols)
                        nr = int(mask.sum())
                        out_rows.add(nr)
                        outs[pidx].append(ColumnarBatch(cols, nr, mask))
            return outs

        def execute_partition(self, ctx, pidx):
            with self._lock:
                if self._out is None:
                    self._out = self._materialize(ctx)
            yield from self._out[pidx]

    return ShardedStageExec


_SHARDED_CLS = None


def sharded_stage_cls():
    global _SHARDED_CLS
    if _SHARDED_CLS is None:
        _SHARDED_CLS = make_sharded_stage_exec()
    return _SHARDED_CLS


# ---------------------------------------------------------------------------
# The planner pass
# ---------------------------------------------------------------------------

def _fallback_reason(node) -> Optional[str]:
    """None when the fused stage can shard; otherwise the reason it stays
    single-device (recorded on the node for plan dumps)."""
    X = _exec_base()
    for b in node.bodies:
        if b.has_carry or b.exhausts:
            return (f"member {b.name or b.key[0]} carries per-partition "
                    "loop state (row_base/limit budget) that cannot live "
                    "inside one SPMD program")
    if any(isinstance(mb, X.DeviceDecodeScanExec) for mb in node.members):
        return ("device-decode input planes are per-batch ragged "
                "(encoded vocab sizes differ per shard)")
    schemas = [node.children[0].schema] + [mb.schema for mb in node.members]
    for sch in schemas:
        for f in sch.fields:
            if isinstance(f.dtype, _WIDE_TYPES):
                return (f"column {f.name} is {type(f.dtype).__name__}: "
                        "ragged byte planes cannot pack into one SPMD "
                        "operand")
    return None


def shard_stages(exec_root, conf):
    """Entry point: rewrite eligible FusedStageExec nodes into
    ShardedStageExec (applied by plan/overrides.convert_plan after
    fuse_stages, before pipeline insertion). No-op unless
    spark.rapids.sql.multichip.enabled."""
    if not conf.get(C.MULTICHIP_ENABLED):
        return exec_root
    m = MESH.multichip_devices(conf)
    fused_cls = fused_stage_cls()
    cls = sharded_stage_cls()

    def rewrite(node):
        node.children = [rewrite(c) for c in node.children]
        if isinstance(node, fused_cls):
            reason = _fallback_reason(node)
            if reason is None:
                return cls(node.plan, node.children, node.conf,
                           node.members, stage_id=node.stage_id,
                           n_shards=m)
            node._shard_fallback_reason = reason
            log.debug("stage %d stays single-device: %s",
                      node.stage_id, reason)
        return node

    return rewrite(exec_root)
