"""CPU reference backend: a pandas/numpy interpreter of the plan algebra.

This plays the role CPU Spark plays for the reference's differential test
harness (integration_tests asserts GPU results == CPU results;
SURVEY.md §4.1): an independent implementation the TPU engine is diffed
against, and the fallback executor for operators/expressions the TPU
planner rejects (reference per-operator fallback).

Implementation notes:
- Data currency is List[CpuCol] (numpy values + validity) per plan schema.
- Grouping/joining keys are pre-normalized to exact integer codes so SQL
  semantics hold where pandas' own NaN/NA rules differ (NaN groups equal,
  nulls group together, null join keys never match).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import CpuCol, Expression
from spark_rapids_tpu.expr.aggregates import CountAll, NamedAgg
from spark_rapids_tpu.plan import nodes as P


# ---------------------------------------------------------------------------
# pyarrow <-> CpuCol
# ---------------------------------------------------------------------------

def table_to_cols(table: pa.Table) -> List[CpuCol]:
    out = []
    for i, field in enumerate(table.schema):
        dtype = T.from_arrow(field.type)
        arr = table.column(i).combine_chunks()
        valid = np.ones(len(arr), np.bool_) if arr.null_count == 0 \
            else np.asarray(arr.is_valid())
        if isinstance(dtype, (T.StringType, T.ArrayType, T.StructType,
                              T.MapType)):
            vals = np.empty(len(arr), object)
            vals[:] = arr.to_pylist()
        elif isinstance(dtype, T.DecimalType):
            vals = np.array([0 if v is None else int(v.scaleb(dtype.scale))
                             for v in arr.to_pylist()], np.int64)
        elif isinstance(dtype, T.TimestampType):
            vals = np.asarray(arr.cast(pa.timestamp("us")).fill_null(0)) \
                .astype("datetime64[us]").astype(np.int64)
        elif isinstance(dtype, T.DateType):
            vals = np.asarray(arr.fill_null(0)).astype("datetime64[D]").astype(np.int32)
        elif isinstance(dtype, T.NullType):
            vals = np.zeros(len(arr), np.int8)
            valid = np.zeros(len(arr), np.bool_)
        else:
            fill = False if pa.types.is_boolean(arr.type) else 0
            vals = np.asarray(arr.fill_null(fill)).astype(dtype.np_dtype)
        out.append(CpuCol(dtype, vals, valid))
    return out


def cols_to_table(cols: List[CpuCol], names: List[str]) -> pa.Table:
    arrays = []
    fields = []
    for c, name in zip(cols, names):
        at = T.to_arrow(c.dtype)
        if isinstance(c.dtype, T.StringType):
            vals = [v if (ok and isinstance(v, str)) else None
                    for v, ok in zip(c.values, c.valid)]
            arr = pa.array(vals, type=at)
        elif isinstance(c.dtype, (T.ArrayType, T.StructType, T.MapType)):
            vals = [v if ok else None for v, ok in zip(c.values, c.valid)]
            arr = pa.array(vals, type=at)
        elif isinstance(c.dtype, T.NullType):
            arr = pa.nulls(len(c.values), type=at)
        elif isinstance(c.dtype, T.DecimalType):
            import decimal
            vals = [decimal.Decimal(int(v)).scaleb(-c.dtype.scale) if ok else None
                    for v, ok in zip(c.values, c.valid)]
            arr = pa.array(vals, type=at)
        elif isinstance(c.dtype, T.TimestampType):
            arr = pa.array(c.values.astype("datetime64[us]"), type=at, mask=~c.valid)
        elif isinstance(c.dtype, T.DateType):
            arr = pa.array(c.values.astype(np.int32).astype("datetime64[D]"),
                           type=at, mask=~c.valid)
        else:
            arr = pa.array(c.values.astype(c.dtype.np_dtype), type=at, mask=~c.valid)
        arrays.append(arr)
        fields.append(pa.field(name, at))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def _gather_cols(cols: List[CpuCol], idx: np.ndarray) -> List[CpuCol]:
    """Row gather with -1 -> null."""
    out = []
    oob = idx < 0
    safe = np.where(oob, 0, idx)
    for c in cols:
        is_obj = isinstance(c.dtype, (T.StringType, T.ArrayType,
                                      T.StructType, T.MapType))
        if len(c.values) == 0:
            np_dt = object if is_obj else c.dtype.np_dtype
            out.append(CpuCol(c.dtype, np.zeros(len(idx), np_dt),
                              np.zeros(len(idx), np.bool_)))
            continue
        vals = c.values[safe]
        if is_obj:
            vals = vals.copy()
            vals[oob] = None
        valid = c.valid[safe] & ~oob
        out.append(CpuCol(c.dtype, vals, valid))
    return out


# ---------------------------------------------------------------------------
# Key normalization for grouping/joining/sorting (exact SQL semantics)
# ---------------------------------------------------------------------------

def _norm_key_np(c: CpuCol, shared_dict: Optional[dict] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (uint64 order-preserving codes, null_mask). shared_dict lets
    join sides share one string dictionary."""
    nulls = ~c.valid
    if isinstance(c.dtype, T.StringType):
        if shared_dict is None:
            uniq = sorted({v for v, ok in zip(c.values, c.valid) if ok and v is not None})
            shared_dict = {s: i for i, s in enumerate(uniq)}
        codes = np.array([shared_dict.get(v, 0) if ok else 0
                          for v, ok in zip(c.values, c.valid)], np.uint64)
        return codes, nulls
    if isinstance(c.dtype, (T.Float32Type, T.Float64Type)):
        v = c.values.astype(np.float64)
        v = np.where(np.isnan(v), np.nan, v)
        v = np.where(v == 0.0, 0.0, v)  # -0.0 -> +0.0
        bits = v.view(np.uint64) if v.dtype == np.float64 else v.astype(np.float64).view(np.uint64)
        bits = np.where(np.isnan(v), np.uint64(0x7FF8000000000000), bits)
        neg = (bits >> np.uint64(63)) != 0
        key = np.where(neg, ~bits, bits | np.uint64(1 << 63))
        return np.where(nulls, np.uint64(0), key), nulls
    if isinstance(c.dtype, (T.ArrayType, T.StructType)):
        # Spark nested ordering: lexicographic, null element first, NaN
        # greatest. Rank rows by a recursive tuple encoding.
        keys = [(_encode_sortable(v, c.dtype) if ok else ())
                for v, ok in zip(c.values, c.valid)]
        order = sorted(range(len(keys)), key=lambda i: keys[i])
        ranks = np.zeros(len(keys), np.uint64)
        for pos, idx in enumerate(order):
            ranks[idx] = pos
        return np.where(nulls, np.uint64(0), ranks), nulls
    if isinstance(c.dtype, T.MapType):
        from spark_rapids_tpu.expr.core import SparkException
        raise SparkException("map type cannot be used in ORDER BY or "
                             "grouping keys")
    key = c.values.astype(np.int64).view(np.uint64) ^ np.uint64(1 << 63)
    return np.where(nulls, np.uint64(0), key), nulls


def _encode_sortable(v, dt: T.DataType):
    """Recursive tuple encoding whose python ordering matches Spark's
    nested-type ordering (element null-first, NaN greatest)."""
    if isinstance(dt, T.ArrayType):
        return tuple((0,) if x is None else (1, _encode_sortable(x, dt.element))
                     for x in v)
    if isinstance(dt, T.StructType):
        return tuple(
            (0,) if v.get(f.name) is None
            else (1, _encode_sortable(v[f.name], f.dtype))
            for f in dt.fields)
    if isinstance(dt, (T.Float32Type, T.Float64Type)):
        fv = float(v)
        if fv != fv:
            return (2, 0.0)
        return (1, 0.0 + fv)  # -0.0 -> +0.0 for total-order ties
    return (1, v)


def _shared_string_dict(*cols: CpuCol) -> dict:
    uniq = set()
    for c in cols:
        uniq |= {v for v, ok in zip(c.values, c.valid) if ok and v is not None}
    return {s: i for i, s in enumerate(sorted(uniq))}


# ---------------------------------------------------------------------------
# Node interpreters
# ---------------------------------------------------------------------------

def execute_cpu(plan: P.PlanNode, ansi: bool = False) -> pa.Table:
    cols = _exec(plan, ansi)
    return cols_to_table(cols, plan.schema.names)


def _exec(plan: P.PlanNode, ansi: bool) -> List[CpuCol]:
    return apply_node(plan, [_exec(c, ansi) for c in plan.children], ansi)


def apply_node(plan: P.PlanNode, children: List[List[CpuCol]],
               ansi: bool = False) -> List[CpuCol]:
    """Interpret one plan node given its children's results. Used both by the
    full-plan interpreter and by per-operator CPU fallback inside TPU plans
    (the reference's convertIfNeeded fallback path)."""
    if isinstance(plan, P.InMemorySource):
        return table_to_cols(plan.table)
    if isinstance(plan, P.ParquetScan):
        from spark_rapids_tpu.io import read_parquet_file
        tables = [plan.with_partition_cols(
            read_parquet_file(p, getattr(plan, "file_columns",
                                         plan.columns)), i)
            for i, p in enumerate(plan.paths)]
        table = pa.concat_tables(tables, promote_options="permissive") \
            if len(tables) > 1 else tables[0]
        return table_to_cols(table)
    if isinstance(plan, P.TextScan):
        tables = [plan.read_host(p) for p in plan.paths]
        table = pa.concat_tables(tables, promote_options="permissive") \
            if len(tables) > 1 else tables[0]
        return table_to_cols(table)
    if isinstance(plan, P.CachedRelation):
        return children[0]
    if isinstance(plan, P.ShuffleFileScan):
        from spark_rapids_tpu.columnar.batch import to_arrow
        from spark_rapids_tpu.shuffle.exchange_files import (
            read_partition_batches,
        )
        tables = []
        for r in range(plan.n_reduce):
            for b in read_partition_batches(plan.root, r):
                tables.append(to_arrow(b, plan.schema.names))
        table = pa.concat_tables(tables) if tables else \
            pa.table({n: pa.array([], T.to_arrow(t))
                      for n, t in zip(plan.schema.names, plan.schema.types)})
        return table_to_cols(table)
    if isinstance(plan, P.Range):
        vals = np.arange(plan.start, plan.end, plan.step, np.int64)
        return [CpuCol(T.INT64, vals, np.ones(len(vals), np.bool_))]
    if isinstance(plan, P.Project):
        return [e.eval_cpu(children[0], ansi) for e in plan.exprs]
    if isinstance(plan, P.Filter):
        pred = plan.condition.eval_cpu(children[0], ansi)
        keep = pred.values.astype(np.bool_) & pred.valid
        return _gather_cols(children[0], np.nonzero(keep)[0])
    if isinstance(plan, P.Aggregate):
        return _exec_aggregate(plan, children[0], ansi)
    if isinstance(plan, P.Sort):
        return _exec_sort(plan, children[0], ansi)
    if isinstance(plan, P.Limit):
        child = children[0]
        n = len(child[0].values) if child else 0
        return _gather_cols(child, np.arange(min(plan.n, n)))
    if isinstance(plan, P.Union):
        return _exec_union(plan, children)
    if isinstance(plan, P.Repartition):
        # partitioning is a physical-layout concern: row-wise the result
        # is the child unchanged (comparisons downstream ignore order)
        return children[0]
    if isinstance(plan, P.WindowNode):
        return _exec_window(plan, children[0], ansi)
    if isinstance(plan, P.Join):
        return _exec_join(plan, children[0], children[1], ansi)
    if isinstance(plan, P.Generate):
        return _exec_generate(plan, children[0], ansi)
    if isinstance(plan, P.Expand):
        child = children[0]
        parts = []
        for proj in plan.projections:
            parts.append([e.eval_cpu(child, ansi) for e in proj])
        out = []
        out_types = plan.schema.types
        for i in range(len(plan.projections[0])):
            vals = np.concatenate([_cast_vals(p[i], out_types[i]) for p in parts])
            valid = np.concatenate([p[i].valid for p in parts])
            out.append(CpuCol(out_types[i], vals, valid))
        return out
    raise NotImplementedError(f"CPU backend: {type(plan).__name__}")


def _cast_vals(c: CpuCol, dt: T.DataType):
    if isinstance(dt, (T.StringType, T.ArrayType, T.StructType, T.MapType)):
        return c.values
    return c.values.astype(dt.np_dtype)


def _exec_generate(plan: "P.Generate", child: List[CpuCol], ansi: bool
                   ) -> List[CpuCol]:
    gen = plan.generator
    src = gen.children[0].eval_cpu(child, ansi)
    is_map = isinstance(gen.children[0].data_type(), T.MapType)
    position = bool(getattr(gen, "position", False))
    outer = bool(gen.outer)
    parent_idx: List[int] = []
    pos_vals: List[int] = []
    gen_vals: List[list] = [[] for _ in plan.gen_fields]
    g_off = 1 if position else 0
    for i, (v, ok) in enumerate(zip(src.values, src.valid)):
        items = v if (ok and v is not None) else None
        if not items:
            if outer:
                parent_idx.append(i)
                pos_vals.append(None)
                for g in gen_vals:
                    g.append(None)
            continue
        for j, el in enumerate(items):
            parent_idx.append(i)
            pos_vals.append(j)
            if is_map:
                k, val = el
                gen_vals[g_off].append(k)
                gen_vals[g_off + 1].append(val)
            else:
                gen_vals[g_off].append(el)
    if position:
        gen_vals[0] = pos_vals
    out = _gather_cols([child[i] for i in plan.required],
                       np.asarray(parent_idx, np.int64))
    for (name, dt), vals in zip(plan.gen_fields, gen_vals):
        ok = [v is not None for v in vals]
        if isinstance(dt, (T.StringType, T.ArrayType, T.StructType, T.MapType)):
            arr = np.empty(len(vals), object)
            arr[:] = vals
            out.append(CpuCol(dt, arr, np.asarray(ok, np.bool_)))
        else:
            np_vals = np.array([0 if v is None else v for v in vals],
                               dt.np_dtype)
            out.append(CpuCol(dt, np_vals, np.asarray(ok, np.bool_)))
    return out


def _exec_union(plan: P.Union, parts: List[List[CpuCol]]) -> List[CpuCol]:
    out = []
    for i, f in enumerate(plan.schema.fields):
        vals = np.concatenate([_cast_vals(p[i], f.dtype) for p in parts])
        valid = np.concatenate([p[i].valid for p in parts])
        out.append(CpuCol(f.dtype, vals, valid))
    return out


def _exec_window(plan: "P.WindowNode", child: List[CpuCol], ansi: bool
                 ) -> List[CpuCol]:
    """Reference semantics for window functions, evaluated row-by-row per
    sorted partition (test-scale interpreter)."""
    from spark_rapids_tpu.expr import window as WE
    from spark_rapids_tpu.expr import aggregates as A
    n = len(child[0].values) if child else 0
    spec = plan.window_exprs[0].spec
    pc = [_norm_key_np(e.eval_cpu(child, ansi)) for e in spec.partition_exprs]
    oc = [_norm_key_np(o.expr.eval_cpu(child, ansi)) for o in spec.order_specs]
    # sort by (partition, order) with spark null ordering; lexsort's last
    # key is primary, so push order keys first then partition keys
    keys = []
    for (code, nulls), o in zip(reversed(oc), reversed(spec.order_specs)):
        nf = o.resolved_nulls_first()
        keys.append(code if o.ascending else ~code)
        keys.append(np.where(nulls, 0 if nf else 1, 1 if nf else 0).astype(np.uint8))
    for code, nulls in reversed(pc):
        keys.append(code)
        keys.append(nulls.astype(np.uint8))
    perm = np.lexsort(keys) if keys else np.arange(n)
    out = _gather_cols(child, perm)

    def boundary(cols_codes):
        b = np.zeros(n, np.bool_)
        if n:
            b[0] = True
        for code, nulls in cols_codes:
            cs, ns = code[perm], nulls[perm]
            b[1:] |= (cs[1:] != cs[:-1]) | (ns[1:] != ns[:-1])
        return b

    segb = boundary(pc)
    peerb = (segb | boundary(oc)) if oc else segb.copy()
    for w, name in zip(plan.window_exprs, plan.names):
        out.append(_one_window_cpu(w, child, perm, segb, peerb, n, ansi))
    return out


def _one_window_cpu(w, child, perm, segb, peerb, n, ansi) -> CpuCol:
    from spark_rapids_tpu.expr import window as WE
    from spark_rapids_tpu.expr import aggregates as A
    fn = w.fn
    rt = fn.result_type()
    frame = w.spec.resolved_frame()
    starts = np.flatnonzero(segb)
    bounds = list(starts) + [n]
    vals = np.zeros(n, object)
    valid = np.ones(n, np.bool_)
    src = None
    if fn.children:
        src = fn.children[0].eval_cpu(child, ansi)
        src = CpuCol(src.dtype, src.values[perm], src.valid[perm])
    for gi in range(len(starts)):
        lo, hi = bounds[gi], bounds[gi + 1]
        rows = range(lo, hi)
        if isinstance(fn, WE.RowNumber):
            for i in rows:
                vals[i] = i - lo + 1
        elif isinstance(fn, (WE.Rank, WE.DenseRank)):
            r = d = 0
            for i in rows:
                if peerb[i] or i == lo:
                    r = i - lo + 1
                    d += 1
                vals[i] = r if isinstance(fn, WE.Rank) else d
        elif isinstance(fn, WE.NTile):
            size = hi - lo
            base, rem = divmod(size, fn.n)
            for i in rows:
                pos = i - lo
                cut = (base + 1) * rem
                vals[i] = (pos // (base + 1) if pos < cut
                           else rem + (pos - cut) // max(base, 1)) + 1
        elif isinstance(fn, WE.LeadLag):
            off = fn.offset if fn.is_lead else -fn.offset
            for i in rows:
                j = i + off
                if lo <= j < hi:
                    vals[i] = src.values[j]
                    valid[i] = bool(src.valid[j])
                elif fn.default is not None:
                    vals[i] = fn.default
                else:
                    valid[i] = False
        elif isinstance(fn, WE.PercentRank):
            size = hi - lo
            r = 0
            for i in rows:
                if peerb[i] or i == lo:
                    r = i - lo + 1
                vals[i] = 0.0 if size <= 1 else (r - 1) / (size - 1)
        elif isinstance(fn, WE.CumeDist):
            size = hi - lo
            for i in rows:
                e = i
                while e + 1 < hi and not peerb[e + 1]:
                    e += 1
                vals[i] = (e - lo + 1) / size
        elif isinstance(fn, (WE.NthValue, WE.FirstValue, WE.LastValue)):
            for i in rows:
                if frame.upper is None:
                    fe = hi - 1
                elif frame.kind == "rows":
                    fe = min(i + frame.upper, hi - 1)
                else:  # range: frame end = end of peer group (+bound)
                    fe = i
                    while fe + 1 < hi and not peerb[fe + 1]:
                        fe += 1
                fs = lo
                if frame.lower is not None and frame.kind == "rows":
                    fs = max(i + frame.lower, lo)
                if isinstance(fn, WE.LastValue):
                    pos = fe
                elif isinstance(fn, WE.FirstValue):
                    pos = fs
                else:
                    pos = fs + fn.n - 1
                    if pos > fe:
                        valid[i] = False
                        continue
                if pos < fs or pos > fe:
                    valid[i] = False
                    continue
                vals[i] = src.values[pos]
                valid[i] = bool(src.valid[pos])
        elif isinstance(fn, WE.WindowAgg):
            agg = fn.fn
            for i in rows:
                if frame.kind == "range" and frame.upper == 0:
                    e = i
                    while e + 1 < hi and not peerb[e + 1]:
                        e += 1
                    a, b = lo, e
                elif frame.lower is None and frame.upper is None:
                    a, b = lo, hi - 1
                elif frame.kind == "rows":
                    a = lo if frame.lower is None else max(i + frame.lower, lo)
                    b = hi - 1 if frame.upper is None else min(i + frame.upper, hi - 1)
                else:
                    a, b = lo, i
                if isinstance(agg, A.CountAll):
                    vals[i] = max(b - a + 1, 0)
                    continue
                window_vals = [src.values[j] for j in range(a, b + 1)
                               if src.valid[j]] if b >= a else []
                if isinstance(agg, A.Count):
                    vals[i] = len(window_vals)
                elif not window_vals:
                    valid[i] = False
                elif isinstance(agg, A.Sum):
                    vals[i] = sum(window_vals)
                elif isinstance(agg, A.Average):
                    vals[i] = float(sum(window_vals)) / len(window_vals)
                elif isinstance(agg, (A.Min, A.Max)):
                    import math
                    key = (lambda x: (isinstance(x, float) and math.isnan(x), x))
                    vals[i] = (min if isinstance(agg, A.Min) else max)(
                        window_vals, key=key)
                elif isinstance(agg, (A.First, A.Last)):
                    vals[i] = window_vals[-1 if isinstance(agg, A.Last) else 0]
                elif isinstance(agg, A._MomentAgg):
                    arr = np.asarray(window_vals, np.float64)
                    ddof = 1 if isinstance(agg, (A.StddevSamp, A.VarianceSamp)) else 0
                    if len(arr) <= ddof:
                        valid[i] = False
                    elif isinstance(agg, (A.StddevSamp, A.StddevPop)):
                        vals[i] = float(np.std(arr, ddof=ddof))
                    else:
                        vals[i] = float(np.var(arr, ddof=ddof))
                else:
                    raise NotImplementedError(type(agg).__name__)
        else:
            raise NotImplementedError(type(fn).__name__)
    if isinstance(rt, T.StringType):
        np_vals = np.array([v if valid[i] else None
                            for i, v in enumerate(vals)], object)
    else:
        np_vals = np.array([v if valid[i] else 0 for i, v in enumerate(vals)]
                           ).astype(rt.np_dtype)
    return CpuCol(rt, np_vals, valid)


def _exec_sort(plan: P.Sort, child: List[CpuCol], ansi: bool) -> List[CpuCol]:
    n = len(child[0].values) if child else 0
    if n == 0:
        return child
    # np.lexsort: last key is primary
    keys = []
    for o in reversed(plan.orders):
        c = o.expr.eval_cpu(child, ansi)
        code, nulls = _norm_key_np(c)
        if not o.ascending:
            code = ~code
        nf = o.resolved_nulls_first()
        null_plane = np.where(nulls, 0 if nf else 1, 1 if nf else 0).astype(np.uint8)
        keys.append(code)
        keys.append(null_plane)
    perm = np.lexsort(keys)
    return _gather_cols(child, perm)


def _exec_aggregate(plan: P.Aggregate, child: List[CpuCol], ansi: bool) -> List[CpuCol]:
    n = len(child[0].values) if child else 0
    key_cols = [e.eval_cpu(child, ansi) for e in plan.group_exprs]

    # evaluate agg inputs (all children: min_by/max_by consume two)
    agg_inputs: List[Optional[List[CpuCol]]] = []
    for a in plan.aggs:
        if isinstance(a.fn, CountAll) or not a.fn.children:
            agg_inputs.append(None)
        else:
            agg_inputs.append([c.eval_cpu(child, ansi) for c in a.fn.children])

    if not key_cols:
        return _global_agg(plan, agg_inputs, n)

    # group ids via normalized codes
    df_data = {}
    for i, kc in enumerate(key_cols):
        code, nulls = _norm_key_np(kc)
        s = pd.array(code.view(np.int64), dtype="Int64")
        s[nulls] = pd.NA
        df_data[f"__k{i}"] = s
    df = pd.DataFrame(df_data)
    grouped = df.groupby(list(df_data.keys()), dropna=False, sort=True)
    gid = grouped.ngroup().to_numpy()
    n_groups = int(gid.max()) + 1 if n else 0
    first_idx = np.zeros(n_groups, np.int64)
    seen = np.zeros(n_groups, np.bool_)
    for i in range(n - 1, -1, -1):
        first_idx[gid[i]] = i
    out: List[CpuCol] = []
    for kc in key_cols:
        out.append(_gather_cols([kc], first_idx)[0])
    for a, inp in zip(plan.aggs, agg_inputs):
        out.append(_agg_by_gid(a, inp, gid, n_groups))
    return out


def _agg_by_gid(a: NamedAgg, inp, gid: np.ndarray,
                n_groups: int) -> CpuCol:
    from spark_rapids_tpu.expr.aggregates import SegmentedAgg
    if isinstance(a.fn, SegmentedAgg):
        return a.fn.eval_cpu_groups(inp, gid, n_groups)
    spec = a.fn.pandas_spec()
    rt = a.fn.result_type()
    if spec == "size":
        cnt = np.bincount(gid, minlength=n_groups).astype(np.int64)
        return CpuCol(T.INT64, cnt, np.ones(n_groups, np.bool_))
    assert inp is not None
    inp = inp[0]
    if isinstance(inp.dtype, (T.Float32Type, T.Float64Type)):
        # pandas conflates NaN with null; floats need explicit Spark
        # semantics (NaN is a VALUE: sums/avg propagate it, min/max use the
        # total order where NaN > +inf).
        return _agg_float_np(spec, rt, inp, gid, n_groups)
    valid = inp.valid
    if isinstance(inp.dtype, T.StringType):
        ser = pd.Series([v if ok else None for v, ok in zip(inp.values, valid)],
                        dtype=object)
    else:
        vals = inp.values.astype(np.float64) if not inp.dtype.is_integral \
            else inp.values.astype(np.int64)
        if inp.dtype.is_integral or isinstance(inp.dtype, (T.BooleanType, T.DateType,
                                                           T.TimestampType, T.DecimalType)):
            ser = pd.Series(pd.array(inp.values.astype(np.int64), dtype="Int64"))
        else:
            ser = pd.Series(pd.array(vals, dtype="Float64"))
        ser[~valid] = pd.NA
    g = ser.groupby(pd.Series(gid))
    ddof = None
    if isinstance(spec, tuple):
        spec, ddof = spec
    if spec == "sum":
        res = g.sum(min_count=1)
    elif spec == "count":
        res = g.count()
    elif spec == "mean":
        res = g.mean()
    elif spec == "min":
        res = g.min()
    elif spec == "max":
        res = g.max()
    elif spec == "first":
        res = g.first()
    elif spec == "last":
        res = g.last()
    elif spec == "std":
        res = g.std(ddof=1 if ddof is None else ddof)
    elif spec == "var":
        res = g.var(ddof=1 if ddof is None else ddof)
    else:
        raise NotImplementedError(spec)
    res = res.reindex(range(n_groups))
    na = res.isna().to_numpy()
    if isinstance(rt, T.StringType):
        vals = res.to_numpy(dtype=object)
        return CpuCol(rt, vals, ~na)
    # extract without a float64 round trip — int64 sums/minima beyond 2^53
    # must stay exact
    if np.dtype(rt.np_dtype).kind in "iub":
        filled = res.fillna(0).to_numpy(dtype=np.int64)
    else:
        filled = res.fillna(0).to_numpy(dtype=np.float64)
    if spec == "mean" and isinstance(a.fn.children[0].data_type(),
                                     T.DecimalType):
        # decimal state is unscaled int64; the mean must be a VALUE
        filled = filled / 10.0 ** a.fn.children[0].data_type().scale
    return CpuCol(rt, filled.astype(rt.np_dtype), ~na)


def _agg_float_np(spec, rt, inp: CpuCol, gid: np.ndarray, n_groups: int) -> CpuCol:
    ddof = None
    if isinstance(spec, tuple):
        spec, ddof = spec
    v = inp.values.astype(np.float64)
    valid = inp.valid
    order = np.argsort(gid, kind="stable")
    gs, vs, oks = gid[order], v[order], valid[order]
    starts = np.searchsorted(gs, np.arange(n_groups), side="left")
    nvalid = np.bincount(gs, weights=oks.astype(np.float64),
                         minlength=n_groups).astype(np.int64)
    has = nvalid > 0
    with np.errstate(all="ignore"):
        if spec == "count":
            return CpuCol(T.INT64, nvalid, np.ones(n_groups, np.bool_))
        if spec in ("sum", "mean", "std", "var"):
            sums = np.add.reduceat(np.where(oks, vs, 0.0), starts) \
                if n_groups else np.zeros(0)
            if spec == "sum":
                return CpuCol(rt, sums, has)
            if spec == "mean":
                return CpuCol(rt, sums / np.maximum(nvalid, 1), has)
            sq = np.add.reduceat(np.where(oks, vs * vs, 0.0), starts) \
                if n_groups else np.zeros(0)
            n_ = nvalid.astype(np.float64)
            m2 = np.maximum(sq - sums * sums / np.maximum(n_, 1.0), 0.0)
            # propagate NaN through m2 when sums are NaN
            m2 = np.where(np.isnan(sums) | np.isnan(sq), np.nan, m2)
            dd = 1 if ddof is None else ddof
            denom = n_ - dd
            var = np.where(denom <= 0, np.nan, m2 / np.where(denom <= 0, 1.0, denom))
            out = np.sqrt(var) if spec == "std" else var
            return CpuCol(rt, out, has)
        if spec in ("min", "max"):
            # total-order bits reduction
            vv = np.where(vs == 0.0, 0.0, vs)
            bits = vv.view(np.uint64)
            neg = (bits >> np.uint64(63)) != 0
            key = np.where(neg, ~bits, bits | np.uint64(1 << 63))
            ident = np.uint64(0xFFFFFFFFFFFFFFFF) if spec == "min" else np.uint64(0)
            key = np.where(oks, key, ident)
            red = np.minimum if spec == "min" else np.maximum
            out_key = red.reduceat(key, starts) if n_groups else key[:0]
            pos = (out_key & np.uint64(1 << 63)) != 0
            raw = np.where(pos, out_key ^ np.uint64(1 << 63), ~out_key)
            out = raw.view(np.float64)
            return CpuCol(rt, out.astype(rt.np_dtype), has)
        if spec in ("first", "last"):
            pos = np.where(oks, np.arange(len(vs)), len(vs) if spec == "first" else -1)
            red = np.minimum if spec == "first" else np.maximum
            sel = red.reduceat(pos, starts) if n_groups else pos[:0]
            ok = (sel >= 0) & (sel < len(vs))
            out = vs[np.clip(sel, 0, max(len(vs) - 1, 0))]
            return CpuCol(rt, out.astype(rt.np_dtype), has & ok)
    raise NotImplementedError(spec)


def _global_agg(plan: P.Aggregate, agg_inputs, n: int) -> List[CpuCol]:
    out = []
    gid = np.zeros(max(n, 0), np.int64)
    for a, inp in zip(plan.aggs, agg_inputs):
        if n == 0:
            from spark_rapids_tpu.expr.aggregates import SegmentedAgg
            rt = a.fn.result_type()
            if isinstance(a.fn, SegmentedAgg):
                if isinstance(rt, T.ArrayType):  # collect_* of empty = []
                    vals = np.empty(1, object)
                    vals[0] = []
                    out.append(CpuCol(rt, vals, np.ones(1, np.bool_)))
                else:
                    npdt = object if isinstance(rt, T.StringType) \
                        else rt.np_dtype
                    out.append(CpuCol(rt, np.zeros(1, npdt),
                                      np.zeros(1, np.bool_)))
                continue
            if a.fn.pandas_spec() in ("size", "count"):
                out.append(CpuCol(T.INT64, np.zeros(1, np.int64),
                                  np.ones(1, np.bool_)))
            else:
                npdt = object if isinstance(rt, T.StringType) else rt.np_dtype
                out.append(CpuCol(rt, np.zeros(1, npdt), np.zeros(1, np.bool_)))
        else:
            out.append(_agg_by_gid(a, inp, gid, 1))
    return out


def _exec_join(plan: P.Join, left: List[CpuCol], right: List[CpuCol],
               ansi: bool) -> List[CpuCol]:
    ln = len(left[0].values) if left else 0
    rn = len(right[0].values) if right else 0
    lk = [e.eval_cpu(left, ansi) for e in plan.left_keys]
    rk = [e.eval_cpu(right, ansi) for e in plan.right_keys]

    if plan.how == "cross" or not plan.left_keys:
        # cross join, or non-equi join (empty keys): all pairs, then the
        # condition filter below prunes; outer completion follows
        lidx = np.repeat(np.arange(ln), rn)
        ridx = np.tile(np.arange(rn), ln)
    else:
        # build pair lists via sorted codes per key, exact semantics: null
        # keys never match; NaN matches NaN (normalized code equality)
        lcodes = []
        rcodes = []
        lnull = np.zeros(ln, np.bool_)
        rnull = np.zeros(rn, np.bool_)
        for lc, rc in zip(lk, rk):
            shared = _shared_string_dict(lc, rc) \
                if isinstance(lc.dtype, T.StringType) else None
            lcd, lnu = _norm_key_np(lc, shared)
            rcd, rnu = _norm_key_np(rc, shared)
            lcodes.append(lcd)
            rcodes.append(rcd)
            lnull |= lnu
            rnull |= rnu
        ldf = pd.DataFrame({f"k{i}": c.view(np.int64) for i, c in enumerate(lcodes)})
        rdf = pd.DataFrame({f"k{i}": c.view(np.int64) for i, c in enumerate(rcodes)})
        ldf["_l"] = np.arange(ln)
        rdf["_r"] = np.arange(rn)
        ldf = ldf[~lnull]
        rdf = rdf[~rnull]
        merged = ldf.merge(rdf, on=[f"k{i}" for i in range(len(lcodes))], how="inner")
        lidx = merged["_l"].to_numpy()
        ridx = merged["_r"].to_numpy()

    # extra condition filters matched pairs
    if plan.condition is not None:
        pair_cols = _gather_cols(left, lidx) + _gather_cols(right, ridx)
        pred = plan.condition.eval_cpu(pair_cols, ansi)
        keep = pred.values.astype(np.bool_) & pred.valid
        lidx, ridx = lidx[keep], ridx[keep]

    how = plan.how
    if how in ("inner", "cross"):
        pass
    elif how == "left":
        matched = np.zeros(ln, np.bool_)
        matched[lidx] = True
        extra = np.nonzero(~matched)[0]
        lidx = np.concatenate([lidx, extra])
        ridx = np.concatenate([ridx, np.full(len(extra), -1)])
    elif how == "right":
        matched = np.zeros(rn, np.bool_)
        matched[ridx] = True
        extra = np.nonzero(~matched)[0]
        lidx = np.concatenate([lidx, np.full(len(extra), -1)])
        ridx = np.concatenate([ridx, extra])
    elif how == "full":
        lmatched = np.zeros(ln, np.bool_)
        lmatched[lidx] = True
        rmatched = np.zeros(rn, np.bool_)
        rmatched[ridx] = True
        lex = np.nonzero(~lmatched)[0]
        rex = np.nonzero(~rmatched)[0]
        lidx = np.concatenate([lidx, lex, np.full(len(rex), -1)])
        ridx = np.concatenate([ridx, np.full(len(lex), -1), rex])
    elif how == "left_semi":
        hit = np.zeros(ln, np.bool_)
        hit[lidx] = True
        return _gather_cols(left, np.nonzero(hit)[0])
    elif how == "left_anti":
        hit = np.zeros(ln, np.bool_)
        hit[lidx] = True
        return _gather_cols(left, np.nonzero(~hit)[0])
    return _gather_cols(left, lidx) + _gather_cols(right, ridx)
