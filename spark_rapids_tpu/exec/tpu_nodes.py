"""TPU exec operator library.

Reference parity: the GpuExec hierarchy (GpuExec.scala:286 producing
RDD[ColumnarBatch]) and the operator inventory of SURVEY.md §2.4:
project/filter (basicPhysicalOperators.scala), hash aggregate
(GpuAggregateExec.scala), sort (GpuSortExec.scala), joins (GpuHashJoin /
GpuBroadcastHashJoinExec), coalesce (GpuCoalesceBatches.scala), exchanges
(GpuShuffleExchangeExecBase), expand, limit, union.

Execution model: each exec transforms per-partition iterators of device
ColumnarBatches. Exchanges are stage barriers that materialize their child
(running its partitions as tasks) and re-partition -- the role Spark's
shuffle plays for the reference. Device admission is gated by the
TpuSemaphore; projection/filter expression lists run as single fused XLA
stages (exec/compiled.py).
"""
from __future__ import annotations

import threading
from typing import Iterator, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (
    ColumnVector, ColumnarBatch, LazyRowCount, from_arrow, to_arrow,
    round_capacity, rows_int, traced_rows,
)
from spark_rapids_tpu.exec import compiled
from spark_rapids_tpu.exec import cpu_backend as CPU
from spark_rapids_tpu.exec import fuse
from spark_rapids_tpu.expr.core import Alias, BoundRef, Cast, EvalCtx, Expression
from spark_rapids_tpu.expr.aggregates import CountAll
from spark_rapids_tpu.ops import groupby as G
from spark_rapids_tpu.ops import join as J
from spark_rapids_tpu.ops import kernels as K
from spark_rapids_tpu.ops import radix as R
from spark_rapids_tpu.ops import repartition as RP
from spark_rapids_tpu.plan import nodes as P
from spark_rapids_tpu.runtime import faults as FLT
from spark_rapids_tpu.runtime import lifecycle as LC
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import trace as TR
from spark_rapids_tpu.runtime.semaphore import get_semaphore
from spark_rapids_tpu.runtime.task import TaskContext


class TpuExec:
    def __init__(self, plan: P.PlanNode, children: List["TpuExec"], conf):
        self.plan = plan
        self.children = children
        self.conf = conf
        self.metrics = M.MetricsRegistry(M.metrics_level_from_conf(conf))

    @property
    def schema(self) -> T.Schema:
        return self.plan.schema

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions if self.children else 1

    def execute_partition(self, ctx: TaskContext, pidx: int
                          ) -> Iterator[ColumnarBatch]:
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__

    def tree_string(self, indent: int = 0) -> str:
        pad = "  " * indent
        lines = [f"{pad}{self.name()} <- {self.plan.describe()}"]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def span(self, metric):
        """Trace span + the paired GpuMetric timer as ONE instrumentation
        point (the NvtxWithMetrics contract): tracing off returns the
        metric's own timer; tracing on additionally emits a
        `ExecName.metricName` complete event on this task's track and
        forwards the range to jax.profiler.TraceAnnotation."""
        return TR.exec_span(self, metric)

    def _acquire(self, ctx: TaskContext) -> None:
        get_semaphore(self.conf).acquire_if_necessary(ctx)
        ctx.holds_device_data = True


def _split_rows(total: int, parts: int) -> List[tuple]:
    base = total // parts
    rem = total % parts
    out = []
    start = 0
    for i in range(parts):
        n = base + (1 if i < rem else 0)
        out.append((start, n))
        start += n
    return out


class InMemoryScanExec(TpuExec):
    """Local-mode source: slice a pyarrow table into partitions/batches and
    upload (reference HostColumnarToGpu-ish boundary)."""

    @property
    def num_partitions(self):
        return self.plan.num_partitions

    def execute_partition(self, ctx, pidx):
        table = self.plan.table
        start, n = _split_rows(table.num_rows, self.num_partitions)[pidx]
        max_rows = self.conf.get(C.MAX_READER_BATCH_SIZE_ROWS)
        out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
        out_batches = self.metrics.metric(M.NUM_OUTPUT_BATCHES)
        copy_t = self.metrics.metric(M.COPY_TO_DEVICE_TIME)
        off = 0
        while off < n or (n == 0 and off == 0):
            take = min(max_rows, n - off)
            chunk = table.slice(start + off, take)
            self._acquire(ctx)
            FLT.site("scan.decode")
            with self.span(copy_t):
                b = from_arrow(chunk)
            yield b
            out_rows.add(take)
            out_batches.add(1)
            off += max(take, 1)
            if n == 0:
                break


class ParquetScanExec(TpuExec):
    """Parquet scan: host-side read (pyarrow footer+decode) then one device
    upload per batch. Pushed-down filters prune hive-partition files at
    plan time and row groups by footer min/max statistics at execute time
    (reference GpuParquetScan.scala:673 filterBlocks). Reader strategies
    (reference MULTIFILE_READER_TYPE, GpuMultiFileReader):
      PERFILE       sequential row-group loads, no lookahead
      MULTITHREADED bounded prefetch pool overlapping decode with upload
      COALESCING    prefetch + host-side concat of row groups up to the
                    reader batch size, so each upload is one big batch
      AUTO          COALESCING (local files; no cloud path distinction)
    """

    def __init__(self, plan, children, conf):
        super().__init__(plan, children, conf)
        from spark_rapids_tpu.io.parquet_pruning import prune_partition_file
        pv = self.plan.partition_values
        paths = list(self.plan.paths)
        # snapshot: a later wrap_and_tag/explain of a sibling plan sharing
        # this scan object must not rewrite the filters under a
        # converted exec
        self._pushed = list(self.plan.pushed_filters)
        if pv and self._pushed:
            kept = [i for i in range(len(paths)) if prune_partition_file(
                pv[i], self.plan.schema, self._pushed)]
        else:
            kept = list(range(len(paths)))
        self._kept_files = kept

    @property
    def num_partitions(self):
        return max(1, len(self._kept_files))

    def execute_partition(self, ctx, pidx):
        import pyarrow.parquet as pq
        from spark_rapids_tpu.io.parquet_pruning import prune_row_groups
        if not self._kept_files:
            return
        fidx = self._kept_files[pidx]
        path = self.plan.paths[fidx]
        decode_t = self.metrics.metric(M.DECODE_TIME)
        copy_t = self.metrics.metric(M.COPY_TO_DEVICE_TIME)
        out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
        rg_total = self.metrics.metric(M.NUM_ROW_GROUPS)
        rg_pruned = self.metrics.metric(M.NUM_ROW_GROUPS_PRUNED)
        read_bytes = self.metrics.metric(M.READ_BYTES)
        cols = getattr(self.plan, "file_columns", self.plan.columns)
        mode = str(self.conf.get(C.MULTIFILE_READER_TYPE)).upper()
        threads = 1 if mode == "PERFILE" \
            else self.conf.get(C.MULTIFILE_READER_THREADS)

        metadata = pq.ParquetFile(path).metadata
        groups, total = prune_row_groups(metadata, self._pushed)
        rg_total.add(total)
        rg_pruned.add(total - len(groups))
        for g in groups:
            read_bytes.add(metadata.row_group(g).total_byte_size)
        if not groups:
            if total:
                return  # every row group statically refuted
            groups = [-1]  # row-group-less file: read whole

        def load(g):
            # one ParquetFile per call: parquet-cpp FileReader is NOT
            # thread-safe and loads run on prefetch workers
            FLT.site("scan.decode")
            with self.span(decode_t):
                f = pq.ParquetFile(path)
                if g < 0:
                    return f.read(columns=cols)
                return f.read_row_group(g, columns=cols)

        # host decode of row group g+1.. overlaps device upload of g
        batch_rows = self.conf.get(C.MAX_READER_BATCH_SIZE_ROWS)
        tables = _prefetched(groups, load, threads, conf=self.conf)
        if mode in ("COALESCING", "AUTO"):
            tables = _host_coalesced(tables, batch_rows)
        for tbl in tables:
            tbl = self.plan.with_partition_cols(tbl, fidx)
            off = 0
            while off < tbl.num_rows or (tbl.num_rows == 0 and off == 0):
                chunk = tbl.slice(off, batch_rows)
                self._acquire(ctx)
                with self.span(copy_t):
                    b = from_arrow(chunk)
                yield b
                out_rows.add(chunk.num_rows)
                off += max(chunk.num_rows, 1)
                if tbl.num_rows == 0:
                    break


def _host_coalesced(tables, target_rows: int):
    """Concat host tables until the target row count is reached, so one
    device upload carries many small row groups (COALESCING strategy)."""
    import pyarrow as pa
    pending, rows = [], 0
    for t in tables:
        pending.append(t)
        rows += t.num_rows
        if rows >= target_rows:
            yield pa.concat_tables(pending) if len(pending) > 1 else pending[0]
            pending, rows = [], 0
    if pending:
        yield pa.concat_tables(pending) if len(pending) > 1 else pending[0]


def _prefetched(items, load_fn, n_threads: int, conf=None):
    """Iterator over load_fn(item) with BOUNDED background lookahead on the
    process-wide host pool (reference MultiFileReaderThreadPool: host parse
    overlaps device upload/compute; lookahead is capped so a large input
    cannot buffer itself entirely into host memory, and the pool is shared
    by every scan instead of constructed per call)."""
    if n_threads <= 1 or len(items) <= 1:
        for it in items:
            yield load_fn(it)
        return
    from spark_rapids_tpu.runtime.host_pool import get_host_pool
    yield from get_host_pool(conf).map_ordered(load_fn, items,
                                               max_concurrency=n_threads)


def device_decode_stage_body() -> fuse.StageBody:
    """Decode-on-device as a fusable stage body: the fused trace's INPUT
    is the EncodedBatch pytree (raw chunk planes) and its first stage is
    the pallas_decode expansion, so downstream bodies (Filter, partial
    agg) compose after it and Scan→Filter→partial-agg stays ONE dispatch
    per batch over encoded bytes. The builder captures no exec state;
    already-decoded batches (replay/fallback paths) pass through — a
    trace-time structure distinction, not a runtime branch."""
    def build():
        from spark_rapids_tpu.ops import pallas_decode as PD

        def fn(batch, pid, carry):
            if isinstance(batch, ColumnarBatch):
                return batch, {}, carry
            return PD.decode_batch(batch), {}, carry
        return fn

    return fuse.StageBody(("device_decode",), build,
                          bounds_map=lambda bs: list(bs),
                          name="DeviceDecode")


class EncodedParquetSourceExec(TpuExec):
    """Leaf half of the device-decode scan pair: footer read + partition
    -file and row-group pruning exactly as ParquetScanExec, but instead
    of host-decoding through pyarrow it extracts the still-ENCODED
    column chunk bytes (io/encoded.py) and uploads them as EncodedBatch
    planes — what crosses the host->device link is the compressed
    encoding, not decoded plates. Columns outside the supported matrix
    host-decode HERE (the per-column fallback) and ride inside the
    EncodedBatch as ready ColumnVectors; reasons accumulate in
    `fallback_columns` for explain/history. DeviceDecodeScanExec is the
    paired unary exec expanding the planes inside the fused stage body
    (reference: the host half of libcudf's GPU Parquet reader —
    gpu::DecodePageHeaders feeding gpuDecodePages)."""

    def __init__(self, plan, children, conf):
        super().__init__(plan, children, conf)
        from spark_rapids_tpu.io.parquet_pruning import prune_partition_file
        pv = plan.partition_values
        paths = list(plan.paths)
        self._pushed = list(plan.pushed_filters)
        if pv and self._pushed:
            kept = [i for i in range(len(paths)) if prune_partition_file(
                pv[i], plan.schema, self._pushed)]
        else:
            kept = list(range(len(paths)))
        self._kept_files = kept
        #: column -> fallback reason (plan-time probe + execute-time
        #: page surprises): the explain/history surface
        self.fallback_columns: dict = {}
        if kept:
            # static footer probe of the first kept file: fallback
            # reasons are visible in explain BEFORE the query runs
            # (page-level surprises still merge in at execute time)
            from spark_rapids_tpu.io import encoded as ENC
            try:
                self.fallback_columns.update(ENC.probe_support(
                    paths[kept[0]], self._file_fields()))
            except Exception:  # noqa: BLE001 - probe is advisory only
                pass

    def tree_string(self, indent: int = 0) -> str:
        pad = "  " * indent
        note = ""
        if self.fallback_columns:
            note = " host-fallback{" + ", ".join(
                f"{k}: {v}" for k, v in
                sorted(self.fallback_columns.items())) + "}"
        lines = [f"{pad}{self.name()}{note} <- {self.plan.describe()}"]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    @property
    def num_partitions(self):
        return max(1, len(self._kept_files))

    def _file_fields(self):
        n_part = len(self.plan.partition_fields())
        fields = list(self.plan.schema.fields)
        return fields[: len(fields) - n_part] if n_part else fields

    def _partition_columns(self, fidx, n, cap):
        """Constant partition-value columns as ready (decoded) planes —
        the same arrays with_partition_cols + from_arrow would build."""
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import column_from_arrow
        from spark_rapids_tpu.io import encoded as ENC
        out = []
        if not self.plan.partition_values:
            return out
        vals = self.plan.partition_values[fidx]
        for f in self.plan.partition_fields():
            v = vals.get(f.name)
            if v is not None and f.dtype == T.INT64:
                v = int(v)
            arr = pa.array([v] * n, type=T.to_arrow(f.dtype))
            cv = column_from_arrow(arr, f.dtype, cap)
            out.append(ENC.EncodedColumn("decoded", f.dtype, {}, (),
                                         cv=cv, bounds=cv.bounds))
        return out

    def execute_partition(self, ctx, pidx):
        import pyarrow as pa
        import pyarrow.parquet as pq
        from spark_rapids_tpu.columnar.batch import column_from_arrow
        from spark_rapids_tpu.io import encoded as ENC
        from spark_rapids_tpu.io.parquet_pruning import prune_row_groups
        if not self._kept_files:
            return
        fidx = self._kept_files[pidx]
        path = self.plan.paths[fidx]
        decode_t = self.metrics.metric(M.DECODE_TIME)
        copy_t = self.metrics.metric(M.COPY_TO_DEVICE_TIME)
        out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
        out_batches = self.metrics.metric(M.NUM_OUTPUT_BATCHES)
        rg_total = self.metrics.metric(M.NUM_ROW_GROUPS)
        rg_pruned = self.metrics.metric(M.NUM_ROW_GROUPS_PRUNED)
        read_bytes = self.metrics.metric(M.READ_BYTES)
        enc_bytes = self.metrics.metric(M.ENCODED_BYTES)
        dec_bytes = self.metrics.metric(M.DECODED_BYTES)
        fb_cols = self.metrics.metric(M.NUM_DECODE_FALLBACK_COLUMNS)
        fields = self._file_fields()

        pf = pq.ParquetFile(path)
        metadata = pf.metadata
        groups, total = prune_row_groups(metadata, self._pushed)
        rg_total.add(total)
        rg_pruned.add(total - len(groups))
        for g in groups:
            read_bytes.add(metadata.row_group(g).total_byte_size)
        if not groups:
            if total:
                return  # every row group statically refuted: nothing
                # read, nothing uploaded (pruning composes)
            # row-group-less / empty file: host read, all-decoded batch
            FLT.site("scan.decode")
            with self.span(decode_t):
                tbl = pf.read(columns=[f.name for f in fields] or None)
            tbl = self.plan.with_partition_cols(tbl, fidx)
            self._acquire(ctx)
            with self.span(copy_t):
                b = from_arrow(tbl)
            cols = [ENC.EncodedColumn("decoded", c.dtype, {}, (), cv=c,
                                      bounds=c.bounds) for c in b.columns]
            yield ENC.EncodedBatch(cols, rows_int(b.num_rows), b.capacity)
            out_rows.add(rows_int(b.num_rows))
            out_batches.add(1)
            return

        batch_rows = self.conf.get(C.MAX_READER_BATCH_SIZE_ROWS)
        max_bits = min(32, int(self.conf.get(C.DEVICE_DECODE_MAX_BITS)))
        delta_ok = bool(self.conf.get(C.DEVICE_DECODE_DELTA))
        hbs = ENC.read_encoded_batches(path, metadata, groups, fields,
                                       batch_rows, max_bits, delta_ok)
        while True:
            FLT.site("scan.decode")
            with self.span(decode_t):
                hb = next(hbs, None)
            if hb is None:
                return
            self.fallback_columns.update(hb.fallback)
            decoded = {}
            fb_idx = [i for i, c in enumerate(hb.columns) if c is None]
            if fb_idx:
                fb_cols.add(len(fb_idx))
                names = [fields[i].name for i in fb_idx]
                with self.span(decode_t):
                    parts = [pf.read_row_group(g, columns=names)
                             for g in hb.groups]
                    tbl = (pa.concat_tables(parts) if len(parts) > 1
                           else parts[0]).combine_chunks()
            self._acquire(ctx)
            with self.span(copy_t):
                for j, i in enumerate(fb_idx):
                    col = tbl.column(j)
                    arr = col.chunk(0) if col.num_chunks \
                        else col.combine_chunks()
                    decoded[i] = column_from_arrow(arr, fields[i].dtype,
                                                   hb.cap)
                eb = ENC.upload(hb, decoded)
            eb.columns.extend(
                self._partition_columns(fidx, hb.num_rows, hb.cap))
            enc_bytes.add(hb.encoded_bytes)
            # decoded footprint is static (cap x itemsize): recorded HERE
            # because on the fused path the decode body runs inside
            # FusedStageExec's dispatch, not DeviceDecodeScanExec's
            dec_bytes.add(eb.decoded_size())
            out_rows.add(hb.num_rows)
            out_batches.add(1)
            yield eb


class DeviceDecodeScanExec(TpuExec):
    """Unary half of the device-decode scan pair (the PR's tentpole):
    expands the child's EncodedBatches into decoded ColumnarBatches ON
    DEVICE via a fuse.StageBody, so stage_fusion composes Filter /
    partial-agg bodies behind the decode into one dispatch per batch
    over encoded bytes (the cuDF gpuDecodePages analog). The kernel
    cost auditor sees the encoded planes as the dispatch inputs, so the
    roofline credits encoded-input bytes and decode time lands in
    opTime -> device_compute: the host_decode bucket collapses
    structurally for device-decoded scans."""

    def stage_body(self) -> fuse.StageBody:
        return device_decode_stage_body()

    def execute_partition(self, ctx, pidx):
        op_t = self.metrics.metric(M.OP_TIME)
        out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
        out_batches = self.metrics.metric(M.NUM_OUTPUT_BATCHES)
        body = self.stage_body()
        fn = fuse.fused(body.key, body.builder)
        carry = body.init_carry()
        pid = jnp.int32(pidx)
        for batch in self.children[0].execute_partition(ctx, pidx):
            self._acquire(ctx)
            n = batch.num_rows  # host int on the encoded source path
            with self.span(op_t):
                out, errs, carry = fn(batch, pid, carry)
            compiled.raise_errors(errs)
            if isinstance(n, int):
                # keep the row count host-side: the source knew it
                # exactly, so no device sync is ever needed for it
                out = ColumnarBatch(out.columns, n, out.row_mask)
            out_rows.add(n if isinstance(n, int) else out.num_rows)
            out_batches.add(1)
            yield out


class TextScanExec(TpuExec):
    """CSV/JSON/ORC scan: prefetched host parse, chunked device upload
    (reference GpuCSVScan / GpuJsonScan / GpuOrcScan MULTITHREADED)."""

    @property
    def num_partitions(self):
        return max(1, len(self.plan.paths))

    def execute_partition(self, ctx, pidx):
        decode_t = self.metrics.metric(M.DECODE_TIME)
        copy_t = self.metrics.metric(M.COPY_TO_DEVICE_TIME)
        out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
        FLT.site("scan.decode")
        with self.span(decode_t):
            table = self.plan.read_host(self.plan.paths[pidx])
        batch_rows = self.conf.get(C.MAX_READER_BATCH_SIZE_ROWS)
        n = table.num_rows
        off = 0
        while off < n or (n == 0 and off == 0):
            take = min(batch_rows, n - off)
            chunk = table.slice(off, take)
            self._acquire(ctx)
            with self.span(copy_t):
                b = from_arrow(chunk)
            yield b
            out_rows.add(take)
            off += max(take, 1)
            if n == 0:
                break


class CachedScanExec(TpuExec):
    """Materializes the child once into HBM-resident batches stored on the
    CachedRelation plan node (shared across collects of the same
    DataFrame); later scans stream straight from device memory."""

    _lock = threading.Lock()

    @property
    def schema(self):
        return self.children[0].schema

    @property
    def num_partitions(self):
        if self.plan.materialized is not None:
            return len(self.plan.materialized)
        return self.children[0].num_partitions

    def _materialize(self):
        from spark_rapids_tpu.runtime.memory import SpillableColumnarBatch
        with CachedScanExec._lock:
            if self.plan.materialized is None:
                child = self.children[0]
                out = []
                for p in range(child.num_partitions):
                    with TaskContext(partition_id=p) as tctx:
                        batches = list(child.execute_partition(tctx, p))
                    if batches:
                        # ONE device batch per partition: every query over
                        # the cache then costs a fixed handful of fused
                        # dispatches instead of one chain per source chunk.
                        # Registered spillable: under HBM pressure the
                        # cache pages out to host/disk instead of OOMing.
                        merged = K.compact_batch(K.concat_batches(batches))
                        _attach_column_stats(merged)
                        batches = [SpillableColumnarBatch(merged)]
                    out.append(batches)
                self.plan.materialized = out
        return self.plan.materialized

    def execute_partition(self, ctx, pidx):
        for sb in self._materialize()[pidx]:
            yield sb.get_batch()


def _attach_column_stats(batch: ColumnarBatch) -> None:
    """Cache-time column stats (the ParquetCachedBatchSerializer-stats
    analog): one bulk fetch of per-int-column min/max at materialization,
    carried as ColumnVector.bounds so later radix packing over these
    columns skips its per-batch device range probe (a ~90ms sync)."""
    idxs, pending = [], []
    for i, c in enumerate(batch.columns):
        if c.is_dict or c.is_nested or c.is_string:
            continue
        if not isinstance(c.dtype, (T.Int8Type, T.Int16Type, T.Int32Type,
                                    T.Int64Type, T.DateType,
                                    T.TimestampType, T.DecimalType)):
            continue
        v = c.data.astype(jnp.int64)
        valid = c.validity_or_default(batch.num_rows)
        lo = jnp.min(jnp.where(valid, v, jnp.int64(2**62)))
        hi = jnp.max(jnp.where(valid, v, -jnp.int64(2**62)))
        idxs.append(i)
        pending.extend([lo, hi])
    if not idxs:
        return
    vals = jax.device_get(pending)
    for j, i in enumerate(idxs):
        lo, hi = int(vals[2 * j]), int(vals[2 * j + 1])
        if lo <= hi:
            batch.columns[i].bounds = (lo, hi)


class RangeExec(TpuExec):
    @property
    def num_partitions(self):
        return self.plan.num_partitions

    def execute_partition(self, ctx, pidx):
        p = self.plan
        total = max(0, -(-(p.end - p.start) // p.step))
        start_i, n = _split_rows(total, self.num_partitions)[pidx]
        self._acquire(ctx)
        max_rows = self.conf.get(C.MAX_READER_BATCH_SIZE_ROWS)
        off = 0
        while off < n or (n == 0 and off == 0):
            take = min(max_rows, n - off) if n else 0
            cap = round_capacity(max(take, 1))
            base = p.start + (start_i + off) * p.step
            vals = base + jnp.arange(cap, dtype=jnp.int64) * p.step
            yield ColumnarBatch(
                [ColumnVector(T.INT64, vals, jnp.arange(cap) < take)], take)
            off += max(take, 1)
            if n == 0:
                break


# ---------------------------------------------------------------------------
# Stage bodies (whole-stage vertical fusion, exec/stage_fusion.py)
#
# Each fusable exec separates its traced per-batch body from its driver
# loop as a fuse.StageBody with the uniform signature
#     fn(batch, pid, carry) -> (batch, errors, carry)
# so a planner pass can compose a Scan→Filter→Project→partial-agg chain
# into ONE dispatch per batch. Builders are module-level and capture only
# expressions/static config — never the exec (the fuse-cache pinning
# hazard documented on _AggKernels).
# ---------------------------------------------------------------------------

def _project_bounds_map(exprs):
    """Column-stat bounds across a projection: passthrough refs carry
    their input column's bounds (the host half of compiled.carry_bounds)."""
    def bmap(in_bounds):
        out = []
        for e in exprs:
            inner = e.children[0] if isinstance(e, Alias) else e
            if isinstance(inner, BoundRef) and inner.index < len(in_bounds):
                out.append(in_bounds[inner.index])
            else:
                out.append(None)
        return out
    return bmap


def project_stage_body(exprs, ansi: bool, trivial=None) -> fuse.StageBody:
    if trivial is not None:
        idx = tuple(trivial)

        def build_trivial():
            def fn(batch, pid, carry):
                return (ColumnarBatch([batch.columns[i] for i in idx],
                                      batch.num_rows, batch.row_mask),
                        {}, carry)
            return fn

        return fuse.StageBody(
            ("project_trivial", idx), build_trivial,
            bounds_map=lambda bs: [bs[i] if i < len(bs) else None
                                   for i in idx],
            name="Project")

    from spark_rapids_tpu.plan.overrides import _contains_project_only
    needs_part_ctx = any(_contains_project_only(e) for e in exprs)

    def build():
        def fn(batch, pid, row_base):
            ectx = EvalCtx(batch.columns, traced_rows(batch.num_rows),
                           batch.capacity, ansi, live=batch.live_mask(),
                           partition_id=pid, row_base=row_base)
            cols = [e.eval_tpu(ectx) for e in exprs]
            if needs_part_ctx:  # only pay the count when ids need it
                row_base = row_base + jnp.sum(
                    batch.live_mask().astype(jnp.int64))
            return (ColumnarBatch(cols, batch.num_rows, batch.row_mask),
                    dict(ectx.errors), row_base)
        return fn

    key = ("project", tuple(e.fingerprint() for e in exprs), ansi,
           needs_part_ctx)
    return fuse.StageBody(key, build, bounds_map=_project_bounds_map(exprs),
                          has_carry=needs_part_ctx, name="Project")


def filter_stage_body(cond, ansi: bool) -> fuse.StageBody:
    def build():
        def fn(batch, pid, carry):
            ectx = EvalCtx(batch.columns, traced_rows(batch.num_rows),
                           batch.capacity, ansi, live=batch.live_mask())
            pred = cond.eval_tpu(ectx)
            # validity=None means "valid on every live row"; the live
            # rows of a masked batch (chained filter, exchange output)
            # sit at positions >= live_count, so arange<num_rows would
            # silently drop them — use the live mask instead.
            valid = (pred.validity if pred.validity is not None
                     else ectx.row_mask)
            mask = pred.data.astype(jnp.bool_) & valid
            return K.mask_filter_batch(batch, mask), dict(ectx.errors), carry
        return fn

    # a filter's output columns are 1:1 row subsets of its input: bounds
    # (host metadata, valid under any row subset) pass straight through
    return fuse.StageBody(("filter", cond.fingerprint(), ansi), build,
                          bounds_map=lambda bs: list(bs), name="Filter")


def expand_stage_body(proj_exprs, n_cols: int) -> fuse.StageBody:
    """All projections of an Expand evaluated and stacked in ONE traced
    computation (the unfused exec dispatches once per projection). Output
    capacity is n_proj * input capacity with a tiled selection mask; only
    built for fixed-width output schemas (stage_fusion gates strings —
    cross-projection vocab unification cannot run inside a trace)."""
    nproj = len(proj_exprs)

    def build():
        def fn(batch, pid, carry):
            live = batch.live_mask()
            nr = traced_rows(batch.num_rows)
            errs = {}
            per_proj = []
            for exprs in proj_exprs:
                ectx = EvalCtx(batch.columns, nr, batch.capacity, False,
                               live=live)
                per_proj.append([e.eval_tpu(ectx) for e in exprs])
                errs.update(ectx.errors)
            out_cols = []
            for ci in range(n_cols):
                cols = [p[ci] for p in per_proj]
                data = jnp.concatenate([c.data for c in cols])
                # validity=None means "valid on every LIVE row"; a masked
                # input (chained filter) keeps live rows at positions >=
                # live_count, so arange<num_rows would null them — use the
                # live mask as the default plane
                valid = jnp.concatenate(
                    [c.validity if c.validity is not None else live
                     for c in cols])
                out_cols.append(ColumnVector(cols[0].dtype, data, valid))
            mask = jnp.concatenate([live] * nproj)
            count = jnp.sum(mask.astype(jnp.int32))
            return (ColumnarBatch(out_cols, LazyRowCount(count), mask),
                    errs, carry)
        return fn

    key = ("expand_stage",
           tuple(tuple(e.fingerprint() for e in p) for p in proj_exprs))
    return fuse.StageBody(key, build,
                          bounds_map=lambda bs: [None] * n_cols,
                          name="Expand")


def limit_stage_body(n: int) -> fuse.StageBody:
    """Device-side LIMIT: rows past the remaining budget are masked dead;
    the budget rides as a device carry. The fused driver fetches the
    carry per batch to stop consuming input once it hits zero (exhausts=
    True) — the same one-scalar-per-batch sync the unfused LimitExec
    already pays materializing each batch's row count."""
    def build():
        def fn(batch, pid, remaining):
            live = batch.live_mask()
            pos = jnp.cumsum(live.astype(jnp.int64))
            keep = live & (pos <= remaining)
            taken = jnp.sum(keep.astype(jnp.int64))
            count = jnp.sum(keep.astype(jnp.int32))
            return (ColumnarBatch(batch.columns, LazyRowCount(count), keep),
                    {}, jnp.maximum(remaining - taken, 0))
        return fn

    # n reaches the trace only as the carried device scalar, so one cache
    # entry serves every LIMIT value (no per-n recompiles)
    return fuse.StageBody(("limit_stage",), build,
                          carry_init=lambda: jnp.int64(n),
                          bounds_map=lambda bs: list(bs),
                          has_carry=True, exhausts=True, name="Limit")


class ProjectExec(TpuExec):
    def _trivial_indices(self):
        """Pure column selection (only BoundRef / Alias(BoundRef)) costs no
        kernel at all: planes are shared, just re-listed."""
        idx = []
        for e in self.plan.exprs:
            inner = e.children[0] if isinstance(e, Alias) else e
            if isinstance(inner, BoundRef) and inner.dtype == e.data_type():
                idx.append(inner.index)
            else:
                return None
        return idx

    def stage_body(self) -> fuse.StageBody:
        return project_stage_body(self.plan.exprs,
                                  self.conf.get(C.ANSI_ENABLED),
                                  trivial=self._trivial_indices())

    def execute_partition(self, ctx, pidx):
        op_t = self.metrics.metric(M.OP_TIME)
        exprs = self.plan.exprs
        trivial = self._trivial_indices()
        if trivial is not None:
            for batch in self.children[0].execute_partition(ctx, pidx):
                yield ColumnarBatch([batch.columns[i] for i in trivial],
                                    batch.num_rows, batch.row_mask)
            return

        body = self.stage_body()
        fn = fuse.fused(body.key, body.builder)
        row_base = body.init_carry()
        pid = jnp.int32(pidx)
        for batch in self.children[0].execute_partition(ctx, pidx):
            self._acquire(ctx)
            with self.span(op_t):
                out, errs, row_base = fn(batch, pid, row_base)
            compiled.raise_errors(errs)
            compiled.carry_bounds(exprs, batch.columns, out.columns)
            yield out


class FilterExec(TpuExec):
    """Predicate eval + compaction fused into ONE jitted computation per
    batch; the surviving-row count stays on device (LazyRowCount)."""

    def stage_body(self) -> fuse.StageBody:
        return filter_stage_body(self.plan.condition,
                                 self.conf.get(C.ANSI_ENABLED))

    def execute_partition(self, ctx, pidx):
        op_t = self.metrics.metric(M.FILTER_TIME)
        out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
        body = self.stage_body()
        fn = fuse.fused(body.key, body.builder)
        carry = body.init_carry()
        pid = jnp.int32(pidx)
        for batch in self.children[0].execute_partition(ctx, pidx):
            self._acquire(ctx)
            with self.span(op_t):
                out, errs, carry = fn(batch, pid, carry)
            compiled.raise_errors(errs)
            # column-stat bounds are host metadata (not pytree leaves):
            # a filter's output columns are 1:1 row subsets of its input
            for ic, oc in zip(batch.columns, out.columns):
                oc.bounds = ic.bounds
            out_rows.add(out.num_rows)
            yield out


class LimitExec(TpuExec):
    def stage_body(self) -> fuse.StageBody:
        return limit_stage_body(self.plan.n)

    def execute_partition(self, ctx, pidx):
        remaining = self.plan.n
        for batch in self.children[0].execute_partition(ctx, pidx):
            if remaining <= 0:
                break
            if batch.row_mask is not None:
                batch = K.compact_batch(batch)
            if batch.num_rows <= remaining:
                remaining -= batch.num_rows
                yield batch
            else:
                self._acquire(ctx)
                yield K.slice_batch(batch, 0, remaining)
                remaining = 0


class UnionExec(TpuExec):
    """Concatenate children partition-spaces; each child's output is cast to
    the union schema (reference GpuUnionExec)."""

    @property
    def num_partitions(self):
        return sum(c.num_partitions for c in self.children)

    def _cast_exprs(self, child_schema):
        out = []
        for i, (f_out, f_in) in enumerate(zip(self.plan.schema.fields, child_schema.fields)):
            ref = BoundRef(i, f_in.dtype, f_in.name)
            out.append(ref if f_in.dtype == f_out.dtype else Cast(ref, f_out.dtype))
        return out

    def execute_partition(self, ctx, pidx):
        for child in self.children:
            if pidx < child.num_partitions:
                exprs = self._cast_exprs(child.schema)
                needs_cast = any(isinstance(e, Cast) for e in exprs)
                for batch in child.execute_partition(ctx, pidx):
                    if needs_cast:
                        self._acquire(ctx)
                        yield compiled.run_projection(exprs, batch)
                    else:
                        yield batch
                return
            pidx -= child.num_partitions
        raise IndexError(pidx)


class ExpandExec(TpuExec):
    def _proj_exprs(self):
        out_types = self.plan.schema.types
        return [[e if e.data_type() == dt else Cast(e, dt)
                 for e, dt in zip(proj, out_types)]
                for proj in self.plan.projections]

    def stage_body(self) -> fuse.StageBody:
        return expand_stage_body(self._proj_exprs(),
                                 len(self.plan.schema.types))

    def execute_partition(self, ctx, pidx):
        for batch in self.children[0].execute_partition(ctx, pidx):
            self._acquire(ctx)
            for exprs in self._proj_exprs():
                yield compiled.run_projection(exprs, batch)


class ShuffleFileScanExec(TpuExec):
    """Reads a cross-process shuffle directory: each reduce partition
    streams its kudo frames straight onto the device (reference: shuffle
    reader fetching map outputs)."""

    @property
    def num_partitions(self):
        return max(1, self.plan.n_reduce)

    def execute_partition(self, ctx, pidx):
        from spark_rapids_tpu.shuffle.exchange_files import (
            read_partition_batches,
        )
        copy_t = self.metrics.metric(M.COPY_TO_DEVICE_TIME)
        out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
        self._acquire(ctx)
        it = read_partition_batches(self.plan.root, pidx)
        while True:
            with self.span(copy_t):
                batch = next(it, None)
            if batch is None:
                return
            out_rows.add(rows_int(batch.num_rows))
            yield batch


class GenerateExec(TpuExec):
    """explode / posexplode over array and map columns, incl. _outer
    (reference GpuGenerateExec.scala).

    TPU-first: the output stays at the CHILD planes' static capacity — the
    generated column IS the child planes (zero copy), parent columns gather
    by an element->row segment map, and liveness is a selection mask
    (elements of dead/null parent rows are masked, not compacted). The
    outer variant emits a second masked batch carrying one null-generated
    row per empty/null input instead of rebuilding offsets."""

    def execute_partition(self, ctx, pidx):
        op_t = self.metrics.metric(M.OP_TIME)
        out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
        gen = self.plan.generator
        src = gen.children[0]
        is_map = isinstance(src.data_type(), T.MapType)
        position = bool(getattr(gen, "position", False))
        outer = bool(gen.outer)

        def build():
            def fn(batch):
                ectx = EvalCtx(batch.columns, traced_rows(batch.num_rows),
                               batch.capacity, False, live=batch.live_mask())
                arr = src.eval_tpu(ectx)
                cap = batch.capacity
                off = arr.data["offsets"][: cap + 1]
                kids = ([arr.data["keys"], arr.data["values"]] if is_map
                        else [arr.data["child"]])
                child_cap = kids[0].capacity
                e = jnp.arange(child_cap, dtype=jnp.int32)
                seg = jnp.clip(
                    jnp.searchsorted(off, e, side="right").astype(jnp.int32) - 1,
                    0, cap - 1)
                live = batch.live_mask()
                arr_valid = (arr.validity if arr.validity is not None
                             else jnp.ones(cap, jnp.bool_))
                elem_live = (e < off[cap]) & live[seg] & arr_valid[seg]
                req = [batch.columns[i] for i in self.plan.required]
                if not outer:
                    parent = [K.gather_column(c, seg, batch.num_rows,
                                              src_live=live)
                              for c in req]
                    gen_cols = []
                    if position:
                        pos = (e - off[seg]).astype(jnp.int32)
                        gen_cols.append(ColumnVector(T.INT32, pos, None))
                    gen_cols.extend(kids)
                    n_live = jnp.sum(elem_live.astype(jnp.int32))
                    return ColumnarBatch(parent + gen_cols, n_live, elem_live)
                # OUTER: null/empty rows still emit one row, in input
                # order. One order-preserving scatter builds a combined
                # source map: output slot off[i]+empties_before(i)+j for
                # element j of row i, slot off[i]+empties_before(i) for an
                # empty row i.
                out_cap = round_capacity(child_cap + cap)
                empty = live & (~arr_valid | ((off[1:] - off[:-1]) == 0))
                cume = (jnp.cumsum(empty.astype(jnp.int32))
                        - empty.astype(jnp.int32))
                src_row = jnp.full(out_cap, -1, jnp.int32)
                src_elem = jnp.full(out_cap, -1, jnp.int32)
                dest_e = jnp.where(elem_live, e + cume[seg], out_cap)
                src_row = src_row.at[dest_e].set(seg, mode="drop")
                src_elem = src_elem.at[dest_e].set(e, mode="drop")
                i = jnp.arange(cap, dtype=jnp.int32)
                dest_r = jnp.where(empty, off[:cap] + cume, out_cap)
                src_row = src_row.at[dest_r].set(i, mode="drop")
                live_out = src_row >= 0
                parent = [K.gather_column(c, src_row, batch.num_rows,
                                          src_live=live)
                          for c in req]
                gen_cols = []
                if position:
                    safe_row = jnp.clip(src_row, 0, cap - 1)
                    pos = (src_elem - off[safe_row]).astype(jnp.int32)
                    gen_cols.append(ColumnVector(T.INT32, pos,
                                                 src_elem >= 0))
                for k in kids:
                    gen_cols.append(K.gather_column(k, src_elem, child_cap))
                n_live = jnp.sum(live_out.astype(jnp.int32))
                return ColumnarBatch(parent + gen_cols, n_live, live_out)
            return fn

        key = ("generate", src.fingerprint(), is_map, position, outer,
               tuple(self.plan.required))
        fn = fuse.fused(key, build)
        for batch in self.children[0].execute_partition(ctx, pidx):
            self._acquire(ctx)
            with self.span(op_t):
                out = fn(batch)
            out_rows.add(rows_int(out.num_rows))
            yield out


class CoalesceBatchesExec(TpuExec):
    """Concat small batches up to the target size (reference
    GpuCoalesceBatches.scala TargetSize goal)."""

    def __init__(self, plan, children, conf, target_bytes: Optional[int] = None,
                 require_single: bool = False):
        super().__init__(plan, children, conf)
        self.target_bytes = target_bytes or conf.get(C.TARGET_BATCH_SIZE)
        self.require_single = require_single

    @property
    def schema(self):
        # concat never changes columns: like ExchangeExec, report the
        # child's schema even when self.plan is a downstream node (the
        # collected-complete-agg wrapper hands us the aggregate's plan)
        return self.children[0].schema

    def execute_partition(self, ctx, pidx):
        concat_t = self.metrics.metric(M.CONCAT_TIME)
        n_in = self.metrics.metric(M.NUM_INPUT_BATCHES)
        n_out = self.metrics.metric(M.NUM_OUTPUT_BATCHES)
        pending: List[ColumnarBatch] = []
        pending_bytes = 0

        def flush():
            n_out.add(1)
            if len(pending) == 1:
                # single-batch passthrough: no concat kernel runs, so no
                # semaphore acquire either
                return pending[0]
            self._acquire(ctx)
            with self.span(concat_t):
                return K.concat_batches(pending)

        for batch in self.children[0].execute_partition(ctx, pidx):
            pending.append(batch)
            n_in.add(1)
            pending_bytes += batch.device_memory_size()
            if not self.require_single and pending_bytes >= self.target_bytes:
                yield flush()
                pending, pending_bytes = [], 0
        if pending:
            yield flush()


def _order_keys(kc: ColumnVector, o, num_rows, live=None, n_chunks=None):
    """(key_u64, nulls, asc, nulls_first) list for one sort order: one
    entry for fixed-width types, one per 8-byte chunk for strings (EXACT
    lexicographic device ordering via kernels.string_chunk_keys)."""
    if isinstance(kc.dtype, T.StringType):
        if n_chunks is None:
            n_chunks = K.string_chunk_count(kc)
        return [(k, nulls, o.ascending, o.resolved_nulls_first())
                for k, nulls in K.string_chunk_keys(kc, num_rows, n_chunks,
                                                    live=live)]
    k, nulls = K.normalize_key(kc, num_rows, live=live)
    return [(k, nulls, o.ascending, o.resolved_nulls_first())]


def _sort_perm_for(orders, batch):
    key_cols = compiled.run_stage([o.expr for o in orders], batch)
    keys = []
    for o, kc in zip(orders, key_cols):
        keys.extend(_order_keys(kc, o, batch.num_rows,
                                live=batch.live_mask()))
    return K.lexsort_indices(keys, traced_rows(batch.num_rows),
                             live=batch.live_mask())


def _topn_image(kc: ColumnVector, order, live) -> Optional[jax.Array]:
    """Monotone int32 'goodness' image of a sort key: rows that belong
    EARLIER in the output get LARGER values (so lax.top_k selects them).
    Ties may collapse (f32-rounded 64-bit keys) — the image only gates a
    candidate threshold; exact order comes from the final small sort.
    Returns None for types without a cheap image (strings, nested)."""
    d = kc.dtype
    min32 = jnp.int32(np.int32(-2**31))
    if kc.is_string or kc.is_nested:
        return None
    if isinstance(d, (T.Float32Type, T.Float64Type)):
        x = kc.data.astype(jnp.float32)
        x = jnp.where(jnp.isnan(x), jnp.float32(np.nan), x)
        x = jnp.where(x == 0.0, jnp.zeros_like(x), x)
        bits = jax.lax.bitcast_convert_type(x, jnp.int32)
        img = jnp.where(bits < 0, ~bits ^ min32, bits)
    elif isinstance(d, (T.Int64Type, T.TimestampType, T.DecimalType)):
        x = kc.data.astype(jnp.float32)
        bits = jax.lax.bitcast_convert_type(x, jnp.int32)
        img = jnp.where(bits < 0, ~bits ^ min32, bits)
    else:
        img = kc.data.astype(jnp.int32)
    if order.ascending:
        img = ~img  # monotone reversal, no INT_MIN overflow
    valid = kc.validity
    if valid is not None:
        null_img = (jnp.int32(np.int32(2**31 - 1))
                    if order.resolved_nulls_first() else min32)
        img = jnp.where(valid, img, null_img)
    return jnp.where(live, img, min32)


class TopNExec(TpuExec):
    """ORDER BY + LIMIT n without sorting the full input (reference
    GpuTopN): lax.top_k over a monotone 32-bit image of the primary key
    gives a threshold; only the <= ~n surviving candidate rows get the
    exact multi-key sort. Ties and image collapse just widen the
    candidate set; a pathological width falls back to the full sort.
    Two fused dispatches + ONE host sync (the candidate count) — the
    per-dispatch cost on a tunneled device outweighs any kernel-level
    saving, so each stage is a single jit."""

    def __init__(self, plan, children, conf, orders, n: int):
        super().__init__(plan, children, conf)
        self.orders = orders
        self.n = n
        self._fusable = all(
            not isinstance(o.expr.data_type(),
                           (T.StringType, T.ArrayType, T.MapType,
                            T.StructType))
            for o in orders)

    def _fp(self):
        return (tuple((o.expr.fingerprint(), o.ascending,
                       o.resolved_nulls_first()) for o in self.orders),
                self.n)

    def execute_partition(self, ctx, pidx):
        sort_t = self.metrics.metric(M.SORT_TIME)
        batches = list(self.children[0].execute_partition(ctx, pidx))
        if not batches:
            return
        self._acquire(ctx)
        batch = K.concat_batches(batches) if len(batches) > 1 else batches[0]
        n = self.n
        bound = max(4 * n, 4096)
        with self.span(sort_t):
            if self._fusable and batch.capacity > bound:
                orders = self.orders

                def build_select():
                    def fn(b):
                        live = b.live_mask()
                        ectx = EvalCtx(b.columns, traced_rows(b.num_rows),
                                       b.capacity, False, live=live)
                        kc = orders[0].expr.eval_tpu(ectx)
                        img = _topn_image(kc, orders[0], live)
                        k = min(n, b.capacity)
                        thr = jax.lax.top_k(img, k)[0][-1]
                        cand = live & (img >= thr)
                        return cand, jnp.sum(cand.astype(jnp.int32))
                    return fn

                sel = fuse.fused(("topn_select", self._fp()), build_select)
                cand, cnt_d = sel(batch)
                # start the count D2H before blocking on it: the transfer
                # overlaps the tail of the select computation instead of
                # waiting for an idle device to begin
                from spark_rapids_tpu.runtime.pipeline import start_d2h
                start_d2h(cnt_d)
                cnt = int(cnt_d)
                if cnt <= bound:
                    out_cap = round_capacity(bound)

                    def build_sort():
                        def fn(b, cand, cnt):
                            idx = K._compact_indices(cand, b.capacity,
                                                     out_cap)
                            small = K.gather_batch(b, idx, cnt)
                            keys = []
                            sctx = EvalCtx(small.columns, cnt, out_cap,
                                           False)
                            for o in orders:
                                kc = o.expr.eval_tpu(sctx)
                                keys.extend(_order_keys(kc, o, cnt))
                            perm = K.lexsort_indices(keys, cnt)
                            ncap = round_capacity(n)  # <= out_cap (bound >= 4n)
                            sel_idx = jnp.where(
                                jnp.arange(ncap, dtype=jnp.int32)
                                < jnp.minimum(cnt, n), perm[:ncap], -1)
                            out = K.gather_batch(small, sel_idx, cnt)
                            return ColumnarBatch(
                                out.columns,
                                LazyRowCount(jnp.minimum(cnt, n)))
                        return fn

                    srt = fuse.fused(("topn_sort", self._fp()), build_sort)
                    yield srt(batch, cand, cnt_d)
                    return
            # fallback: exact full sort (string keys, tiny inputs, or a
            # pathologically wide tie set)
            if batch.row_mask is not None:
                batch = K.compact_batch(batch)
            total = int(batch.num_rows)
            perm = _sort_perm_for(self.orders, batch)
            out = K.gather_batch(batch, perm, batch.num_rows)
            yield K.slice_batch(out, 0, min(n, total))


class SortExec(TpuExec):
    """Whole-partition sort: evaluate sort-key expressions as a fused stage,
    normalize, single lexsort, gather (reference GpuSortExec in-core path;
    the out-of-core merge path arrives with the spill framework)."""

    def execute_partition(self, ctx, pidx):
        sort_t = self.metrics.metric(M.SORT_TIME)
        batches = list(self.children[0].execute_partition(ctx, pidx))
        if not batches:
            return
        self._acquire(ctx)
        total = sum(b.device_memory_size() for b in batches)
        if total > self.conf.get(C.SORT_OOC_BYTES):
            it = self._out_of_core(batches)
            while True:
                with self.span(sort_t):
                    b = next(it, None)
                if b is None:
                    return
                yield b
        batch = K.concat_batches(batches) if len(batches) > 1 else batches[0]
        if batch.row_mask is not None:
            batch = K.compact_batch(batch)
        with self.span(sort_t):
            perm = self._sort_perm(batch)
            out = K.gather_batch(batch, perm, batch.num_rows)
        yield out

    def _sort_perm(self, batch):
        return _sort_perm_for(self.plan.orders, batch)

    def _out_of_core(self, batches):
        """Out-of-core sort (reference GpuSortExec.scala:281 merge path,
        TPU-shaped): only the u64 key planes live on device — per-chunk
        keys are computed and the row data immediately staged to host
        (pyarrow); one global argsort of the keys yields the permutation,
        and pyarrow assembles the sorted output host-side, re-uploaded in
        reader-sized slices."""
        import pyarrow as pa
        names = self.schema.names
        compacted, per_batch_keycols = [], []
        for b in batches:
            if b.row_mask is not None:
                b = K.compact_batch(b)
            if int(b.num_rows) == 0:
                continue
            compacted.append(b)
            per_batch_keycols.append(
                compiled.run_stage([o.expr for o in self.plan.orders], b))
        if not compacted:
            return
        # string chunk counts can differ per batch: fix each order's width
        # to the max across batches so key planes align
        widths = []
        for ci, o in enumerate(self.plan.orders):
            if isinstance(o.expr.data_type(), T.StringType):
                widths.append(max(K.string_chunk_count(kc[ci])
                                  for kc in per_batch_keycols))
            else:
                widths.append(1)
        key_planes, tables = [], []
        for b, key_cols in zip(compacted, per_batch_keycols):
            per_col = []
            for o, kc, w in zip(self.plan.orders, key_cols, widths):
                for k, nulls, _, _ in _order_keys(kc, o, b.num_rows,
                                                  n_chunks=w):
                    per_col.append((k[: int(b.num_rows)],
                                    nulls[: int(b.num_rows)]))
            key_planes.append(per_col)
            tables.append(to_arrow(b, names))  # stages the data off-device
        keys = []
        pi = 0
        for o, w in zip(self.plan.orders, widths):
            for _ in range(w):
                k = jnp.concatenate([kp[pi][0] for kp in key_planes])
                nl = jnp.concatenate([kp[pi][1] for kp in key_planes])
                keys.append((k, nl, o.ascending, o.resolved_nulls_first()))
                pi += 1
        n = int(keys[0][0].shape[0])
        perm = np.asarray(K.lexsort_indices(keys, n))[:n]
        table = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
        sorted_table = table.take(perm)
        step = self.conf.get(C.MAX_READER_BATCH_SIZE_ROWS)
        for off in range(0, n, step):
            yield from_arrow(sorted_table.slice(off, min(step, n - off)))



def _static_expr_ranges(key_cols, kinds, key_exprs):
    """Host-known (lo, hi) bounds for every KIND_INT key — from the
    expression (``x % 1000``) or from cache-time column stats riding on
    the ColumnVector — or None if any is underivable. Skips the
    per-batch device min/max probe (a ~90ms sync)."""
    rs = []
    for i, (c, kind) in enumerate(zip(key_cols, kinds)):
        if kind == R.KIND_INT:
            r = key_exprs[i].static_range() if key_exprs is not None else None
            if r is None:
                r = c.bounds
            if r is None:
                return None
            rs.extend(r)
        else:
            rs.extend((0, 0))
    return np.asarray(rs, np.int64)


def _attach_key_bounds(out_batch, spec, ranges_host) -> None:
    """Stamp (lo, hi) column-stat bounds on a radix agg output's key
    columns so downstream radix consumers (post-exchange merge, window
    sort) skip their own device range probe."""
    if ranges_host is None:
        return
    for i, kind in enumerate(spec.kinds):
        if kind == R.KIND_INT and i < len(out_batch.columns):
            lo = int(ranges_host[2 * i])
            hi = int(ranges_host[2 * i + 1])
            if lo <= hi:
                out_batch.columns[i].bounds = (lo, hi)


def _probe_pack_spec(key_cols, live, key_exprs=None):
    """Host decision: can these key columns pack into one int64 plane?
    Returns (spec, ranges_device, ranges_host) or (None, None, None).
    Costs one small device fetch when integer key ranges are involved and
    not statically derivable — from the expression or from column-stat
    bounds (shared by the aggregate, window, and sort radix paths)."""
    kinds = R.static_kinds(key_cols)
    if kinds is None:
        return None, None, None
    if R.needs_range_probe(kinds):
        ranges_host = _static_expr_ranges(key_cols, kinds, key_exprs)
        if ranges_host is not None:
            ranges = jnp.asarray(ranges_host)
        else:
            probe = fuse.fused(("radix_probe", tuple(kinds)),
                               lambda: R.probe_ranges)
            ranges = probe(key_cols, live)
            ranges_host = np.asarray(jax.device_get(ranges))
    else:
        ranges = jnp.zeros(2 * len(key_cols), jnp.int64)
        ranges_host = np.zeros(2 * len(key_cols), np.int64)
    spec = R.plan_packing(key_cols, ranges_host)
    return spec, ranges, ranges_host


class _AggKernels:
    """Aggregation kernel builders holding ONLY expression-level state.

    Deliberately separate from the exec node: the jitted closures built
    here live in the global fuse cache; if they captured the exec they
    would pin its child tree — including HBM-resident cached batches —
    for the process lifetime.
    """

    _BUCKET_LIMIT = 4096
    _MATMUL_LIMIT = 64

    #: segmented-reduction ops the packed radix path implements
    _SIMPLE_OPS = frozenset({"sum", "sumsq", "count", "count_all", "min",
                             "max", "first", "last", "any", "all"})

    def __init__(self, group_exprs, group_names, aggs, pre_filter):
        self.group_exprs = group_exprs
        self.group_names = group_names
        self.aggs = aggs
        self.pre_filter = pre_filter
        self._packed_ok = self._packed_static_ok()

    def _fp(self):
        return (tuple(e.fingerprint() for e in self.group_exprs),
                tuple(a.fn.fingerprint() for a in self.aggs),
                self.pre_filter.fingerprint() if self.pre_filter is not None
                else None)

    def _packed_static_ok(self) -> bool:
        """Static (plan-time) half of the radix fast-path eligibility:
        simple reduction ops over fixed-width states, packable-looking key
        types. The runtime half (spans fit 62 bits, strings are
        dict-encoded) is decided per batch in update()/merge()."""
        from spark_rapids_tpu.expr.aggregates import SegmentedAgg
        if not self.group_exprs:
            return False
        for e in self.group_exprs:
            dt = e.data_type()
            if not isinstance(dt, (T.Int8Type, T.Int16Type, T.Int32Type,
                                   T.Int64Type, T.DateType, T.TimestampType,
                                   T.BooleanType, T.DecimalType,
                                   T.StringType)):
                return False
        for a in self.aggs:
            if isinstance(a.fn, SegmentedAgg):
                return False
            for (sname, sdt), (op, idx) in zip(a.fn.state_schema(),
                                               a.fn.update_ops()):
                if op not in self._SIMPLE_OPS:
                    return False
                if isinstance(sdt, (T.StringType, T.ArrayType, T.MapType,
                                    T.StructType)):
                    return False
        return True

    # -- radix fast-path dispatch (see ops/radix.py) ------------------------

    def _probe_spec(self, key_cols, live, key_exprs=None):
        return _probe_pack_spec(key_cols, live, key_exprs)

    def update(self, batch: ColumnarBatch, ansi: bool):
        """The update phase entry: picks (in order) the tiny-bucket MXU
        path, the packed radix path, or the general sort path. Returns
        (state_batch, errors)."""
        if self._packed_ok:
            key_cols = compiled.run_stage(self.group_exprs, batch)
            if self._bucket_layout(key_cols) is None:
                spec, ranges, rh = self._probe_spec(key_cols,
                                                    batch.live_mask(),
                                                    self.group_exprs)
                if spec is not None:
                    fn = fuse.fused(
                        ("hashagg_packed_update", self._fp(), spec.key, ansi),
                        lambda: self._build_packed_update(ansi, spec))
                    out, errs = fn(batch, ranges)
                    _attach_key_bounds(out, spec, rh)
                    return out, errs
        fn = fuse.fused(("hashagg_update", self._fp(), ansi),
                        lambda: self._build_update(ansi))
        return fn(batch)

    def merge(self, batch: ColumnarBatch) -> ColumnarBatch:
        nkeys = len(self.group_exprs)
        if self._packed_ok and nkeys:
            key_cols = list(batch.columns[:nkeys])
            spec, ranges, rh = self._probe_spec(key_cols, batch.live_mask())
            if spec is not None:
                fn = fuse.fused(
                    ("hashagg_packed_merge", self._fp(), spec.key),
                    lambda: self._build_packed_merge(spec))
                out = fn(batch, ranges)
                _attach_key_bounds(out, spec, rh)
                return out
        fn = fuse.fused(("hashagg_merge", self._fp()),
                        lambda: self._merge_states)
        return fn(batch)

    def _build_packed_update(self, ansi: bool, spec):
        def fn(batch, ranges):
            live = batch.live_mask()
            errs = {}
            if self.pre_filter is not None:
                pctx = EvalCtx(batch.columns, traced_rows(batch.num_rows),
                               batch.capacity, ansi, live=live)
                pred = self.pre_filter.eval_tpu(pctx)
                live = live & pred.data.astype(jnp.bool_)
                if pred.validity is not None:
                    live = live & pred.validity
                batch = ColumnarBatch(
                    batch.columns,
                    LazyRowCount(jnp.sum(live.astype(jnp.int32))), live)
                errs.update(pctx.errors)
            ectx = EvalCtx(batch.columns, traced_rows(batch.num_rows),
                           batch.capacity, ansi, live=live)
            nkeys = len(self.group_exprs)
            exprs = [e for e in self._state_input_exprs() if e is not None]
            cols = [e.eval_tpu(ectx) for e in exprs]
            key_cols = cols[:nkeys]
            input_cols = {}
            ci = nkeys
            for ai, a in enumerate(self.aggs):
                input_cols[ai] = cols[ci: ci + len(a.fn.children)]
                ci += len(a.fn.children)
            errs.update(ectx.errors)
            state_specs = []
            for ai, a in enumerate(self.aggs):
                for (sname, sdt), (op, idx) in zip(a.fn.state_schema(),
                                                   a.fn.update_ops()):
                    src = input_cols[ai][idx] if idx >= 0 else None
                    state_specs.append((op, src, sdt))
            out = self._packed_agg(batch, live, key_cols, state_specs,
                                   spec, ranges)
            return out, errs
        return fn

    def _build_packed_merge(self, spec):
        def fn(batch, ranges):
            live = batch.live_mask()
            nkeys = len(self.group_exprs)
            key_cols = list(batch.columns[:nkeys])
            state_specs = []
            ci = nkeys
            for a in self.aggs:
                for (sname, sdt), op in zip(a.fn.state_schema(),
                                            a.fn.merge_ops()):
                    state_specs.append((op, batch.columns[ci], sdt))
                    ci += 1
            return self._packed_agg(batch, live, key_cols, state_specs,
                                    spec, ranges)
        return fn

    def _packed_agg(self, batch, live, key_cols, state_specs, spec, ranges):
        """Shared packed-radix reduction core for update and merge. Small
        packed key spaces (<= 2^23 buckets) take the SORT-FREE scatter
        path; wider ones pack + sort + cumsum reductions (ops/radix.py)."""
        if spec.total_bits <= R.BUCKET_BITS:
            return self._bucket_scatter_agg(live, key_cols, state_specs,
                                            spec, ranges)
        packed = R.pack_keys(spec, key_cols, ranges, live)
        lay = R.group_layout(packed, live)
        sg = jnp.clip(lay.starts, 0, lay.cap - 1)
        group_packed = lay.sorted_packed[sg]
        pad_ok = lay.starts >= 0
        out_cols: List[ColumnVector] = []
        for c in R.unpack_keys(spec, group_packed, ranges, key_cols):
            v = c.validity & pad_ok if c.validity is not None else pad_ok
            out_cols.append(ColumnVector(c.dtype, c.data, v,
                                         dict_unique=c.dict_unique))
        for op, src, sdt in state_specs:
            ov, oval = self._packed_op(op, src, sdt, live, lay)
            out_cols.append(ColumnVector(sdt, ov.astype(sdt.np_dtype)
                                         if ov.dtype != np.dtype(sdt.np_dtype)
                                         else ov, oval))
        return ColumnarBatch(out_cols, LazyRowCount(lay.n_groups))

    #: pallas sorted-window path gate: packed key bits in [11, 24] keeps
    #: the bucket space 2*TILE-aligned and the key-digit lanes <= 3
    _PALLAS_SEG_MIN_BITS = 11
    _PALLAS_SEG_MAX_BITS = 24

    def _pallas_ops_ok(self, state_specs) -> bool:
        n_sums = 0
        for op, src, sdt in state_specs:
            if op in ("count", "count_all"):
                continue
            if op == "sum" and src is not None and not src.is_string                     and not src.is_nested and np.dtype(sdt.np_dtype) in (
                        np.dtype(np.float64), np.dtype(np.float32)):
                n_sums += 1
                continue
            return False
        return 1 <= n_sums <= 2

    def _pallas_seg_eligible(self, live, state_specs, spec) -> bool:
        from spark_rapids_tpu.ops import pallas_kernels as PK
        if not PK.enabled():
            return False
        if not (self._PALLAS_SEG_MIN_BITS <= spec.total_bits
                <= self._PALLAS_SEG_MAX_BITS):
            return False
        cap = live.shape[0]
        from spark_rapids_tpu.ops.pallas_segsum import CHUNK_ROWS, TILE
        # HBM budget: the fused stage carries the sorted planes, digit
        # lanes, accumulators, AND the cond fallback's scatter temps; the
        # 32M q3 shape measured 18.5G against the v5e's 15.75G —
        # larger batches take the CHUNKED kernel path (below) when the
        # partial merge is cheap, else the scatter path
        if cap % TILE or cap < 4 * TILE or cap > CHUNK_ROWS:
            return False
        return self._pallas_ops_ok(state_specs)

    def _pallas_chunk_plan(self, live, state_specs, spec) -> int:
        """Chunk count for the chunked kernel path (0 = ineligible).
        Batches past the kernel's whole-stage HBM ceiling run it per
        CHUNK_ROWS slice and sum-merge the k small dense partials; only
        worthwhile when that merge (k * 2^bits rows) is itself cheap."""
        from spark_rapids_tpu.ops import pallas_kernels as PK
        if not PK.enabled():
            return 0
        if not (self._PALLAS_SEG_MIN_BITS <= spec.total_bits
                <= self._PALLAS_SEG_MAX_BITS):
            return 0
        if not self._pallas_ops_ok(state_specs):
            return 0
        cap = live.shape[0]
        from spark_rapids_tpu.ops.pallas_segsum import CHUNK_ROWS
        if cap <= CHUNK_ROWS or cap % CHUNK_ROWS:
            return 0
        k = cap // CHUNK_ROWS
        if k * (1 << spec.total_bits) > CHUNK_ROWS:
            return 0
        return k

    def _chunked_pallas_agg(self, live, key_cols, state_specs, spec,
                            ranges, k: int) -> ColumnarBatch:
        """Run the Pallas sorted-window groupby per CHUNK_ROWS slice and
        merge the k dense partials with one recursive bucket agg — the
        stage split that unlocks the kernel at 30M-row shapes (the
        recursive re-aggregation pattern of GpuAggregateExec.scala:
        208-315, done by chunking instead of repartitioning)."""
        from spark_rapids_tpu.ops.pallas_segsum import (CHUNK_ROWS,
                                                        MAX_GROUP_ROWS)

        def cv_rows(c, off):
            if c is None:
                return None
            if c.is_dict:
                data = {"codes": c.data["codes"][off:off + CHUNK_ROWS],
                        "dict_offsets": c.data["dict_offsets"],
                        "dict_bytes": c.data["dict_bytes"]}
            else:
                data = c.data[off:off + CHUNK_ROWS]
            v = None if c.validity is None \
                else c.validity[off:off + CHUNK_ROWS]
            return ColumnVector(c.dtype, data, v,
                                dict_unique=c.dict_unique, bounds=c.bounds)

        nkeys = len(key_cols)
        parts: List[ColumnarBatch] = []
        for i in range(k):
            off = i * CHUNK_ROWS
            live_c = live[off:off + CHUNK_ROWS]
            keys_c = [cv_rows(c, off) for c in key_cols]
            specs_c = [(op, cv_rows(src, off), sdt)
                       for op, src, sdt in state_specs]
            post, (max_cnt, has_specials) = \
                self._pallas_seg_kernel_and_post(live_c, keys_c, specs_c,
                                                 spec, ranges)

            def fallback(lc=live_c, kc=keys_c, sc=specs_c):
                return self._bucket_scatter_agg_xla(lc, kc, sc, spec,
                                                    ranges)
            parts.append(lax.cond(
                (max_cnt <= MAX_GROUP_ROWS) & ~has_specials,
                post, fallback))
        # concatenate the k equal-capacity partials (dict key vocab
        # planes are shared across chunks) and sum-merge per bucket:
        # sum states merge by sum, count states by integer sum
        cat_cols: List[ColumnVector] = []
        for ci in range(nkeys + len(state_specs)):
            cvs = [p.columns[ci] for p in parts]
            c0 = cvs[0]
            if c0.is_dict:
                data = {"codes": jnp.concatenate(
                            [c.data["codes"] for c in cvs]),
                        "dict_offsets": c0.data["dict_offsets"],
                        "dict_bytes": c0.data["dict_bytes"]}
            else:
                data = jnp.concatenate([c.data for c in cvs])
            if any(c.validity is not None for c in cvs):
                val = jnp.concatenate([c.validity_or_default(c.capacity)
                                       for c in cvs])
            else:
                val = None
            cat_cols.append(ColumnVector(c0.dtype, data, val,
                                         dict_unique=c0.dict_unique))
        cat_live = jnp.concatenate([p.live_mask() for p in parts])
        merge_specs = [("sum", cat_cols[nkeys + j], sdt)
                       for j, (_op, _src, sdt) in enumerate(state_specs)]
        return self._bucket_scatter_agg(cat_live, cat_cols[:nkeys],
                                        merge_specs, spec, ranges)

    def _pallas_seg_kernel_and_post(self, live, key_cols, state_specs,
                                    spec, ranges):
        """Returns (postprocess_thunk, max_cnt): the Pallas kernel runs
        immediately (top level); the thunk builds the output batch from
        the accumulator and is safe to call inside lax.cond."""
        return self._pallas_seg_agg(live, key_cols, state_specs, spec,
                                    ranges)

    def _pallas_seg_agg(self, live, key_cols, state_specs, spec, ranges):
        """Sorted-window one-hot-matmul groupby (ops/pallas_segsum):
        ONE co-sortless 2-operand sort + 1-2 gathers + the Pallas kernel
        replace every scatter. Output is in DENSE GROUP-ID space (front-
        packed groups) at the same capacity as the bucket space, so the
        lax.cond overflow fallback to the scatter path keeps identical
        shapes (slot ORDER differs; downstream is order-free over the
        occupied mask)."""
        from spark_rapids_tpu.ops import pallas_segsum as PS
        cap = live.shape[0]
        nb = 1 << spec.total_bits
        packed64 = R.pack_keys(spec, key_cols, ranges, live)
        big = jnp.int32(nb + 1)
        code = jnp.where(live, packed64.astype(jnp.int32), big)
        iota = jnp.arange(cap, dtype=jnp.int32)
        sk, perm = lax.sort((code, iota), num_keys=1)
        boundary = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                    sk[1:] != sk[:-1]])
        gid = (jnp.cumsum(boundary.astype(jnp.int32)) - 1).astype(jnp.int32)
        live_sorted = sk < big

        has_specials = jnp.zeros((), jnp.bool_)
        lanes = [live_sorted.astype(jnp.bfloat16)]  # lane 0: live count
        kd, kshifts = PS.int_digits(jnp.where(live_sorted, sk, 0),
                                    spec.total_bits)
        lanes.extend(kd)
        plan = []  # (op, kind, lane_slices / scales)
        for op, src, sdt in state_specs:
            if op == "count_all":
                plan.append(("count_all", None, None))
                continue
            if op == "count":
                if src is None or src.validity is None:
                    plan.append(("count_live", None, None))
                else:
                    v_s = src.validity[perm] & live_sorted
                    lanes.append(v_s.astype(jnp.bfloat16))
                    plan.append(("count_lane", len(lanes) - 1, None))
                continue
            # float sum: gather the value plane into sorted order once.
            # NaN/Inf rows are stripped BEFORE the scale (an Inf max
            # collapses every digit to zero) and instead force the
            # scatter fallback, which reconstructs specials per bucket
            # (radix.bucket_sum_f64's flag machinery).
            vals = src.data.astype(jnp.float64)[perm]
            valid_s = live_sorted if src.validity is None else                 (src.validity[perm] & live_sorted)
            finite = jnp.isfinite(vals)
            clean = jnp.where(valid_s & finite, vals, 0.0)
            has_specials = has_specials | jnp.any(valid_s & ~finite)
            m = jnp.max(jnp.abs(clean))
            scale = R._exponent_scale(m) * np.float64(2.0 ** 11)
            start = len(lanes)
            lanes.extend(PS.float_digits(clean, scale))
            some_lane = None
            if src.validity is not None:
                lanes.append(valid_s.astype(jnp.bfloat16))
                some_lane = len(lanes) - 1
            plan.append(("sum", (start, scale, some_lane), sdt))
        P = -(-len(lanes) // 8) * 8
        while len(lanes) < P:
            lanes.append(jnp.zeros(cap, jnp.bfloat16))
        # the kernel runs at TOP LEVEL (a pallas custom-call inside a
        # lax.cond branch aborts the runtime on this toolchain); only the
        # cheap postprocessing participates in the overflow cond
        payload = jnp.stack(lanes, axis=1)
        acc = PS.segsum_window(gid, payload, nb)

        def post():
            return self._pallas_seg_post(acc, state_specs, spec, ranges,
                                         key_cols, plan, len(kd), kshifts,
                                         nb)
        return post, (jnp.max(acc[:, 0]), has_specials)

    def _pallas_seg_post(self, acc, state_specs, spec, ranges, key_cols,
                         plan, nkd, kshifts, nb):
        from spark_rapids_tpu.ops import pallas_segsum as PS
        counts_live = acc[:, 0]
        key_code = PS.int_digits_to_val(
            [acc[:, 1 + i] for i in range(nkd)], kshifts, counts_live)
        occupied = counts_live > 0.5
        out_cols: List[ColumnVector] = []
        for c in R.unpack_keys(spec, key_code.astype(jnp.int64), ranges,
                               key_cols):
            v = c.validity & occupied if c.validity is not None else occupied
            out_cols.append(ColumnVector(c.dtype, c.data, v,
                                         dict_unique=c.dict_unique))
        for (op, src, sdt), (kind, info, _sdt) in zip(state_specs, plan):
            if kind in ("count_all", "count_live"):
                ov = counts_live.astype(jnp.int64)
                out_cols.append(ColumnVector(
                    sdt, ov.astype(sdt.np_dtype), jnp.ones(nb, jnp.bool_)))
                continue
            if kind == "count_lane":
                ov = acc[:, info].astype(jnp.int64)
                out_cols.append(ColumnVector(
                    sdt, ov.astype(sdt.np_dtype), jnp.ones(nb, jnp.bool_)))
                continue
            start, scale, some_lane = info
            tot = PS.digits_to_f64(
                [acc[:, start + i] for i in range(len(PS.SHIFTS))]) / scale
            some = acc[:, some_lane] > 0.5 if some_lane is not None \
                else occupied
            out_cols.append(ColumnVector(
                sdt, tot.astype(sdt.np_dtype), some))
        n_groups = jnp.sum(occupied.astype(jnp.int32))
        return ColumnarBatch(out_cols, LazyRowCount(n_groups), occupied)

    def _bucket_scatter_agg(self, live, key_cols, state_specs, spec, ranges):
        if self._pallas_seg_eligible(live, state_specs, spec):
            post, (max_cnt, has_specials) = \
                self._pallas_seg_kernel_and_post(
                    live, key_cols, state_specs, spec, ranges)
            from spark_rapids_tpu.ops.pallas_segsum import MAX_GROUP_ROWS
            # One cond over the whole batch pytree: the scatter fallback
            # only EXECUTES when a group exceeds the digit-accumulation
            # bound (the count lane stays trustworthy well past the
            # threshold, so the predicate is reliable even then). Slot
            # ORDER differs between branches (dense-gid vs bucket index),
            # which downstream — occupied-masked and order-free — never
            # observes.
            return lax.cond(
                (max_cnt <= MAX_GROUP_ROWS) & ~has_specials,
                post,
                lambda: self._bucket_scatter_agg_xla(
                    live, key_cols, state_specs, spec, ranges))
        k = self._pallas_chunk_plan(live, state_specs, spec)
        if k:
            return self._chunked_pallas_agg(live, key_cols, state_specs,
                                            spec, ranges, k)
        return self._bucket_scatter_agg_xla(live, key_cols, state_specs,
                                            spec, ranges)

    def _bucket_scatter_agg_xla(self, live, key_cols, state_specs, spec,
                                ranges):
        lay = R.bucket_layout(spec, key_cols, ranges, live)
        out_cols: List[ColumnVector] = []
        for c in R.bucket_unpack_keys(spec, ranges, key_cols):
            v = c.validity & lay.occupied if c.validity is not None \
                else lay.occupied
            out_cols.append(ColumnVector(c.dtype, c.data, v,
                                         dict_unique=c.dict_unique))
        nb = lay.bucket  # noqa: F841
        ones = jnp.ones(1 << spec.total_bits, jnp.bool_)
        for op, src, sdt in state_specs:
            if src is not None:
                if (src.is_string or src.is_nested) and \
                        op not in ("count", "count_all"):
                    raise NotImplementedError(
                        "string/nested agg state on device")
                valid = live if src.validity is None \
                    else (src.validity & live)
                vals = src.data if not (src.is_string or src.is_nested) \
                    else jnp.zeros(live.shape[0], sdt.np_dtype)
            else:
                valid = live
                vals = jnp.zeros(live.shape[0], sdt.np_dtype)
            ov, oval = self._bucket_op(op, vals, valid, sdt, lay, ones)
            out_cols.append(ColumnVector(
                sdt, ov.astype(sdt.np_dtype)
                if ov.dtype != np.dtype(sdt.np_dtype) else ov, oval))
        return ColumnarBatch(out_cols, LazyRowCount(lay.n_groups),
                             lay.occupied)

    def _bucket_op(self, op, vals, valid, sdt, lay, ones):
        def nv():
            # a no-null column's validity IS the live mask, which the
            # layout already counted — skip the extra scatter
            return lay.counts.astype(jnp.int64) if valid is lay.live \
                else R.bucket_count(lay, valid)
        if op == "count":
            return nv(), ones
        if op == "count_all":
            return lay.counts.astype(jnp.int64), ones
        nvalid = nv()
        some = nvalid > 0
        if op in ("sum", "sumsq"):
            v = vals * vals if op == "sumsq" else vals
            if np.dtype(sdt.np_dtype) in (np.dtype(np.float64),
                                          np.dtype(np.float32)):
                tot = R.bucket_sum_f64(lay, v, valid)
                return tot, some
            return R.bucket_sum_int(lay, v, valid), some
        if op in ("min", "max"):
            d = np.dtype(vals.dtype)
            if d == np.dtype(np.float64):
                return R.bucket_minmax_f64(op, lay, vals, valid), some
            if d == np.dtype(np.float32):
                return R.bucket_minmax_f32(op, lay, vals, valid), some
            if d == np.dtype(np.int64):
                return R.bucket_minmax_i64(op, lay, vals, valid), some
            init = (G._MIN_INIT if op == "min" else G._MAX_INIT)[
                np.dtype(np.int32) if d == np.dtype(np.bool_) else d]
            out = R.bucket_minmax_i32(op, lay, vals, valid, int(init))
            return out.astype(vals.dtype), some
        if op in ("first", "last"):
            v, has = R.bucket_first_last(op, lay, vals, valid)
            return v, has & some
        if op == "any":
            return R.bucket_count(lay, valid & vals.astype(jnp.bool_)) > 0, \
                some
        if op == "all":
            return R.bucket_count(lay, valid & ~vals.astype(jnp.bool_)) == 0, \
                some
        raise ValueError(f"unknown bucket op {op}")

    def _packed_op(self, op, src, sdt, live, lay):
        cap = lay.cap
        if src is not None:
            if (src.is_string or src.is_nested) and \
                    op not in ("count", "count_all"):
                raise NotImplementedError(
                    "string/nested agg state on device")
            valid = (live if src.validity is None
                     else (src.validity & live))[lay.perm]
            vals = src.data[lay.perm] \
                if not (src.is_string or src.is_nested) \
                else jnp.zeros(cap, sdt.np_dtype)
        else:
            valid = live[lay.perm]
            vals = jnp.zeros(cap, sdt.np_dtype)
        if op == "count":
            return R.seg_count(valid, lay), jnp.ones(cap, jnp.bool_)
        if op == "count_all":
            return R.seg_count_all(lay), jnp.ones(cap, jnp.bool_)
        nvalid = R.seg_count(valid, lay)
        some = nvalid > 0
        if op in ("sum", "sumsq"):
            v = vals * vals if op == "sumsq" else vals
            if np.dtype(sdt.np_dtype) in (np.dtype(np.float64),
                                          np.dtype(np.float32)):
                return R.seg_sum_f64(v.astype(jnp.float64), valid, lay), some
            return R.seg_sum_int(v, valid, lay), some
        if op in ("min", "max"):
            d = np.dtype(vals.dtype)
            if d == np.dtype(np.float64):
                return R.seg_minmax_f64(op, vals, valid, lay), some
            if d == np.dtype(np.float32):
                return R.seg_minmax_f32(op, vals, valid, lay), some
            if d in (np.dtype(np.int64),):
                return R.seg_minmax_i64(op, vals, valid, lay), some
            init = (G._MIN_INIT if op == "min" else G._MAX_INIT)[
                np.dtype(np.int32) if d == np.dtype(np.bool_) else d]
            out = R.seg_minmax_i32(op, vals, valid, lay,
                                   int(init))
            return out.astype(vals.dtype), some
        if op in ("first", "last"):
            v, has = R.seg_first_last(op, vals, valid, lay)
            return v, has & some
        if op == "any":
            t = valid & vals.astype(jnp.bool_)
            return R.seg_count(t, lay) > 0, some
        if op == "all":
            f = valid & ~vals.astype(jnp.bool_)
            return R.seg_count(f, lay) == 0, some
        raise ValueError(f"unknown packed op {op}")

    def _state_input_exprs(self):
        """Expressions evaluated per input row: keys then, per agg, ALL its
        input children (min_by/max_by consume two)."""
        exprs = list(self.group_exprs)
        for a in self.aggs:
            exprs.extend(a.fn.children)
        return exprs

    @property
    def has_custom(self) -> bool:
        from spark_rapids_tpu.expr.aggregates import SegmentedAgg
        return any(isinstance(a.fn, SegmentedAgg) for a in self.aggs)

    def _build_update(self, ansi: bool):
        """Build the fused update phase: expression eval + sort-group +
        segmented reductions as ONE traced computation over batch pytrees."""
        def fn(batch):
            live = batch.live_mask()
            errs = {}
            if self.pre_filter is not None:
                pctx = EvalCtx(batch.columns, traced_rows(batch.num_rows),
                               batch.capacity, ansi, live=live)
                pred = self.pre_filter.eval_tpu(pctx)
                live = live & pred.data.astype(jnp.bool_)
                if pred.validity is not None:
                    live = live & pred.validity
                batch = ColumnarBatch(
                    batch.columns,
                    LazyRowCount(jnp.sum(live.astype(jnp.int32))), live)
                errs.update(pctx.errors)
            ectx = EvalCtx(batch.columns, traced_rows(batch.num_rows),
                           batch.capacity, ansi, live=live)
            out = self._update_batch(batch, ectx)
            errs.update(ectx.errors)
            return out, errs
        return fn

    def _update_batch(self, batch: ColumnarBatch, ectx) -> ColumnarBatch:
        from spark_rapids_tpu.expr.aggregates import SegmentedAgg
        nkeys = len(self.group_exprs)
        exprs = [e for e in self._state_input_exprs() if e is not None]
        cols = [e.eval_tpu(ectx) for e in exprs]
        key_cols = cols[:nkeys]
        input_cols = {}
        ci = nkeys
        for ai, a in enumerate(self.aggs):
            input_cols[ai] = cols[ci: ci + len(a.fn.children)]
            ci += len(a.fn.children)
        cap = batch.capacity
        live = batch.live_mask()

        def col_valid(src):
            return live if src.validity is None else (src.validity & live)

        if nkeys == 0:
            out_cols = []
            nrows = traced_rows(batch.num_rows)
            for ai, a in enumerate(self.aggs):
                if isinstance(a.fn, SegmentedAgg):
                    # global custom agg: one segment over all rows
                    res = a.fn.segmented_eval_tpu(
                        input_cols[ai], jnp.arange(cap, dtype=jnp.int32),
                        jnp.zeros(cap, jnp.int32), 1, live, nrows)
                    out_cols.append(_resize_col(res, round_capacity(1)))
                    continue
                for (sname, sdt), (op, idx) in zip(a.fn.state_schema(),
                                                   a.fn.update_ops()):
                    if idx >= 0:
                        src = input_cols[ai][idx]
                        if src.is_string or src.is_nested:
                            if op not in ("count", "count_all"):
                                raise NotImplementedError(
                                    "string agg state on device")
                            vals = jnp.zeros(cap, sdt.np_dtype)
                        else:
                            vals = src.data
                            if vals.dtype != sdt.np_dtype:
                                vals = vals.astype(sdt.np_dtype)
                        ov, oval = G.global_agg(op, vals, col_valid(src))
                    else:
                        ov, oval = G.global_agg(op, jnp.zeros(cap, sdt.np_dtype), live)
                    out_cols.append(_resize_plane(ov, oval, sdt, round_capacity(1)))
            return ColumnarBatch(out_cols, 1)

        fast = None if any(isinstance(a.fn, SegmentedAgg) for a in self.aggs) \
            else self._bucket_layout(key_cols)
        if fast is not None:
            return self._bucket_update(batch, key_cols, input_cols, live, fast)

        if nkeys:
            # Deferred shrink: output keeps the input capacity and the group
            # count stays on device (LazyRowCount); the shrink to the true
            # size happens once, at yield, not per batch.
            perm, seg_ids, boundary = G.group_segments(key_cols, batch.num_rows,
                                                       live=live)
            n_groups = LazyRowCount(jnp.sum(boundary.astype(jnp.int32)))
            seg_cap = cap
            out_cap = cap
        else:
            perm = jnp.arange(cap, dtype=jnp.int32)
            seg_ids = jnp.zeros(cap, jnp.int32)
            boundary = jnp.zeros(cap, jnp.bool_).at[0].set(True)
            n_groups = 1
            seg_cap = 1
            out_cap = round_capacity(1)
        out_cols: List[ColumnVector] = []
        if nkeys:
            out_key_cols = G.gather_group_keys(key_cols, perm, boundary,
                                               n_groups, batch.num_rows,
                                               live=live)
            for c in out_key_cols:
                out_cols.append(_resize_col(c, out_cap))
        nrows = traced_rows(batch.num_rows)
        for ai, a in enumerate(self.aggs):
            if isinstance(a.fn, SegmentedAgg):
                res = a.fn.segmented_eval_tpu(input_cols[ai], perm, seg_ids,
                                              seg_cap, live, nrows)
                out_cols.append(_resize_col(res, out_cap))
                continue
            for (sname, sdt), (op, idx) in zip(a.fn.state_schema(), a.fn.update_ops()):
                if idx >= 0:
                    src = input_cols[ai][idx]
                    if src.is_string or src.is_nested:
                        if op not in ("count", "count_all"):
                            # min/max/first/last over strings: handled via
                            # host fallback by tagging; sum never string
                            raise NotImplementedError(
                                "string agg state on device")
                        # count reads only the validity plane
                        sorted_vals = jnp.zeros(cap, sdt.np_dtype)
                        sorted_valid = col_valid(src)[perm]
                    else:
                        vals = src.data
                        vals = vals.astype(sdt.np_dtype) \
                            if vals.dtype != sdt.np_dtype else vals
                        sorted_vals = vals[perm]
                        sorted_valid = col_valid(src)[perm]
                else:
                    sorted_vals = jnp.zeros(cap, sdt.np_dtype)
                    sorted_valid = live[perm]
                ov, oval = G.segmented_agg(op, sorted_vals, sorted_valid,
                                           seg_ids, seg_cap)
                out_cols.append(_resize_plane(ov, oval, sdt, out_cap))
        return ColumnarBatch(out_cols, n_groups)

    # -- bucketed (MXU) aggregation fast path ------------------------------

    _BUCKET_LIMIT = 4096
    _MATMUL_LIMIT = 64

    def _bucket_layout(self, key_cols):
        """When every group key has a small static cardinality (dict-encoded
        strings, booleans), groups map to dense bucket ids and aggregation
        needs NO sort: sums/counts become a one-hot matmul on the MXU (tiny
        bucket spaces) or a bounded scatter-add. Returns per-key
        (cardinality+1) strides or None if ineligible. The +1 slot per key
        encodes NULL (Spark groups null keys)."""
        sizes = []
        for c in key_cols:
            if c.is_dict and c.dict_unique:
                sizes.append(c.dict_size + 1)
            elif isinstance(c.dtype, T.BooleanType):
                sizes.append(3)
            else:
                return None
        total = 1
        for s in sizes:
            total *= s
            if total > self._BUCKET_LIMIT:
                return None
        return sizes

    def _bucket_update(self, batch, key_cols, input_cols, live, sizes):
        B = 1
        for s in sizes:
            B *= s
        bucket = jnp.zeros(batch.capacity, jnp.int32)
        for c, s in zip(key_cols, sizes):
            if c.is_dict:
                code = c.data["codes"].astype(jnp.int32)
            else:
                code = c.data.astype(jnp.int32)
            null_code = s - 1
            if c.validity is not None:
                code = jnp.where(c.validity, code, null_code)
            bucket = bucket * s + jnp.clip(code, 0, null_code)
        if B <= self._MATMUL_LIMIT:
            # keep the whole tiny-B path scatter-FREE: XLA fuses all the
            # per-bucket masked reductions (occupancy + every agg state)
            # into a handful of passes over the shared input planes; one
            # scatter in the middle splits that fusion island and was
            # measured to cost ~8x on a 30M-row q1 shape
            occupancy = jnp.stack([jnp.any(live & (bucket == b))
                                   for b in range(B)])
        else:
            occupancy = (jax.ops.segment_sum(
                jnp.where(live, 1, 0), jnp.where(live, bucket, B),
                num_segments=B + 1)[:B] > 0)
        out_cols: List[ColumnVector] = []
        # reconstruct key columns from the bucket index (B is small)
        codes = []
        rem = jnp.arange(B, dtype=jnp.int32)
        for s in reversed(sizes):
            codes.append(rem % s)
            rem = rem // s
        codes.reverse()
        for c, s, code in zip(key_cols, sizes, codes):
            kvalid = code < (s - 1)
            if c.is_dict:
                data = {"codes": code.astype(jnp.int32),
                        "dict_offsets": c.data["dict_offsets"],
                        "dict_bytes": c.data["dict_bytes"]}
                out_cols.append(ColumnVector(c.dtype, data, kvalid))
            else:
                out_cols.append(ColumnVector(c.dtype, code.astype(c.data.dtype), kvalid))
        for ai, a in enumerate(self.aggs):
            for (sname, sdt), (op, idx) in zip(a.fn.state_schema(), a.fn.update_ops()):
                if idx >= 0:
                    src = input_cols[ai][idx]
                    if (src.is_string or src.is_nested) and \
                            op not in ("count", "count_all"):
                        raise NotImplementedError(
                            "string agg state on device")
                    vals = src.data \
                        if not (src.is_string or src.is_nested) \
                        else jnp.zeros(batch.capacity, sdt.np_dtype)
                    vals = vals.astype(sdt.np_dtype) if vals.dtype != sdt.np_dtype else vals
                    valid = live if src.validity is None else (src.validity & live)
                else:
                    vals = jnp.zeros(batch.capacity, sdt.np_dtype)
                    valid = live
                ov, oval = G.bucket_agg(op, vals, valid, bucket, B,
                                        matmul_ok=B <= self._MATMUL_LIMIT)
                out_cols.append(ColumnVector(sdt, ov, oval))
        n_groups = LazyRowCount(jnp.sum(occupancy.astype(jnp.int32)))
        return ColumnarBatch(out_cols, n_groups, occupancy)

    def _merge_states(self, batch: ColumnarBatch) -> ColumnarBatch:
        nkeys = len(self.group_exprs)
        cap = batch.capacity
        live = batch.live_mask()
        if nkeys == 0:
            out_cols = []
            ci = 0
            for a in self.aggs:
                for (sname, sdt), op in zip(a.fn.state_schema(), a.fn.merge_ops()):
                    src = batch.columns[ci]
                    ci += 1
                    src_valid = live if src.validity is None else (src.validity & live)
                    ov, oval = G.global_agg(op, src.data, src_valid)
                    out_cols.append(_resize_plane(ov, oval, sdt, round_capacity(1)))
            return ColumnarBatch(out_cols, 1)
        key_cols = batch.columns[:nkeys]
        if nkeys:
            perm, seg_ids, boundary = G.group_segments(key_cols, batch.num_rows,
                                                       live=live)
            n_groups = LazyRowCount(jnp.sum(boundary.astype(jnp.int32)))
            seg_cap = cap
            out_cap = cap
        else:
            perm = jnp.arange(cap, dtype=jnp.int32)
            seg_ids = jnp.zeros(cap, jnp.int32)
            boundary = jnp.zeros(cap, jnp.bool_).at[0].set(True)
            n_groups = 1
            seg_cap = 1
            out_cap = round_capacity(1)
        out_cols = []
        if nkeys:
            for c in G.gather_group_keys(key_cols, perm, boundary, n_groups,
                                         batch.num_rows, live=live):
                out_cols.append(_resize_col(c, out_cap))
        ci = nkeys
        for a in self.aggs:
            for (sname, sdt), op in zip(a.fn.state_schema(), a.fn.merge_ops()):
                src = batch.columns[ci]
                ci += 1
                sorted_vals = src.data[perm]
                src_valid = live if src.validity is None else (src.validity & live)
                ov, oval = G.segmented_agg(op, sorted_vals, src_valid[perm],
                                           seg_ids, seg_cap)
                out_cols.append(_resize_plane(ov, oval, sdt, out_cap))
        return ColumnarBatch(out_cols, n_groups)

    def _evaluate_states(self, state: ColumnarBatch) -> ColumnarBatch:
        nkeys = len(self.group_exprs)
        out_cols = list(state.columns[:nkeys])
        ci = nkeys
        for a in self.aggs:
            n_state = len(a.fn.state_schema())
            scols = state.columns[ci: ci + n_state]
            ci += n_state
            res = a.fn.evaluate_tpu(scols, state.num_rows)
            # clamp dtype
            rt = a.fn.result_type()
            if not res.is_string and not res.is_nested \
                    and res.data.dtype != np.dtype(rt.np_dtype):
                res = ColumnVector(rt, res.data.astype(rt.np_dtype), res.validity)
            out_cols.append(res)
        return ColumnarBatch(out_cols, state.num_rows, state.row_mask)


class WindowExec(TpuExec):
    """Window evaluation: one sort by (partition, order) keys, then every
    window function as fused segmented scans (reference GpuWindowExec /
    GpuRunningWindowExec; the whole node is ONE device dispatch)."""

    def execute_partition(self, ctx, pidx):
        from spark_rapids_tpu.ops import window as W
        from spark_rapids_tpu.expr import window as WE
        win_t = self.metrics.metric(M.OP_TIME)
        batches = list(self.children[0].execute_partition(ctx, pidx))
        if not batches:
            return
        self._acquire(ctx)
        batch = K.concat_batches(batches) if len(batches) > 1 else batches[0]
        if batch.row_mask is not None:
            batch = K.compact_batch(batch)
        exprs = self.plan.window_exprs
        spec = exprs[0].spec  # one spec per node (planner groups)

        # packed-radix sort path: all (partition, order) keys compressed
        # into ONE int64 plane -> single-key stable argsort + boundary
        # diffs on the packed plane. The general multi-operand u64
        # lax.sort below takes MINUTES to compile on TPU and pays one
        # gather per key plane; this path is one sort + one gather.
        nparts = len(spec.partition_exprs)
        key_exprs = list(spec.partition_exprs) + [o.expr
                                                  for o in spec.order_specs]
        pspec = ranges = None
        if key_exprs:
            kcols = compiled.run_stage(key_exprs, batch)
            pspec, ranges, _ = _probe_pack_spec(kcols, batch.live_mask(),
                                                key_exprs)
            if pspec is not None and not all(
                    k in (R.KIND_INT, R.KIND_BOOL)
                    for k in pspec.kinds[nparts:]):
                pspec = None  # dict codes are not value-ordered

        def build_packed(pk):
            flags = [(True, True)] * nparts + \
                [(o.ascending, o.resolved_nulls_first())
                 for o in spec.order_specs]
            obits = sum(pk.bits[nparts:])

            def fn(batch, ranges):
                from spark_rapids_tpu.ops import window as W  # noqa: F811
                nr = traced_rows(batch.num_rows)
                cap = batch.capacity
                ectx = EvalCtx(batch.columns, nr, cap, False)
                kcols = [e.eval_tpu(ectx) for e in key_exprs]
                live = jnp.arange(cap) < nr
                packed = R.pack_keys_sort(pk, kcols, ranges, live, flags)
                perm = jnp.argsort(packed, stable=True).astype(jnp.int32)
                sp = packed[perm]
                first = jnp.zeros(cap, jnp.bool_).at[0].set(True)
                part_plane = sp >> jnp.int64(obits)
                segb = first | jnp.concatenate(
                    [jnp.zeros(1, jnp.bool_),
                     part_plane[1:] != part_plane[:-1]])
                peerb = first | jnp.concatenate(
                    [jnp.zeros(1, jnp.bool_), sp[1:] != sp[:-1]])
                seg_start, seg_end, peer_start, peer_end = \
                    W.segment_layout(segb, peerb)
                seg_end = jnp.minimum(
                    seg_end, jnp.maximum(nr - 1, 0).astype(seg_end.dtype))
                peer_end = jnp.minimum(peer_end, seg_end)
                seg_id = jnp.cumsum(segb.astype(jnp.int32))
                idx = jnp.arange(cap, dtype=jnp.int32)
                # pass-through columns stay in ORIGINAL row order (window
                # output order is unspecified); window results compute in
                # sorted space and scatter back — data columns are only
                # gathered if a frame agg / lead-lag reads them
                sctx = EvalCtx([], nr, cap, False)
                sctx.columns = K.LazyGatheredCols(batch.columns, perm,
                                                  batch.num_rows)
                out_cols = list(batch.columns)
                for w in exprs:
                    wc = _eval_window_fn(
                        w, sctx, seg_start, seg_end, peer_start, peer_end,
                        seg_id, segb, peerb, idx, live)
                    out_cols.append(_scatter_window_output(
                        wc, perm, cap, live, batch.num_rows))
                return ColumnarBatch(out_cols, batch.num_rows)
            return fn

        def _layout_of(pk, batch, ranges):
            flags = [(True, True)] * nparts + \
                [(o.ascending, o.resolved_nulls_first())
                 for o in spec.order_specs]
            obits = sum(pk.bits[nparts:])
            nr = traced_rows(batch.num_rows)
            cap = batch.capacity
            ectx = EvalCtx(batch.columns, nr, cap, False)
            kcols = [e.eval_tpu(ectx) for e in key_exprs]
            live = jnp.arange(cap) < nr
            packed = R.pack_keys_sort(pk, kcols, ranges, live, flags)
            perm = jnp.argsort(packed, stable=True).astype(jnp.int32)
            sp = packed[perm]
            first = jnp.zeros(cap, jnp.bool_).at[0].set(True)
            part_plane = sp >> jnp.int64(obits)
            segb = first | jnp.concatenate(
                [jnp.zeros(1, jnp.bool_),
                 part_plane[1:] != part_plane[:-1]])
            peerb = first | jnp.concatenate(
                [jnp.zeros(1, jnp.bool_), sp[1:] != sp[:-1]])
            seg_start, seg_end, peer_start, peer_end = \
                W.segment_layout(segb, peerb)
            seg_end = jnp.minimum(
                seg_end, jnp.maximum(nr - 1, 0).astype(seg_end.dtype))
            peer_end = jnp.minimum(peer_end, seg_end)
            seg_id = jnp.cumsum(segb.astype(jnp.int32))
            return (perm, seg_start, seg_end, peer_start, peer_end,
                    seg_id, segb, peerb, live)

        def build_sort_layout(pk):
            def fn(batch, ranges):
                from spark_rapids_tpu.ops import window as W  # noqa: F811
                return _layout_of(pk, batch, ranges)
            return fn

        def build_apply_fns(pk):
            def fn(batch, perm, seg_start, seg_end, peer_start, peer_end,
                   seg_id, segb, peerb, live):
                nr = traced_rows(batch.num_rows)
                cap = batch.capacity
                idx = jnp.arange(cap, dtype=jnp.int32)
                sctx = EvalCtx([], nr, cap, False)
                sctx.columns = K.LazyGatheredCols(batch.columns, perm,
                                                  batch.num_rows)
                out_cols = list(batch.columns)
                for w in exprs:
                    wc = _eval_window_fn(
                        w, sctx, seg_start, seg_end, peer_start, peer_end,
                        seg_id, segb, peerb, idx, live)
                    out_cols.append(_scatter_window_output(
                        wc, perm, cap, live, batch.num_rows))
                return ColumnarBatch(out_cols, batch.num_rows)
            return fn


        from spark_rapids_tpu.expr import window as WEm
        has_window_agg = any(isinstance(w.fn, WEm.WindowAgg) for w in exprs)
        if pspec is not None and has_window_agg:
            # two dispatches for frame-aggregation windows: the fully
            # fused sort+cumsum+gather pipeline for THIS shape wedges the
            # remote TPU compiler (observed: window-ratio NDS queries
            # hang >10 min in compile); splitting at the sort boundary
            # changes the fusion islands and compiles
            kA = ("window_sortlay", tuple(e.fingerprint()
                                          for e in key_exprs),
                  tuple((o.ascending, o.resolved_nulls_first())
                        for o in spec.order_specs), pspec.key)
            kB = ("window_fns", tuple(w.fingerprint() for w in exprs),
                  pspec.key)
            fnA = fuse.fused(kA, lambda: build_sort_layout(pspec))
            fnB = fuse.fused(kB, lambda: build_apply_fns(pspec))
            with self.span(win_t):
                lay = fnA(batch, ranges)
                out = fnB(batch, *lay)
            yield out
            return
        if pspec is not None:
            key = ("window_packed", tuple(w.fingerprint() for w in exprs),
                   pspec.key)
            fn = fuse.fused(key, lambda: build_packed(pspec))
            with self.span(win_t):
                out = fn(batch, ranges)
            yield out
            return

        def build():
            def fn(batch):
                nr = traced_rows(batch.num_rows)
                ectx = EvalCtx(batch.columns, nr, batch.capacity, False)
                pkeys = [e.eval_tpu(ectx) for e in spec.partition_exprs]
                okeys = [o.expr.eval_tpu(ectx) for o in spec.order_specs]
                pnorm = [K.normalize_key(c, nr) for c in pkeys]
                onorm = [K.normalize_key(c, nr) for c in okeys]
                sort_keys = [(k, nl, True, True) for k, nl in pnorm]
                sort_keys += [(k, nl, o.ascending, o.resolved_nulls_first())
                              for (k, nl), o in zip(onorm, spec.order_specs)]
                if not sort_keys:
                    perm = jnp.arange(batch.capacity, dtype=jnp.int32)
                else:
                    perm = K.lexsort_indices(sort_keys, nr)
                sorted_batch = K.gather_batch(batch, perm, batch.num_rows)
                cap = batch.capacity
                first = jnp.zeros(cap, jnp.bool_).at[0].set(True)
                segb = first
                for k, nl in pnorm:
                    ks, ns = k[perm], nl[perm]
                    segb = segb | jnp.concatenate(
                        [jnp.zeros(1, jnp.bool_),
                         (ks[1:] != ks[:-1]) | (ns[1:] != ns[:-1])])
                peerb = segb
                for k, nl in onorm:
                    ks, ns = k[perm], nl[perm]
                    peerb = peerb | jnp.concatenate(
                        [jnp.zeros(1, jnp.bool_),
                         (ks[1:] != ks[:-1]) | (ns[1:] != ns[:-1])])
                seg_start, seg_end, peer_start, peer_end = \
                    W.segment_layout(segb, peerb)
                live = jnp.arange(cap) < nr
                seg_end = jnp.minimum(seg_end,
                                      jnp.maximum(nr - 1, 0).astype(seg_end.dtype))
                peer_end = jnp.minimum(peer_end, seg_end)
                seg_id = jnp.cumsum(segb.astype(jnp.int32))
                idx = jnp.arange(cap, dtype=jnp.int32)
                sctx = EvalCtx(sorted_batch.columns, nr, cap, False)
                out_cols = list(sorted_batch.columns)
                for w in exprs:
                    out_cols.append(_eval_window_fn(
                        w, sctx, seg_start, seg_end, peer_start, peer_end,
                        seg_id, segb, peerb, idx, live))
                return ColumnarBatch(out_cols, batch.num_rows)
            return fn

        key = ("window", tuple(w.fingerprint() for w in exprs))
        fn = fuse.fused(key, build)
        with self.span(win_t):
            out = fn(batch)
        yield out


# Module-level (state-free) window kernels: the fused builder closure is
# cached process-global by expr fingerprint, so it must capture only the
# bound window exprs/spec — never the exec node, whose child tree can pin
# HBM-resident cached batches for the process lifetime (same hazard the
# _AggKernels class exists to avoid).
def _scatter_window_output(col: ColumnVector, perm, cap, live_orig,
                           num_rows):
    """Sorted-space window result -> original row order (one inverse-perm
    gather instead of gathering every output column into sorted order).
    gather_column handles every plane layout (dict strings from lead/lag
    included); XLA CSEs the shared inverse permutation across outputs."""
    inv = jnp.zeros(cap, jnp.int32).at[perm].set(
        jnp.arange(cap, dtype=jnp.int32), mode="drop")
    out = K.gather_column(col, inv, num_rows)
    valid = out.validity & live_orig if out.validity is not None \
        else live_orig
    return ColumnVector(out.dtype, out.data, valid,
                        dict_unique=out.dict_unique)


def _eval_window_fn(w, sctx, seg_start, seg_end, peer_start,
                    peer_end, seg_id, segb, peerb, idx, live):
    from spark_rapids_tpu.ops import window as W
    from spark_rapids_tpu.expr import window as WE
    fn = w.fn
    frame = w.spec.resolved_frame()
    rt = fn.result_type()
    if isinstance(fn, WE.RowNumber):
        return ColumnVector(rt, W.row_number(seg_start), live)
    if isinstance(fn, WE.Rank):
        return ColumnVector(rt, W.rank(seg_start, peer_start), live)
    if isinstance(fn, WE.DenseRank):
        return ColumnVector(rt, W.dense_rank(segb, peerb, seg_start), live)
    if isinstance(fn, WE.NTile):
        return ColumnVector(rt, W.ntile(fn.n, seg_start, seg_end), live)
    if isinstance(fn, WE.LeadLag):
        src = fn.children[0].eval_tpu(sctx)
        off = fn.offset if fn.is_lead else -fn.offset
        svalid = src.validity if src.validity is not None else live
        vals, valid = W.lead_lag(src.data, svalid, seg_id, off)
        if fn.default is not None:
            in_seg = (idx + off >= seg_start) & (idx + off <= seg_end)
            dv = jnp.asarray(fn.default, src.data.dtype)
            vals = jnp.where(~in_seg, dv, vals)
            valid = valid | ~in_seg
        return ColumnVector(src.dtype, vals, valid & live)
    if isinstance(fn, WE.PercentRank):
        n_seg = (seg_end - seg_start + 1).astype(jnp.float64)
        rk = W.rank(seg_start, peer_start).astype(jnp.float64)
        v = jnp.where(n_seg > 1, (rk - 1.0) / jnp.maximum(n_seg - 1.0, 1.0),
                      0.0)
        return ColumnVector(rt, v, live)
    if isinstance(fn, WE.CumeDist):
        n_seg = (seg_end - seg_start + 1).astype(jnp.float64)
        v = (peer_end - seg_start + 1).astype(jnp.float64) / n_seg
        return ColumnVector(rt, v, live)
    if isinstance(fn, (WE.NthValue, WE.FirstValue, WE.LastValue)):
        src = fn.children[0].eval_tpu(sctx)
        svalid = src.validity if src.validity is not None else live
        if frame.lower is None and frame.upper is None:
            frame_end = seg_end
        elif frame.kind == "rows":
            frame_end = idx if frame.upper == 0 else seg_end
        else:
            frame_end = peer_end if frame.upper == 0 else seg_end
        if isinstance(fn, WE.LastValue):
            pos = frame_end
            ok = live
        elif isinstance(fn, WE.FirstValue):
            pos = seg_start
            ok = live
        else:
            pos = seg_start + (fn.n - 1)
            ok = live & (pos <= frame_end)
        from spark_rapids_tpu.ops import kernels as _K
        gathered = _K.gather_column(
            src, jnp.where(ok, jnp.clip(pos, 0, idx.shape[0] - 1), -1),
            idx.shape[0], src_live=svalid)
        return ColumnVector(gathered.dtype, gathered.data, gathered.validity,
                            dict_unique=gathered.dict_unique)
    if isinstance(fn, WE.WindowAgg):
        return _eval_window_agg(fn, frame, sctx, seg_start, seg_end,
                                peer_end, seg_id, idx, live)
    raise NotImplementedError(type(fn).__name__)


def _eval_window_agg(fn, frame, sctx, seg_start, seg_end,
                     peer_end, seg_id, idx, live):
    from spark_rapids_tpu.ops import window as W
    from spark_rapids_tpu.expr import aggregates as A
    agg = fn.fn
    rt = agg.result_type()
    if agg.children:
        src = agg.children[0].eval_tpu(sctx)
        vals = src.data
        svalid = (src.validity if src.validity is not None else live) & live
    else:  # count(*)
        vals = jnp.ones(idx.shape[0], jnp.int64)
        svalid = live
    # frame end per row
    if frame.kind == "range":
        frame_end = peer_end if frame.upper == 0 else seg_end
    else:
        frame_end = idx if frame.upper == 0 else seg_end
    unbounded = frame.lower is None and frame.upper is None
    bounded_rows = frame.kind == "rows" and not (
        frame.lower is None and frame.upper == 0) and not unbounded

    def sum_count():
        if bounded_rows:
            v = vals
            if isinstance(agg, A.Average):
                v = v.astype(jnp.float64)
            elif not jnp.issubdtype(v.dtype, jnp.floating):
                v = v.astype(jnp.int64)
            return W.bounded_sum_count(v, svalid, seg_start, seg_end,
                                       frame.lower, frame.upper)
        fe = seg_end if unbounded else frame_end
        v = vals
        if isinstance(agg, (A.Sum, A.Average)) and \
                not jnp.issubdtype(v.dtype, jnp.floating):
            v = v.astype(jnp.int64)
        if isinstance(agg, A.Average):
            v = v.astype(jnp.float64)
        return W.running_sum_count(v, svalid, seg_start, fe)

    if isinstance(agg, A.Average):
        s, c = sum_count()
        return ColumnVector(rt, s / jnp.maximum(c, 1), (c > 0) & live)
    if isinstance(agg, A.Sum):
        s, c = sum_count()
        return ColumnVector(rt, s.astype(rt.np_dtype), (c > 0) & live)
    if isinstance(agg, (A.Count, A.CountAll)):
        s, c = sum_count()
        cnt = c if isinstance(agg, A.Count) else None
        if isinstance(agg, A.CountAll):
            # count(*) counts rows regardless of validity
            if bounded_rows:
                ones = jnp.ones(idx.shape[0], jnp.int64)
                s2, _ = W.bounded_sum_count(ones, live, seg_start, seg_end,
                                            frame.lower, frame.upper)
                cnt = s2
            else:
                fe = seg_end if unbounded else frame_end
                s2, _ = W.running_sum_count(
                    jnp.ones(idx.shape[0], jnp.int64), live, seg_start, fe)
                cnt = s2
        return ColumnVector(T.INT64, cnt.astype(jnp.int64),
                            jnp.ones_like(live) & live)
    if isinstance(agg, (A.Min, A.Max)):
        op = "min" if isinstance(agg, A.Min) else "max"
        fe = seg_end if unbounded else frame_end
        v, c = W.running_minmax(op, vals, svalid, seg_id, seg_start, fe)
        return ColumnVector(rt, v.astype(rt.np_dtype), (c > 0) & live)
    raise NotImplementedError(type(agg).__name__)


class HashAggregateExec(TpuExec):
    """Sort-based segmented aggregation in three phases (reference
    GpuAggregateExec.scala three-pass design §2.4):
    - partial: per input batch, evaluate keys + agg inputs as one fused
      stage, group, apply update reductions -> (keys, state) batches
    - within-partition merge: concat partials, re-group, merge reductions
    - final: merge again post-exchange and run each agg's evaluate
    State layout: [key_0..key_k, agg0_state0.., agg1_state0..].
    """

    def __init__(self, plan, children, conf, mode: str, pre_filter=None):
        super().__init__(plan, children, conf)
        assert mode in ("partial", "final", "complete")
        self.mode = mode
        self.kern = _AggKernels(plan.group_exprs, plan.group_names,
                                plan.aggs, pre_filter)
        # A filter condition absorbed into the update kernel (predicate
        # fusion): scan -> filter -> partial agg runs as ONE dispatch.
        self.pre_filter = pre_filter
        #: whole-stage vertical fusion (exec/stage_fusion.py): traced
        #: bodies of a narrow-operator chain composed BEFORE the update
        #: phase inside one jit — scan -> filter -> project -> partial agg
        #: is then exactly one dispatch per input batch. Set by the
        #: planner pass; only carry-free bodies are absorbed (retry may
        #: re-run the composed trace on a split batch).
        self.pre_chain: Optional[List[fuse.StageBody]] = None
        self.pre_chain_members: List[TpuExec] = []
        self.fused_stage_id = 0
        self._chain_failed = False

    # ---- schema of the partial (state) batches ----
    def state_fields(self):
        fields = [T.StructField(n, e.data_type())
                  for n, e in zip(self.plan.group_names, self.plan.group_exprs)]
        for a in self.plan.aggs:
            for sname, sdt in a.fn.state_schema():
                fields.append(T.StructField(f"{a.name}__{sname}", sdt))
        return fields

    @property
    def schema(self):
        if self.mode == "partial":
            return T.Schema(tuple(self.state_fields()))
        return self.plan.schema

    def _sig(self, phase: str, ansi: bool = False):
        p = self.plan
        gfp = tuple(e.fingerprint() for e in p.group_exprs)
        afp = tuple(a.fn.fingerprint() for a in p.aggs)
        pf = self.pre_filter.fingerprint() if self.pre_filter is not None else None
        return ("hashagg", phase, gfp, afp, ansi, pf)

    # -- whole-stage fusion (absorbed narrow-operator chain) ---------------

    def _chain_key(self, ansi: bool):
        return ("hashagg_chain_update",
                tuple(b.key for b in self.pre_chain),
                self._sig("update", ansi))

    def _build_chain_update(self, ansi: bool):
        bodies = list(self.pre_chain)
        kern = self.kern

        def build():
            fns = [b.builder() for b in bodies]
            upd = kern._build_update(ansi)
            zero = jnp.int64(0)

            def fn(batch, pid):
                errs_all, rows = [], []
                for f in fns:
                    batch, errs, _ = f(batch, pid, zero)
                    errs_all.append(errs)
                    rows.append(jnp.sum(
                        batch.live_mask().astype(jnp.int64)))
                out, uerrs = upd(batch)
                errs_all.append(uerrs)
                return out, tuple(errs_all), tuple(rows)
            return fn
        return build

    def _unfused_pre_chain(self, source):
        from spark_rapids_tpu.exec.stage_fusion import rebuild_chain
        return rebuild_chain(self.pre_chain_members, source)

    def tree_string(self, indent: int = 0) -> str:
        if not self.pre_chain_members:
            return super().tree_string(indent)
        pad = "  " * indent
        sid = self.fused_stage_id
        lines = [f"{pad}*({sid}) {self.name()} <- {self.plan.describe()}"]
        for m in reversed(self.pre_chain_members):
            lines.append(f"{pad}  *({sid}) {type(m).__name__} "
                         f"<- {m.plan.describe()} [fused]")
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def execute_partition(self, ctx, pidx):
        agg_t = self.metrics.metric(M.AGG_TIME)
        child_batches = self.children[0].execute_partition(ctx, pidx)
        nkeys = len(self.plan.group_exprs)

        if self.mode in ("partial", "complete"):
            ansi = self.conf.get(C.ANSI_ENABLED)
            from spark_rapids_tpu.runtime.retry import with_retry

            def plain_attempt(b):
                # raise_errors inside the attempt so ANSI-mode syncs
                # (and any device OOM they surface) are seen by the
                # retry loop. Note: under async dispatch a physical
                # RESOURCE_EXHAUSTED can still surface at a LATER sync
                # point; the cooperative budget (SpillFramework.
                # reserve) is the primary defense, this translation is
                # best-effort.
                out, errs = self.kern.update(b, ansi)
                compiled.raise_errors(errs)
                return out

            attempt = plain_attempt
            chain_live = False
            chain_in_rows = [None]  # update-phase input rows (device)
            in_batches = self.metrics.metric(M.NUM_INPUT_BATCHES)
            if self.pre_chain and self._chain_failed:
                # an earlier partition's composed trace failed: run the
                # unfused member chain in front of the plain update
                child_batches = self._unfused_pre_chain(
                    self.children[0]).execute_partition(ctx, pidx)
            elif self.pre_chain:
                chain_fn = fuse.fused(self._chain_key(ansi),
                                      self._build_chain_update(ansi))
                pid = jnp.int32(pidx)
                disp = self.metrics.metric(M.STAGE_DISPATCHES)
                member_rows = [m.metrics.metric(M.NUM_OUTPUT_ROWS)
                               for m in self.pre_chain_members]

                def chain_attempt(b):
                    # the absorbed chain + update phase is ONE composed
                    # trace, idempotent over its input (chain bodies are
                    # carry-free by the absorb gate), so retry/split-retry
                    # treat it exactly like a plain update
                    disp.add(1)
                    if TR.active() is not None:  # args gated when off
                        TR.instant("stageDispatch", cat="dispatch", args={
                            "stage_id": self.fused_stage_id,
                            "absorbed": True,
                            # chain members + the update phase, composed
                            # into this ONE dispatch (the report's
                            # fusion-wins denominator)
                            "members": len(self.pre_chain_members) + 1})
                    out, errs_list, rows = chain_fn(b, pid)
                    for e in errs_list:
                        compiled.raise_errors(e)
                    for mr, r in zip(member_rows, rows):
                        mr.add(LazyRowCount(r))
                    if rows:  # what the update phase actually saw
                        chain_in_rows[0] = rows[-1]
                    return out

                attempt = chain_attempt
                chain_live = True

            if (self.conf.get(C.AGG_FORCE_SINGLE_PASS) and nkeys > 0) \
                    or self.kern.has_custom:
                # One update pass over the concatenated input: the testing
                # knob (reference forceSinglePassPartialSortAgg), and the
                # REQUIRED path for custom segmented aggs (collect_*,
                # min_by/max_by, percentile) whose results cannot merge —
                # the planner already exchanged raw rows by key for them.
                batches = list(child_batches)
                child_batches = iter(
                    [K.concat_batches(batches)] if len(batches) > 1 else batches)

            skip_ratio = self.conf.get(C.SKIP_AGG_PASS_RATIO)
            skip_merge = False
            partials = []
            it = iter(child_batches)
            bi = -1
            while True:
                batch = next(it, None)
                if batch is None:
                    break
                bi += 1
                self._acquire(ctx)
                in_batches.add(1)
                n_before = len(partials)
                try:
                    with self.span(agg_t):
                        # update is idempotent over its input batch:
                        # retried after a spill drain, or split in half,
                        # on OOM
                        for out in with_retry(attempt, batch):
                            if nkeys == 0:
                                out = ColumnarBatch(out.columns, 1)
                            partials.append(out)
                except Exception as ex:
                    from spark_rapids_tpu.expr.core import SparkException
                    if not chain_live or isinstance(ex, SparkException):
                        # ANSI/analysis errors are deterministic runtime
                        # errors, never trace failures — replaying them
                        # through the unfused chain would double the work
                        # just to raise the same error
                        raise
                    # per-stage fallback (the stageFusion contract): the
                    # composed chain+update trace failed — drop this
                    # batch's partials (update is idempotent), route the
                    # batch and the rest of the input through the unfused
                    # member chain, and continue with the plain update
                    import logging
                    logging.getLogger("spark_rapids_tpu").warning(
                        "absorbed-chain trace failed for %s; falling back"
                        " to the unfused chain", self.name(),
                        exc_info=True)
                    del partials[n_before:]
                    self._chain_failed = True
                    chain_live = False
                    attempt = plain_attempt
                    from spark_rapids_tpu.exec.stage_fusion import (
                        _ReplaySourceExec,
                    )
                    src = _ReplaySourceExec(self.children[0].schema,
                                            [batch], it)
                    it = self._unfused_pre_chain(src).execute_partition(
                        ctx, pidx)
                    bi -= 1
                    continue
                if bi == 0 and skip_ratio < 1.0 and nkeys > 0 \
                        and self.mode == "partial":
                    # Reference skipAggPassReductionRatio: when the first
                    # batch's update barely reduced rows (groups/rows above
                    # the ratio), skip the within-partition merge pass and
                    # defer cross-batch merging to the post-exchange final
                    # agg. Sampled on the first batch only — row counts
                    # live on device and each fetch is a host sync. With an
                    # absorbed chain, the ratio is against the CHAIN's
                    # output (the rows the update phase actually saw), not
                    # the raw scan batch.
                    src_rows = (chain_in_rows[0]
                                if chain_live and chain_in_rows[0] is not None
                                else batch.num_rows)
                    in_rows = max(int(src_rows), 1)
                    skip_merge = int(partials[0].num_rows) > skip_ratio * in_rows
            if not partials:
                if nkeys == 0:
                    partials = [self._empty_state_batch()]
                else:
                    if self.mode == "complete":
                        return
                    return
        else:  # final: inputs are state batches
            skip_merge = False
            partials = list(child_batches)
            if not partials:
                if nkeys == 0:
                    partials = [self._empty_state_batch()]
                else:
                    return
        if partials:
            # rollup export (EXPLAIN ANALYZE / history / live registry):
            # lazy row counts — no sync unless something reads them
            out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
            out_batches = self.metrics.metric(M.NUM_OUTPUT_BATCHES)
            if skip_merge and len(partials) > 1:
                for p in partials:
                    p = K.compact_batch(p)
                    out_rows.add(p.num_rows)
                    out_batches.add(1)
                    yield p
                return
            self._acquire(ctx)
            with self.span(agg_t):
                merged = self._merge(partials)
                # no compact at yield: exchanges, downstream aggs, and the
                # collect boundary consume masked batches natively
                # (zero-copy mask slices; session compacts on device right
                # before download), and every compact costs a ~90ms count
                # sync on the tunneled device
                if self.mode != "partial":
                    merged = self._evaluate(merged)
            out_rows.add(merged.num_rows)
            out_batches.add(1)
            yield merged

    # -- phase helpers -----------------------------------------------------

    def _merge(self, partials: List[ColumnarBatch]) -> ColumnarBatch:
        if len(partials) == 1 and not getattr(partials[0], "coalesced",
                                              False):
            # A single partial already has unique keys — merging is
            # identity. NOT true of an exchange-coalesced batch: that is
            # a concat of several partials (duplicate keys across the
            # seams), exactly what the merge kernel below exists to fold.
            return partials[0]
        batch = K.concat_batches(partials)
        nkeys = len(self.plan.group_exprs)
        if nkeys == 0 and batch.num_rows <= 1:
            return batch
        out = self.kern.merge(batch)
        if nkeys == 0:
            out = ColumnarBatch(out.columns, 1)
        return out

    def _evaluate(self, state: ColumnarBatch) -> ColumnarBatch:
        nkeys = len(self.plan.group_exprs)
        fn = fuse.fused(self._sig("evaluate"), lambda: self.kern._evaluate_states)
        out = fn(state)
        n = state.num_rows if nkeys else 1
        return ColumnarBatch(out.columns, n, out.row_mask)

    def _empty_state_batch(self) -> ColumnarBatch:
        fields = self.state_fields()
        cols = []
        # zero-row update produces: count states = 0 (valid), collect
        # results = [] (valid), others null
        for f in fields:
            cap = round_capacity(1)
            if isinstance(f.dtype, T.ArrayType):
                if isinstance(f.dtype.element, T.StringType):
                    child = ColumnVector(
                        f.dtype.element,
                        {"offsets": jnp.zeros(9, jnp.int32),
                         "bytes": jnp.zeros(8, jnp.uint8)},
                        jnp.zeros(8, jnp.bool_))
                else:
                    child = ColumnVector(f.dtype.element,
                                         jnp.zeros(8, f.dtype.element.np_dtype),
                                         jnp.zeros(8, jnp.bool_))
                cols.append(ColumnVector(
                    f.dtype, {"offsets": jnp.zeros(cap + 1, jnp.int32),
                              "child": child},
                    jnp.arange(cap) < 1))
                continue
            if isinstance(f.dtype, T.StringType):
                cols.append(ColumnVector(
                    f.dtype, {"offsets": jnp.zeros(cap + 1, jnp.int32),
                              "bytes": jnp.zeros(8, jnp.uint8)},
                    jnp.zeros(cap, jnp.bool_)))
                continue
            is_count = f.name.endswith("__count")
            data = jnp.zeros(cap, f.dtype.np_dtype)
            valid = (jnp.arange(cap) < 1) if is_count else jnp.zeros(cap, jnp.bool_)
            cols.append(ColumnVector(f.dtype, data, valid))
        return ColumnarBatch(cols, 1)


def _resize_col(c: ColumnVector, cap: int) -> ColumnVector:
    if c.capacity == cap:
        return c
    idx = jnp.arange(cap, dtype=jnp.int32)
    idx = jnp.where(idx < c.capacity, idx, -1)
    return K.gather_column(c, idx, c.capacity)


def _resize_plane(vals, valid, dtype, cap: int) -> ColumnVector:
    n = vals.shape[0]
    if n == cap:
        pass
    elif n > cap:
        vals, valid = vals[:cap], valid[:cap]
    else:
        vals = jnp.concatenate([vals, jnp.zeros(cap - n, vals.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros(cap - n, jnp.bool_)])
    if vals.dtype != np.dtype(dtype.np_dtype):
        vals = vals.astype(dtype.np_dtype)
    return ColumnVector(dtype, vals, valid)


# ---------------------------------------------------------------------------
# Exchanges (stage barriers)
# ---------------------------------------------------------------------------

def _partitioning_mode(conf) -> str:
    """spark.rapids.shuffle.partitioning: 'compact' (counting-sort, the
    default) or 'masked' (legacy mask-sliced sub-batches)."""
    v = str(conf.get(C.SHUFFLE_PARTITIONING)).strip().lower()
    if v not in ("compact", "masked"):
        raise ValueError(
            "spark.rapids.shuffle.partitioning must be 'compact' or "
            f"'masked', got {v!r}")
    return v


class ExchangeExec(TpuExec):
    """Base: materialize child partitions as concurrent tasks, re-partition,
    serve. Plays the role of Spark shuffle for the reference
    (RapidsShuffleInternalManagerBase MULTITHREADED mode runs parallel
    serialization through thread pools; here batches stay on device --
    the CACHE_ONLY/UCX 'stay on device' design, SURVEY §2.7).

    Two device partitioning strategies share the emit helpers below
    (spark.rapids.shuffle.partitioning): 'compact' counting-sorts each
    input batch by target partition in ONE fused dispatch and fetches the
    offsets vector ONCE, yielding contiguous right-sized sub-batches;
    'masked' emits n_out full-capacity selection-mask slices whose row
    counts each sync lazily. The partitionDispatches / partitionHostFetches
    metrics record exactly that asymmetry — partitioning-KERNEL launches
    and sizing round trips, not the compact path's per-slice assembly
    gathers (those are O(output rows)) — so tests can assert the O(1)
    contract instead of eyeballing profiles."""

    def __init__(self, plan, children, conf):
        super().__init__(plan, children, conf)
        self._lock = threading.Lock()
        self._out: Optional[List[List[ColumnarBatch]]] = None
        #: streaming tap: when set, every emitted (partition, sub_batch)
        #: is ALSO handed to this callable as it is produced — the
        #: serialized writer hooks it so serde/spill of batch i overlaps
        #: the device partitioning of batch i+1
        self._emit_sink = None
        #: measured cost pass override of coalesceTinyRows, snapshotted
        #: at convert time (the thread-local hints are gone by execute):
        #: history said this plan is dispatch-bound, so coalesce harder
        from spark_rapids_tpu.plan import cost as COST
        h = COST.current_hints()
        self._tiny_override: Optional[int] = (
            h.coalesce_tiny_rows if h is not None else None)

    @property
    def schema(self):
        return self.children[0].schema

    #: a streaming-capable _repartition consumes each child partition in
    #: ONE forward pass, so _materialize may hand it live iterators
    #: instead of materialized lists (ShuffleExchangeExec narrows this
    #: for the ICI mode, whose eligibility probe iterates twice)
    _streaming_ok = True

    def _materialize(self) -> List[List[ColumnarBatch]]:
        with self._lock:
            if self._out is None:
                child = self.children[0]
                streams = self._streamed_children(child)
                if streams is not None:
                    try:
                        self._out = self._repartition(streams)
                    finally:
                        for s in streams:
                            s.close()
                        self._finish_stream_tasks()
                    return self._out
                results: List[List[ColumnarBatch]] = [None] * child.num_partitions

                def run(p):
                    with TaskContext(partition_id=p) as tctx:
                        return list(child.execute_partition(tctx, p))

                if child.num_partitions == 1:
                    results[0] = run(0)
                else:
                    # child partitions run as tasks on the process-wide
                    # host pool (one bounded pool instead of a throwaway
                    # executor per exchange; nested exchanges degrade to
                    # inline execution rather than deadlocking); the
                    # writer-threads conf still caps THIS exchange's
                    # concurrent materializations (HBM admission)
                    from spark_rapids_tpu.runtime.host_pool import (
                        get_host_pool,
                    )
                    pool = get_host_pool(self.conf)
                    nthreads = self.conf.get(C.SHUFFLE_WRITER_THREADS)
                    for p, res in enumerate(
                            pool.map_ordered(run,
                                             range(child.num_partitions),
                                             max_concurrency=nthreads)):
                        results[p] = res
                self._out = self._repartition(results)
        return self._out

    def _streamed_children(self, child):
        """The compute->exchange-write pipeline boundary: each child
        partition becomes a bounded PipelinedIterator whose producer runs
        the partition's generator (decode, compute, upload) on the host
        pool WHILE this thread's repartition loop consumes earlier
        batches — the partitioning kernel, its offsets fetch, and the
        serialized writer's throttled serde all overlap upstream compute
        instead of waiting for full materialization. Child partitions
        still produce concurrently (every producer is armed up front,
        each with `depth` lookahead — a tighter memory bound than the
        historical materialize-everything). Returns None when streaming
        must not engage: pipelining off, a two-pass _repartition (ICI),
        a nested (pool-worker) caller, or more child partitions than
        device-semaphore permits. The permit gate is a deadlock fence: a
        producer past the permit count would park its pool worker in the
        semaphore wait queue, and enough parked producers would starve
        the pool of the workers the permit HOLDERS need to finish and
        release — the materialize-worker path (below) gives each
        partition a dedicated worker for its whole life, so it has no
        such cycle and keeps the wide-partition case."""
        from spark_rapids_tpu.runtime.host_pool import (
            HostTaskPool, get_host_pool,
        )
        from spark_rapids_tpu.runtime.pipeline import (
            PipelinedIterator, pipeline_conf,
        )
        depth = pipeline_conf(self.conf)
        nparts = self.children[0].num_partitions
        if depth <= 0 or not self._streaming_ok \
                or HostTaskPool._depth() != 0 \
                or nparts > self.conf.get(C.CONCURRENT_TPU_TASKS) \
                or nparts >= get_host_pool(self.conf).n_threads:
            return None

        def gen(p, tctx, fin):
            # the producer thread owns the task end-of-life exactly like
            # the materialize worker did (semaphore release, accumulator
            # rollup); `fin` makes completion exactly-once across this
            # finally and the close-path sweep in _finish_stream_tasks
            status = "failed"
            try:
                for b in self.children[0].execute_partition(tctx, p):
                    yield b
                status = "ok"
            except GeneratorExit:
                # early close (sibling partition failed, consumer bailed)
                # cancels this task — it did not itself fail
                status = "cancelled"
                raise
            except LC.QueryCancelledError:
                # the query's cancel token fired at a checkpoint inside
                # this producer: same rollup as the close path, and the
                # error still travels to the consumer
                status = "cancelled"
                raise
            finally:
                if not fin[0]:
                    fin[0] = True
                    tctx.complete(failed=(status == "failed"),
                                  cancelled=(status == "cancelled"))

        streams = []
        finals = []
        try:
            for p in range(self.children[0].num_partitions):
                tctx = TaskContext(partition_id=p)
                fin = [False]
                finals.append((tctx, fin))
                streams.append(PipelinedIterator(
                    gen(p, tctx, fin), depth, ctx=tctx, conf=self.conf,
                    label=f"{self.name()}@p{p}",
                    stall_metric=self.metrics.metric(M.PIPELINE_STALL_TIME),
                    producer_metric=self.metrics.metric(
                        M.PIPELINE_PRODUCER_TIME)))
        except Exception:  # noqa: BLE001 - setup fallback: synchronous
            for s in streams:
                s.close()
            self._stream_finals = finals
            self._finish_stream_tasks()
            return None
        self._stream_finals = finals
        self.metrics.metric(M.PIPELINE_DEPTH).set(depth)
        return streams

    def _finish_stream_tasks(self) -> None:
        """Complete any streamed-child task whose generator never ran
        (close() on a not-yet-started generator skips its finally): the
        task did no work and did not fail, but its (empty) rollup and
        completion callbacks must still fire exactly once."""
        for tctx, fin in getattr(self, "_stream_finals", ()):
            if not fin[0]:
                fin[0] = True
                tctx.complete(failed=False)
        self._stream_finals = []

    def _repartition(self, child_results) -> List[List[ColumnarBatch]]:
        raise NotImplementedError

    def _partition_metrics(self):
        return (self.metrics.metric(M.PARTITION_DISPATCHES),
                self.metrics.metric(M.PARTITION_HOST_FETCHES),
                self.metrics.metric(M.NUM_OUTPUT_ROWS))

    def _repartition_passthrough(self, child_results):
        """n_out == 1: every row lands in the single output partition —
        emit the batches unchanged. No partition kernel, no data
        movement, no sizing fetch (either strategy would only have
        reshuffled rows onto themselves)."""
        rows_m = self.metrics.metric(M.NUM_OUTPUT_ROWS)
        flat = []
        for part in child_results:
            for b in part:
                rows_m.add(b.num_rows)
                flat.append(b)
                if self._emit_sink is not None:
                    self._emit_sink(0, b)
        return [flat]

    def _emit_compact(self, batch, fused_out, out) -> None:
        """Compact-path emission: `fused_out` is (sorted_batch, offsets)
        from ONE counting-sort dispatch; the single offsets fetch here is
        the entire host synchronization for partitioning this batch.
        Column bounds re-attach host-side (they are not pytree leaves and
        stay valid under any row subset); empty partitions emit nothing."""
        disp, fetch, rows_m = self._partition_metrics()
        sorted_b, off_dev = fused_out
        disp.add(1)
        LC.check_current()  # per-batch exchange checkpoint: the offsets
        FLT.site("exchange.fetch")  # sync is where a shuffle blocks
        offsets = np.asarray(jax.device_get(off_dev))
        fetch.add(1)
        for p, sub in enumerate(
                RP.compact_slices(sorted_b, offsets, self.n_out)):
            if sub is None:
                continue
            for ic, oc in zip(batch.columns, sub.columns):
                oc.bounds = ic.bounds
            rows_m.add(int(sub.num_rows))
            out[p].append(sub)
            if self._emit_sink is not None:
                self._emit_sink(p, sub)

    def _emit_masked(self, batch, subs, out) -> None:
        """Masked-path emission with the bookkeeping the compact path gets
        for free: each input batch costs n_out full-capacity sub-batch
        computations and n_out deferred count syncs (the LazyRowCounts
        materialize one by one downstream)."""
        disp, fetch, rows_m = self._partition_metrics()
        disp.add(self.n_out)
        fetch.add(self.n_out)
        for p, sub in enumerate(subs):
            for ic, oc in zip(batch.columns, sub.columns):
                oc.bounds = ic.bounds
            rows_m.add(sub.num_rows)
            out[p].append(sub)
            if self._emit_sink is not None:
                self._emit_sink(p, sub)

    def _compact_stream(self, batches, dispatch, out, part_t) -> None:
        """Drive a compact partitioning loop with a one-deep deferred
        offsets fetch (pipeline-gated): dispatch batch i+1's counting
        sort and START its offsets D2H before consuming batch i's
        offsets, so the transfer rides under device compute instead of
        serializing against it. Emission order (and therefore every
        downstream result) is unchanged; with pipelining disabled this
        is exactly the historical dispatch-then-fetch loop."""
        from spark_rapids_tpu.runtime.pipeline import pipeline_conf, start_d2h
        if pipeline_conf(self.conf) <= 0:
            for batch in batches:
                with self.span(part_t):
                    self._emit_compact(batch, dispatch(batch), out)
            return
        pending = None
        for batch in batches:
            with self.span(part_t):
                fo = dispatch(batch)
            start_d2h(fo[1])
            if pending is not None:
                with self.span(part_t):
                    self._emit_compact(pending[0], pending[1], out)
            pending = (batch, fo)
        if pending is not None:
            with self.span(part_t):
                self._emit_compact(pending[0], pending[1], out)

    def execute_partition(self, ctx, pidx):
        out = self._materialize()
        # coalesce first, then split: the two repair opposite tails (dust
        # -> fewer dispatches, giants -> bounded dispatches) and a split
        # slice must never be re-merged back into the giant it came from
        yield from self._split_skewed(self._coalesce_tiny(out[pidx]), pidx)

    def _item_rows(self, item, pidx) -> Optional[int]:
        """Free (host-int) row count of one materialized item, or None
        when counting would sync — the skew detector's unit of account."""
        if isinstance(item, ColumnarBatch) and item.row_mask is None \
                and isinstance(item.num_rows, int):
            return item.num_rows
        return None

    def _skew_plan(self):
        """(threshold_rows, target_rows, totals) once per exchange, or
        None when no partition qualifies for splitting. Computed from
        the already-materialized output's host-int counts only — the
        decision never syncs (partitions with any lazy count are
        excluded and never split)."""
        with self._lock:
            sp = getattr(self, "_skew_decision", None)
            if sp is None:
                from spark_rapids_tpu.exec import adaptive as AQ
                totals: List[Optional[int]] = []
                for p, part in enumerate(self._out or []):
                    n: Optional[int] = 0
                    for item in part:
                        r = self._item_rows(item, p)
                        if r is None:
                            n = None
                            break
                        n += r
                    totals.append(n)
                t = AQ.skew_threshold(self.conf, totals)
                sp = self._skew_decision = (
                    False if t is None else (t[0], t[1], totals))
        return sp or None

    def _split_skewed(self, batches, pidx):
        """Skewed-partition split (spark.rapids.sql.adaptive.skewFactor;
        reference GpuSkewJoin / skewedPartitionFactor): a partition whose
        row total exceeds factor x median splits its oversized batches
        into ~median-row contiguous slices (bounded fan-out), so one hot
        key range stops serializing the whole downstream stage behind a
        single giant dispatch. In-order slices — every downstream result
        is byte-identical, sub-batches just rejoin under the existing
        batch semantics."""
        if getattr(self, "n_out", 1) <= 1:
            return batches
        from spark_rapids_tpu.exec import adaptive as AQ
        if not AQ.enabled(self.conf) \
                or float(self.conf.get(C.ADAPTIVE_SKEW_FACTOR)) <= 0:
            return batches
        sp = self._skew_plan()
        if sp is None:
            return batches
        threshold, target, totals = sp
        total = totals[pidx] if pidx < len(totals) else None
        if total is None or total <= threshold:
            return batches
        return self._split_stream(batches, pidx, total, threshold, target)

    def _split_stream(self, batches, pidx, total, threshold, target):
        from spark_rapids_tpu.exec import adaptive as AQ
        nsplits = 0
        for b in batches:
            n = self._item_rows(b, pidx)
            if n is None or n <= 2 * target:
                yield b
                continue
            # bounded fan-out: at most 8 sub-dispatches per batch, each
            # a contiguous in-order slice sharing the compact exchange's
            # capacity buckets (ops/repartition.py slice_rows)
            step = max(target, -(-n // 8))
            start = 0
            while start < n:
                ln = min(step, n - start)
                sub = RP.slice_rows(b, start, ln)
                for ic, oc in zip(b.columns, sub.columns):
                    oc.bounds = ic.bounds
                sub.coalesced = getattr(b, "coalesced", False)
                nsplits += 1
                yield sub
                start += ln
        if nsplits:
            AQ.record(AQ.SKEW_SPLIT, partition=pidx, rows=int(total),
                      median=int(target), threshold_rows=int(threshold),
                      splits=nsplits)

    def _coalesce_tiny(self, batches):
        """Post-shuffle tiny-partition coalescing (spark.rapids.shuffle.
        coalesceTinyRows): ragged post-shuffle slice sizes make nearly
        every sub-batch shape a fresh downstream trace AND a separate
        dispatch — the q72shfl shape zoo. Adjacent device sub-batches
        under the tiny threshold merge (bounded at 4x the threshold)
        before downstream dispatch. The decision is free: compact slices
        carry plain host-int row counts from the already-fetched offsets
        vector, so nothing here ever syncs a lazy count (batches whose
        count is still on device pass through untouched, as do masked
        batches and lazily-deserialized shuffle blobs). Merges count
        into shuffleCoalescedBatches — visible in EXPLAIN ANALYZE."""
        override = getattr(self, "_tiny_override", None)
        tiny = int(override) if override is not None \
            else int(self.conf.get(C.SHUFFLE_COALESCE_TINY_ROWS))
        if tiny <= 0 or getattr(self, "n_out", 1) <= 1:
            yield from batches
            return
        budget = tiny * 4
        run: List[ColumnarBatch] = []
        run_rows = 0
        for b in batches:
            small = (isinstance(b, ColumnarBatch)
                     and b.row_mask is None
                     and isinstance(b.num_rows, int)
                     and 0 < b.num_rows < tiny)
            if small and run_rows + b.num_rows <= budget:
                run.append(b)
                run_rows += b.num_rows
                continue
            yield from self._flush_coalesce_run(run)
            if small:
                run, run_rows = [b], b.num_rows
            else:
                run, run_rows = [], 0
                yield b
        yield from self._flush_coalesce_run(run)

    def _flush_coalesce_run(self, run):
        if not run:
            return
        if len(run) == 1:
            yield run[0]
            return
        merged = K.concat_batches(run)
        # a coalesced batch is a CONCAT of exchange sub-batches: any
        # per-batch invariant the sources carried individually (a final
        # agg's "one partial has unique keys") no longer holds — the
        # flag tells _merge to run its merge kernel even for a single
        # input batch
        merged.coalesced = True
        self.metrics.metric(M.SHUFFLE_COALESCED_BATCHES).add(len(run))
        TR.instant("shuffleCoalesce", cat="exchange",
                   args={"merged": len(run),
                         "rows": int(merged.num_rows)}, level=TR.DEBUG)
        yield merged


class CollectExchangeExec(ExchangeExec):
    """N -> 1 concat exchange (single partitioning analog)."""

    @property
    def num_partitions(self):
        return 1

    def _repartition(self, child_results):
        flat = [b for part in child_results for b in part]
        return [flat]


class ShuffleExchangeExec(ExchangeExec):
    """Hash-partitioned exchange. Two modes (spark.rapids.shuffle.mode):

    MULTITHREADED (default, any device count): murmur3(keys) pmod n on
    device, then zero-copy mask slicing into per-target sub-batches
    (reference GpuShuffleExchangeExecBase + GpuHashPartitioningBase).

    ICI (requires >= n_out jax devices): one partition shard per device;
    the ENTIRE exchange is a single shard_map-ped XLA program whose
    lax.all_to_all moves rows over the interconnect — the engine-level
    realization of the reference's UCX transport replacement (SURVEY.md
    §2.7 "TPU-native equivalent"). Falls back to MULTITHREADED when the
    device count or column layout doesn't fit (flat strings / differing
    vocabs can't ride a fixed-width collective)."""

    def __init__(self, plan, children, conf, keys: List[Expression], n_out: int):
        super().__init__(plan, children, conf)
        self.keys = keys
        self.n_out = n_out

    @property
    def num_partitions(self):
        return self.n_out

    @property
    def _ici_first(self):
        # the in-program all_to_all is the shuffle whenever the session
        # runs sharded (multichip) or asks for it outright (SHUFFLE_MODE)
        return (self.conf.get(C.SHUFFLE_MODE).upper() == "ICI"
                or bool(self.conf.get(C.MULTICHIP_ENABLED)))

    @property
    def _streaming_ok(self):
        # the ICI eligibility probe and vocab alignment iterate the child
        # results twice — a live stream cannot be replayed
        return not self._ici_first

    def _item_rows(self, item, pidx):
        if isinstance(item, _LazyShuffleBlobs):
            # serialized partitions are sized by the writer-side tally —
            # decoding blobs just to count them would defeat the free-
            # decision contract
            store = getattr(self, "_store", None)
            n = store.partition_rows(pidx) if store is not None else 0
            return n if n > 0 else None
        return super()._item_rows(item, pidx)

    def _repartition(self, child_results):
        mode = self.conf.get(C.SHUFFLE_MODE).upper()
        if self._ici_first:
            with self.span(self.metrics.metric(M.PARTITION_TIME)):
                out = self._repartition_ici(child_results)
            if out is not None:
                return out
        if mode == "SERIALIZED":
            return self._repartition_serialized(child_results)
        return self._repartition_device(child_results)

    def _repartition_device(self, child_results):
        """In-memory device partitioning (the MULTITHREADED mode body and
        the SERIALIZED mode's device half)."""
        if self.n_out == 1:
            return self._repartition_passthrough(child_results)
        if _partitioning_mode(self.conf) == "masked":
            return self._repartition_masked(child_results)
        return self._repartition_compact(child_results)

    def _repartition_compact(self, child_results):
        """Counting-sort exchange: one fused XLA computation per input
        batch hashes the keys, pmods to partition ids, stable-sorts rows
        by pid and emits the permuted planes plus the n_out+1 offsets
        vector (ops/repartition.py). ONE host fetch of the offsets then
        yields contiguous sub-batches sized by actual row counts — the
        cudf hashPartitionAndClose contract, not an n_out-mask fanout."""
        part_t = self.metrics.metric(M.PARTITION_TIME)
        keys, n_out = self.keys, self.n_out

        def build():
            def fn(batch):
                live = batch.live_mask()
                ectx = EvalCtx(batch.columns, traced_rows(batch.num_rows),
                               batch.capacity, False, live=live)
                key_cols = [e.eval_tpu(ectx) for e in keys]
                h = K.partition_hash_batch(key_cols, batch.num_rows,
                                           live=live)
                pid = _pmod(h, n_out)
                return RP.counting_sort_by_pid(batch, pid, n_out)
            return fn

        fn = fuse.fused(("hash_exchange_compact",
                         tuple(e.fingerprint() for e in keys), n_out), build)
        out: List[List[ColumnarBatch]] = [[] for _ in range(n_out)]
        self._compact_stream((b for part in child_results for b in part),
                             fn, out, part_t)
        return out

    def _repartition_serialized(self, child_results):
        """Masked device partition, then parallel serialization through the
        kudo-analog wire format into a spillable host store (reference
        RapidsShuffleThreadedWriterBase:291-513 + ShuffleBufferCatalog).
        Device planes are released once serialized; blobs page to disk
        under spark.rapids.shuffle.hostSpillBudget. The returned partition
        lists deserialize lazily at read time."""
        from spark_rapids_tpu.shuffle import serde
        from spark_rapids_tpu.shuffle.store import ShuffleStore
        from spark_rapids_tpu.runtime.pipeline import pipeline_conf
        ser_t = self.metrics.metric(M.PARTITION_TIME)
        codec = self.conf.get(C.SHUFFLE_COMPRESSION)
        serde.codec_id(codec)  # validate up front
        store = ShuffleStore(self.n_out,
                             self.conf.get(C.SHUFFLE_HOST_BUDGET))
        nthreads = max(1, self.conf.get(C.SHUFFLE_WRITER_THREADS))

        def ser(item):
            # the compact partitioning path hands over already-contiguous
            # right-sized slices; serialize_batch compacts the masked
            # path's sub-batches itself. The row count rides along into
            # the store's per-partition tally (skew detection reads it
            # without decoding blobs).
            p, b = item
            n = rows_int(b.num_rows)
            if n == 0:
                return p, None, 0  # empty sub-batches never ship
            return p, FLT.site_bytes("shuffle.write",
                                     serde.serialize_batch(b, codec)), n

        if pipeline_conf(self.conf) > 0 and nthreads > 1:
            self._serialize_streaming(child_results, store, ser, nthreads,
                                      ser_t)
        else:
            parted = self._repartition_device(child_results)
            work = [(p, b) for p, part in enumerate(parted) for b in part]
            with self.span(ser_t):
                if len(work) > 1 and nthreads > 1:
                    from spark_rapids_tpu.runtime.host_pool import (
                        get_host_pool,
                    )
                    for p, blob, n in get_host_pool(self.conf).map_ordered(
                            ser, work, max_concurrency=nthreads):
                        if blob is not None:
                            store.add(p, blob, rows=n)
                else:
                    for item in work:
                        p, blob, n = ser(item)
                        if blob is not None:
                            store.add(p, blob, rows=n)
        self._store = store
        tot = store.totals()
        self.metrics.metric(M.SHUFFLE_BYTES_WRITTEN).add(
            tot["bytes_written"])
        self.metrics.metric(M.SHUFFLE_BYTES_SPILLED).add(
            tot["bytes_spilled"])
        rthreads = self.conf.get(C.SHUFFLE_READER_THREADS)
        return [[_LazyShuffleBlobs(store, p, rthreads, self.conf)]
                if store.partition_bytes(p)
                else [] for p in range(self.n_out)]

    def _serialize_streaming(self, child_results, store, ser,
                             nthreads: int, ser_t) -> None:
        """Async throttled serialized write (reference ThrottlingExecutor
        / RapidsShuffleThreadedWriterBase): the emit sink submits each
        sub-batch for serde the moment the device partitioning produces
        it, so serde/spill of batch i overlaps the partitioning kernel of
        batch i+1. TrafficController caps the host bytes in flight;
        completed blobs drain into the store IN SUBMISSION ORDER (the
        deque head gates on done()), so per-partition blob order — and
        every downstream result — is identical to the synchronous path."""
        from collections import deque

        from spark_rapids_tpu.io.async_io import (
            ThrottlingExecutor, TrafficController,
        )
        from spark_rapids_tpu.runtime.host_pool import get_host_pool
        ctrl = TrafficController(
            self.conf.get(C.ASYNC_WRITE_MAX_INFLIGHT),
            stall_warn_s=self.conf.get(C.ASYNC_WRITE_STALL_WARN_S) or None)
        # serde runs on the SHARED host pool (PR-2 boundedness invariant:
        # no per-writer throwaway executors); the TrafficController's
        # byte budget is the per-exchange admission bound
        ex = ThrottlingExecutor(nthreads, ctrl,
                                pool=get_host_pool(self.conf))
        futures = deque()

        def drain(block: bool) -> None:
            while futures and (block or futures[0].done()):
                p, blob, n = futures.popleft().result()
                if blob is not None:
                    store.add(p, blob, rows=n)

        def sink(p, b):
            futures.append(ex.submit(b.device_memory_size(), ser, (p, b)))
            drain(False)

        self._emit_sink = sink
        ok = False
        try:
            self._repartition_device(child_results)
            ok = True
        finally:
            self._emit_sink = None
            if ok:
                with self.span(ser_t):
                    drain(True)
                ex.shutdown()
            else:
                # partitioning raised: settle the in-flight serde work
                # without letting ITS errors mask the propagating one
                try:
                    drain(True)
                except Exception:  # noqa: BLE001
                    pass
                ex.shutdown(wait=False)

    def execute_partition(self, ctx, pidx):
        out = self._materialize()

        def decoded():
            for item in out[pidx]:
                if isinstance(item, _LazyShuffleBlobs):
                    yield from item.batches()
                else:
                    yield item

        # deserialized blobs coalesce exactly like device sub-batches:
        # the serialized path chops partitions even finer. Skew split
        # applies after (the store's writer-side row tally sizes lazy
        # partitions without decoding them).
        yield from self._split_skewed(self._coalesce_tiny(decoded()), pidx)

    def _ici_eligible(self, child_results):
        import jax as _jax
        # the shard math assumes exactly one cap-sized shard per device:
        # source partition count must equal the output count
        if len(child_results) != self.n_out or self.n_out < 2:
            return False
        if len(_jax.devices()) < self.n_out:
            return False
        for part in child_results:
            for b in part:
                for c in b.columns:
                    if c.is_string and not c.is_dict:
                        return False  # variable-length payloads
        # differing dict vocabs are ALIGNED by _align_vocabs, not rejected
        return True

    @staticmethod
    def _align_vocabs(batches):
        """Remap dict-string codes across shards onto ONE union vocab so
        string keys ride the fixed-width collective (VERDICT r3 #5: 'the
        TPU-native shuffle does not work for string keys'). Builds NEW
        batches — the inputs may alias cached/session batches whose
        identity-keyed caches assume immutability."""
        live = [b for b in batches if b is not None]
        if not live:
            return batches
        ncols = len(live[0].columns)
        new_cols = {i: list(b.columns) for i, b in enumerate(batches)
                    if b is not None}
        changed = False
        for ci in range(ncols):
            cols = [b.columns[ci] for b in live]
            if not cols[0].is_dict:
                continue
            aligned = K.align_dict_columns(cols)
            if aligned[0] is cols[0]:
                continue
            changed = True
            li = 0
            for i, b in enumerate(batches):
                if b is None:
                    continue
                new_cols[i][ci] = aligned[li]
                li += 1
        if not changed:
            return batches
        return [None if b is None
                else ColumnarBatch(new_cols[i], b.num_rows, b.row_mask)
                for i, b in enumerate(batches)]

    def _repartition_ici(self, child_results):
        """One shard per device, rows moved by lax.all_to_all inside a
        single shard_map program (parallel/exchange.py)."""
        if not self._ici_eligible(child_results):
            return None
        from jax.sharding import NamedSharding, PartitionSpec as PS
        from spark_rapids_tpu.parallel import exchange as X
        from spark_rapids_tpu.parallel.mesh import make_mesh
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        import jax as _jax

        n = self.n_out
        # one compacted batch per source partition, padded to one capacity
        batches = []
        for part in child_results:
            b = K.compact_batch(K.concat_batches(part)) if part else None
            batches.append(b)
        live_parts = [b for b in batches if b is not None]
        if not live_parts:
            return [[] for _ in range(n)]
        batches = self._align_vocabs(batches)
        live_parts = [b for b in batches if b is not None]
        schema_cols = live_parts[0].columns
        cap = max(round_capacity(max(int(b.num_rows), 1)) for b in live_parts)
        mesh = make_mesh(n, axis_names=("part",))

        # build global [n*cap] planes sharded over the mesh. Assembled
        # in NUMPY: each jnp pad/concat here is an eager XLA program
        # (~200 of them per exchange), while numpy pad+concat is a
        # memcpy — the planes hit the device exactly once, at the
        # sharded device_put below.
        def pad_plane(arr, fill, dtype):
            dt = np.dtype(dtype)
            out = np.full(cap, fill, dt)
            a = np.asarray(arr)[:cap]
            out[: a.shape[0]] = a.astype(dt, copy=False)
            return out

        planes = {}
        per_col_meta = []
        for ci, c in enumerate(schema_cols):
            key = f"c{ci}"
            if c.is_dict:
                per_col_meta.append(("dict", c.dtype, c.data["dict_offsets"],
                                     c.data["dict_bytes"], c.dict_unique))
                shards = [pad_plane(b.columns[ci].data["codes"], 0, np.int32)
                          if b is not None else np.zeros(cap, np.int32)
                          for b in batches]
            else:
                dt = np.dtype(c.data.dtype)
                per_col_meta.append(("fixed", c.dtype, None, None, True))
                shards = [pad_plane(b.columns[ci].data, 0, dt)
                          if b is not None else np.zeros(cap, dt)
                          for b in batches]
            planes[key] = np.concatenate(shards)
            vshards = []
            for b in batches:
                if b is None:
                    vshards.append(np.zeros(cap, np.bool_))
                else:
                    col = b.columns[ci]
                    v = col.validity if col.validity is not None else \
                        (np.arange(col.capacity) <
                         int(traced_rows(b.num_rows)))
                    vshards.append(pad_plane(v, False, np.bool_))
            planes[key + "_v"] = np.concatenate(vshards)
        live = np.concatenate([
            pad_plane(b.live_mask(), False, np.bool_) if b is not None
            else np.zeros(cap, np.bool_) for b in batches])

        # target partition ids from the key hash, computed globally, plus
        # per-(source, destination) counts for the right-sizing pass.
        # FAST PATH (all fixed-width columns): ONE jitted program
        # evaluates the keys, hashes, and counts the per-(src,dst) lanes
        # over the packed planes — the per-source loop costs three eager
        # kernel launches per source. Grouping keys are row-local
        # expressions, so evaluating them on the concatenated planes is
        # exact; dict-encoded keys hash decoded values, so they keep the
        # per-source path.
        n_cols = len(per_col_meta)
        if all(meta[0] == "fixed" for meta in per_col_meta):
            dts = [meta[1] for meta in per_col_meta]

            def _build_hash():
                def f(data_planes, valid_planes, live):
                    cols = [ColumnVector(dt, d, v) for dt, d, v
                            in zip(dts, data_planes, valid_planes)]
                    total = live.shape[0]
                    ectx = EvalCtx(cols, total, total, False, live=live)
                    key_cols = [e.eval_tpu(ectx) for e in self.keys]
                    h = K.partition_hash_batch(key_cols, total, live=live)
                    pid = jnp.where(live, _pmod(h, n), 0).astype(jnp.int32)
                    # per-(src,dst) counts via the counting-sort kernel's
                    # bucket pass (ops/repartition.py) — one code path
                    # sizes both the compact slices and the ICI send lanes
                    counts = jax.vmap(
                        lambda p_, l_: RP.partition_counts(p_, l_, n)
                    )(pid.reshape(n, cap), live.reshape(n, cap))
                    return pid, counts
                return f

            hfn = fuse.fused(
                ("ici_hash", n, cap,
                 tuple(e.fingerprint() for e in self.keys),
                 tuple(str(planes[f"c{ci}"].dtype)
                       for ci in range(n_cols))),
                _build_hash)
            pid_all, counts_dev = hfn(
                [planes[f"c{ci}"] for ci in range(n_cols)],
                [planes[f"c{ci}_v"] for ci in range(n_cols)], live)
            target, counts_host = jax.device_get((pid_all, counts_dev))
            counts_host = np.asarray(counts_host)
        else:
            tgt_parts = []
            count_parts = []
            for b in batches:
                if b is None:
                    tgt_parts.append(np.zeros(cap, np.int32))
                    count_parts.append(jnp.zeros(n, jnp.int32))
                    continue
                ectx = EvalCtx(b.columns, traced_rows(b.num_rows),
                               b.capacity, False, live=b.live_mask())
                key_cols = [e.eval_tpu(ectx) for e in self.keys]
                h = K.partition_hash_batch(key_cols, b.num_rows,
                                           live=b.live_mask())
                pid = _pmod(h, n)
                count_parts.append(
                    RP.partition_counts(pid, b.live_mask(), n))
                tgt_parts.append(pad_plane(pid, 0, np.int32))
            target = np.concatenate(tgt_parts)
            counts_host = np.asarray(jax.device_get(jnp.stack(count_parts)))
        # ONE host fetch sizes the send lanes: C = max rows any source
        # sends any destination, rounded to a capacity bucket — the ICI
        # collective then moves ~rows/P per lane instead of the whole
        # local capacity (VERDICT r3 weak #5: capacity-naive buffers)
        send_cap = min(cap, round_capacity(max(int(counts_host.max()), 1)))

        spec = PS("part")
        sh = NamedSharding(mesh, spec)
        planes = {k: _jax.device_put(v, sh) for k, v in planes.items()}
        live = _jax.device_put(live, sh)
        target = _jax.device_put(target, sh)

        def shard_fn(planes, live, target):
            return X.all_to_all_exchange(planes, live, target, ("part",),
                                         send_cap=send_cap)

        # the KEYED compile layer, not _cc.jit: shard_fn is a fresh
        # closure every repartition, so raw jax.jit would retrace the
        # whole collective each collect. The key pins the shapes that
        # matter (mesh width, capacity buckets, plane dtypes) and the
        # compile-cache fingerprint adds the mesh component under
        # multichip — repeated exchanges replay the warm executable.
        key = ("ici_exchange", n, cap, send_cap,
               tuple((k, str(planes[k].dtype)) for k in sorted(planes)))
        fn = fuse.fused(key, lambda: shard_map(
            shard_fn, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=({k: spec for k in planes}, spec)))
        # the collective dispatch itself, timed with NO host sync inside
        # the span (async dispatch; the interval is issue cost plus any
        # backend blocking). NESTED inside the partitionTime span the
        # caller opened — rollups/attribution exclude it (metrics.
        # NESTED_TIME_METRICS) and the 'ici_exchange' attribution view
        # reports it separately.
        with self.span(self.metrics.metric(M.ICI_EXCHANGE_TIME)):
            out_planes, out_live = fn(planes, live, target)

        # slice the global result back into per-partition, PER-SENDER
        # batches (consumers like the aggregate merge rely on "one batch =
        # rows from one upstream partial" for their unique-key reasoning).
        # ONE host assembly first: the n*n slices below are eager ops, and
        # on the sharded collective output each would run the GSPMD
        # partitioner (20-40x a single-device slice). device_get gathers
        # the local shards without an XLA program; the emitted batches
        # keep the host numpy views — consumers feed them into jitted
        # kernels (which accept numpy) or host packers, and re-uploading
        # each of the n*n*planes slices measured ~0.15ms apiece.
        out_planes, out_live = jax.device_get((out_planes, out_live))
        out: List[List[ColumnarBatch]] = []
        shard_rows = n * send_cap  # each device receives n*send_cap slots
        for p in range(n):
            subs = []
            for src in range(n):
                base = p * shard_rows + src * send_cap
                sl = slice(base, base + send_cap)
                cols = []
                for ci, (kind, dtype, doff, dby, uniq) in enumerate(per_col_meta):
                    data = out_planes[f"c{ci}"][sl]
                    valid = out_planes[f"c{ci}_v"][sl]
                    if kind == "dict":
                        cols.append(ColumnVector(
                            dtype, {"codes": data, "dict_offsets": doff,
                                    "dict_bytes": dby}, valid,
                            dict_unique=uniq))
                    else:
                        cols.append(ColumnVector(dtype, data, valid))
                mask = out_live[sl]
                subs.append(ColumnarBatch(
                    cols, int(mask.sum()), mask))
            out.append(subs)
        return out

    def _repartition_masked(self, child_results):
        part_t = self.metrics.metric(M.PARTITION_TIME)
        keys, n_out = self.keys, self.n_out

        def build():
            def fn(batch):
                live = batch.live_mask()
                ectx = EvalCtx(batch.columns, traced_rows(batch.num_rows),
                               batch.capacity, False, live=live)
                key_cols = [e.eval_tpu(ectx) for e in keys]
                h = K.partition_hash_batch(key_cols, batch.num_rows, live=live)
                pid = _pmod(h, n_out)
                subs = []
                for p in range(n_out):
                    m = live & (pid == p)
                    subs.append(ColumnarBatch(
                        batch.columns, LazyRowCount(jnp.sum(m.astype(jnp.int32))), m))
                return subs
            return fn

        fn = fuse.fused(("hash_exchange",
                         tuple(e.fingerprint() for e in keys), n_out), build)
        out: List[List[ColumnarBatch]] = [[] for _ in range(self.n_out)]
        for part in child_results:
            for batch in part:
                with self.span(part_t):
                    # mask-sliced sub-batches: the planes are SHARED across
                    # all n_out outputs (zero-copy partitioning); only the
                    # selection masks differ.
                    self._emit_masked(batch, fn(batch), out)
        return out


def _pmod(h, n):
    r = h % n
    return jnp.where(r < 0, r + n, r)


class _LazyShuffleBlobs:
    """A reduce partition's serialized blobs; deserializes at read time.
    Host-side decode (decompression + frame parsing) runs on the shuffle
    reader pool (spark.rapids.shuffle.multiThreaded.reader.threads);
    device upload stays ordered.

    Integrity recovery: each blob's wire CRC (and frame xxhash64) is
    verified during deserialization (spark.rapids.shuffle.
    verifyChecksums); a ShuffleCorruptionError triggers ONE transparent
    re-fetch of the same blob from the store — disk-resident blobs
    re-read their spill-file segment, so a transient read corruption
    heals — counted in the shuffleCorruptionRetries task accumulator
    before a second failure surfaces (and, under
    spark.rapids.fallback.cpu.enabled, degrades the query to CPU)."""

    def __init__(self, store, partition: int, reader_threads: int = 1,
                 conf=None):
        self.store = store
        self.partition = partition
        self.reader_threads = max(1, reader_threads)
        self.conf = conf
        self.verify = True if conf is None \
            else bool(conf.get(C.SHUFFLE_VERIFY_CHECKSUMS))
        self._task_ctx = None

    def _read(self, index: int) -> bytes:
        return FLT.site_bytes(
            "shuffle.read", self.store.read_blob(self.partition, index))

    def _decode(self, index: int):
        from spark_rapids_tpu.shuffle import serde
        try:
            return serde.deserialize_batch(self._read(index),
                                           verify=self.verify)
        except serde.ShuffleCorruptionError as e:
            # decode may run on a host-pool worker with no TaskContext
            # bound: the retry accounts to the CONSUMING task captured
            # in batches()
            ctx = TaskContext.peek() or self._task_ctx
            if ctx is not None:
                ctx.metric("shuffleCorruptionRetries").add(1)
            TR.instant("shuffleCorruptionRetry", cat="shuffle", args={
                "partition": self.partition, "blob": index,
                "error": str(e)[:120]})
            import logging
            logging.getLogger("spark_rapids_tpu").warning(
                "shuffle blob %d of partition %d failed verification "
                "(%s); re-fetching from the store once", index,
                self.partition, e)
            return serde.deserialize_batch(self._read(index),
                                           verify=self.verify)

    def batches(self):
        self._task_ctx = TaskContext.peek()
        n = self.store.num_blobs(self.partition)
        if self.reader_threads > 1 and n > 1:
            from spark_rapids_tpu.runtime.host_pool import get_host_pool
            yield from get_host_pool(self.conf).map_ordered(
                self._decode, range(n),
                max_concurrency=self.reader_threads)
            return
        for i in range(n):
            yield self._decode(i)


class RoundRobinExchangeExec(ExchangeExec):
    """Round-robin repartition (reference GpuRoundRobinPartitioning)."""

    def __init__(self, plan, children, conf, n_out: int):
        super().__init__(plan, children, conf)
        self.n_out = n_out

    @property
    def num_partitions(self):
        return self.n_out

    def _repartition(self, child_results):
        if self.n_out == 1:
            return self._repartition_passthrough(child_results)
        part_t = self.metrics.metric(M.PARTITION_TIME)
        n_out = self.n_out
        compact = _partitioning_mode(self.conf) == "compact"

        def build():
            def fn(batch):
                live = batch.live_mask()
                pid = jnp.cumsum(live.astype(jnp.int32)) % n_out
                if compact:
                    return RP.counting_sort_by_pid(batch, pid, n_out)
                subs = []
                for p in range(n_out):
                    m = live & (pid == p)
                    subs.append(ColumnarBatch(
                        batch.columns, LazyRowCount(jnp.sum(m.astype(jnp.int32))), m))
                return subs
            return fn

        fn = fuse.fused(("rr_exchange_compact" if compact
                         else "rr_exchange", n_out), build)
        out: List[List[ColumnarBatch]] = [[] for _ in range(self.n_out)]
        if compact:
            self._compact_stream(
                (b for part in child_results for b in part), fn, out,
                part_t)
            return out
        for part in child_results:
            for batch in part:
                with self.span(part_t):
                    self._emit_masked(batch, fn(batch), out)
        return out


class RangeExchangeExec(ExchangeExec):
    """Range repartition by sort keys (reference GpuRangePartitioner +
    SamplingUtils): sample transformed order keys, compute n-1 bounds on
    host, then assign each row its partition by branch-free lexicographic
    bound comparisons on device. Output partition p holds rows ordering
    before partition p+1's — a per-partition sort then yields a globally
    sorted result without collecting to one partition (the scalability
    cliff VERDICT flagged)."""

    def __init__(self, plan, children, conf, orders, n_out: int):
        super().__init__(plan, children, conf)
        self.orders = orders
        self.n_out = n_out

    @property
    def num_partitions(self):
        return self.n_out

    def _key_fn(self):
        orders = self.orders

        def build():
            def fn(batch):
                live = batch.live_mask()
                ectx = EvalCtx(batch.columns, traced_rows(batch.num_rows),
                               batch.capacity, False, live=live)
                planes = []
                for o in orders:
                    kc = o.expr.eval_tpu(ectx)
                    k, nulls = K.normalize_key(kc, batch.num_rows, live=live)
                    null_rank = jnp.uint8(0) if o.resolved_nulls_first() \
                        else jnp.uint8(1)
                    val_rank = jnp.uint8(1) - null_rank
                    planes.append(jnp.where(nulls, null_rank, val_rank))
                    planes.append(k if o.ascending else ~k)
                return tuple(planes), live
            return fn

        return fuse.fused(
            ("range_keys", tuple((o.expr.fingerprint(), o.ascending,
                                  o.resolved_nulls_first())
                                 for o in self.orders)), build)

    def _repartition(self, child_results):
        if self.n_out == 1:
            return self._repartition_passthrough(child_results)
        part_t = self.metrics.metric(M.PARTITION_TIME)
        n_out = self.n_out
        keyfn = self._key_fn()
        per_batch = []   # (batch, planes)
        samples = []     # host tuples
        budget = self.conf.get(C.CPU_RANGE_PARTITION_SAMPLE) * n_out
        with self.span(part_t):
            for part in child_results:
                for batch in part:
                    planes, live = keyfn(batch)
                    per_batch.append((batch, planes))
                    # tpulint: disable=TPU-L004 range bounds need the sample values on host before the slicing kernels can be BUILT — there is no later point to consume a deferred fetch
                    host = jax.device_get(list(planes) + [live])
                    lv = host[-1]
                    idx = np.flatnonzero(lv)
                    if len(idx) > budget:
                        # ceil stride so samples span the WHOLE batch — a
                        # floor stride takes a prefix and biases bounds on
                        # pre-ordered input
                        idx = idx[:: -(-len(idx) // budget)][:budget]
                    for i in idx:
                        samples.append(tuple(int(p[i]) for p in host[:-1]))
            if not samples:
                return [[] for _ in range(n_out)]
            samples.sort()
            bounds = [samples[(len(samples) * (i + 1)) // n_out]
                      for i in range(n_out - 1)]
            # bounds ride in as TRACED plane-aligned arrays — baking their
            # values into the fuse key would permanently cache one compiled
            # executable per dataset
            bound_planes = None
            compact = _partitioning_mode(self.conf) == "compact"

            def build():
                def fn(batch, planes, bplanes):
                    live = batch.live_mask()
                    pid = jnp.zeros(batch.capacity, jnp.int32)
                    for bi in range(n_out - 1):
                        # lexicographic: bound < row
                        lt = jnp.zeros(batch.capacity, jnp.bool_)
                        eq = jnp.ones(batch.capacity, jnp.bool_)
                        for bp, plane in zip(bplanes, planes):
                            bv = bp[bi]
                            lt = lt | (eq & (plane > bv))
                            eq = eq & (plane == bv)
                        pid = pid + lt.astype(jnp.int32)
                    if compact:
                        return RP.counting_sort_by_pid(batch, pid, n_out)
                    subs = []
                    for p in range(n_out):
                        m = live & (pid == p)
                        subs.append(ColumnarBatch(
                            batch.columns,
                            LazyRowCount(jnp.sum(m.astype(jnp.int32))), m))
                    return subs
                return fn

            fn = fuse.fused(("range_exchange_compact" if compact
                             else "range_exchange", n_out,
                             tuple((o.expr.fingerprint(), o.ascending)
                                   for o in self.orders)), build)
            out: List[List[ColumnarBatch]] = [[] for _ in range(n_out)]
            for batch, planes in per_batch:
                if bound_planes is None:
                    bound_planes = tuple(
                        jnp.asarray(np.array([b[j] for b in bounds],
                                             dtype=planes[j].dtype))
                        for j in range(len(planes)))
                if compact:
                    self._emit_compact(
                        batch, fn(batch, planes, bound_planes), out)
                else:
                    self._emit_masked(
                        batch, fn(batch, planes, bound_planes), out)
        return out


# ---------------------------------------------------------------------------
# Joins
# ---------------------------------------------------------------------------

class _HashJoinBase(TpuExec):
    """Shared probe loop for the hash-join family (reference GpuHashJoin /
    JoinGatherer assembly). Skew handling: when the build side exceeds the
    sub-partition threshold, both sides mask-split by key hash into k
    buckets (zero-copy: shared planes, different selection masks) and join
    pairwise — reference GpuSubPartitionHashJoin.scala:32,156-180."""

    def _sub_parts(self, build_rows: int) -> int:
        thr = self.conf.get(C.JOIN_SUBPARTITION_ROWS)
        if build_rows <= thr:
            return 1
        return min(-(-build_rows // thr), 64)

    def __init__(self, plan, children, conf):
        super().__init__(plan, children, conf)
        #: width-normalized (lkeys, rkeys) for hashing; set by the planner
        #: on the shuffled path, derived lazily elsewhere
        self.part_keys = None
        self._split_lock = threading.Lock()
        self._split_cache = None
        #: caching the split only pays when partitions share ONE build (the
        #: broadcast path); shuffled joins have per-partition builds and a
        #: shared lock would serialize them
        self._cache_build_split = False
        self._dense_lock = threading.Lock()
        self._dense_cache = None  # (build identity, DenseBuildTable|None)

    def _dense_table_for(self, build, build_keys):
        """Direct-address build table for the mask-through probe, prepared
        once per build batch (one 4-scalar fetch). Shared across actions
        through the plan node when the build itself is, and across whole
        ACTIONS through the session broadcast cache entry (the reference's
        reused-broadcast semantics: the table is a pure function of the
        build batch + probe key types)."""
        plan_cache = getattr(self.plan, "_dense_table_cache", None)
        if plan_cache is not None and plan_cache[0] is build:
            return plan_cache[1]
        entry = getattr(self.plan, "_bcast_session_entry", None)
        tkey = tuple(type(e.data_type()).__name__
                     for e in self.plan.left_keys)
        if entry is not None and entry["build"] is build \
                and tkey in entry["dense"]:
            table = entry["dense"][tkey]
            self.plan._dense_table_cache = (build, table)
            return table
        with self._dense_lock:
            if self._dense_cache is None or self._dense_cache[0] is not build:
                table = None
                if int(build.num_rows) > 0:
                    table = J.prepare_dense_build(
                        build_keys, build.num_rows,
                        [e.data_type() for e in self.plan.left_keys])
                self._dense_cache = (build, table)
                self.plan._dense_table_cache = (build, table)
                if entry is not None and entry["build"] is build:
                    entry["dense"][tkey] = table
            return self._dense_cache[1]

    def _hash_keys(self, side: int):
        if self.part_keys is None:
            # Spark murmur3 is width-sensitive (int32 and int64 hash
            # differently): bucket hashing must use a common key type on
            # both sides or equal values split across buckets.
            lks, rks = [], []
            for lk, rk in zip(self.plan.left_keys, self.plan.right_keys):
                ct = T.common_type(lk.data_type(), rk.data_type())
                lks.append(lk if lk.data_type() == ct else Cast(lk, ct))
                rks.append(rk if rk.data_type() == ct else Cast(rk, ct))
            self.part_keys = (lks, rks)
        return self.part_keys[side]

    def _split_build(self, build, k):
        """Split/compact the build side into k key-hash buckets; cached
        only when the exec shares one build across partitions."""
        def compute():
            parts = []
            for bp in self._bucket_split(build, self._hash_keys(1), k):
                bpc = K.compact_batch(bp)
                parts.append(
                    (bpc, compiled.run_stage(self.plan.right_keys, bpc)))
            return parts

        if not self._cache_build_split:
            return compute()
        with self._split_lock:
            if self._split_cache is None or self._split_cache[0] is not build:
                self._split_cache = (build, compute())
            return self._split_cache[1]

    def _bucket_split(self, batch, keys, k, seed=107):
        """Mask-partition a batch into k hash buckets of its join keys
        (seed 107 — the reference's agg-repartition seed)."""
        key_cols = compiled.run_stage(keys, batch)
        live = batch.live_mask()
        h = K.partition_hash_batch(key_cols, batch.num_rows, seed=seed, live=live)
        b = _pmod(h, k)
        out = []
        for i in range(k):
            m = live & (b == i)
            out.append(ColumnarBatch(batch.columns,
                                     LazyRowCount(jnp.sum(m.astype(jnp.int32))), m))
        return out

    def _probe_stream(self, ctx, probe_iter, build, build_keys, join_t,
                      track_build_matches: bool):
        """Yields joined batches; returns via StopIteration the build-side
        matched mask (for right/full outer)."""
        how = self.plan.how
        matched_build = (jnp.zeros(build.capacity, jnp.bool_)
                         if track_build_matches else None)
        if how in ("inner", "left", "left_semi", "left_anti"):
            # mask-through fast path: unique dense build keys mean each
            # probe row matches <= 1 build row, so the join emits the probe
            # planes UNTOUCHED plus build columns gathered at probe
            # positions — no pair expansion, no compaction, no per-batch
            # host sync (reference contrast: GpuHashJoin always assembles
            # gather maps; on this hardware the gathers + count syncs they
            # imply cost more than the whole probe).
            table = self._dense_table_for(build, build_keys)
            if table is not None and table.max_dup <= 1:
                for probe in probe_iter:
                    self._acquire(ctx)
                    with self.span(join_t):
                        out = self._probe_masked(probe, build, table)
                    yield out
                return
        # sub-partitioning applies to inner/left/semi/anti; right/full track
        # a build-global matched mask that bucket-local indices would
        # corrupt, so they stay on the single-pass path
        k = self._sub_parts(int(build.num_rows)) \
            if how in ("inner", "left", "left_semi", "left_anti") else 1
        build_parts = self._split_build(build, k) if k > 1 else None
        for probe in probe_iter:
            self._acquire(ctx)
            with self.span(join_t):
                if build_parts is not None:
                    probe_parts = self._bucket_split(probe, self._hash_keys(0), k)
                    for pp, (bpc, bkeys) in zip(probe_parts, build_parts):
                        ppc = K.compact_batch(pp)
                        _, out = self._probe_one(ppc, bpc, bkeys, None)
                        if out is not None:
                            yield out
                    continue
                matched_build, out = self._probe_one(probe, build, build_keys,
                                                     matched_build)
                if out is not None:
                    yield out
        if track_build_matches:
            un_idx, n_un = J.unmatched_indices(matched_build,
                                               build.live_mask())
            if n_un:
                from spark_rapids_tpu.columnar.batch import empty_like_schema
                dummy = empty_like_schema(self.children[0].schema, capacity=8)
                pi = jnp.full(un_idx.shape, -1, jnp.int32)
                yield self._emit(dummy, build, pi, un_idx, n_un)

    def _probe_masked(self, probe, build, table) -> ColumnarBatch:
        """Unique-build-key join without pair materialization: output is a
        masked batch sharing the probe's planes. Handles inner/left/semi/
        anti, including join conditions (evaluated as a mask over the
        mask-through batch — valid because each probe row has at most one
        candidate)."""
        how = self.plan.how
        plan = self.plan
        left_keys, right_keys = plan.left_keys, plan.right_keys
        condition = plan.condition
        # key_map: build cols reconstructable from probe keys (static
        # decision from plan schemas)
        key_map = {}
        for ki, rk in enumerate(right_keys):
            lt = left_keys[ki].data_type()
            if isinstance(rk, BoundRef) and rk.index < len(build.columns):
                c = build.columns[rk.index]
                if lt == c.dtype and not c.is_string and not c.is_nested:
                    key_map[rk.index] = ki

        def build_fn():
            def fn(probe, build, slot_idx, bmin):
                plive = probe.live_mask()
                ectx = EvalCtx(probe.columns, traced_rows(probe.num_rows),
                               probe.capacity, False, live=plive)
                probe_keys = [e.eval_tpu(ectx) for e in left_keys]
                pk0 = probe_keys[0]
                p_in = plive if pk0.validity is None \
                    else (plive & pk0.validity)
                bidx = J.dense_lookup_planes(slot_idx, bmin,
                                             pk0.data.astype(jnp.int64),
                                             p_in)
                matched = bidx >= 0
                blive = build.live_mask() if build.row_mask is not None \
                    else None
                bcols = []
                for ci, c in enumerate(build.columns):
                    ki = key_map.get(ci)
                    if ki is not None:
                        pk = probe_keys[ki]
                        v = (pk.validity & matched) \
                            if pk.validity is not None else matched
                        bcols.append(ColumnVector(c.dtype, pk.data, v))
                    else:
                        bcols.append(K.gather_column(c, bidx, build.num_rows,
                                                     src_live=blive))
                if condition is not None:
                    cctx = EvalCtx(list(probe.columns) + bcols,
                                   traced_rows(probe.num_rows),
                                   probe.capacity, False, live=plive)
                    pred = condition.eval_tpu(cctx)
                    cond_ok = pred.data.astype(jnp.bool_) \
                        & (pred.validity if pred.validity is not None
                           else jnp.ones(probe.capacity, jnp.bool_))
                    matched = matched & cond_ok
                if how == "left_semi":
                    return K.mask_filter_batch(probe, matched)
                if how == "left_anti":
                    return K.mask_filter_batch(probe, ~matched)
                if how == "inner":
                    live = plive & matched
                    return ColumnarBatch(
                        list(probe.columns) + bcols,
                        LazyRowCount(jnp.sum(live.astype(jnp.int32))), live)
                ob = [ColumnVector(c.dtype, c.data,
                                   (c.validity & matched)
                                   if c.validity is not None else matched,
                                   dict_unique=c.dict_unique)
                      for c in bcols]
                return ColumnarBatch(list(probe.columns) + ob,
                                     probe.num_rows, probe.row_mask)
            return fn

        key = ("dense_probe_masked", how,
               tuple(e.fingerprint() for e in left_keys),
               tuple(e.fingerprint() for e in right_keys),
               condition.fingerprint() if condition is not None else None,
               tuple(sorted(key_map.items())))
        fn = fuse.fused(key, build_fn)
        out = fn(probe, build, table.slot_idx, table.bmin)
        # probe planes pass through: carry their column-stat bounds
        for ic, oc in zip(probe.columns, out.columns):
            oc.bounds = ic.bounds
        return out

    def _probe_one(self, probe, build, build_keys, matched_build):
        how = self.plan.how
        probe_keys = compiled.run_stage(self.plan.left_keys, probe)
        live = probe.live_mask() if probe.row_mask is not None else None
        pi, bi, nmatch = J.join_pairs(build_keys, build.num_rows,
                                      probe_keys, probe.num_rows,
                                      probe_live=live)
        pi, bi, nmatch = self._apply_condition(probe, build, pi, bi, nmatch)
        if how in ("left_semi", "left_anti"):
            mask = J.probe_matched_mask(pi, probe.capacity)
            if how == "left_anti":
                mask = ~mask
            return matched_build, K.mask_filter_batch(probe, mask)
        if how in ("left", "full"):
            mask = J.probe_matched_mask(pi, probe.capacity)
            un_idx, n_un = J.unmatched_indices(mask, probe.live_mask())
            if n_un:
                tot = nmatch + n_un
                cap = round_capacity(max(tot, 1))
                pi = _concat_idx(pi, nmatch, un_idx, n_un, cap)
                bi = _concat_idx(bi, nmatch,
                                 jnp.full(un_idx.shape, -1, jnp.int32),
                                 n_un, cap)
                nmatch = tot
        if matched_build is not None:
            matched_build = matched_build | J.probe_matched_mask(
                bi, build.capacity)
        return matched_build, self._emit(probe, build, pi, bi, nmatch)

    def _apply_condition(self, probe, build, pi, bi, nmatch):
        if self.plan.condition is None or nmatch == 0:
            return pi, bi, nmatch
        pair_batch = _pair_batch(probe, build, pi, bi, nmatch)
        [pred] = compiled.run_stage([self.plan.condition], pair_batch)
        keep = pred.data.astype(jnp.bool_) & pred.validity_or_default(nmatch)
        keep = keep & (jnp.arange(pi.shape[0]) < nmatch)
        idx, cnt = K.filter_indices(keep, pi.shape[0])
        sel = jnp.clip(idx, 0, pi.shape[0] - 1)
        return (jnp.where(idx >= 0, pi[sel], -1),
                jnp.where(idx >= 0, bi[sel], -1), cnt)

    def _emit(self, probe, build, pi, bi, n):
        return _pair_batch(probe, build, pi, bi, n)


class BroadcastHashJoinExec(_HashJoinBase):
    """Build side fully materialized (broadcast analog), probe side streamed
    per partition (reference GpuBroadcastHashJoinExecBase). Build side =
    RIGHT child. right/full outer joins are planned through a collect
    exchange so this exec sees a single probe partition."""

    def __init__(self, plan, children, conf):
        super().__init__(plan, children, conf)
        self._build_lock = threading.Lock()
        self._build: Optional[ColumnarBatch] = None
        self._build_keys = None
        self._cache_build_split = True  # one shared build for all partitions

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def _cacheable_build_plan(self) -> bool:
        """The build result may be cached ACROSS actions (reused broadcast,
        the ReusedExchange analog) when the build subtree is a pure view
        over an immutable cached relation."""
        def ok(n):
            if isinstance(n, (P.CachedRelation,)):
                return True
            if isinstance(n, (P.Filter, P.Project, P.Limit)):
                return all(ok(c) for c in n.children)
            return False
        return ok(self.plan.children[1])

    def _reuse_anchor(self):
        """(CachedRelation, structural fingerprint) for the cross-action
        broadcast cache, or (None, None). Only build subtrees reading
        EXACTLY ONE cached relation participate: the reused entry lives ON
        that relation (so it is dropped with the cache, never pins HBM
        past it, and object identity cannot be confused by recycled ids —
        the reference's exchange-reuse map scopes lifetime the same way)."""
        rels = []

        def walk(n):
            if isinstance(n, P.CachedRelation):
                rels.append(n)
                return "cached"
            parts = tuple(walk(c) for c in n.children)
            if isinstance(n, P.Filter):
                return ("filter", n.condition.fingerprint(), parts)
            if isinstance(n, P.Project):
                return ("project",
                        tuple(e.fingerprint() for e in n.exprs), parts)
            if isinstance(n, P.Limit):
                return ("limit", n.n, parts)
            # _cacheable_build_plan() admits only the node kinds above;
            # anything else poisons the key so no reuse can happen
            rels.append(None)
            rels.append(None)
            return ("uncacheable",)

        fp = walk(self.plan.children[1])
        if len(rels) != 1 or rels[0] is None:
            return None, None
        return rels[0], (fp, tuple(e.fingerprint()
                                   for e in self.plan.right_keys))

    def _build_side(self) -> ColumnarBatch:
        with self._build_lock:
            if self._build is None:
                cached = getattr(self.plan, "_bcast_cache", None)
                if cached is not None and self._cacheable_build_plan():
                    self._build, self._build_keys = cached
                    return self._build
                anchor = skey = None
                if self._cacheable_build_plan():
                    anchor, skey = self._reuse_anchor()
                if anchor is not None:
                    store = getattr(anchor, "_bcast_reuse", {})
                    entry = store.get(skey)
                    # entry is valid only for the materialization it was
                    # built from (identity checked against LIVE state: a
                    # re-cache replaces the list and invalidates)
                    if entry is not None \
                            and entry["mat"] is not anchor.materialized:
                        del store[skey]  # stale: stop pinning old batches
                        entry = None
                    from spark_rapids_tpu.exec import adaptive as AQ
                    src = "anchor"
                    if entry is None:
                        # second chance: the digest-keyed cross-query
                        # cache (exec/adaptive.py) — a DIFFERENT plan
                        # tree joining the same cached relation through
                        # the same build shape reuses the materialized
                        # broadcast; the hit re-warms the anchor store
                        entry = AQ.build_cache_get(
                            self.conf, self.plan.children[1], skey, anchor)
                        src = "digest"
                        if entry is not None:
                            if len(store) >= 8:
                                store.pop(next(iter(store)))
                            store[skey] = entry
                            if getattr(anchor, "_bcast_reuse",
                                       None) is None:
                                anchor._bcast_reuse = store
                    if entry is not None:
                        self._build = entry["build"]
                        self._build_keys = entry["keys"]
                        self.plan._bcast_cache = (self._build,
                                                  self._build_keys)
                        self.plan._bcast_session_entry = entry
                        if AQ.enabled(self.conf):
                            AQ.record(
                                AQ.BUILD_REUSE, source=src,
                                dispatches_saved=int(
                                    entry.get("build_batches", 0)) or 1)
                        return self._build
                build_t = self.metrics.metric(M.BUILD_TIME)
                right = self.children[1]
                batches = []
                with self.span(build_t):
                    for p in range(right.num_partitions):
                        with TaskContext(partition_id=p) as tctx:
                            batches.extend(right.execute_partition(tctx, p))
                    if batches:
                        self._build = K.compact_batch(K.concat_batches(batches))
                    else:
                        from spark_rapids_tpu.columnar.batch import empty_like_schema
                        self._build = empty_like_schema(right.schema)
                    self._build_keys = compiled.run_stage(
                        self.plan.right_keys, self._build)
                if anchor is not None and anchor.materialized is not None:
                    entry = {"build": self._build, "keys": self._build_keys,
                             "dense": {}, "mat": anchor.materialized,
                             "build_batches": len(batches)}
                    store = getattr(anchor, "_bcast_reuse", None)
                    if store is None:
                        store = anchor._bcast_reuse = {}
                    if len(store) >= 8:
                        store.pop(next(iter(store)))
                    store[skey] = entry
                    self.plan._bcast_session_entry = entry
                    self.plan._bcast_cache = (self._build, self._build_keys)
                    from spark_rapids_tpu.exec import adaptive as AQ
                    AQ.build_cache_put(self.conf, self.plan.children[1],
                                       skey, anchor, entry)
        return self._build

    def execute_partition(self, ctx, pidx):
        join_t = self.metrics.metric(M.JOIN_TIME)
        build = self._build_side()
        track = self.plan.how in ("right", "full")
        probe_iter = self.children[0].execute_partition(ctx, pidx)
        yield from self._probe_stream(ctx, probe_iter, build,
                                      self._build_keys, join_t, track)


class AdaptiveJoinExec(TpuExec):
    """Runtime join-strategy pick (the AQE role; reference
    GpuCustomShuffleReaderExec + per-stage re-planning): when the planner
    cannot estimate the build side, materialize it ONCE at execution time
    and route on the MEASURED row count — broadcast when it fits, hash
    exchange both sides otherwise. The materialized build feeds whichever
    strategy wins (no recompute: the exchange path consumes it through an
    in-memory source, matching AQE's reuse of materialized stages)."""

    def __init__(self, plan, children, conf, part_keys):
        super().__init__(plan, children, conf)
        self.part_keys = part_keys
        self._lock = threading.Lock()
        self._chosen: Optional[TpuExec] = None

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def _choose(self) -> TpuExec:
        with self._lock:
            if self._chosen is None:
                left, right = self.children
                threshold = self.conf.get(C.BROADCAST_JOIN_ROW_THRESHOLD)
                # stream the build side only UP TO the threshold: measuring
                # by materializing everything would hold the whole side in
                # HBM exactly when it is too big to broadcast
                batches, rows, overflow = [], 0, False
                for p in range(right.num_partitions):
                    with TaskContext(partition_id=p) as tctx:
                        for b in right.execute_partition(tctx, p):
                            batches.append(b)
                            rows += rows_int(b.num_rows)
                            if rows > threshold:
                                overflow = True
                                break
                    if overflow:
                        break
                if not overflow:
                    right_src = _MaterializedExec(self.plan.children[1],
                                                  batches, self.conf)
                    self._chosen = BroadcastHashJoinExec(
                        self.plan, [left, right_src], self.conf)
                    from spark_rapids_tpu.exec import adaptive as AQ
                    AQ.record(AQ.BROADCAST_CONVERSION, source="row_probe",
                              build_rows=rows, threshold_rows=threshold,
                              # both sides' exchanges (partition kernel +
                              # offsets fetch per input batch) never run
                              dispatches_saved=2 * max(len(batches), 1))
                else:
                    del batches  # release; the exchange re-executes right
                    lkeys, rkeys = self.part_keys
                    n_out = left.num_partitions
                    lex = ShuffleExchangeExec(self.plan, [left], self.conf,
                                              lkeys, n_out)
                    rex = ShuffleExchangeExec(self.plan, [right], self.conf,
                                              rkeys, n_out)
                    self._chosen = ShuffledHashJoinExec(
                        self.plan, [lex, rex], self.conf,
                        part_keys=self.part_keys)
        return self._chosen

    def execute_partition(self, ctx, pidx):
        yield from self._choose().execute_partition(ctx, pidx)


class _MaterializedExec(TpuExec):
    """Already-materialized device batches as a single-partition exec (the
    reused-stage input of the adaptive path)."""

    def __init__(self, plan, batches, conf):
        super().__init__(plan, [], conf)
        self._batches = list(batches)

    @property
    def schema(self):
        return self.plan.schema

    @property
    def num_partitions(self):
        return 1

    def execute_partition(self, ctx, pidx):
        yield from self._batches


class BroadcastNestedLoopJoinExec(TpuExec):
    """Non-equi joins (reference GpuBroadcastNestedLoopJoinExecBase): the
    build (right) side broadcasts whole; the join condition evaluates over
    TILED row pairs — left batch x one build tile per fused dispatch, with
    the pair batch emitted directly as a selection-masked output (inner)
    and per-side matched masks accumulated by scatter-or for outer/semi/
    anti completions. All shapes static per (left capacity, tile rows)."""

    MAX_PAIRS = 1 << 20

    def __init__(self, plan, children, conf):
        super().__init__(plan, children, conf)
        self._build_lock = threading.Lock()
        self._build: Optional[ColumnarBatch] = None

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def _build_side(self) -> ColumnarBatch:
        with self._build_lock:
            if self._build is None:
                build_t = self.metrics.metric(M.BUILD_TIME)
                right = self.children[1]
                batches = []
                with self.span(build_t):
                    for p in range(right.num_partitions):
                        with TaskContext(partition_id=p) as tctx:
                            batches.extend(right.execute_partition(tctx, p))
                    if batches:
                        self._build = K.compact_batch(K.concat_batches(batches))
                    else:
                        from spark_rapids_tpu.columnar.batch import empty_like_schema
                        self._build = empty_like_schema(right.schema)
        return self._build

    def _tile_fn(self, tile_rows: int, how: str, ansi: bool):
        cond = self.plan.condition

        def build():
            def fn(left: ColumnarBatch, build: ColumnarBatch, tile0,
                   lmatched, bmatched):
                lcap = left.capacity
                pairs = lcap * tile_rows
                p = jnp.arange(pairs, dtype=jnp.int32)
                lidx = p // tile_rows
                bidx = tile0 + (p % tile_rows)
                bcap = build.capacity
                b_in = bidx < traced_rows(build.num_rows)
                bsafe = jnp.clip(bidx, 0, bcap - 1)
                lcols = [K.gather_column(c, lidx, left.num_rows,
                                         src_live=left.live_mask())
                         for c in left.columns]
                bcols = [K.gather_column(c, bsafe, build.num_rows)
                         for c in build.columns]
                live_pair = left.live_mask()[lidx] & b_in
                ectx = EvalCtx(lcols + bcols, jnp.sum(live_pair.astype(jnp.int32)),
                               pairs, ansi, live=live_pair)
                if cond is not None:
                    pred = cond.eval_tpu(ectx)
                    pvalid = (pred.validity if pred.validity is not None
                              else ectx.row_mask)
                    match = live_pair & pred.data.astype(jnp.bool_) & pvalid
                else:
                    match = live_pair
                lmatched = lmatched.at[lidx].max(match)
                bmatched = bmatched.at[bsafe].max(match & b_in)
                out = None
                if how in ("inner", "left", "right", "full"):
                    out = ColumnarBatch(
                        lcols + bcols,
                        LazyRowCount(jnp.sum(match.astype(jnp.int32))), match)
                return out, lmatched, bmatched, dict(ectx.errors)
            return fn

        return fuse.fused(
            ("bnlj_tile", tile_rows, how, ansi,
             cond.fingerprint() if cond is not None else None), build)

    def execute_partition(self, ctx, pidx):
        join_t = self.metrics.metric(M.JOIN_TIME)
        how = self.plan.how
        ansi = self.conf.get(C.ANSI_ENABLED)
        build = self._build_side()
        n_build = int(build.num_rows)
        bcap = max(build.capacity, 1)
        bmatched_total = jnp.zeros(bcap, jnp.bool_)
        null_right_by_cap = {}

        for left in self.children[0].execute_partition(ctx, pidx):
            self._acquire(ctx)
            lcap = max(left.capacity, 1)
            tile_rows = max(1, min(bcap, self.MAX_PAIRS // lcap))
            fn = self._tile_fn(tile_rows, how, ansi)
            lmatched = jnp.zeros(lcap, jnp.bool_)
            with self.span(join_t):
                for t0 in range(0, max(n_build, 1), tile_rows):
                    if n_build == 0:
                        break
                    out, lmatched, bmatched_total, errs = fn(
                        left, build, jnp.int32(t0), lmatched, bmatched_total)
                    compiled.raise_errors(errs)
                    if out is not None and how != "left_semi":
                        yield out
                if how in ("left", "full"):
                    null_right = null_right_by_cap.get(lcap)
                    if null_right is None:
                        # per left-capacity: columns of one output batch
                        # must share a capacity
                        null_right = [
                            K.gather_column(c, jnp.full(lcap, -1, jnp.int32),
                                            build.num_rows)
                            for c in build.columns]
                        null_right_by_cap[lcap] = null_right
                    m = left.live_mask() & ~lmatched
                    yield ColumnarBatch(
                        list(left.columns) + null_right,
                        LazyRowCount(jnp.sum(m.astype(jnp.int32))), m)
                elif how == "left_semi":
                    m = left.live_mask() & lmatched
                    yield ColumnarBatch(
                        list(left.columns),
                        LazyRowCount(jnp.sum(m.astype(jnp.int32))), m)
                elif how == "left_anti":
                    m = left.live_mask() & ~lmatched
                    yield ColumnarBatch(
                        list(left.columns),
                        LazyRowCount(jnp.sum(m.astype(jnp.int32))), m)

        if how in ("right", "full") and n_build > 0:
            # single probe partition guaranteed by the planner
            null_left = [
                _null_gather(f.dtype, bcap)
                for f in self.plan.children[0].schema.fields]
            m = build.live_mask() & ~bmatched_total
            yield ColumnarBatch(
                null_left + list(build.columns),
                LazyRowCount(jnp.sum(m.astype(jnp.int32))), m)


def _null_gather(dtype, cap: int):
    """All-null column of `dtype` at capacity `cap`."""
    no = jnp.zeros(cap, jnp.bool_)
    if isinstance(dtype, T.StringType):
        return ColumnVector(dtype, {"offsets": jnp.zeros(cap + 1, jnp.int32),
                                    "bytes": jnp.zeros(8, jnp.uint8)}, no)
    return ColumnVector(dtype, jnp.zeros(cap, dtype.np_dtype), no)


class ShuffledHashJoinExec(_HashJoinBase):
    """Both sides hash-exchanged on the join keys; each partition builds
    from its slice of the right side and probes its slice of the left
    (reference GpuShuffledHashJoinExec:125). Unlike the broadcast path,
    right/full outer joins work per partition with NO collect: the
    exchange guarantees equal keys co-locate."""

    def __init__(self, plan, children, conf, part_keys=None):
        super().__init__(plan, children, conf)
        self.part_keys = part_keys

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def execute_partition(self, ctx, pidx):
        join_t = self.metrics.metric(M.JOIN_TIME)
        build_t = self.metrics.metric(M.BUILD_TIME)
        with self.span(build_t):
            batches = list(self.children[1].execute_partition(ctx, pidx))
            if batches:
                build = K.compact_batch(K.concat_batches(batches))
            else:
                from spark_rapids_tpu.columnar.batch import empty_like_schema
                build = empty_like_schema(self.children[1].schema)
            build_keys = compiled.run_stage(self.plan.right_keys, build)
        track = self.plan.how in ("right", "full")
        probe_iter = self.children[0].execute_partition(ctx, pidx)
        yield from self._probe_stream(ctx, probe_iter, build, build_keys,
                                      join_t, track)


def _pair_batch(left: ColumnarBatch, right: ColumnarBatch, li, ri, n: int
                ) -> ColumnarBatch:
    # masked sides join uncompacted: gathers must use the LIVE mask, not
    # arange<num_rows (live rows sit at arbitrary positions)
    llive = left.live_mask() if left.row_mask is not None else None
    rlive = right.live_mask() if right.row_mask is not None else None
    cols = [K.gather_column(c, li, left.num_rows, src_live=llive)
            for c in left.columns]
    cols += [K.gather_column(c, ri, right.num_rows, src_live=rlive)
             for c in right.columns]
    return ColumnarBatch(cols, n)


def _concat_idx(a, na: int, b, nb: int, cap: int):
    r = jnp.arange(cap, dtype=jnp.int32)
    from_a = r < na
    from_b = (r >= na) & (r < na + nb)
    av = a[jnp.clip(r, 0, a.shape[0] - 1)]
    bv = b[jnp.clip(r - na, 0, b.shape[0] - 1)]
    return jnp.where(from_a, av, jnp.where(from_b, bv, -1))


class CartesianProductExec(TpuExec):
    """Chunked cross join (reference GpuCartesianProductExec)."""

    @property
    def num_partitions(self):
        return self.children[0].num_partitions

    def execute_partition(self, ctx, pidx):
        right = self.children[1]
        rbatches = []
        for p in range(right.num_partitions):
            with TaskContext(partition_id=p) as tctx:
                rbatches.extend(right.execute_partition(tctx, p))
        build = K.compact_batch(K.concat_batches(rbatches)) if rbatches else None
        for probe in self.children[0].execute_partition(ctx, pidx):
            self._acquire(ctx)
            if probe.row_mask is not None:
                probe = K.compact_batch(probe)
            if build is None or build.num_rows == 0 or probe.num_rows == 0:
                continue
            n = probe.num_rows * build.num_rows
            cap = round_capacity(n)
            r = jnp.arange(cap, dtype=jnp.int32)
            li = jnp.where(r < n, r // build.num_rows, -1)
            ri = jnp.where(r < n, r % build.num_rows, -1)
            out = _pair_batch(probe, build, li, ri, n)
            if self.plan.condition is not None:
                [pred] = compiled.run_stage([self.plan.condition], out)
                mask = pred.data.astype(jnp.bool_) & pred.validity_or_default(n)
                out = K.filter_batch(out, mask)
            yield out


# ---------------------------------------------------------------------------
# CPU fallback
# ---------------------------------------------------------------------------

class CpuFallbackExec(TpuExec):
    """Runs one plan node on the CPU backend, bridging device<->host at the
    boundaries (reference: unconverted nodes stay as CPU Spark operators
    with GpuColumnarToRow/RowToColumnar transitions inserted). Adjacent CPU
    fallbacks chain host-side without bouncing through the device."""

    @property
    def num_partitions(self):
        return 1

    def _child_cols(self, child: TpuExec):
        if isinstance(child, CpuFallbackExec):
            return child.cpu_result()
        tables = []
        for p in range(child.num_partitions):
            with TaskContext(partition_id=p) as tctx:
                for batch in child.execute_partition(tctx, p):
                    tables.append(to_arrow(batch, child.schema.names))
        if not tables:
            import pyarrow as pa
            fields = [pa.field(f.name, T.to_arrow(f.dtype))
                      for f in child.schema.fields]
            tables = [pa.Table.from_arrays(
                [pa.array([], type=f.type) for f in fields],
                schema=pa.schema(fields))]
        import pyarrow as pa
        return CPU.table_to_cols(pa.concat_tables(tables))

    def cpu_result(self):
        ansi = self.conf.get(C.ANSI_ENABLED)
        child_cols = [self._child_cols(c) for c in self.children]
        return CPU.apply_node(self.plan, child_cols, ansi)

    def execute_partition(self, ctx, pidx):
        cols = self.cpu_result()
        table = CPU.cols_to_table(cols, self.plan.schema.names)
        self._acquire(ctx)
        yield from_arrow(table)
