"""Whole-operator fusion: one jitted XLA computation per operator stage.

Reference parity/divergence: the reference calls one cuDF kernel per
primitive (a gather here, a hash there) — cheap when the device is on the
local PCIe bus. Over a tunneled PJRT link every eager dispatch costs
milliseconds, so this framework fuses an ENTIRE operator (expression eval
+ filter-compact, or expression eval + sort + segmented aggregation) into
a single jit'd function over ColumnarBatch pytrees. XLA then fuses across
the whole stage; the host issues exactly one call per operator per batch.

The cache is keyed by a semantic fingerprint (expression fingerprints +
operator shape); jax.jit's own signature cache handles layout/capacity
variation beneath each entry.
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax

_FUSE_CACHE: Dict[Tuple, Callable] = {}


def fused(key: Tuple, builder: Callable[[], Callable]) -> Callable:
    fn = _FUSE_CACHE.get(key)
    if fn is None:
        fn = jax.jit(builder())
        _FUSE_CACHE[key] = fn
    return fn


def clear_cache() -> None:
    _FUSE_CACHE.clear()
