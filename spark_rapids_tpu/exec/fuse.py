"""Whole-operator fusion: one jitted XLA computation per operator stage.

Reference parity/divergence: the reference calls one cuDF kernel per
primitive (a gather here, a hash there) — cheap when the device is on the
local PCIe bus. Over a tunneled PJRT link every eager dispatch costs
milliseconds, so this framework fuses an ENTIRE operator (expression eval
+ filter-compact, or expression eval + sort + segmented aggregation) into
a single jit'd function over ColumnarBatch pytrees. XLA then fuses across
the whole stage; the host issues exactly one call per operator per batch.

Whole-STAGE vertical fusion (exec/stage_fusion.py) goes one level up:
linear chains of narrow operators expose their traced bodies as StageBody
records here and compose into one entry, so the host issues one call per
PIPELINE STAGE per batch.

The cache is keyed by a semantic fingerprint (expression fingerprints +
operator shape); jax.jit's own signature cache handles layout/capacity
variation beneath each entry — and runtime/shapes.py guarantees those
capacities come from a small bucket set, so the variation is bounded.
Storage, stats and first-call compile attribution live in the sanctioned
compile choke point (runtime/compile_cache.py); this module remains the
per-batch DISPATCH choke point where the failure-domain hooks hang.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

from spark_rapids_tpu.runtime import compile_cache as _cc
from spark_rapids_tpu.runtime import faults as _faults
from spark_rapids_tpu.runtime import lifecycle as _lc
from spark_rapids_tpu.runtime import watchdog as _watchdog

#: test/diagnostic hook called with the fuse key once per device dispatch
#: issued through fused() (the dispatch-budget regression harness; see
#: tests/test_stage_fusion.py). None in production — the wrapper costs one
#: attribute read per call.
_DISPATCH_HOOK: Optional[Callable[[Tuple], None]] = None


def set_dispatch_hook(hook: Optional[Callable[[Tuple], None]]) -> None:
    global _DISPATCH_HOOK
    _DISPATCH_HOOK = hook


def notify_dispatch(key: Tuple) -> None:
    """Report a device dispatch issued outside fused() (compiled.run_stage)
    to the budget hook."""
    if _DISPATCH_HOOK is not None:
        _DISPATCH_HOOK(key)


def fused(key: Tuple, builder: Callable[[], Callable]) -> Callable:
    # key[0] names the operator family ("hash_exchange_compact",
    # "stage", ...): it doubles as the compile cache's exec-class so
    # hit/miss stats and warmup coverage group by operator kind. The
    # cache owns storage, conf fingerprinting, and first-call compile
    # attribution (7-11s first-run vs 0.6s steady on NDS).
    exec_class = key[0] if key and isinstance(key[0], str) else "fuse"
    fn = _cc.get(exec_class, key, builder)
    # fused() is THE per-batch device-dispatch choke point, so it is
    # also where the failure-domain hooks live: the device.dispatch
    # fault site, the dispatch watchdog's in-flight registration, and
    # the cooperative cancellation checkpoint. All gates are module-
    # global reads; with nothing armed AND no query lifecycle in flight
    # the raw jitted function returns and a dispatch costs exactly what
    # it did before any of this machinery existed. With only a cancel
    # token live (every real query), the wrapper is the checkpoint
    # alone — one token-table read per dispatch.
    if _DISPATCH_HOOK is None and not _faults.armed("device.dispatch") \
            and not _watchdog.active():
        if not _lc.active():
            return fn

        def checked(*args, **kwargs):
            _lc.check_current()
            return fn(*args, **kwargs)

        return checked

    def counted(*args, **kwargs):
        _lc.check_current()
        if _DISPATCH_HOOK is not None:
            notify_dispatch(key)
        with _watchdog.guard("device.dispatch"):
            # inside the guard so a wedge-kind fault is exactly what the
            # watchdog exists to detect
            _faults.site("device.dispatch")
            return fn(*args, **kwargs)

    return counted


def clear_cache() -> None:
    """Drop every cached fused entry (tests/profiling; delegates to the
    process-wide compile cache, which also drops the run_stage and
    absorbed-agg entries)."""
    _cc.clear()


class StageBody:
    """One fusable operator's traced body, separated from its driver loop
    so exec/stage_fusion.py can compose several into ONE jitted entry.

    builder() returns the uniform traced function
        fn(batch, pid, carry) -> (batch, errors_dict, carry)
    where `pid` is the traced partition id and `carry` is the operator's
    per-partition loop state (ProjectExec's row_base, LimitExec's
    remaining budget; a constant zero scalar for carry-free operators).
    Builders MUST capture only expression-level state — never the exec
    node, whose child tree can pin HBM-resident batches in the process-
    global fuse cache.

    bounds_map maps host-side column-stat bounds (ColumnVector.bounds,
    NOT pytree leaves) across the operator: in_bounds per input column ->
    bounds per output column.
    """

    __slots__ = ("key", "builder", "carry_init", "bounds_map", "has_carry",
                 "exhausts", "name")

    def __init__(self, key: Tuple, builder: Callable[[], Callable],
                 carry_init: Optional[Callable] = None,
                 bounds_map: Optional[Callable] = None,
                 has_carry: bool = False, exhausts: bool = False,
                 name: str = ""):
        self.key = key
        self.builder = builder
        self.carry_init = carry_init
        self.bounds_map = bounds_map
        self.has_carry = has_carry
        #: carry == 0 means every later batch is all-dead (LimitExec's
        #: remaining budget): the fused driver may stop consuming input
        self.exhausts = exhausts
        self.name = name

    def init_carry(self):
        import jax.numpy as jnp
        if self.carry_init is None:
            return jnp.int64(0)
        return self.carry_init()
