"""Whole-stage vertical fusion: one device dispatch per batch per stage.

The framework already fuses each operator's INTERNAL work into one jitted
call (exec/fuse.py header), but a Scan→Filter→Project→partial-HashAggregate
chain still paid one dispatch PER OPERATOR per batch — milliseconds each
over a tunneled PJRT link. This pass is the TPU-idiomatic analog of
Spark's whole-stage codegen (which the reference GPU plugin deliberately
lacks, SURVEY §2.4): it walks the converted TpuExec tree and collapses
maximal linear chains of narrow operators into ONE traced computation, so
the host issues exactly one XLA call per input batch per pipeline stage.

Two collapse shapes:

- a chain of narrow operators (non-trivial Project, Filter, Expand,
  device Limit) becomes a ``FusedStageExec`` whose per-batch function
  composes the members' traced bodies (fuse.StageBody) inside one
  ``fuse.fused`` entry, threading ANSI error planes and per-operator
  carries (ProjectExec's row_base, LimitExec's remaining budget);
- a chain feeding the update phase of a partial/complete
  HashAggregateExec is ABSORBED into the aggregate's update kernel
  (HashAggregateExec.pre_chain — the generalization of the existing
  pre_filter predicate fusion), so scan→filter→project→partial-agg runs
  as one dispatch per batch. Absorption is gated to aggregations taking
  the general sort-based update path: the packed-radix fast path needs
  eager host probes of the evaluated key columns, which a composed trace
  cannot provide, and losing radix would cost more than a dispatch saves.

Fallback: a stage whose composed trace fails on its FIRST batch rebuilds
the unfused operator chain over the remaining input (gated per stage, so
one exotic expression never disables fusion elsewhere). Everything sits
behind spark.rapids.sql.stageFusion.enabled (default on).

Per-operator attribution: the fused function additionally returns each
member's live output row count (a device scalar, added to the member's
NUM_OUTPUT_ROWS as a LazyRowCount — no sync), and the stage's measured
opTime is split evenly across members. stageDispatches counts composed
entries so dispatch-budget tests can assert the one-per-batch contract.
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional

import jax.numpy as jnp

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar.batch import LazyRowCount
from spark_rapids_tpu.exec import compiled, fuse
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import trace as TR

log = logging.getLogger("spark_rapids_tpu")


# ---------------------------------------------------------------------------
# The fused stage exec
# ---------------------------------------------------------------------------

class _ReplaySourceExec:
    """Single-use source yielding already-pulled batches then the rest of
    a live iterator (the unfused-fallback bridge: the chain's real input
    iterator has already been advanced and must not re-execute)."""

    def __init__(self, schema, batches, rest):
        self.schema = schema
        self._batches = list(batches)
        self._rest = rest
        self.children: List = []
        self.num_partitions = 1

    def execute_partition(self, ctx, pidx):
        yield from self._batches
        yield from self._rest


def _exec_base():
    from spark_rapids_tpu.exec import tpu_nodes as X
    return X


def rebuild_chain(members, source):
    """Reconstruct the original unfused operator chain over `source`
    (members are child-most first; all construct as (plan, children,
    conf)). Shared by FusedStageExec's and HashAggregateExec's per-stage
    trace-failure fallbacks. Each rebuilt exec shares its member's
    MetricsRegistry so rows processed through the fallback still show up
    under the members that last_metrics() reports."""
    prev = source
    for m in members:
        prev = type(m)(m.plan, [prev], m.conf)
        prev.metrics = m.metrics
    return prev


def make_fused_stage_exec():
    """FusedStageExec is defined against the live TpuExec base lazily to
    keep this module importable without pulling the whole operator
    library at import time."""
    X = _exec_base()

    class FusedStageExec(X.TpuExec):
        """Linear chain of narrow operators executed as ONE composed jit
        per input batch. `members` are the original exec nodes, child-most
        first; they keep their plan nodes (explain/metrics attribution)
        but their driver loops never run — only their stage bodies do."""

        def __init__(self, plan, children, conf, members, stage_id=0):
            super().__init__(plan, children, conf)
            self.members = members
            self.stage_id = stage_id
            self.bodies = [m.stage_body() for m in members]
            self._key = ("fused_stage", tuple(b.key for b in self.bodies))
            self._failed = False

        @property
        def schema(self):
            return self.members[-1].schema

        def name(self) -> str:
            ops = "+".join(type(m).__name__.replace("Exec", "")
                           for m in reversed(self.members))
            return f"FusedStageExec({ops})"

        def tree_string(self, indent: int = 0) -> str:
            pad = "  " * indent
            sid = self.stage_id
            lines = [f"{pad}*({sid}) {self.name()}"]
            for m in reversed(self.members):
                lines.append(f"{pad}  *({sid}) {type(m).__name__} "
                             f"<- {m.plan.describe()} [fused]")
            lines.append(self.children[0].tree_string(indent + 1))
            return "\n".join(lines)

        def _build(self):
            bodies = self.bodies

            def build():
                fns = [b.builder() for b in bodies]

                def fn(batch, pid, carries):
                    errs_all, rows, out_carries = [], [], []
                    for f, c in zip(fns, carries):
                        batch, errs, c2 = f(batch, pid, c)
                        errs_all.append(errs)
                        out_carries.append(c2)
                        rows.append(jnp.sum(
                            batch.live_mask().astype(jnp.int64)))
                    return (batch, tuple(errs_all), tuple(out_carries),
                            tuple(rows))
                return fn
            return build

        def _unfused_chain(self, source):
            return rebuild_chain(self.members, source)

        def _carry_bounds(self, in_batch, out_batch):
            bounds = [c.bounds for c in in_batch.columns]
            for b in self.bodies:
                if b.bounds_map is None:
                    return
                bounds = b.bounds_map(bounds)
            for c, bd in zip(out_batch.columns, bounds):
                if bd is not None:
                    c.bounds = bd

        def execute_partition(self, ctx, pidx):
            if self._failed:
                yield from self._unfused_chain(
                    self.children[0]).execute_partition(ctx, pidx)
                return
            out_rows = self.metrics.metric(M.NUM_OUTPUT_ROWS)
            in_batches = self.metrics.metric(M.NUM_INPUT_BATCHES)
            disp = self.metrics.metric(M.STAGE_DISPATCHES)
            # opTime attribution: the dispatch time splits EVENLY across
            # members (the stage records only dispatch/row metrics itself,
            # so summing opTime over a snapshot is not double-counted)
            member_t = [m.metrics.metric(M.OP_TIME) for m in self.members]
            member_rows = [m.metrics.metric(M.NUM_OUTPUT_ROWS)
                           for m in self.members]
            exhaust_idx = [i for i, b in enumerate(self.bodies)
                           if b.exhausts]
            fn = fuse.fused(self._key, self._build())
            carries = tuple(b.init_carry() for b in self.bodies)
            pid = jnp.int32(pidx)
            it = self.children[0].execute_partition(ctx, pidx)
            first = True
            for batch in it:
                self._acquire(ctx)
                in_batches.add(1)
                t0 = time.perf_counter_ns()
                try:
                    out, errs_all, carries, rows = fn(batch, pid, carries)
                except Exception as ex:
                    # per-stage fallback: run the unfused chain over this
                    # batch and the rest of the input. (A retrace for a
                    # NEW column layout can fail even after other layouts
                    # succeeded, so no first-call-only gate.) ANSI/
                    # analysis errors are deterministic, not trace
                    # failures — re-raise instead of replaying them; and
                    # mid-stream the members' loop carries (row_base,
                    # limit budget) cannot be reconstructed — only a
                    # clean start falls back then.
                    from spark_rapids_tpu.expr.core import SparkException
                    if isinstance(ex, SparkException) or (
                            not first
                            and any(b.has_carry for b in self.bodies)):
                        raise
                    self._failed = True
                    log.warning(
                        "stage fusion trace failed for %s; falling back "
                        "to the unfused chain", self.name(), exc_info=True)
                    src = _ReplaySourceExec(self.children[0].schema,
                                            [batch], it)
                    yield from self._unfused_chain(src).execute_partition(
                        ctx, pidx)
                    return
                first = False
                dt = time.perf_counter_ns() - t0
                if TR.active() is not None:
                    # the stage owns the timing (dt also splits across
                    # member opTime below), so the trace event is emitted
                    # from the already-measured interval instead of a
                    # metric_span; gated so name() never builds when off
                    TR.emit_span(self.name(), t0, dt, cat="exec", args={
                        "stage_id": self.stage_id,
                        "members": len(self.members)})
                    TR.instant("stageDispatch", cat="dispatch",
                               args={"stage_id": self.stage_id})
                for errs in errs_all:
                    compiled.raise_errors(errs)
                disp.add(1)
                share = dt // len(self.members)
                for mt, mr, r in zip(member_t, member_rows, rows):
                    mt.add(share)
                    mr.add(LazyRowCount(r))
                out_rows.add(out.num_rows)
                self._carry_bounds(batch, out)
                if exhaust_idx:
                    # issue the carry D2H NOW and consume it only after
                    # the yield: the scalar transfer overlaps downstream
                    # consumption of this batch instead of serializing
                    # between dispatches (runtime/pipeline.py deferred-
                    # fetch discipline; semantics unchanged — the value
                    # is still read before the next batch is pulled)
                    from spark_rapids_tpu.runtime.pipeline import start_d2h
                    for i in exhaust_idx:
                        start_d2h(carries[i])
                yield out
                # LIMIT early exit: a zero remaining-budget carry means
                # every later batch is all-dead — stop consuming input
                # (one scalar fetch per batch, only when a limit member
                # exists; the unfused LimitExec pays the same sync)
                if exhaust_idx and all(int(carries[i]) <= 0
                                       for i in exhaust_idx):
                    return

    return FusedStageExec


_FUSED_CLS = None


def fused_stage_cls():
    global _FUSED_CLS
    if _FUSED_CLS is None:
        _FUSED_CLS = make_fused_stage_exec()
    return _FUSED_CLS


# ---------------------------------------------------------------------------
# The planner pass
# ---------------------------------------------------------------------------

def _fusable(node) -> bool:
    """Static chain-membership check. Trivial projects join chains for
    free (pure column re-listing inside the trace) but never justify one
    — see _dispatching."""
    X = _exec_base()
    if isinstance(node, (X.ProjectExec, X.FilterExec, X.LimitExec,
                         X.DeviceDecodeScanExec)):
        return len(node.children) == 1
    if isinstance(node, X.ExpandExec):
        if len(node.children) != 1:
            return False
        # cross-projection vocab unification cannot run inside a trace,
        # and output capacity grows n_proj-fold: fixed-width, small fans
        from spark_rapids_tpu import types as T
        if len(node.plan.projections) > 8:
            return False
        return all(not isinstance(dt, (T.StringType, T.ArrayType,
                                       T.StructType, T.MapType))
                   for dt in node.plan.schema.types)
    return False


def _dispatching(node) -> bool:
    """Does this member cost a device dispatch when run unfused? (Trivial
    projects and limits do not; fusing is only worthwhile when >= 2
    dispatching members collapse, or >= 1 absorbs into an aggregate.)"""
    X = _exec_base()
    if isinstance(node, X.ProjectExec):
        return node._trivial_indices() is None
    return isinstance(node, (X.FilterExec, X.ExpandExec,
                             X.DeviceDecodeScanExec))


def _collect_chain(node):
    """Maximal fusable chain starting at `node` going down. Returns
    (members_top_first, input_exec). An already-built FusedStageExec
    decomposes back into its members (so an aggregate constructed over a
    fused chain still absorbs it)."""
    fused_cls = fused_stage_cls()
    chain = []
    cur = node
    while True:
        if isinstance(cur, fused_cls):
            chain.extend(reversed(cur.members))
            cur = cur.children[0]
            continue
        if not _fusable(cur):
            break
        chain.append(cur)
        cur = cur.children[0]
    return chain, cur


def _agg_absorbable(node) -> bool:
    X = _exec_base()
    if not isinstance(node, X.HashAggregateExec):
        return False
    if node.mode not in ("partial", "complete"):
        return False
    # the packed-radix and MXU-bucket fast paths probe EVALUATED key
    # columns host-side per batch; a composed trace cannot feed them, and
    # trading radix for one saved dispatch loses on big batches
    return not node.kern.has_custom and not node.kern._packed_ok


def fuse_stages(exec_root, conf):
    """Entry point: rewrite a converted TpuExec tree, collapsing fusable
    chains (applied by plan/overrides.convert_plan after conversion)."""
    if not conf.get(C.STAGE_FUSION_ENABLED):
        return exec_root
    counter = [0]
    return _rewrite(exec_root, conf, counter)


def fusion_groups(exec_root) -> list:
    """Export the fused stages of a converted exec tree as data (what the
    query-history record stores and the history server renders): one
    entry per stage — id, kind (fused chain vs aggregate-absorbed), and
    the member operator names child-most first (an absorbed chain ends
    with the aggregate it dispatches through). Derived from the ONE
    canonical walk (metrics.walk_exec_tree), so the member/pre-chain/
    no-recurse discipline can never drift from what last_metrics and
    explain_analyze report."""
    from spark_rapids_tpu.runtime.metrics import walk_exec_tree
    groups, cur = [], None
    for _k, node, _d, role, sid in walk_exec_tree(exec_root):
        if role is None:
            cur = None
            if sid is not None:
                cur = {"stage_id": sid,
                       "kind": ("fused" if getattr(node, "members", None)
                                else "absorbed"),
                       "members": [], "_self": type(node).__name__}
                groups.append(cur)
        elif cur is not None:
            cur["members"].append(type(node).__name__)
    for g in groups:
        self_name = g.pop("_self")
        if g["kind"] == "absorbed":
            g["members"].append(self_name)
    return groups


def _rewrite(node, conf, counter):
    X = _exec_base()

    if _agg_absorbable(node):
        chain, input_exec = _collect_chain(node.children[0])
        bodies = [m.stage_body() for m in reversed(chain)]
        # forceSinglePass concatenates the RAW child batches host-side
        # before one update — impossible over still-encoded batches, so
        # a chain rooted at a device-decode scan must not absorb there
        concat_ok = not (conf.get(C.AGG_FORCE_SINGLE_PASS) and any(
            isinstance(m, X.DeviceDecodeScanExec) for m in chain))
        if chain and concat_ok and all(not b.has_carry for b in bodies) \
                and any(_dispatching(m) for m in chain):
            counter[0] += 1
            node.pre_chain = bodies
            node.pre_chain_members = list(reversed(chain))
            node.fused_stage_id = counter[0]
            node.children = [_rewrite(input_exec, conf, counter)]
            return node

    if _fusable(node):
        chain, input_exec = _collect_chain(node)
        # the fusion boundary is 2 dispatching members (any fewer is
        # illegal — plan_verify PV-FUSE); the measured cost pass may
        # RAISE it for this plan when history shows fusion's retrace
        # cost outweighs the dispatch savings
        min_members = 2
        from spark_rapids_tpu.plan import cost as _cost
        h = _cost.current_hints()
        if h is not None and h.fusion_min_members is not None:
            min_members = max(2, int(h.fusion_min_members))
        if sum(1 for m in chain if _dispatching(m)) >= min_members:
            counter[0] += 1
            members = list(reversed(chain))  # child-most first
            cls = fused_stage_cls()
            return cls(node.plan, [_rewrite(input_exec, conf, counter)],
                       conf, members, stage_id=counter[0])

    node.children = [_rewrite(c, conf, counter) for c in node.children]
    return node
