from spark_rapids_tpu.columnar.batch import (  # noqa: F401
    ColumnVector, ColumnarBatch, round_capacity,
    from_arrow, to_arrow, from_pydict, to_pydict,
)
