"""Device columnar batch currency.

Reference parity: sql-plugin/src/main/java/com/nvidia/spark/rapids/
GpuColumnVector.java (cudf ColumnVector wrapped as Spark ColumnVector) and
ColumnarBatch usage throughout the exec layer.

TPU-first design decisions, deliberately different from the cuDF model:

- **Arrow-ish planes as JAX arrays.** A column is (data, validity) device
  arrays; strings are (offsets, bytes, validity). XLA operates on whole
  planes; there is no per-element object model.
- **Bucketed static capacity.** Every batch's arrays are padded to a
  power-of-two row capacity. `num_rows` is a host-side int. This keeps XLA
  shapes static so each operator stage compiles once per size bucket instead
  of once per batch (cuDF has dynamic shapes; XLA must not).
- **Validity is a bool plane, True = valid.** Data lanes of invalid or padded
  rows are *defined garbage*: kernels must mask through validity. Padded rows
  (row >= num_rows) always have validity False.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T

MIN_CAPACITY = 8


def round_capacity(n: int, minimum: int = MIN_CAPACITY) -> int:
    """Round a row count up to the capacity bucket (next power of two)."""
    n = max(int(n), 1, minimum)
    return 1 << (n - 1).bit_length()


def _pad_to(arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    if arr.shape[0] == capacity:
        return arr
    out = np.full((capacity,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


@dataclasses.dataclass
class ColumnVector:
    """One device-resident column.

    data:
      - fixed-width types: jnp array[capacity] of the type's np_dtype
      - StringType: dict(offsets=int32[capacity+1], bytes=uint8[byte_cap])
    validity: bool[capacity], True = valid. None means all rows < num_rows
      are valid (padded tail is implicitly invalid).
    """

    dtype: T.DataType
    data: Union[jax.Array, Dict[str, jax.Array]]
    validity: Optional[jax.Array] = None

    @property
    def capacity(self) -> int:
        if isinstance(self.data, dict):
            return int(self.data["offsets"].shape[0]) - 1
        return int(self.data.shape[0])

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, T.StringType)

    def validity_or_default(self, num_rows: int) -> jax.Array:
        """Materialize the validity plane (capacity-length bool)."""
        cap = self.capacity
        if self.validity is not None:
            return self.validity
        return jnp.arange(cap) < num_rows

    def device_memory_size(self) -> int:
        def sz(a):
            return int(np.prod(a.shape)) * a.dtype.itemsize
        total = 0
        if isinstance(self.data, dict):
            total += sum(sz(a) for a in self.data.values())
        else:
            total += sz(self.data)
        if self.validity is not None:
            total += sz(self.validity)
        return total


@dataclasses.dataclass
class ColumnarBatch:
    """A set of equal-capacity columns plus the true row count."""

    columns: List[ColumnVector]
    num_rows: int

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        if not self.columns:
            return round_capacity(self.num_rows)
        return self.columns[0].capacity

    def device_memory_size(self) -> int:
        return sum(c.device_memory_size() for c in self.columns)

    def column(self, i: int) -> ColumnVector:
        return self.columns[i]

    def select(self, indices: Sequence[int]) -> "ColumnarBatch":
        return ColumnarBatch([self.columns[i] for i in indices], self.num_rows)


# ---------------------------------------------------------------------------
# Host <-> device conversion (the R2C / C2R transition analog; reference
# GpuRowToColumnarExec / GpuColumnarToRowExec, here via Arrow planes).
# ---------------------------------------------------------------------------

def _np_valid_from_arrow(arr) -> Optional[np.ndarray]:
    import pyarrow as pa  # noqa: F401
    if arr.null_count == 0:
        return None
    # pyarrow validity bitmap -> bool array
    return np.asarray(arr.is_valid())


def column_from_arrow(arr, dtype: T.DataType, capacity: int) -> ColumnVector:
    """Build a device ColumnVector from a pyarrow Array (one chunk)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    n = len(arr)
    valid_np = _np_valid_from_arrow(arr)

    if isinstance(dtype, T.StringType):
        arr = arr.cast(pa.large_string()) if not pa.types.is_large_string(arr.type) else arr
        # fill nulls with "" so offsets stay monotone and bytes well-defined
        filled = pc.fill_null(arr, "")
        if isinstance(filled, pa.ChunkedArray):
            filled = filled.combine_chunks()
        off_buf = np.frombuffer(filled.buffers()[1], dtype=np.int64)
        buf_offsets = off_buf[filled.offset: filled.offset + n + 1]
        byte_len = int(buf_offsets[-1] - buf_offsets[0])
        data_buf = np.frombuffer(filled.buffers()[2] or b"", dtype=np.uint8)
        base = int(buf_offsets[0])
        bytes_np = data_buf[base: base + byte_len]
        offsets_np = (buf_offsets - base).astype(np.int32)
        byte_cap = round_capacity(max(byte_len, 1))
        off_padded = np.full(capacity + 1, offsets_np[-1], dtype=np.int32)
        off_padded[: n + 1] = offsets_np
        data = {
            "offsets": jnp.asarray(off_padded),
            "bytes": jnp.asarray(_pad_to(bytes_np, byte_cap)),
        }
    elif isinstance(dtype, T.BooleanType):
        np_arr = np.asarray(pc.fill_null(arr, False), dtype=np.bool_)
        data = jnp.asarray(_pad_to(np_arr, capacity))
    elif isinstance(dtype, T.NullType):
        data = jnp.zeros(capacity, dtype=np.int8)
        valid_np = np.zeros(n, dtype=np.bool_)
    elif isinstance(dtype, T.DecimalType):
        np_arr = np.zeros(n, dtype=np.int64)
        py = arr.to_pylist()
        scale = dtype.scale
        for i, v in enumerate(py):
            if v is not None:
                np_arr[i] = int((v.scaleb(scale)).to_integral_value())
        data = jnp.asarray(_pad_to(np_arr, capacity))
    elif isinstance(dtype, T.TimestampType):
        import pyarrow as pa
        cast = arr.cast(pa.timestamp("us"))
        np_arr = np.asarray(pc.fill_null(cast, 0)).astype("datetime64[us]").astype(np.int64)
        data = jnp.asarray(_pad_to(np_arr, capacity))
    elif isinstance(dtype, T.DateType):
        np_arr = np.asarray(pc.fill_null(arr, 0)).astype("datetime64[D]").astype(np.int32)
        data = jnp.asarray(_pad_to(np_arr, capacity))
    else:
        np_arr = np.asarray(pc.fill_null(arr, 0)).astype(dtype.np_dtype)
        data = jnp.asarray(_pad_to(np_arr, capacity))

    if valid_np is None:
        validity = None
    else:
        validity = jnp.asarray(_pad_to(valid_np.astype(np.bool_), capacity, fill=False))
    return ColumnVector(dtype, data, validity)


def from_arrow(table) -> ColumnarBatch:
    """pyarrow Table -> device ColumnarBatch (single upload per plane)."""
    table = table.combine_chunks()
    n = table.num_rows
    cap = round_capacity(n)
    cols = []
    for i, field in enumerate(table.schema):
        dtype = T.from_arrow(field.type)
        chunked = table.column(i)
        arr = chunked.chunk(0) if chunked.num_chunks else chunked.combine_chunks()
        cols.append(column_from_arrow(arr, dtype, cap))
    return ColumnarBatch(cols, n)


def column_to_numpy(col: ColumnVector, num_rows: int):
    """Device -> host materialization of one column as (values, validity)."""
    valid = None
    if col.validity is not None:
        valid = np.asarray(col.validity)[:num_rows]
    if col.is_string:
        offsets = np.asarray(col.data["offsets"])[: num_rows + 1]
        raw = np.asarray(col.data["bytes"])
        out = []
        for i in range(num_rows):
            if valid is not None and not valid[i]:
                out.append(None)
            else:
                out.append(bytes(raw[offsets[i]: offsets[i + 1]]).decode("utf-8", "replace"))
        return out, valid
    vals = np.asarray(col.data)[:num_rows]
    return vals, valid


def to_arrow(batch: ColumnarBatch, names: Optional[Sequence[str]] = None):
    """Device ColumnarBatch -> pyarrow Table (C2R boundary)."""
    import pyarrow as pa
    n = batch.num_rows
    arrays = []
    fields = []
    for i, col in enumerate(batch.columns):
        name = names[i] if names else f"c{i}"
        at = T.to_arrow(col.dtype)
        vals, valid = column_to_numpy(col, n)
        if col.is_string:
            arr = pa.array(vals, type=at)
        elif isinstance(col.dtype, T.NullType):
            arr = pa.nulls(n, type=at)
        elif isinstance(col.dtype, T.DecimalType):
            import decimal
            scale = col.dtype.scale
            py = [None if (valid is not None and not valid[j])
                  else decimal.Decimal(int(vals[j])).scaleb(-scale)
                  for j in range(n)]
            arr = pa.array(py, type=at)
        elif isinstance(col.dtype, T.TimestampType):
            mask = None if valid is None else ~valid
            arr = pa.array(vals.astype("datetime64[us]"), type=at,
                           mask=mask)
        elif isinstance(col.dtype, T.DateType):
            mask = None if valid is None else ~valid
            arr = pa.array(vals.astype("datetime64[D]"), type=at, mask=mask)
        else:
            mask = None if valid is None else ~valid
            arr = pa.array(vals, type=at, mask=mask)
        arrays.append(arr)
        fields.append(pa.field(name, at))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def from_pydict(d: dict, schema: Optional[T.Schema] = None) -> ColumnarBatch:
    import pyarrow as pa
    if schema is not None:
        pa_schema = pa.schema([pa.field(f.name, T.to_arrow(f.dtype)) for f in schema.fields])
        return from_arrow(pa.table(d, schema=pa_schema))
    return from_arrow(pa.table(d))


def to_pydict(batch: ColumnarBatch, names: Optional[Sequence[str]] = None) -> dict:
    return to_arrow(batch, names).to_pydict()


def empty_like_schema(schema: T.Schema, capacity: int = MIN_CAPACITY) -> ColumnarBatch:
    cols = []
    for f in schema.fields:
        if isinstance(f.dtype, T.StringType):
            data = {"offsets": jnp.zeros(capacity + 1, jnp.int32),
                    "bytes": jnp.zeros(MIN_CAPACITY, jnp.uint8)}
        else:
            data = jnp.zeros(capacity, dtype=f.dtype.np_dtype)
        cols.append(ColumnVector(f.dtype, data, jnp.zeros(capacity, jnp.bool_)))
    return ColumnarBatch(cols, 0)
