"""Device columnar batch currency.

Reference parity: sql-plugin/src/main/java/com/nvidia/spark/rapids/
GpuColumnVector.java (cudf ColumnVector wrapped as Spark ColumnVector) and
ColumnarBatch usage throughout the exec layer.

TPU-first design decisions, deliberately different from the cuDF model:

- **Arrow-ish planes as JAX arrays.** A column is (data, validity) device
  arrays; strings are (offsets, bytes, validity). XLA operates on whole
  planes; there is no per-element object model.
- **Bucketed static capacity.** Every batch's arrays are padded to a
  power-of-two row capacity. `num_rows` is a host-side int. This keeps XLA
  shapes static so each operator stage compiles once per size bucket instead
  of once per batch (cuDF has dynamic shapes; XLA must not).
- **Validity is a bool plane, True = valid.** Data lanes of invalid or padded
  rows are *defined garbage*: kernels must mask through validity. Padded rows
  (row >= num_rows) always have validity False.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.runtime import shapes as _shapes

MIN_CAPACITY = 8


def round_capacity(n: int, minimum: Optional[int] = None,
                   itemsize: Optional[int] = None) -> int:
    """Round a row count up to its capacity bucket. The bucket policy
    (geometric growth factor, per-dtype tile alignment) lives in
    runtime/shapes.py — spark.rapids.compile.shapes.*; the default
    reproduces the historical next-power-of-two capacities exactly."""
    if minimum is None:
        minimum = MIN_CAPACITY
    return _shapes.bucket_rows(n, minimum, itemsize)


class LazyRowCount:
    """A row count that lives on device until a host consumer forces it.

    Every device->host scalar readback costs a full round trip (~100ms over
    a tunneled PJRT link), so operators with data-dependent output sizes
    (filter, join, group) keep the count as a device scalar. Traced code
    reads it via `traced_rows` with NO synchronization; host control flow
    that truly needs the int (capacity decisions, limits, empty checks)
    materializes it once through the int dunders below.

    The reference pays this as a stream sync per cudf kernel with a dynamic
    result; deferring it is the TPU-idiomatic answer (SURVEY.md §7.3.1).
    """

    __slots__ = ("_dev", "_val")

    def __init__(self, dev):
        self._dev = dev
        self._val: Optional[int] = None

    def traced(self):
        return self._dev if self._val is None else self._val

    def materialize(self) -> int:
        if self._val is None:
            self._val = int(self._dev)
        return self._val

    @property
    def is_materialized(self) -> bool:
        return self._val is not None

    def __int__(self):
        return self.materialize()

    __index__ = __int__

    def __bool__(self):
        return self.materialize() != 0

    def __eq__(self, o):
        return self.materialize() == o

    def __ne__(self, o):
        return self.materialize() != o

    def __lt__(self, o):
        return self.materialize() < o

    def __le__(self, o):
        return self.materialize() <= o

    def __gt__(self, o):
        return self.materialize() > o

    def __ge__(self, o):
        return self.materialize() >= o

    def __add__(self, o):
        return self.materialize() + o

    __radd__ = __add__

    def __sub__(self, o):
        return self.materialize() - o

    def __rsub__(self, o):
        return o - self.materialize()

    def __mul__(self, o):
        return self.materialize() * o

    __rmul__ = __mul__

    def __hash__(self):
        return hash(self.materialize())

    def __repr__(self):
        return (f"LazyRowCount({self._val})" if self._val is not None
                else "LazyRowCount(<device>)")


def traced_rows(n):
    """num_rows as a trace-safe value (device scalar or python int)."""
    return n.traced() if isinstance(n, LazyRowCount) else n


def rows_int(n) -> int:
    """num_rows as a host int (synchronizes if lazy)."""
    return int(n)


def materialize_counts(batches: Sequence["ColumnarBatch"]) -> None:
    """Force all lazy row counts in ONE bulk device fetch instead of a
    serial sync per batch."""
    lazies = [b.num_rows for b in batches
              if isinstance(b.num_rows, LazyRowCount) and not b.num_rows.is_materialized]
    if not lazies:
        return
    import jax as _jax
    vals = _jax.device_get([lz._dev for lz in lazies])
    for lz, v in zip(lazies, vals):
        lz._val = int(v)


def _pad_to(arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    if arr.shape[0] == capacity:
        return arr
    out = np.full((capacity,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


@dataclasses.dataclass
class ColumnVector:
    """One device-resident column.

    data:
      - fixed-width types: jnp array[capacity] of the type's np_dtype
      - StringType flat: dict(offsets=int32[capacity+1], bytes=uint8[byte_cap])
      - StringType dict-encoded: dict(codes=int32[capacity],
        dict_offsets=int32[k+1], dict_bytes=uint8[m]) — the vocab is small
        and shared by all rows. Dictionary encoding is the default upload
        layout for strings: hashing/grouping/equality run over the vocab
        once and gather by code (string group-bys and joins become integer
        ops on the MXU/VPU instead of byte-plane work).
    validity: bool[capacity], True = valid. None means all rows < num_rows
      are valid (padded tail is implicitly invalid).
    """

    dtype: T.DataType
    data: Union[jax.Array, Dict[str, jax.Array]]
    validity: Optional[jax.Array] = None
    #: dict columns only: True when vocab entries are known distinct
    #: (dictionary_encode / unified concat). Transformed vocabs (upper()
    #: can merge 'a' and 'A') set False — bucket-by-code aggregation
    #: requires code uniqueness.
    dict_unique: bool = True
    #: optional host-side (min, max) int bounds (cache-time column stats,
    #: the ParquetCachedBatchSerializer-stats analog). NOT part of the
    #: pytree: consumed only host-side (radix packing skips its device
    #: range probe). Conservative bounds stay valid under any row subset.
    bounds: "Optional[Tuple[int, int]]" = None

    @property
    def capacity(self) -> int:
        if isinstance(self.data, dict):
            if "codes" in self.data:
                return int(self.data["codes"].shape[0])
            if "children" in self.data:  # struct: first child's capacity
                return self.data["children"][0].capacity
            return int(self.data["offsets"].shape[0]) - 1
        return int(self.data.shape[0])

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, T.StringType)

    @property
    def is_dict(self) -> bool:
        return isinstance(self.data, dict) and "codes" in self.data

    @property
    def is_nested(self) -> bool:
        return isinstance(self.dtype, (T.ArrayType, T.StructType, T.MapType))

    @property
    def dict_size(self) -> int:
        return int(self.data["dict_offsets"].shape[0]) - 1

    def validity_or_default(self, num_rows) -> jax.Array:
        """Materialize the validity plane (capacity-length bool)."""
        cap = self.capacity
        if self.validity is not None:
            return self.validity
        return jnp.arange(cap) < traced_rows(num_rows)

    def device_memory_size(self) -> int:
        def sz(a):
            if isinstance(a, ColumnVector):
                return a.device_memory_size()
            if isinstance(a, (list, tuple)):
                return sum(sz(x) for x in a)
            return int(np.prod(a.shape)) * a.dtype.itemsize
        total = 0
        if isinstance(self.data, dict):
            total += sum(sz(a) for a in self.data.values())
        else:
            total += sz(self.data)
        if self.validity is not None:
            total += sz(self.validity)
        return total


@dataclasses.dataclass
class ColumnarBatch:
    """A set of equal-capacity columns plus the true row count.

    row_mask (optional bool[capacity], True = live) is a selection vector:
    filters mark rows dead instead of gathering survivors (TPU gathers cost
    O(output); compaction of a mostly-surviving batch is the single most
    expensive thing you can do on this hardware, while masking is free and
    fuses into the next op). None means rows [0, num_rows) are live.
    Operators must treat dead rows as NONEXISTENT (not as null rows).
    """

    columns: List[ColumnVector]
    num_rows: int
    row_mask: Optional[jax.Array] = None

    def live_mask(self) -> jax.Array:
        """bool[capacity] marking live rows."""
        if self.row_mask is not None:
            return self.row_mask
        return jnp.arange(self.capacity) < traced_rows(self.num_rows)

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        if not self.columns:
            return round_capacity(self.num_rows)
        return self.columns[0].capacity

    def device_memory_size(self) -> int:
        return sum(c.device_memory_size() for c in self.columns)

    def column(self, i: int) -> ColumnVector:
        return self.columns[i]

    def select(self, indices: Sequence[int]) -> "ColumnarBatch":
        return ColumnarBatch([self.columns[i] for i in indices], self.num_rows)


# ---------------------------------------------------------------------------
# Host <-> device conversion (the R2C / C2R transition analog; reference
# GpuRowToColumnarExec / GpuColumnarToRowExec, here via Arrow planes).
# ---------------------------------------------------------------------------

def _np_valid_from_arrow(arr) -> Optional[np.ndarray]:
    import pyarrow as pa  # noqa: F401
    if arr.null_count == 0:
        return None
    # pyarrow validity bitmap -> bool array
    return np.asarray(arr.is_valid())


def _fixed_width_view(arr, np_dtype) -> np.ndarray:
    """Zero-copy view of a fixed-width pyarrow array's data buffer (a host
    `.astype()` round trip through object dtype is ~100x slower for
    date/timestamp columns)."""
    buf = arr.buffers()[1]
    view = np.frombuffer(buf, dtype=np_dtype, count=arr.offset + len(arr))
    out = view[arr.offset:]
    return out if out.dtype == np_dtype else out.astype(np_dtype)


def _pad_offsets(offsets_np: np.ndarray, n: int, capacity: int) -> np.ndarray:
    out = np.full(capacity + 1, offsets_np[n] if n < len(offsets_np)
                  else offsets_np[-1], dtype=np.int32)
    out[: n + 1] = offsets_np[: n + 1]
    return out


def column_from_arrow(arr, dtype: T.DataType, capacity: int) -> ColumnVector:
    """Build a device ColumnVector from a pyarrow Array (one chunk)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    n = len(arr)
    valid_np = _np_valid_from_arrow(arr)

    if isinstance(dtype, T.ArrayType):
        arr = _normalize_null_slices(arr, pa.list_(T.to_arrow(dtype.element)))
        off = np.asarray(arr.offsets, dtype=np.int64)
        base = int(off[0])
        values = arr.values[base: int(off[-1])]
        offsets_np = (off - base).astype(np.int32)
        child_cap = round_capacity(max(len(values), 1))
        child = column_from_arrow(values, dtype.element, child_cap)
        data = {"offsets": jnp.asarray(_pad_offsets(offsets_np, n, capacity)),
                "child": child}
        validity = None if valid_np is None else jnp.asarray(
            _pad_to(valid_np.astype(np.bool_), capacity, fill=False))
        return ColumnVector(dtype, data, validity)

    if isinstance(dtype, T.MapType):
        arr = _normalize_null_slices(
            arr, pa.map_(T.to_arrow(dtype.key), T.to_arrow(dtype.value)))
        off = np.asarray(arr.offsets, dtype=np.int64)
        base = int(off[0])
        keys = arr.keys[base: int(off[-1])]
        items = arr.items[base: int(off[-1])]
        offsets_np = (off - base).astype(np.int32)
        child_cap = round_capacity(max(len(keys), 1))
        data = {"offsets": jnp.asarray(_pad_offsets(offsets_np, n, capacity)),
                "keys": column_from_arrow(keys, dtype.key, child_cap),
                "values": column_from_arrow(items, dtype.value, child_cap)}
        validity = None if valid_np is None else jnp.asarray(
            _pad_to(valid_np.astype(np.bool_), capacity, fill=False))
        return ColumnVector(dtype, data, validity)

    if isinstance(dtype, T.StructType):
        if not dtype.fields:
            raise TypeError("empty struct columns are not supported")
        kids = [column_from_arrow(arr.field(i), f.dtype, capacity)
                for i, f in enumerate(dtype.fields)]
        validity = None if valid_np is None else jnp.asarray(
            _pad_to(valid_np.astype(np.bool_), capacity, fill=False))
        return ColumnVector(dtype, {"children": kids}, validity)

    if isinstance(dtype, T.StringType):
        if pa.types.is_dictionary(arr.type):
            denc = arr
        else:
            denc = arr.dictionary_encode()
        vocab = denc.dictionary
        # Dictionary layout pays off when the vocab is materially smaller
        # than the data; otherwise flat offsets+bytes (e.g. unique IDs).
        if len(vocab) <= max(64, n // 2):
            codes = denc.indices
            if codes.null_count:
                codes = pc.fill_null(codes, 0)
            codes_np = np.asarray(codes).astype(np.int32)
            voc = vocab.cast(pa.large_string()) if not pa.types.is_large_string(vocab.type) else vocab
            voff = np.frombuffer(voc.buffers()[1], dtype=np.int64)
            voff = voff[voc.offset: voc.offset + len(voc) + 1]
            base = int(voff[0])
            vlen = int(voff[-1] - base)
            vbytes = np.frombuffer(voc.buffers()[2] or b"", dtype=np.uint8)[base: base + vlen]
            data = {
                "codes": jnp.asarray(_pad_to(codes_np, capacity)),
                "dict_offsets": jnp.asarray((voff - base).astype(np.int32)),
                "dict_bytes": jnp.asarray(np.ascontiguousarray(vbytes)
                                          if vlen else np.zeros(1, np.uint8)),
            }
            if valid_np is None:
                validity = None
            else:
                validity = jnp.asarray(_pad_to(valid_np.astype(np.bool_), capacity, fill=False))
            return ColumnVector(dtype, data, validity)
        arr = arr.cast(pa.large_string()) if not pa.types.is_large_string(arr.type) else arr
        # fill nulls with "" so offsets stay monotone and bytes well-defined
        filled = pc.fill_null(arr, "")
        if isinstance(filled, pa.ChunkedArray):
            filled = filled.combine_chunks()
        off_buf = np.frombuffer(filled.buffers()[1], dtype=np.int64)
        buf_offsets = off_buf[filled.offset: filled.offset + n + 1]
        byte_len = int(buf_offsets[-1] - buf_offsets[0])
        data_buf = np.frombuffer(filled.buffers()[2] or b"", dtype=np.uint8)
        base = int(buf_offsets[0])
        bytes_np = data_buf[base: base + byte_len]
        offsets_np = (buf_offsets - base).astype(np.int32)
        byte_cap = round_capacity(max(byte_len, 1), itemsize=1)
        off_padded = np.full(capacity + 1, offsets_np[-1], dtype=np.int32)
        off_padded[: n + 1] = offsets_np
        data = {
            "offsets": jnp.asarray(off_padded),
            "bytes": jnp.asarray(_pad_to(bytes_np, byte_cap)),
        }
    elif isinstance(dtype, T.BooleanType):
        np_arr = np.asarray(pc.fill_null(arr, False), dtype=np.bool_)
        data = jnp.asarray(_pad_to(np_arr, capacity))
    elif isinstance(dtype, T.NullType):
        data = jnp.zeros(capacity, dtype=np.int8)
        valid_np = np.zeros(n, dtype=np.bool_)
    elif isinstance(dtype, T.DecimalType):
        np_arr = np.zeros(n, dtype=np.int64)
        py = arr.to_pylist()
        scale = dtype.scale
        for i, v in enumerate(py):
            if v is not None:
                np_arr[i] = int((v.scaleb(scale)).to_integral_value())
        data = jnp.asarray(_pad_to(np_arr, capacity))
    elif isinstance(dtype, T.TimestampType):
        cast = arr.cast(pa.timestamp("us"))
        if cast.null_count:
            cast = pc.fill_null(cast, 0)
        data = jnp.asarray(_pad_to(_fixed_width_view(cast, np.int64), capacity))
    elif isinstance(dtype, T.DateType):
        if arr.null_count:
            arr = pc.fill_null(arr, 0)
        data = jnp.asarray(_pad_to(_fixed_width_view(arr, np.int32), capacity))
    else:
        if arr.null_count:
            arr = pc.fill_null(arr, 0)
        np_arr = _fixed_width_view(arr, np.dtype(dtype.np_dtype))
        data = jnp.asarray(_pad_to(np_arr, capacity))

    if valid_np is None:
        validity = None
    else:
        validity = jnp.asarray(_pad_to(valid_np.astype(np.bool_), capacity, fill=False))
    return ColumnVector(dtype, data, validity)


def from_arrow(table) -> ColumnarBatch:
    """pyarrow Table -> device ColumnarBatch (single upload per plane)."""
    table = table.combine_chunks()
    n = table.num_rows
    cap = round_capacity(n)
    cols = []
    for i, field in enumerate(table.schema):
        dtype = T.from_arrow(field.type)
        chunked = table.column(i)
        arr = chunked.chunk(0) if chunked.num_chunks else chunked.combine_chunks()
        cols.append(column_from_arrow(arr, dtype, cap))
    return ColumnarBatch(cols, n)


def _normalize_null_slices(arr, target_type):
    """Cast a list/map array to the canonical layout and ensure null rows
    own empty slices (so child planes carry no garbage elements). Arrow
    permits null entries with non-empty ranges; the device layout does not."""
    import pyarrow as pa
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if arr.type != target_type:
        arr = arr.cast(target_type)
    if arr.null_count:
        off = np.asarray(arr.offsets, dtype=np.int64)
        lengths = np.diff(off)
        valid = np.asarray(arr.is_valid())
        if (lengths[: len(valid)][~valid] != 0).any():
            # pa.array rebuilds with zero-length slices under null entries
            arr = pa.array(arr.to_pylist(), type=target_type)
    return arr


def _leaf_to_py(col: ColumnVector, vals, valid, i: int):
    """One leaf value as an arrow-acceptable python object."""
    if valid is not None and not valid[i]:
        return None
    v = vals[i]
    if isinstance(col.dtype, T.DecimalType):
        import decimal
        return decimal.Decimal(int(v)).scaleb(-col.dtype.scale)
    if isinstance(col.dtype, T.TimestampType):
        return int(v)
    if isinstance(col.dtype, T.DateType):
        return int(v)
    if isinstance(v, (np.generic,)):
        return v.item()
    return v


def column_to_pylist(col: ColumnVector, n: int) -> list:
    """Host materialization of the first n rows of a (possibly nested)
    column as python values (None = null). Planes must already be host
    arrays or cheap to fetch."""
    if isinstance(col.dtype, T.ArrayType):
        off = np.asarray(col.data["offsets"])
        child_vals = column_to_pylist(col.data["child"], int(off[n]))
        valid = None if col.validity is None else np.asarray(col.validity)
        return [None if (valid is not None and not valid[i])
                else child_vals[off[i]: off[i + 1]] for i in range(n)]
    if isinstance(col.dtype, T.MapType):
        off = np.asarray(col.data["offsets"])
        keys = column_to_pylist(col.data["keys"], int(off[n]))
        vals = column_to_pylist(col.data["values"], int(off[n]))
        valid = None if col.validity is None else np.asarray(col.validity)
        return [None if (valid is not None and not valid[i])
                else list(zip(keys[off[i]: off[i + 1]],
                              vals[off[i]: off[i + 1]]))
                for i in range(n)]
    if isinstance(col.dtype, T.StructType):
        kids = [column_to_pylist(ch, n) for ch in col.data["children"]]
        names = [f.name for f in col.dtype.fields]
        valid = None if col.validity is None else np.asarray(col.validity)
        return [None if (valid is not None and not valid[i])
                else {nm: kid[i] for nm, kid in zip(names, kids)}
                for i in range(n)]
    vals, valid = column_to_numpy(col, n)
    if col.is_string:
        return vals
    return [_leaf_to_py(col, vals, valid, i) for i in range(n)]


def column_to_numpy(col: ColumnVector, num_rows: int, sel=None):
    """Device -> host materialization of one column as (values, validity).
    sel: optional host int array of live row positions (selection-mask
    compaction happens here, on host, where it is a cheap numpy take)."""
    valid = None
    if col.validity is not None:
        valid = np.asarray(col.validity)
        valid = valid[sel] if sel is not None else valid[:num_rows]
    if col.is_dict:
        codes = np.asarray(col.data["codes"])
        codes = codes[sel] if sel is not None else codes[:num_rows]
        offsets = np.asarray(col.data["dict_offsets"])
        raw = np.asarray(col.data["dict_bytes"])
        vocab = [bytes(raw[offsets[i]: offsets[i + 1]]).decode("utf-8", "replace")
                 for i in range(len(offsets) - 1)]
        out = []
        for i in range(len(codes)):
            if valid is not None and not valid[i]:
                out.append(None)
            else:
                out.append(vocab[codes[i]])
        return out, valid
    if col.is_string:
        offsets = np.asarray(col.data["offsets"])
        raw = np.asarray(col.data["bytes"])
        rows = sel if sel is not None else range(num_rows)
        out = []
        for j, i in enumerate(rows):
            if valid is not None and not valid[j]:
                out.append(None)
            else:
                out.append(bytes(raw[offsets[i]: offsets[i + 1]]).decode("utf-8", "replace"))
        return out, valid
    vals = np.asarray(col.data)
    vals = vals[sel] if sel is not None else vals[:num_rows]
    return vals, valid


def fetch_batch_host(batch: ColumnarBatch) -> ColumnarBatch:
    """Pull every plane of a batch to host in ONE bulk transfer (a
    per-plane np.asarray costs a round trip each). Returns a batch whose
    planes are host numpy arrays; the lazy row count rides along."""
    leaves, treedef = jax.tree_util.tree_flatten(batch)
    host = jax.device_get(leaves)
    out = jax.tree_util.tree_unflatten(treedef, host)
    n = int(out.num_rows)
    if isinstance(batch.num_rows, LazyRowCount):
        batch.num_rows._val = n
    return ColumnarBatch(out.columns, n, out.row_mask)


def to_arrow(batch: ColumnarBatch, names: Optional[Sequence[str]] = None):
    """Device ColumnarBatch -> pyarrow Table (C2R boundary). Selection-mask
    compaction happens host-side with numpy (free next to the transfer)."""
    import pyarrow as pa
    batch = fetch_batch_host(batch)
    n = batch.num_rows
    sel = None
    if batch.row_mask is not None:
        sel = np.flatnonzero(np.asarray(batch.row_mask))
        n = len(sel)
    arrays = []
    fields = []
    for i, col in enumerate(batch.columns):
        name = names[i] if names else f"c{i}"
        at = T.to_arrow(col.dtype)
        if col.is_nested:
            # sel holds raw capacity positions; materialize up to capacity
            full = column_to_pylist(col, col.capacity if sel is not None else n)
            vals = [full[i] for i in sel] if sel is not None else full
            arrays.append(pa.array(vals, type=at))
            fields.append(pa.field(name, at))
            continue
        vals, valid = column_to_numpy(col, n, sel)
        if col.is_string:
            arr = pa.array(vals, type=at)
        elif isinstance(col.dtype, T.NullType):
            arr = pa.nulls(n, type=at)
        elif isinstance(col.dtype, T.DecimalType):
            import decimal
            scale = col.dtype.scale
            py = [None if (valid is not None and not valid[j])
                  else decimal.Decimal(int(vals[j])).scaleb(-scale)
                  for j in range(n)]
            arr = pa.array(py, type=at)
        elif isinstance(col.dtype, T.TimestampType):
            mask = None if valid is None else ~valid
            arr = pa.array(vals.astype("datetime64[us]"), type=at,
                           mask=mask)
        elif isinstance(col.dtype, T.DateType):
            mask = None if valid is None else ~valid
            arr = pa.array(vals.astype("datetime64[D]"), type=at, mask=mask)
        else:
            mask = None if valid is None else ~valid
            arr = pa.array(vals, type=at, mask=mask)
        arrays.append(arr)
        fields.append(pa.field(name, at))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def from_pydict(d: dict, schema: Optional[T.Schema] = None) -> ColumnarBatch:
    import pyarrow as pa
    if schema is not None:
        pa_schema = pa.schema([pa.field(f.name, T.to_arrow(f.dtype)) for f in schema.fields])
        return from_arrow(pa.table(d, schema=pa_schema))
    return from_arrow(pa.table(d))


def to_pydict(batch: ColumnarBatch, names: Optional[Sequence[str]] = None) -> dict:
    return to_arrow(batch, names).to_pydict()


# ---------------------------------------------------------------------------
# JAX pytree registration: ColumnVector/ColumnarBatch/LazyRowCount pass
# straight through jax.jit, so a WHOLE operator (filter, group+aggregate,
# sort, join) fuses into one XLA computation — one dispatch per batch
# instead of one per kernel. Dtypes are static aux data; row counts are
# traced scalars (no recompile per batch size, no host sync).
# ---------------------------------------------------------------------------

def _cv_flatten(c: ColumnVector):
    if isinstance(c.data, dict):
        if "codes" in c.data:
            return ((c.data["codes"], c.data["dict_offsets"],
                     c.data["dict_bytes"], c.validity),
                    ("dict", c.dtype, c.dict_unique))
        if "child" in c.data:  # array: offsets + nested child CV
            return ((c.data["offsets"], c.data["child"], c.validity),
                    ("array", c.dtype))
        if "keys" in c.data:  # map: offsets + key/value child CVs
            return ((c.data["offsets"], c.data["keys"], c.data["values"],
                     c.validity), ("map", c.dtype))
        if "children" in c.data:  # struct: per-field child CVs
            return ((tuple(c.data["children"]), c.validity),
                    ("struct", c.dtype))
        return (c.data["offsets"], c.data["bytes"], c.validity), ("str", c.dtype)
    return (c.data, c.validity), ("fixed", c.dtype)


def _cv_unflatten(aux, children):
    kind, dtype = aux[0], aux[1]
    if kind == "dict":
        codes, doff, dby, validity = children
        return ColumnVector(dtype, {"codes": codes, "dict_offsets": doff,
                                    "dict_bytes": dby}, validity,
                            dict_unique=aux[2])
    if kind == "array":
        off, child, validity = children
        return ColumnVector(dtype, {"offsets": off, "child": child}, validity)
    if kind == "map":
        off, keys, values, validity = children
        return ColumnVector(dtype, {"offsets": off, "keys": keys,
                                    "values": values}, validity)
    if kind == "struct":
        kids, validity = children
        return ColumnVector(dtype, {"children": list(kids)}, validity)
    if kind == "str":
        off, by, validity = children
        return ColumnVector(dtype, {"offsets": off, "bytes": by}, validity)
    data, validity = children
    return ColumnVector(dtype, data, validity)


def _lrc_flatten(lz: LazyRowCount):
    return (lz.traced(),), None


def _lrc_unflatten(aux, children):
    v = children[0]
    return v if isinstance(v, int) else LazyRowCount(v)


def _cb_flatten(b: ColumnarBatch):
    return (b.columns, b.num_rows, b.row_mask), None


def _cb_unflatten(aux, children):
    cols, n, row_mask = children
    if not isinstance(n, (int, LazyRowCount)):
        n = LazyRowCount(n)  # raw int leaves come back as device scalars
    return ColumnarBatch(cols, n, row_mask)


jax.tree_util.register_pytree_node(ColumnVector, _cv_flatten, _cv_unflatten)
jax.tree_util.register_pytree_node(LazyRowCount, _lrc_flatten, _lrc_unflatten)
jax.tree_util.register_pytree_node(ColumnarBatch, _cb_flatten, _cb_unflatten)


def empty_like_schema(schema: T.Schema, capacity: int = MIN_CAPACITY) -> ColumnarBatch:
    cols = []
    for f in schema.fields:
        if isinstance(f.dtype, T.StringType):
            data = {"offsets": jnp.zeros(capacity + 1, jnp.int32),
                    "bytes": jnp.zeros(MIN_CAPACITY, jnp.uint8)}
        else:
            data = jnp.zeros(capacity, dtype=f.dtype.np_dtype)
        cols.append(ColumnVector(f.dtype, data, jnp.zeros(capacity, jnp.bool_)))
    return ColumnarBatch(cols, 0)
