"""Avro Object Container File reader (and a minimal writer for tests).

Reference parity: GpuAvroScan.scala + AvroDataFileReader.scala — the
reference ships its own pure-Scala Avro block parser instead of depending
on avro-java; same approach here in Python (fastavro is not in this
environment). Scope: flat record schemas over the Avro primitives
(null/boolean/int/long/float/double/bytes/string), nullable unions
(["null", X] in either order), and the date / timestamp-millis /
timestamp-micros logical types; codecs null and deflate (zlib). Nested
records/arrays/maps are rejected with a clear error.

The decode is host-side (like every text-format scan in this engine) and
lands in a pyarrow Table that uploads through the normal scan path.
"""
from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import List, Optional, Tuple

import numpy as np

MAGIC = b"Obj\x01"


class AvroError(ValueError):
    pass


# ---------------------------------------------------------------------------
# binary decode primitives
# ---------------------------------------------------------------------------

class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos: self.pos + n]
        if len(b) < n:
            raise AvroError("truncated avro data")
        self.pos += n
        return b

    def long(self) -> int:
        """zigzag varint"""
        shift = 0
        acc = 0
        while True:
            if self.pos >= len(self.buf):
                raise AvroError("truncated varint")
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def eof(self) -> bool:
        return self.pos >= len(self.buf)


def _nullable_dec(base, null_index: int):
    """Wrap a decoder for a [null, X] union branch."""
    def dec(r: _Reader):
        if r.long() == null_index:
            return None
        return base(r)
    return dec


def _field_decoder(ftype):
    """Returns (decode_fn(reader)->python value, arrow_type_name)."""
    import pyarrow as pa
    nullable = False
    null_index = 0
    if isinstance(ftype, list):
        # union: support exactly [null, X] / [X, null]
        non_null = [t for t in ftype if t != "null"]
        if len(non_null) != 1 or len(ftype) > 2:
            raise AvroError(f"unsupported avro union {ftype}")
        nullable = len(ftype) == 2
        null_index = ftype.index("null") if "null" in ftype else -1
        ftype = non_null[0]
    logical = None
    if isinstance(ftype, dict) and ftype.get("type") not in ("record",
                                                             "array"):
        logical = ftype.get("logicalType")
        ftype = ftype["type"]

    def base(r: _Reader):
        if ftype == "boolean":
            return r.read(1)[0] != 0
        if ftype in ("int", "long"):
            return r.long()
        if ftype == "float":
            return struct.unpack("<f", r.read(4))[0]
        if ftype == "double":
            return struct.unpack("<d", r.read(8))[0]
        if ftype == "string":
            return r.read(r.long()).decode("utf-8")
        if ftype == "bytes":
            return r.read(r.long())
        if ftype == "null":
            return None
        raise AvroError(f"unsupported avro type {ftype!r}")

    if isinstance(ftype, dict) and ftype.get("type") == "record":
        # nested record -> python dict + arrow struct (Iceberg manifest
        # entries carry a nested data_file record)
        sub = [(f["name"],) + _field_decoder(f["type"])
               for f in ftype["fields"]]

        def base(r: _Reader):  # noqa: F811 - intentional override
            return {name: dec(r) for name, dec, _ in sub}

        at = pa.struct([pa.field(name, t) for name, _, t in sub])
        return (base if not nullable
                else _nullable_dec(base, null_index)), at
    if isinstance(ftype, dict) and ftype.get("type") == "array":
        item_dec, item_t = _field_decoder(ftype["items"])

        def base(r: _Reader):  # noqa: F811 - intentional override
            out = []
            while True:
                n = r.long()
                if n == 0:
                    break
                if n < 0:
                    r.long()  # block byte size (skippable form)
                    n = -n
                for _ in range(n):
                    out.append(item_dec(r))
            return out

        at = pa.list_(item_t)
        return (base if not nullable
                else _nullable_dec(base, null_index)), at
    if ftype == "boolean":
        at = pa.bool_()
    elif ftype == "int":
        at = pa.int32()
    elif ftype == "long":
        at = pa.int64()
    elif ftype == "float":
        at = pa.float32()
    elif ftype == "double":
        at = pa.float64()
    elif ftype in ("string",):
        at = pa.string()
    elif ftype == "bytes":
        at = pa.binary()
    elif ftype == "null":
        at = pa.null()
    else:
        raise AvroError(f"unsupported avro type {ftype!r} (maps are not "
                        f"supported by this reader)")
    if logical == "date" and ftype == "int":
        at = pa.date32()
    elif logical == "timestamp-millis" and ftype == "long":
        at = pa.timestamp("ms")
    elif logical == "timestamp-micros" and ftype == "long":
        at = pa.timestamp("us")

    return (base if not nullable
            else _nullable_dec(base, null_index)), at


def read_avro(path: str):
    """Avro OCF -> pyarrow Table."""
    import pyarrow as pa
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != MAGIC:
        raise AvroError(f"{path}: not an avro object container file")
    r = _Reader(data)
    r.pos = 4
    meta = {}
    while True:
        n = r.long()
        if n == 0:
            break
        if n < 0:  # block with explicit byte size
            r.long()
            n = -n
        for _ in range(n):
            k = r.read(r.long()).decode()
            v = r.read(r.long())
            meta[k] = v
    sync = r.read(16)
    schema = json.loads(meta[b"avro.schema".decode()].decode()
                        if isinstance(meta.get("avro.schema"), bytes)
                        else meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise AvroError(f"unsupported avro codec {codec!r}")
    if schema.get("type") != "record":
        raise AvroError("top-level avro schema must be a record")
    fields = schema["fields"]
    decoders = []
    arrow_fields = []
    for fld in fields:
        dec, at = _field_decoder(fld["type"])
        decoders.append(dec)
        arrow_fields.append(pa.field(fld["name"], at))

    cols: List[list] = [[] for _ in fields]
    while not r.eof():
        count = r.long()
        size = r.long()
        block = r.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        br = _Reader(block)
        for _ in range(count):
            for ci, dec in enumerate(decoders):
                cols[ci].append(dec(br))
        if r.read(16) != sync:
            raise AvroError("avro sync marker mismatch")

    arrays = []
    for vals, fld in zip(cols, arrow_fields):
        if pa.types.is_timestamp(fld.type):
            unit = fld.type.unit
            arrays.append(pa.array(vals, type=pa.timestamp(unit)))
        else:
            arrays.append(pa.array(vals, type=fld.type))
    return pa.Table.from_arrays(arrays, schema=pa.schema(arrow_fields))


# ---------------------------------------------------------------------------
# minimal writer (tests + tooling; the reference is read-only for Avro)
# ---------------------------------------------------------------------------

def _zigzag(v: int) -> bytes:
    v = (v << 1) ^ (v >> 63) if v < 0 else (v << 1)
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def write_avro(path: str, table, codec: str = "null") -> None:
    """pyarrow Table -> Avro OCF (flat primitive schemas)."""
    import pyarrow as pa

    def avro_type(at):
        if pa.types.is_boolean(at):
            return "boolean"
        if pa.types.is_int32(at):
            return "int"
        if pa.types.is_int64(at):
            return "long"
        if pa.types.is_float32(at):
            return "float"
        if pa.types.is_float64(at):
            return "double"
        if pa.types.is_string(at):
            return "string"
        if pa.types.is_binary(at):
            return "bytes"
        if pa.types.is_date32(at):
            return {"type": "int", "logicalType": "date"}
        if pa.types.is_timestamp(at):
            lt = "timestamp-micros" if at.unit == "us" else "timestamp-millis"
            return {"type": "long", "logicalType": lt}
        if pa.types.is_struct(at):
            avro_type._n = getattr(avro_type, "_n", 0) + 1
            return {"type": "record", "name": f"r{avro_type._n}",
                    "fields": [{"name": f.name,
                                "type": ["null", avro_type(f.type)]}
                               for f in at]}
        if pa.types.is_list(at):
            return {"type": "array",
                    "items": ["null", avro_type(at.value_type)]}
        raise AvroError(f"cannot write arrow type {at} to avro")

    schema = {"type": "record", "name": "row", "fields": [
        {"name": f.name, "type": ["null", avro_type(f.type)]}
        for f in table.schema]}

    def enc_val(at, v) -> bytes:
        if pa.types.is_boolean(at):
            return bytes([1 if v else 0])
        if pa.types.is_date32(at):
            import datetime
            if isinstance(v, datetime.date):
                v = (v - datetime.date(1970, 1, 1)).days
            return _zigzag(int(v))
        if pa.types.is_timestamp(at):
            import datetime
            if isinstance(v, datetime.datetime):
                epoch = datetime.datetime(1970, 1, 1, tzinfo=v.tzinfo)
                us = int((v - epoch).total_seconds() * 1_000_000)
                v = us if at.unit == "us" else us // 1000
            return _zigzag(int(v))
        if pa.types.is_int32(at) or pa.types.is_int64(at):
            return _zigzag(int(v))
        if pa.types.is_float32(at):
            return struct.pack("<f", v)
        if pa.types.is_float64(at):
            return struct.pack("<d", v)
        if pa.types.is_string(at):
            b = v.encode("utf-8")
            return _zigzag(len(b)) + b
        if pa.types.is_struct(at):
            # fields mirror the top-level convention: nullable union per
            # field, branch 1 = the value
            out = bytearray()
            for f in at:
                fv = v.get(f.name) if isinstance(v, dict) else None
                if fv is None:
                    out += _zigzag(0)
                else:
                    out += _zigzag(1) + enc_val(f.type, fv)
            return bytes(out)
        if pa.types.is_list(at):
            out = bytearray()
            if v:
                out += _zigzag(len(v))
                for item in v:
                    if item is None:
                        out += _zigzag(0)
                    else:
                        out += _zigzag(1) + enc_val(at.value_type, item)
            out += _zigzag(0)
            return bytes(out)
        b = bytes(v)
        return _zigzag(len(b)) + b

    rows = table.num_rows
    body = bytearray()
    pydata = [table.column(i) for i in range(table.num_columns)]
    for i in range(rows):
        for ci, f in enumerate(table.schema):
            cell = pydata[ci][i]
            if not cell.is_valid:
                body += _zigzag(0)  # union branch: null
            else:
                v = cell.value if pa.types.is_timestamp(f.type) else cell.as_py()
                if pa.types.is_date32(f.type):
                    import datetime
                    v = (cell.as_py() - datetime.date(1970, 1, 1)).days
                body += _zigzag(1) + enc_val(f.type, v)
    payload = bytes(body)
    if codec == "deflate":
        co = zlib.compressobj(wbits=-15)
        payload = co.compress(payload) + co.flush()
    elif codec != "null":
        raise AvroError(f"unsupported codec {codec!r}")

    sync = os.urandom(16)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    out = bytearray(MAGIC)
    out += _zigzag(len(meta))
    for k, v in meta.items():
        kb = k.encode()
        out += _zigzag(len(kb)) + kb + _zigzag(len(v)) + v
    out += _zigzag(0)
    out += sync
    out += _zigzag(rows) + _zigzag(len(payload)) + payload + sync
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(bytes(out))
    os.replace(tmp, path)
