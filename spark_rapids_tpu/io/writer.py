"""Columnar file writer.

Reference parity: ColumnarOutputWriter.scala + GpuFileFormatDataWriter
(dynamic partitioning, per-task part files, maxRecordsPerFile splitting,
_SUCCESS marker) + GpuParquetFileFormat/GpuOrcFileFormat/
GpuHiveFileFormat + BasicColumnarWriteJobStatsTracker (per-write
numFiles/numOutputRows/numOutputBytes/numParts). Device batches download
once per output batch (the C2R boundary) and encode host-side with
pyarrow's native writers; writes go through the ThrottlingExecutor so
buffered output bytes are bounded (reference io/async TrafficController).
"""
from __future__ import annotations

import os
import shutil
from typing import List, Optional

import pyarrow as pa

from spark_rapids_tpu import config as C
from spark_rapids_tpu.io.async_io import ThrottlingExecutor, TrafficController

_FORMATS = ("parquet", "csv", "orc", "json")


def _write_one(table: pa.Table, path: str, fmt: str, options: dict) -> None:
    if fmt == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(table, path,
                       compression=options.get("compression", "snappy"))
    elif fmt == "orc":
        import pyarrow.orc as porc
        porc.write_table(table, path)
    elif fmt == "csv":
        import pyarrow.csv as pcsv
        opts = pcsv.WriteOptions(include_header=options.get("header", True),
                                 delimiter=options.get("sep", ","))
        pcsv.write_csv(table, path, write_options=opts)
    else:  # json lines
        with open(path, "wb") as f:
            for row in table.to_pylist():
                import json
                f.write(json.dumps(row, default=str).encode())
                f.write(b"\n")


def _partition_dirs(table: pa.Table, partition_by: List[str]):
    """Split a table into (subdir, sub_table_without_partition_cols) pairs
    (reference GpuFileFormatDataWriter dynamic partitioning)."""
    import pyarrow.compute as pc
    if not partition_by:
        yield "", table
        return
    keys = table.select(partition_by)
    # unique combos via group_by count
    combos = keys.group_by(partition_by).aggregate([([], "count_all")])
    rest = [n for n in table.schema.names if n not in partition_by]
    for row in combos.select(partition_by).to_pylist():
        mask = None
        for k, v in row.items():
            e = pc.is_null(table[k]) if v is None else pc.equal(table[k], v)
            mask = e if mask is None else pc.and_(mask, e)
        sub = table.filter(mask).select(rest)
        from urllib.parse import quote
        subdir = "/".join(
            f"{k}={'__HIVE_DEFAULT_PARTITION__' if v is None else quote(str(v), safe='')}"
            for k, v in row.items())
        yield subdir, sub


class WriteStats:
    """BasicColumnarWriteJobStatsTracker analog: one per write job,
    readable afterwards via DataFrameWriter.last_write_stats."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self.num_files = 0
        self.num_output_rows = 0
        self.num_output_bytes = 0
        self.partition_dirs = set()

    def record(self, rows: int, nbytes: int, subdir: str) -> None:
        with self._lock:
            self.num_files += 1
            self.num_output_rows += rows
            self.num_output_bytes += nbytes
            if subdir:
                self.partition_dirs.add(subdir)

    def as_dict(self) -> dict:
        return {"numFiles": self.num_files,
                "numOutputRows": self.num_output_rows,
                "numOutputBytes": self.num_output_bytes,
                "numParts": len(self.partition_dirs)}


class DataFrameWriter:
    """df.write.mode(...).partition_by(...).parquet(path) — the writer
    facade (reference GpuDataWritingCommandExec + InsertIntoHadoopFs)."""

    def __init__(self, df):
        self._df = df
        self._mode = "error"
        self._partition_by: List[str] = []
        self._options: dict = {}
        #: stats of the most recent write job (tracker analog)
        self.last_write_stats: Optional[dict] = None

    def mode(self, m: str) -> "DataFrameWriter":
        assert m in ("error", "errorifexists", "overwrite", "append"), m
        self._mode = "error" if m == "errorifexists" else m
        return self

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    partitionBy = partition_by

    def option(self, k: str, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def parquet(self, path: str) -> None:
        self._write(path, "parquet")

    def orc(self, path: str) -> None:
        self._write(path, "orc")

    def csv(self, path: str) -> None:
        self._write(path, "csv")

    def json(self, path: str) -> None:
        self._write(path, "json")

    # -- engine ------------------------------------------------------------

    def _write(self, path: str, fmt: str) -> None:
        assert fmt in _FORMATS
        if os.path.exists(path):
            if self._mode == "error":
                raise FileExistsError(
                    f"path {path} already exists (mode=error)")
            if self._mode == "overwrite":
                shutil.rmtree(path)
        os.makedirs(path, exist_ok=True)

        df = self._df
        session = df.session
        conf = session.conf
        from spark_rapids_tpu.columnar.batch import to_arrow
        from spark_rapids_tpu.runtime.task import TaskContext
        exec_root, _ = session.prepare_execution(df.plan)
        names = df.plan.schema.names
        controller = TrafficController(conf.get(C.ASYNC_WRITE_MAX_INFLIGHT))
        pool = ThrottlingExecutor(conf.get(C.WRITER_THREADS), controller)
        ext = {"parquet": "parquet", "orc": "orc", "csv": "csv",
               "json": "json"}[fmt]
        futures = []
        futures_lock = __import__("threading").Lock()
        # unique suffix per write so append mode never collides
        import uuid
        job = uuid.uuid4().hex[:8]

        stats = WriteStats()
        max_records = int(self._options.get(
            "maxRecordsPerFile", conf.get(C.MAX_RECORDS_PER_FILE)) or 0)

        def write_tracked(sub, fpath, subdir):
            _write_one(sub, fpath, fmt, self._options)
            stats.record(sub.num_rows, os.path.getsize(fpath), subdir)

        def run_partition(p: int) -> None:
            with TaskContext(partition_id=p) as tctx:
                tables = [to_arrow(b, names)
                          for b in exec_root.execute_partition(tctx, p)]
            if not tables:
                return
            table = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
            if table.num_rows == 0:
                return
            for subdir, sub in _partition_dirs(table, self._partition_by):
                d = os.path.join(path, subdir) if subdir else path
                os.makedirs(d, exist_ok=True)
                # maxRecordsPerFile: roll to a new numbered part file
                if max_records > 0 and sub.num_rows > max_records:
                    chunks = [sub.slice(off, min(max_records,
                                                 sub.num_rows - off))
                              for off in range(0, sub.num_rows, max_records)]
                else:
                    chunks = [sub]
                for seq, chunk in enumerate(chunks):
                    fpath = os.path.join(
                        d, f"part-{p:05d}-{seq:04d}-{job}.{ext}")
                    with futures_lock:
                        futures.append(pool.submit(
                            chunk.nbytes, write_tracked, chunk, fpath,
                            subdir))

        try:
            nparts = exec_root.num_partitions
            if nparts == 1:
                run_partition(0)
            else:
                from spark_rapids_tpu.runtime.host_pool import run_task_wave
                run_task_wave(run_partition, range(nparts))
            for f in futures:
                f.result()
            with open(os.path.join(path, "_SUCCESS"), "w"):
                pass
            self.last_write_stats = stats.as_dict()
            # df.write is a fresh builder per access: stash where callers
            # can actually reach them afterwards
            self._df.last_write_stats = self.last_write_stats
            session.last_write_stats = self.last_write_stats
        finally:
            pool.shutdown()
