"""Async write throttling.

Reference parity: io/async/{ThrottlingExecutor,TrafficController}.scala —
writes run on a background pool, but an executor-wide controller caps the
bytes in flight so a burst of tasks cannot exhaust host memory buffering
output files (TrafficController initialized in Plugin.scala:558).
"""
from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional

#: bounded wait slice while blocked on admission: each wakeup re-checks
#: the caller's query cancel token (runtime/lifecycle.py), so a
#: cancelled query's writer unwinds instead of waiting out other
#: queries' releases
_CANCEL_SLICE_S = 0.25


class TrafficController:
    """Blocks producers while more than max_in_flight_bytes of writes are
    buffered/unfinished.

    `stall_warn_s` (None disables) arms a diagnostic: a producer that has
    waited that long without admission fires ONE warning — log line,
    `asyncWriteStalled` trace instant, `rapids_async_write_stalls_total`
    obs counter — then keeps waiting. A writer that never completes
    (wedged filesystem, lost release) previously blocked acquire()
    forever with no signal anywhere; admission semantics are unchanged."""

    def __init__(self, max_in_flight_bytes: int,
                 stall_warn_s: Optional[float] = None):
        self.limit = max_in_flight_bytes
        self.stall_warn_s = stall_warn_s
        from spark_rapids_tpu.analysis import sanitizer as _san
        self._inflight = 0
        self._cv = _san.condition("asyncWrite.controller")

    def _warn_stalled(self, waited_s: float, nbytes: int,
                      inflight: int) -> None:
        """Called WITHOUT self._cv held (`inflight` is the caller's
        snapshot): the diagnostic does logging/trace/obs I/O, and a
        blocked log handler must never hold up writers' release()."""
        import logging

        from spark_rapids_tpu.runtime import obs, trace
        logging.getLogger("spark_rapids_tpu").warning(
            "async write throttle stalled: waited %.1fs for %d bytes "
            "(%d in flight, limit %d) — a writer may be wedged",
            waited_s, nbytes, inflight, self.limit)
        trace.instant("asyncWriteStalled", cat="io", args={
            "waited_s": round(waited_s, 3), "bytes": nbytes,
            "in_flight": inflight, "limit": self.limit},
            level=trace.ESSENTIAL)
        st = obs.state()
        if st is not None:
            try:
                st.registry.counter(
                    "rapids_async_write_stalls_total",
                    "Async-write throttle waits that exceeded the stall "
                    "warning threshold").inc()
            except Exception:  # noqa: BLE001 - diagnostics never fail IO
                pass

    def acquire(self, nbytes: int) -> None:
        import time

        from spark_rapids_tpu.runtime import trace
        t0 = time.perf_counter_ns()
        blocked = False
        warned = False
        with self._cv:
            while self._inflight > 0 and self._inflight + nbytes > self.limit:
                blocked = True
                if self.stall_warn_s is not None and not warned:
                    waited = (time.perf_counter_ns() - t0) / 1e9
                    if waited >= self.stall_warn_s:
                        warned = True
                        inflight = self._inflight
                        # warn with the lock DROPPED: release() must
                        # stay reachable while the diagnostic does I/O
                        self._cv.release()
                        try:
                            self._warn_stalled(waited, nbytes, inflight)
                        finally:
                            self._cv.acquire()
                        continue  # re-check admission: it may have freed
                    # timed wait ONLY until the warning threshold — once
                    # fired (or when disabled), the wait drops to the
                    # bounded cancellation slice below
                    self._cv.wait(timeout=min(
                        self.stall_warn_s - waited, _CANCEL_SLICE_S))
                else:
                    # cancellation-aware bounded slices (TPU-L012): a
                    # cancelled query's writer parked on admission that
                    # OTHER queries' releases control must wake and
                    # unwind, not wait out their drain. Only the blocked
                    # path pays the wakeups; steady state never enters
                    # this loop.
                    self._cv.wait(timeout=_CANCEL_SLICE_S)
                from spark_rapids_tpu.runtime import lifecycle as _lc
                _lc.check_current()
            self._inflight += nbytes
        if blocked:
            trace.instant("asyncWriteThrottled", cat="io", args={
                "blocked_ns": time.perf_counter_ns() - t0,
                "bytes": nbytes})

    def release(self, nbytes: int) -> None:
        with self._cv:
            self._inflight -= nbytes
            self._cv.notify_all()

    @property
    def in_flight(self) -> int:
        with self._cv:
            return self._inflight


class ThrottlingExecutor:
    """Thread pool + TrafficController: submit(task_bytes, fn) blocks until
    the controller admits the bytes; completion releases them.

    Pass `pool` (anything with submit(fn) -> Future, e.g. the process-wide
    host pool) to run tasks on a SHARED executor instead of owning one —
    shutdown() then leaves it alive. Per-writer throwaway executors are
    exactly what runtime/host_pool.py exists to prevent. `max_threads`
    bounds THIS writer's concurrency either way: an owned pool sizes its
    workers by it; on a shared pool submit() blocks on a slot semaphore
    (same admission semantics as the byte controller), so the writer
    cannot fan out wider than its conf across the pool's workers."""

    def __init__(self, max_threads: int, controller: TrafficController,
                 pool=None):
        self._owned = pool is None
        # tpulint: disable=TPU-L002 standalone-writer fallback only: the engine always passes pool= (the shared host pool); an owned executor here serves direct ThrottlingExecutor users (tests, tools) with shutdown() semantics the shared pool must not have
        self.pool = ThreadPoolExecutor(max_workers=max_threads) \
            if pool is None else pool
        self.controller = controller
        self._slots = None if pool is None \
            else threading.BoundedSemaphore(max_threads)

    def submit(self, nbytes: int, fn: Callable, *args) -> Future:
        self.controller.acquire(nbytes)
        if self._slots is not None:
            self._slots.acquire()

        def run():
            from spark_rapids_tpu.runtime import trace
            try:
                with trace.span("asyncWrite", cat="io", level=trace.DEBUG,
                                args={"bytes": nbytes}):
                    return fn(*args)
            finally:
                if self._slots is not None:
                    self._slots.release()
                self.controller.release(nbytes)

        return self.pool.submit(run)

    def shutdown(self, wait: bool = True) -> None:
        if self._owned:
            self.pool.shutdown(wait=wait)
