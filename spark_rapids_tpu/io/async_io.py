"""Async write throttling.

Reference parity: io/async/{ThrottlingExecutor,TrafficController}.scala —
writes run on a background pool, but an executor-wide controller caps the
bytes in flight so a burst of tasks cannot exhaust host memory buffering
output files (TrafficController initialized in Plugin.scala:558).
"""
from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional


class TrafficController:
    """Blocks producers while more than max_in_flight_bytes of writes are
    buffered/unfinished."""

    def __init__(self, max_in_flight_bytes: int):
        self.limit = max_in_flight_bytes
        self._inflight = 0
        self._cv = threading.Condition()

    def acquire(self, nbytes: int) -> None:
        import time

        from spark_rapids_tpu.runtime import trace
        t0 = time.perf_counter_ns()
        blocked = False
        with self._cv:
            while self._inflight > 0 and self._inflight + nbytes > self.limit:
                blocked = True
                self._cv.wait()
            self._inflight += nbytes
        if blocked:
            trace.instant("asyncWriteThrottled", cat="io", args={
                "blocked_ns": time.perf_counter_ns() - t0,
                "bytes": nbytes})

    def release(self, nbytes: int) -> None:
        with self._cv:
            self._inflight -= nbytes
            self._cv.notify_all()

    @property
    def in_flight(self) -> int:
        with self._cv:
            return self._inflight


class ThrottlingExecutor:
    """Thread pool + TrafficController: submit(task_bytes, fn) blocks until
    the controller admits the bytes; completion releases them."""

    def __init__(self, max_threads: int, controller: TrafficController):
        self.pool = ThreadPoolExecutor(max_workers=max_threads)
        self.controller = controller

    def submit(self, nbytes: int, fn: Callable, *args) -> Future:
        self.controller.acquire(nbytes)

        def run():
            from spark_rapids_tpu.runtime import trace
            try:
                with trace.span("asyncWrite", cat="io", level=trace.DEBUG,
                                args={"bytes": nbytes}):
                    return fn(*args)
            finally:
                self.controller.release(nbytes)

        return self.pool.submit(run)

    def shutdown(self, wait: bool = True) -> None:
        self.pool.shutdown(wait=wait)
