"""I/O subsystem: format scans, columnar writers, async throttling.

Reference parity: SURVEY.md §2.6 — GpuParquetScan/GpuOrcScan/GpuCSVScan
multi-file reading, ColumnarOutputWriter, io/async/{AsyncOutputStream,
ThrottlingExecutor,TrafficController}.
"""
