"""I/O subsystem: format scans, columnar writers, async throttling.

Reference parity: SURVEY.md §2.6 — GpuParquetScan/GpuOrcScan/GpuCSVScan
multi-file reading, ColumnarOutputWriter, io/async/{AsyncOutputStream,
ThrottlingExecutor,TrafficController}.
"""
from __future__ import annotations

from typing import Optional, Sequence


def read_parquet_file(path: str, columns: Optional[Sequence[str]] = None):
    """Read ONE parquet file with no dataset-level magic. pyarrow >= 13's
    `pq.read_table(path)` routes through the dataset API, which infers
    hive partition columns from `k=v` segments anywhere in the path —
    so a lore dump under `loreId=0/...` grows a phantom `loreId` column
    and a partition-file read duplicates the partition key the scan
    appends itself. `ParquetFile.read` is the file-scoped reader."""
    import pyarrow.parquet as pq
    # [] is a real projection (zero data columns, e.g. a partition-key-
    # only select): only None means "all columns"
    return pq.ParquetFile(path).read(
        columns=None if columns is None else list(columns))
