"""Footer-statistics row-group pruning and partition-value file pruning.

Reference parity: GpuParquetScan.scala:673 ``filterBlocks`` (row groups
whose column min/max statistics cannot satisfy the pushed-down predicate
are never read) and Spark's partition pruning for hive-layout directories.

The evaluator is a conservative tri-state interval check: a conjunct may
only drop a row group when the statistics PROVE no row can satisfy it
under this engine's (IEEE) comparison semantics. Anything unrecognized —
an expression shape outside the supported set, a missing statistic, a
type mismatch — keeps the group. NaN note: parquet writers exclude NaN
from float min/max stats, and NaN fails every IEEE comparison, so pruning
comparisons by min/max stays sound for float columns.
"""
from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E


class _ColStats:
    __slots__ = ("min", "max", "null_count", "num_values", "all_null")

    def __init__(self, min_v, max_v, null_count, num_values):
        self.min = min_v
        self.max = max_v
        self.null_count = null_count
        self.num_values = num_values
        self.all_null = (null_count is not None and num_values is not None
                         and null_count >= num_values)


def _normalize(v):
    """Bring a stats/literal value into a directly comparable python form."""
    if isinstance(v, datetime.datetime):
        # naive means UTC (Literal._scalar convention); tz-aware converts
        # to UTC first — stripping tzinfo directly would compare wall-clock
        # in the literal's zone against UTC footer stats
        if v.tzinfo is not None:
            v = v.astimezone(datetime.timezone.utc).replace(tzinfo=None)
        return ("ts", v)
    if isinstance(v, datetime.date):
        return ("date", v)
    if isinstance(v, bool):
        return ("num", int(v))
    if isinstance(v, (int, float)):
        return ("num", v)
    if isinstance(v, str):
        return ("str", v)
    if isinstance(v, bytes):
        try:
            return ("str", v.decode("utf-8"))
        except UnicodeDecodeError:
            return None
    return None


def _cmp_pair(a, b) -> Optional[Tuple]:
    na, nb = _normalize(a), _normalize(b)
    if na is None or nb is None or na[0] != nb[0]:
        return None
    return na[1], nb[1]


def _ref_and_lit(e: E.Expression):
    """Match `col <op> lit` / `lit <op> col`; returns (name, value, flipped)."""
    l, r = e.children
    if isinstance(l, E.BoundRef) and isinstance(r, E.Literal):
        return l.name, r.value, False
    if isinstance(l, E.Literal) and isinstance(r, E.BoundRef):
        return r.name, l.value, True
    return None


def _may_match(e: E.Expression, stats: Dict[str, _ColStats]) -> bool:
    """True unless the statistics prove no row in the group satisfies e."""
    if isinstance(e, E.And):
        return all(_may_match(c, stats) for c in e.children)
    if isinstance(e, E.Or):
        return any(_may_match(c, stats) for c in e.children)
    if isinstance(e, E.IsNull):
        c = e.children[0]
        if isinstance(c, E.BoundRef) and c.name in stats:
            s = stats[c.name]
            return s.null_count is None or s.null_count > 0
        return True
    if isinstance(e, E.IsNotNull):
        c = e.children[0]
        if isinstance(c, E.BoundRef) and c.name in stats:
            return not stats[c.name].all_null
        return True
    if isinstance(e, E.In):
        c = e.children[0]
        vals = e.children[1:]
        if isinstance(c, E.BoundRef) and c.name in stats \
                and all(isinstance(v, E.Literal) for v in vals):
            s = stats[c.name]
            if s.all_null:
                return False
            if s.min is None or s.max is None:
                return True
            ok = []
            for v in vals:
                if v.value is None:
                    ok.append(False)  # col IN (NULL) is never true
                    continue
                pair = _cmp_pair(s.min, v.value)
                hi_pair = _cmp_pair(s.max, v.value)
                if pair is None or hi_pair is None:
                    return True  # incomparable element: keep
                lo, vv = pair
                ok.append(lo <= vv <= hi_pair[0])
            return any(ok)
        return True
    op = type(e).__name__
    if op in ("EqualTo", "LessThan", "LessThanOrEqual", "GreaterThan",
              "GreaterThanOrEqual"):
        m = _ref_and_lit(e)
        if m is None:
            return True
        name, lit, flipped = m
        if lit is None:
            return False  # comparison with NULL is never true
        s = stats.get(name)
        if s is None:
            return True
        if s.all_null:
            return False
        if s.min is None or s.max is None:
            return True
        pair_lo = _cmp_pair(s.min, lit)
        pair_hi = _cmp_pair(s.max, lit)
        if pair_lo is None or pair_hi is None:
            return True
        lo, v = pair_lo
        hi, _ = pair_hi
        if flipped:  # lit <op> col  ==  col <flip(op)> lit
            op = {"LessThan": "GreaterThan", "GreaterThan": "LessThan",
                  "LessThanOrEqual": "GreaterThanOrEqual",
                  "GreaterThanOrEqual": "LessThanOrEqual",
                  "EqualTo": "EqualTo"}[op]
        if op == "EqualTo":
            return lo <= v <= hi
        if op == "LessThan":
            return lo < v
        if op == "LessThanOrEqual":
            return lo <= v
        if op == "GreaterThan":
            return hi > v
        if op == "GreaterThanOrEqual":
            return hi >= v
    return True


def split_conjuncts(e: E.Expression) -> List[E.Expression]:
    if isinstance(e, E.And):
        out = []
        for c in e.children:
            out.extend(split_conjuncts(c))
        return out
    return [e]


def _group_stats(md_rg) -> Dict[str, _ColStats]:
    out: Dict[str, _ColStats] = {}
    for ci in range(md_rg.num_columns):
        col = md_rg.column(ci)
        name = col.path_in_schema
        if "." in name:
            # nested leaf (struct field / list element): its value-level
            # stats do not describe the root column's rows — attributing
            # them to the root makes IsNull/IsNotNull pruning unsound.
            # Unknown columns keep the group (the module's contract).
            continue
        st = col.statistics
        if st is None:
            out[name] = _ColStats(None, None, None, None)
            continue
        mn = st.min if st.has_min_max else None
        mx = st.max if st.has_min_max else None
        nulls = st.null_count if st.has_null_count else None
        out[name] = _ColStats(mn, mx, nulls, md_rg.num_rows)
    return out


def prune_row_groups(metadata, filters: Sequence[E.Expression]
                     ) -> Tuple[List[int], int]:
    """Returns (kept_group_indices, total_groups) for one file footer."""
    total = metadata.num_row_groups
    if not filters:
        return list(range(total)), total
    kept = []
    for g in range(total):
        stats = _group_stats(metadata.row_group(g))
        if all(_may_match(f, stats) for f in filters):
            kept.append(g)
    return kept, total


def prune_partition_file(partition_values: Dict[str, Optional[str]],
                         schema, filters: Sequence[E.Expression]) -> bool:
    """False when a file's hive partition values refute a pushed conjunct.
    Partition values arrive as strings (or None); they are cast to the
    scan schema's column type before the interval check."""
    stats: Dict[str, _ColStats] = {}
    for k, v in partition_values.items():
        if v is None:
            stats[k] = _ColStats(None, None, 1, 1)
            continue
        dt = None
        for f in schema.fields:
            if f.name == k:
                dt = f.dtype
        pv: object = v
        try:
            if isinstance(dt, (T.Int8Type, T.Int16Type, T.Int32Type,
                               T.Int64Type)):
                pv = int(v)
            elif isinstance(dt, (T.Float32Type, T.Float64Type)):
                pv = float(v)
            elif isinstance(dt, T.DateType):
                pv = datetime.date.fromisoformat(v)
            elif isinstance(dt, T.BooleanType):
                pv = v.lower() == "true"
        except ValueError:
            pass
        stats[k] = _ColStats(pv, pv, 0, 1)
    return all(_may_match(f, stats) for f in filters)
