"""Raw-encoded Parquet column-chunk extraction for device-side decode.

Reference parity: the reference's lowest layer decodes Parquet ON the
accelerator — libcudf's GPU reader (spark-rapids-jni scan path) parses
page headers host-side but runs dictionary/RLE/delta decode as GPU
kernels over the raw chunk bytes. PR 13's roofline verdicts showed the
TPU engine memory-bound at ~1% of HBM peak on scan-heavy NDS probes
because decode happened on the HOST (pyarrow) and batches crossed the
link as fully decoded planes. This module is the TPU analog of the cuDF
reader's front half: it extracts the still-encoded dictionary/RLE/
bit-packed/delta bytes of each column chunk (plus definition levels for
nulls) into compact, bucket-padded device planes; ops/pallas_decode.py
is the back half that expands them on device inside the fused stage
body.

Layering:

- **Host keeps the control plane.** Footer metadata, thrift compact
  PageHeaders and page decompression (snappy/gzip via pa.Codec — the
  container has no zstd) stay on host: they are tiny, branchy, and
  byte-serial. Everything O(rows) ships encoded.
- **RLE/bit-packed hybrids become run tables.** A hybrid stream parses
  into per-run records (output start/length, RLE value or bit-pool
  offset, bit width) whose host cost is O(#runs), not O(#values). The
  device expands runs with a vectorized searchsorted + bit-gather
  (pallas_decode.expand_runs) — the prefix-sum formulation of cuDF's
  warp-cooperative RLE decoder.
- **Per-column fallback, not per-file.** A column whose physical type /
  encoding / codec is outside the supported set host-decodes through
  the existing pyarrow path into a ready ColumnVector that rides INSIDE
  the EncodedBatch (kind "decoded"), so one scan freely mixes device-
  and host-decoded columns and the fallback reason is surfaced in
  explain/history (exec/tpu_nodes.DeviceDecodeScanExec).

Supported today (the dominant NDS shapes): flat required/optional
columns (max_def <= 1, max_rep == 0) of fixed-width physical types
(INT32/INT64/FLOAT/DOUBLE/BOOLEAN) under PLAIN, PLAIN_DICTIONARY /
RLE_DICTIONARY, RLE (booleans) and DELTA_BINARY_PACKED encodings in
data page v1. Everything else — strings, decimals (FLBA), INT96,
nested, data page v2, unknown codecs — falls back per column.
"""
from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.runtime import shapes as _shapes

# -- parquet wire enums -----------------------------------------------------

PAGE_DATA = 0
PAGE_DICT = 2
PAGE_DATA_V2 = 3

ENC_PLAIN = 0
ENC_PLAIN_DICTIONARY = 2
ENC_RLE = 3
ENC_DELTA_BINARY_PACKED = 5
ENC_RLE_DICTIONARY = 8

_ENC_NAMES = {0: "PLAIN", 2: "PLAIN_DICTIONARY", 3: "RLE", 4: "BIT_PACKED",
              5: "DELTA_BINARY_PACKED", 6: "DELTA_LENGTH_BYTE_ARRAY",
              7: "DELTA_BYTE_ARRAY", 8: "RLE_DICTIONARY",
              9: "BYTE_STREAM_SPLIT"}

#: physical type -> (bytes per value, raw little-endian numpy dtype)
_PHYS = {"INT32": (4, np.dtype("<i4")), "INT64": (8, np.dtype("<i8")),
         "FLOAT": (4, np.dtype("<f4")), "DOUBLE": (8, np.dtype("<f8")),
         "BOOLEAN": (0, np.dtype(np.bool_))}

#: int32 sentinel padding run-table cum planes so searchsorted never
#: lands a live row in the padded tail
_CUM_SENTINEL = np.int32(2**31 - 1)


class Unsupported(Exception):
    """This column cannot take the device-decode path; the message is the
    per-column fallback reason surfaced in explain/history."""


# ---------------------------------------------------------------------------
# Thrift compact protocol (PageHeader lives outside the pyarrow API surface:
# the footer tells us where a chunk STARTS, but page boundaries/encodings
# are only in the per-page headers, hand-parsed here)
# ---------------------------------------------------------------------------

class _Compact:
    """Minimal thrift compact-protocol struct reader over a memoryview."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def uvarint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self._byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7
            if shift > 63:
                raise Unsupported("malformed thrift varint")

    def zigzag(self) -> int:
        v = self.uvarint()
        return (v >> 1) ^ -(v & 1)

    def _value(self, wtype: int):
        if wtype == 1:
            return True
        if wtype == 2:
            return False
        if wtype == 3:  # single signed byte
            v = self._byte()
            return v - 256 if v >= 128 else v
        if wtype in (4, 5, 6):  # i16/i32/i64: zigzag varints
            return self.zigzag()
        if wtype == 7:  # double: 8 LE bytes
            v = struct.unpack_from("<d", self.buf, self.pos)[0]
            self.pos += 8
            return v
        if wtype == 8:  # binary: length-prefixed bytes
            n = self.uvarint()
            v = bytes(self.buf[self.pos: self.pos + n])
            self.pos += n
            return v
        if wtype in (9, 10):
            return self._list()
        if wtype == 11:
            return self._map()
        if wtype == 12:
            return self.read_struct()
        raise Unsupported(f"thrift compact wire type {wtype}")

    def _list(self):
        h = self._byte()
        n = h >> 4
        et = h & 0x0F
        if n == 15:
            n = self.uvarint()
        if et in (1, 2):  # bools are one byte each inside containers
            out = [self._byte() == 1 for _ in range(n)]
        else:
            out = [self._value(et) for _ in range(n)]
        return out

    def _map(self):
        n = self.uvarint()
        if n == 0:
            return {}
        kv = self._byte()
        kt, vt = kv >> 4, kv & 0x0F
        return {self._value(kt): self._value(vt) for _ in range(n)}

    def read_struct(self) -> Dict[int, object]:
        fields: Dict[int, object] = {}
        fid = 0
        while True:
            h = self._byte()
            if h == 0:
                return fields
            delta = h >> 4
            wtype = h & 0x0F
            fid = fid + delta if delta else self.zigzag()
            fields[fid] = self._value(wtype)


class _PageHeader:
    __slots__ = ("type", "uncompressed", "compressed", "num_values",
                 "encoding", "def_encoding", "end")


def _read_page_header(view, pos: int) -> _PageHeader:
    rd = _Compact(view, pos)
    f = rd.read_struct()
    ph = _PageHeader()
    ph.type = f.get(1)
    ph.uncompressed = f.get(2)
    ph.compressed = f.get(3)
    ph.end = rd.pos  # first byte of the page payload
    ph.num_values = None
    ph.encoding = None
    ph.def_encoding = None
    if ph.type == PAGE_DATA and isinstance(f.get(5), dict):
        hdr = f[5]
        ph.num_values = hdr.get(1)
        ph.encoding = hdr.get(2)
        ph.def_encoding = hdr.get(3)
    elif ph.type == PAGE_DICT and isinstance(f.get(7), dict):
        hdr = f[7]
        ph.num_values = hdr.get(1)
        ph.encoding = hdr.get(2)
    if ph.type is None or ph.compressed is None:
        raise Unsupported("malformed page header")
    return ph


def _uvarint(view, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = view[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 63:
            raise Unsupported("malformed varint")


def _svarint(view, pos: int) -> Tuple[int, int]:
    v, pos = _uvarint(view, pos)
    return (v >> 1) ^ -(v & 1), pos


# ---------------------------------------------------------------------------
# Host-side run/stream accumulators (cost O(#runs), never O(#values))
# ---------------------------------------------------------------------------

class _Runs:
    """One RLE/bit-packed hybrid stream as a run table + shared bit pool.
    Coalescing row groups/pages is concatenation with offset bumps."""

    __slots__ = ("start", "length", "value", "base", "width", "packed",
                 "bitbase", "pool", "total")

    def __init__(self):
        self.start: List[int] = []
        self.length: List[int] = []
        self.value: List[int] = []
        self.base: List[int] = []
        self.width: List[int] = []
        self.packed: List[bool] = []
        self.bitbase: List[int] = []
        self.pool = bytearray()
        self.total = 0  # values encoded so far == next output offset

    def add_rle(self, n: int, value: int, width: int, base: int) -> None:
        self.start.append(self.total)
        self.length.append(n)
        self.value.append(value)
        self.base.append(base)
        self.width.append(width)
        self.packed.append(False)
        self.bitbase.append(0)
        self.total += n

    def add_packed(self, n: int, data, width: int, base: int) -> None:
        self.start.append(self.total)
        self.length.append(n)
        self.value.append(0)
        self.base.append(base)
        self.width.append(width)
        self.packed.append(True)
        self.bitbase.append(len(self.pool) * 8)
        self.pool += data
        self.total += n


def _parse_hybrid(view, pos: int, end: int, width: int, count: int,
                  runs: _Runs, base: int = 0) -> int:
    """Consume `count` values of one RLE/bit-packed hybrid stream starting
    at `pos`; returns the position after the consumed bytes."""
    if width == 0:
        # width-0 streams carry no bytes: every value is 0
        if count:
            runs.add_rle(count, 0, 0, base)
        return pos
    if width > 32:
        raise Unsupported(f"RLE bit width {width} > 32")
    remaining = count
    vbytes = (width + 7) // 8
    while remaining > 0:
        if pos >= end:
            raise Unsupported("truncated RLE/bit-packed stream")
        header, pos = _uvarint(view, pos)
        if header & 1:  # bit-packed groups of 8 values
            groups = header >> 1
            nbytes = groups * width
            if pos + nbytes > end:
                raise Unsupported("truncated bit-packed run")
            n = min(groups * 8, remaining)
            runs.add_packed(n, view[pos: pos + nbytes], width, base)
            pos += nbytes
        else:  # RLE run
            run = header >> 1
            if run <= 0:
                raise Unsupported("zero-length RLE run")
            if pos + vbytes > end:
                raise Unsupported("truncated RLE run value")
            v = int.from_bytes(view[pos: pos + vbytes], "little")
            pos += vbytes
            n = min(run, remaining)
            runs.add_rle(n, v, width, base)
        remaining -= n
    return pos


def _valid_count(view, start: int, end: int, count: int) -> Tuple[_Runs, int]:
    """Parse a definition-level hybrid (width 1) and return (runs,
    non-null count). The popcount is the one O(values/8) host touch —
    needed because data page v1 headers do not carry a null count and the
    value stream length depends on it."""
    runs = _Runs()
    _parse_hybrid(view, start, end, 1, count, runs)
    nnz = 0
    for i in range(len(runs.start)):
        if runs.packed[i]:
            b0 = runs.bitbase[i] // 8
            nbits = runs.length[i]
            chunk = np.frombuffer(runs.pool, np.uint8,
                                  count=(nbits + 7) // 8, offset=b0)
            nnz += int(np.unpackbits(chunk, bitorder="little")[:nbits].sum())
        elif runs.value[i] == 1:
            nnz += runs.length[i]
    return runs, nnz


class _Delta:
    """DELTA_BINARY_PACKED streams: per-stream (page) header records plus
    a global miniblock table. Each page is an independent delta sequence
    (its own first value); the device restarts the cumulative sum at
    stream boundaries, so multi-page and coalesced multi-group chunks
    decode in one pass."""

    __slots__ = ("s_start", "s_count", "s_first", "s_mbbase",
                 "mb_width", "mb_bitbase", "mb_min", "pool", "vpm", "total")

    def __init__(self):
        self.s_start: List[int] = []
        self.s_count: List[int] = []
        self.s_first: List[int] = []
        self.s_mbbase: List[int] = []
        self.mb_width: List[int] = []
        self.mb_bitbase: List[int] = []
        self.mb_min: List[int] = []
        self.pool = bytearray()
        self.vpm: Optional[int] = None
        self.total = 0


def _parse_delta(view, pos: int, end: int, expected: int, dl: _Delta,
                 max_bits: int) -> None:
    """One DELTA_BINARY_PACKED page payload -> one stream record."""
    block, pos = _uvarint(view, pos)
    mbs, pos = _uvarint(view, pos)
    total, pos = _uvarint(view, pos)
    first, pos = _svarint(view, pos)
    if mbs <= 0 or block % mbs:
        raise Unsupported("malformed delta header")
    vpm = block // mbs
    if dl.vpm is None:
        dl.vpm = vpm
    elif dl.vpm != vpm:
        raise Unsupported("delta miniblock size varies across pages")
    if total != expected:
        raise Unsupported("delta stream count mismatch")
    dl.s_start.append(dl.total)
    dl.s_count.append(total)
    dl.s_first.append(first)
    dl.s_mbbase.append(len(dl.mb_width))
    dl.total += total
    remaining = total - 1 if total > 0 else 0
    while remaining > 0:
        if pos >= end:
            raise Unsupported("truncated delta stream")
        mind, pos = _svarint(view, pos)
        widths = bytes(view[pos: pos + mbs])
        if len(widths) < mbs:
            raise Unsupported("truncated delta bit widths")
        pos += mbs
        for w in widths:
            if remaining <= 0:
                break  # trailing miniblocks of the last block are omitted
            if w > max_bits or w > 32:
                raise Unsupported(f"delta bit width {w} > {min(max_bits, 32)}")
            nbytes = vpm * w // 8
            if pos + nbytes > end:
                raise Unsupported("truncated delta miniblock")
            dl.mb_width.append(w)
            dl.mb_bitbase.append(len(dl.pool) * 8)
            dl.mb_min.append(mind)
            dl.pool += view[pos: pos + nbytes]
            pos += nbytes
            remaining -= min(vpm, remaining)


# ---------------------------------------------------------------------------
# Encoded device currency (pytree-registered: rides through fused traces)
# ---------------------------------------------------------------------------

class EncodedColumn:
    """One column's still-encoded device planes plus static decode recipe.

    kind:
      - "dict":  run table + bit pool of dictionary codes, PLAIN-decoded
                 vocab plane (codes gather through it on device)
      - "plain": raw little-endian value bytes of the non-null values
      - "bool":  bit-packed booleans as a run table (PLAIN bools are one
                 packed run per page; RLE bools map 1:1)
      - "delta": DELTA_BINARY_PACKED miniblock table + bit pool
      - "decoded": host-decoded fallback — a ready ColumnVector rides
                 through the trace untouched
    planes: dict name -> device array (see pallas_decode for the decode
    math). Optional validity planes (prefix "d_") hold the definition-
    level run table; absent means no nulls. meta is the static aux tuple
    (hashable: it keys retraces). bounds are host-side (min, max) footer
    stats for int-family columns — NOT pytree leaves, same contract as
    ColumnVector.bounds.
    """

    __slots__ = ("kind", "dtype", "planes", "meta", "cv", "bounds")

    def __init__(self, kind: str, dtype, planes: Dict[str, object],
                 meta: Tuple = (), cv=None, bounds=None):
        self.kind = kind
        self.dtype = dtype
        self.planes = planes
        self.meta = meta
        self.cv = cv
        self.bounds = bounds

    def device_memory_size(self) -> int:
        if self.kind == "decoded":
            return self.cv.device_memory_size()
        total = 0
        for a in self.planes.values():
            total += int(np.prod(a.shape)) * a.dtype.itemsize
        return total

    def decoded_size(self, cap: int) -> int:
        """Bytes the device decode MATERIALIZES for this column at batch
        capacity `cap` — the decodedBytes numerator beside encodedBytes
        (what actually crossed the host->device link)."""
        if self.kind == "decoded":
            return self.cv.device_memory_size()
        item = 1 if isinstance(self.dtype, T.BooleanType) \
            else np.dtype(self.dtype.np_dtype).itemsize
        has_nulls = bool(dict(self.meta).get("nulls"))
        return cap * item + (cap if has_nulls else 0)


class EncodedBatch:
    """A set of encoded columns covering the same `num_rows` rows. The
    row capacity is static aux (encoded plane shapes do not imply it);
    num_rows is a traced leaf exactly like ColumnarBatch. `columns`
    exposes per-column `.bounds` so FusedStageExec._carry_bounds reads
    uniformly across encoded and decoded inputs."""

    __slots__ = ("columns", "num_rows", "cap")

    def __init__(self, columns: List[EncodedColumn], num_rows, cap: int):
        self.columns = columns
        self.num_rows = num_rows
        self.cap = cap

    @property
    def capacity(self) -> int:
        return self.cap

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def device_memory_size(self) -> int:
        return sum(c.device_memory_size() for c in self.columns)

    def decoded_size(self) -> int:
        return sum(c.decoded_size(self.cap) for c in self.columns)


def _ec_flatten(c: EncodedColumn):
    if c.kind == "decoded":
        return (c.cv,), ("decoded", c.dtype, c.meta, ())
    keys = tuple(sorted(c.planes))
    return tuple(c.planes[k] for k in keys), (c.kind, c.dtype, c.meta, keys)


def _ec_unflatten(aux, children):
    kind, dtype, meta, keys = aux
    if kind == "decoded":
        return EncodedColumn(kind, dtype, {}, meta, cv=children[0])
    return EncodedColumn(kind, dtype, dict(zip(keys, children)), meta)


def _eb_flatten(b: EncodedBatch):
    return (b.columns, b.num_rows), (b.cap,)


def _eb_unflatten(aux, children):
    cols, n = children
    if not isinstance(n, int):
        from spark_rapids_tpu.columnar.batch import LazyRowCount
        if not isinstance(n, LazyRowCount):
            n = LazyRowCount(n)
    return EncodedBatch(list(cols), n, aux[0])


def _register_pytrees() -> None:
    import jax
    jax.tree_util.register_pytree_node(EncodedColumn, _ec_flatten,
                                       _ec_unflatten)
    jax.tree_util.register_pytree_node(EncodedBatch, _eb_flatten,
                                       _eb_unflatten)


_register_pytrees()


# ---------------------------------------------------------------------------
# Plane assembly: host accumulators -> bucket-padded numpy planes
# ---------------------------------------------------------------------------

def _pad32(arr: np.ndarray, cap: int, fill=0) -> np.ndarray:
    out = np.full(cap, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def _pool_plane(pool: bytearray) -> np.ndarray:
    cap = _shapes.bucket_pool_bytes(len(pool))
    out = np.zeros(cap, np.uint8)
    out[: len(pool)] = np.frombuffer(pool, np.uint8)
    return out


def _run_planes(runs: _Runs, prefix: str = "",
                with_width: bool = True) -> Dict[str, np.ndarray]:
    s = len(runs.start)
    # s + 1: at least one sentinel slot so positions past the encoded
    # total always land on a zero pad run, never a live run's tail
    cap = _shapes.bucket_rows(s + 1, 8, 4)
    cum = np.cumsum(np.asarray(runs.length, np.int64)).astype(np.int32) \
        if s else np.zeros(0, np.int32)
    planes = {
        prefix + "cum": _pad32(cum, cap, _CUM_SENTINEL),
        prefix + "start": _pad32(np.asarray(runs.start, np.int32), cap),
        prefix + "val": _pad32(np.asarray(runs.value, np.int32), cap),
        prefix + "packed": _pad32(np.asarray(runs.packed, np.bool_), cap,
                                  False),
        prefix + "bitbase": _pad32(np.asarray(runs.bitbase, np.int64), cap),
        prefix + "pool": _pool_plane(runs.pool),
    }
    if with_width:
        planes[prefix + "width"] = _pad32(
            np.asarray(runs.width, np.int32), cap)
        planes[prefix + "base"] = _pad32(
            np.asarray(runs.base, np.int32), cap)
    return planes


def _delta_planes(dl: _Delta) -> Dict[str, np.ndarray]:
    s = len(dl.s_start)
    scap = _shapes.bucket_rows(s + 1, 8, 4)  # ensure a sentinel slot
    m = len(dl.mb_width)
    mcap = _shapes.bucket_rows(m + 1, 8, 4)
    cum = np.cumsum(np.asarray(dl.s_count, np.int64)).astype(np.int32) \
        if s else np.zeros(0, np.int32)
    return {
        "s_cum": _pad32(cum, scap, _CUM_SENTINEL),
        "s_start": _pad32(np.asarray(dl.s_start, np.int32), scap),
        "s_first": _pad32(np.asarray(dl.s_first, np.int64), scap),
        "s_mbbase": _pad32(np.asarray(dl.s_mbbase, np.int32), scap),
        "mb_width": _pad32(np.asarray(dl.mb_width, np.int32), mcap),
        "mb_bitbase": _pad32(np.asarray(dl.mb_bitbase, np.int64), mcap),
        "mb_min": _pad32(np.asarray(dl.mb_min, np.int64), mcap),
        "pool": _pool_plane(dl.pool),
    }


# ---------------------------------------------------------------------------
# Per-column chunk extraction
# ---------------------------------------------------------------------------

#: engine leaf types eligible for device decode, with the raw-value
#: cast applied after bit reassembly (pallas_decode._finish_values)
_SUPPORTED_TYPES = (T.Int8Type, T.Int16Type, T.Int32Type, T.Int64Type,
                    T.Float32Type, T.Float64Type, T.BooleanType,
                    T.DateType, T.TimestampType)


def _codec(name: str):
    import pyarrow as pa
    name = (name or "UNCOMPRESSED").upper()
    if name == "UNCOMPRESSED":
        return None
    try:
        codec = pa.Codec(name.lower())
    except Exception as ex:  # noqa: BLE001 - unknown/unbuilt codec
        raise Unsupported(f"codec {name} unavailable: {ex}")
    return codec


def check_column_static(schema_col, col_md, dtype) -> None:
    """Static (footer-only) support screen; raises Unsupported with the
    fallback reason. Page-level surprises are caught later, per chunk."""
    if not isinstance(dtype, _SUPPORTED_TYPES):
        raise Unsupported(f"type {type(dtype).__name__} not device-decodable")
    if schema_col.max_repetition_level != 0:
        raise Unsupported("repeated (nested) column")
    if schema_col.max_definition_level > 1:
        raise Unsupported(
            f"max_definition_level {schema_col.max_definition_level} > 1")
    phys = str(col_md.physical_type).upper()
    if phys not in _PHYS:
        raise Unsupported(f"physical type {phys} not device-decodable")
    if isinstance(dtype, T.TimestampType):
        lt = str(getattr(schema_col, "logical_type", "")).upper()
        if "TIMESTAMP" in lt and "MICROS" not in lt:
            raise Unsupported(f"timestamp unit not micros ({lt})")
    _codec(str(col_md.compression))


class _ColumnBuilder:
    """Accumulates ONE logical column's encoded planes across the row
    groups coalesced into a batch."""

    def __init__(self, name: str, dtype, max_def: int, max_bits: int,
                 delta_enabled: bool):
        self.name = name
        self.dtype = dtype
        self.max_def = max_def
        self.max_bits = max_bits
        self.delta_enabled = delta_enabled
        self.kind: Optional[str] = None
        self.runs = _Runs()         # dict codes / bool bits
        self.delta = _Delta()
        self.plain = bytearray()    # PLAIN fixed-width value bytes
        self.dlv = _Runs()          # definition-level runs (width 1)
        self.has_nulls = False
        self.vocab: List[np.ndarray] = []
        self.vocab_size = 0
        self.nnz = 0
        self.rows = 0
        self.phys_width = 0
        self.bounds: Optional[Tuple[int, int]] = None

    def _set_kind(self, kind: str) -> None:
        if self.kind is None:
            self.kind = kind
        elif self.kind != kind:
            raise Unsupported(
                f"mixed encodings across pages ({self.kind} vs {kind})")

    def _merge_bounds(self, st) -> None:
        if st is None or not st.has_min_max:
            self.bounds = None
            return
        if not isinstance(self.dtype, (T.Int8Type, T.Int16Type, T.Int32Type,
                                       T.Int64Type, T.DateType)):
            self.bounds = None
            return
        if self.rows == 0 or self.bounds is not None:
            try:
                lo, hi = int(st.min), int(st.max)
            except (TypeError, ValueError):
                self.bounds = None
                return
            if self.rows == 0:
                self.bounds = (lo, hi)
            else:
                self.bounds = (min(self.bounds[0], lo),
                               max(self.bounds[1], hi))

    def add_group(self, raw: memoryview, col_md, phys_width: int,
                  raw_dtype: np.dtype) -> None:
        """Parse one row group's column chunk (raw = the chunk's bytes,
        page headers + compressed payloads)."""
        codec = _codec(str(col_md.compression))
        self.phys_width = phys_width
        self._merge_bounds(col_md.statistics)
        group_rows = 0
        vocab_base = self.vocab_size
        saw_dict = False
        pos = 0
        expect = col_md.num_values
        while group_rows < expect:
            ph = _read_page_header(raw, pos)
            payload = raw[ph.end: ph.end + ph.compressed]
            pos = ph.end + ph.compressed
            if ph.type == PAGE_DATA_V2:
                raise Unsupported("data page v2")
            if ph.type not in (PAGE_DATA, PAGE_DICT):
                continue  # index pages etc: skip
            if codec is not None:
                payload = memoryview(
                    codec.decompress(payload, ph.uncompressed))
            if ph.type == PAGE_DICT:
                if ph.encoding not in (ENC_PLAIN, ENC_PLAIN_DICTIONARY):
                    raise Unsupported(
                        "dictionary page encoding "
                        f"{_ENC_NAMES.get(ph.encoding, ph.encoding)}")
                if phys_width == 0:
                    raise Unsupported("dictionary-encoded booleans")
                want = ph.num_values * phys_width
                if len(payload) < want:
                    raise Unsupported("truncated dictionary page")
                self.vocab.append(np.frombuffer(
                    payload, raw_dtype, count=ph.num_values))
                self.vocab_size += ph.num_values
                saw_dict = True
                continue
            group_rows += ph.num_values
            self._add_data_page(payload, ph, phys_width, vocab_base,
                                saw_dict)
        self.rows += group_rows

    def _add_data_page(self, payload, ph: _PageHeader, phys_width: int,
                       vocab_base: int, saw_dict: bool) -> None:
        end = len(payload)
        pos = 0
        count = ph.num_values
        nnz = count
        if self.max_def:
            if ph.def_encoding != ENC_RLE:
                raise Unsupported(
                    "definition-level encoding "
                    f"{_ENC_NAMES.get(ph.def_encoding, ph.def_encoding)}")
            dl_len = int.from_bytes(payload[pos: pos + 4], "little")
            dl_runs, nnz = _valid_count(payload, pos + 4, pos + 4 + dl_len,
                                        count)
            pos += 4 + dl_len
            if nnz < count:
                self.has_nulls = True
            # splice the page's def runs onto the batch-wide stream
            for i in range(len(dl_runs.start)):
                if dl_runs.packed[i]:
                    b0 = dl_runs.bitbase[i] // 8
                    nbytes = (dl_runs.length[i] + 7) // 8
                    self.dlv.add_packed(
                        dl_runs.length[i],
                        dl_runs.pool[b0: b0 + nbytes], 1, 0)
                else:
                    self.dlv.add_rle(dl_runs.length[i], dl_runs.value[i],
                                     1, 0)
        enc = ph.encoding
        if enc in (ENC_PLAIN_DICTIONARY, ENC_RLE_DICTIONARY):
            if not saw_dict:
                raise Unsupported("dictionary-encoded page without a "
                                  "dictionary page")
            self._set_kind("dict")
            width = payload[pos] if pos < end else 0
            if width > self.max_bits or width > 32:
                raise Unsupported(f"dictionary bit width {width} > "
                                  f"{min(self.max_bits, 32)}")
            _parse_hybrid(payload, pos + 1, end, width, nnz, self.runs,
                          base=vocab_base)
        elif enc == ENC_PLAIN and phys_width == 0:  # booleans: LSB packed
            self._set_kind("bool")
            nbytes = (nnz + 7) // 8
            if end - pos < nbytes:
                raise Unsupported("truncated boolean page")
            self.runs.add_packed(nnz, payload[pos: pos + nbytes], 1, 0)
        elif enc == ENC_RLE and phys_width == 0:
            self._set_kind("bool")
            rl_len = int.from_bytes(payload[pos: pos + 4], "little")
            _parse_hybrid(payload, pos + 4, pos + 4 + rl_len, 1, nnz,
                          self.runs)
        elif enc == ENC_PLAIN:
            self._set_kind("plain")
            want = nnz * phys_width
            if end - pos < want:
                raise Unsupported("truncated PLAIN page")
            self.plain += payload[pos: pos + want]
        elif enc == ENC_DELTA_BINARY_PACKED:
            if not self.delta_enabled:
                raise Unsupported("DELTA_BINARY_PACKED disabled by "
                                  "spark.rapids.sql.decode.device.delta."
                                  "enabled")
            self._set_kind("delta")
            _parse_delta(payload, pos, end, nnz, self.delta, self.max_bits)
        else:
            raise Unsupported(
                f"encoding {_ENC_NAMES.get(enc, enc)} not device-decodable")
        self.nnz += nnz

    def finish(self, n_rows: int, cap: int) -> EncodedColumn:
        """Assemble the bucket-padded numpy planes for `n_rows` rows at
        row capacity `cap` (the decoded batch's capacity bucket)."""
        if self.kind is None:
            raise Unsupported("no data pages seen")
        if self.rows != n_rows:
            raise Unsupported(
                f"value count mismatch ({self.rows} != {n_rows})")
        if not self.has_nulls and self.nnz != n_rows:
            raise Unsupported(
                f"value/row count mismatch ({self.nnz} != {n_rows})")
        meta: List[Tuple[str, object]] = []
        # without nulls the value stream IS the row stream: expand it at
        # the row capacity so decode is a pure reshape/gather with no
        # placement pass; with nulls it gets its own (smaller) bucket
        vcap = cap if not self.has_nulls \
            else _shapes.bucket_rows(max(self.nnz, 1), 8)
        meta.append(("vcap", vcap))
        if self.kind == "plain":
            w = self.phys_width or 4
            pool = np.zeros(vcap * w, np.uint8)
            pool[: len(self.plain)] = np.frombuffer(self.plain, np.uint8)
            planes: Dict[str, np.ndarray] = {"pool": pool}
            meta.append(("w", w))
        elif self.kind == "bool":
            planes = _run_planes(self.runs, with_width=False)
        elif self.kind == "dict":
            planes = _run_planes(self.runs)
            raw_dtype = self.vocab[0].dtype if self.vocab else np.dtype("<i4")
            vocab = (np.concatenate(self.vocab) if len(self.vocab) > 1
                     else (self.vocab[0] if self.vocab
                           else np.zeros(0, raw_dtype)))
            vc = _shapes.bucket_rows(max(len(vocab), 1), 8,
                                     vocab.dtype.itemsize)
            planes["vocab"] = _pad32(vocab, vc)
        else:  # delta
            planes = _delta_planes(self.delta)
            meta.append(("vpm", self.delta.vpm))
        if self.has_nulls:
            planes.update(_run_planes(self.dlv, prefix="d_",
                                      with_width=False))
        meta.append(("nulls", self.has_nulls))
        nnz_plane = np.asarray([self.nnz], np.int64)
        planes["nnz"] = nnz_plane
        return EncodedColumn(self.kind, self.dtype, planes, tuple(meta),
                             bounds=self.bounds)


# ---------------------------------------------------------------------------
# File-level extraction
# ---------------------------------------------------------------------------

def _chunk_bytes(f, col_md) -> memoryview:
    start = col_md.data_page_offset
    if col_md.dictionary_page_offset is not None:
        start = min(start, col_md.dictionary_page_offset)
    f.seek(start)
    return memoryview(f.read(col_md.total_compressed_size))


def _leaf_index(metadata, name: str) -> Optional[int]:
    rg0 = metadata.row_group(0)
    for ci in range(rg0.num_columns):
        if rg0.column(ci).path_in_schema == name:
            return ci
    return None


def probe_support(path: str, fields: Sequence[T.StructField]
                  ) -> Dict[str, str]:
    """Static (footer-only) per-column fallback reasons for one file —
    the plan-time explain surface. Page-level surprises are still caught
    at execute time."""
    import pyarrow.parquet as pq
    out: Dict[str, str] = {}
    try:
        pf = pq.ParquetFile(path)
        md = pf.metadata
    except Exception as ex:  # noqa: BLE001 - unreadable file: scan raises
        return {f.name: f"footer unreadable: {ex}" for f in fields}
    if md.num_row_groups == 0:
        return {f.name: "file has no row groups" for f in fields}
    for fld in fields:
        ci = _leaf_index(md, fld.name)
        if ci is None:
            out[fld.name] = "column not in file"
            continue
        try:
            check_column_static(pf.schema.column(ci),
                                md.row_group(0).column(ci), fld.dtype)
        except Unsupported as ex:
            out[fld.name] = str(ex)
    return out


class HostEncodedBatch:
    """One coalesced group-set, pre-upload: numpy planes + per-column
    fallback bookkeeping the source exec turns into metrics/history."""

    __slots__ = ("columns", "num_rows", "cap", "fallback", "encoded_bytes",
                 "groups")

    def __init__(self, columns, num_rows, cap, fallback, encoded_bytes,
                 groups):
        self.columns = columns          # List[EncodedColumn|None] (None ->
        self.num_rows = num_rows        # host-decode this column index)
        self.cap = cap
        self.fallback = fallback        # Dict[name, reason]
        self.encoded_bytes = encoded_bytes
        self.groups = groups            # row-group ids in this batch


def _group_sets(metadata, groups: List[int], batch_rows: int
                ) -> Iterator[List[int]]:
    pending: List[int] = []
    rows = 0
    for g in groups:
        pending.append(g)
        rows += metadata.row_group(g).num_rows
        if rows >= batch_rows:
            yield pending
            pending, rows = [], 0
    if pending:
        yield pending


def read_encoded_batches(path: str, metadata, groups: List[int],
                         fields: Sequence[T.StructField], batch_rows: int,
                         max_bits: int = 32, delta_enabled: bool = True
                         ) -> Iterator[HostEncodedBatch]:
    """Extract the kept row groups of one file as encoded batches.
    Row-group pruning composes upstream: `groups` is the already-pruned
    list (io/parquet_pruning.py) and pruned groups are NEVER read, let
    alone uploaded. Columns that cannot take the device path come back as
    None entries with their reason in `fallback`; the caller host-decodes
    exactly those."""
    import pyarrow.parquet as pq
    pf = pq.ParquetFile(path)
    static_reasons: Dict[str, str] = {}
    col_idx: Dict[str, int] = {}
    for fld in fields:
        ci = _leaf_index(metadata, fld.name)
        if ci is None:
            static_reasons[fld.name] = "column not in file"
            continue
        col_idx[fld.name] = ci
        try:
            check_column_static(pf.schema.column(ci),
                                metadata.row_group(groups[0]).column(ci),
                                fld.dtype)
        except Unsupported as ex:
            static_reasons[fld.name] = str(ex)

    with open(path, "rb") as f:
        for gset in _group_sets(metadata, groups, batch_rows):
            n = sum(metadata.row_group(g).num_rows for g in gset)
            from spark_rapids_tpu.columnar.batch import round_capacity
            cap = round_capacity(n)
            cols: List[Optional[EncodedColumn]] = []
            fallback = dict(static_reasons)
            enc_bytes = 0
            for fld in fields:
                if fld.name in static_reasons:
                    cols.append(None)
                    continue
                ci = col_idx[fld.name]
                sc = pf.schema.column(ci)
                builder = _ColumnBuilder(fld.name, fld.dtype,
                                         sc.max_definition_level,
                                         max_bits, delta_enabled)
                try:
                    for g in gset:
                        cm = metadata.row_group(g).column(ci)
                        phys_width, raw_dtype = _PHYS[
                            str(cm.physical_type).upper()]
                        builder.add_group(_chunk_bytes(f, cm), cm,
                                          phys_width, raw_dtype)
                    ec = builder.finish(n, cap)
                except Unsupported as ex:
                    fallback[fld.name] = str(ex)
                    cols.append(None)
                    continue
                enc_bytes += ec.device_memory_size()
                cols.append(ec)
            yield HostEncodedBatch(cols, n, cap, fallback, enc_bytes, gset)


def upload(hb: HostEncodedBatch, decoded_cols: Dict[int, object]
           ) -> EncodedBatch:
    """Numpy planes -> device planes (the H2D boundary the source exec
    times under copyToDeviceTime). `decoded_cols` maps column index ->
    host-decoded ColumnVector for the fallback columns."""
    import jax.numpy as jnp
    out: List[EncodedColumn] = []
    for i, c in enumerate(hb.columns):
        if c is None:
            cv = decoded_cols[i]
            out.append(EncodedColumn("decoded", cv.dtype, {}, (), cv=cv,
                                     bounds=cv.bounds))
            continue
        planes = {k: jnp.asarray(v) for k, v in c.planes.items()}
        out.append(EncodedColumn(c.kind, c.dtype, planes, c.meta,
                                 bounds=c.bounds))
    return EncodedBatch(out, hb.num_rows, hb.cap)
