"""Cross-process / cross-host exchange over shuffle files.

Reference parity: the reference's shuffle rides Spark's shuffle files
(RapidsShuffleThreadedWriterBase writePartitionedData ->
standard shuffle files) so any executor can fetch any map output. Here the
same contract: a writer process hash-partitions a DataFrame and writes one
kudo-framed file per (map partition, reduce partition) plus a manifest;
any other process mounts the directory as a scan. Files are
self-describing (schema in the manifest, checksummed frames), so the
reader needs no shared memory with the writer — this is the unit the
DCN/object-store story builds on.
"""
from __future__ import annotations

import json
import os
from typing import List

from spark_rapids_tpu.shuffle import serde
from spark_rapids_tpu.shuffle.store import (
    read_reduce_partition, write_shuffle_file,
)

MANIFEST = "manifest.json"


def write_exchange(df, root: str, keys: List[str], n_out: int,
                   codec: str = "auto") -> None:
    """Hash-partition `df` by `keys` (murmur3 pmod, bit-parity with the
    in-process exchange) and write shuffle files + manifest under root."""
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.exec import tpu_nodes as X
    from spark_rapids_tpu.plan.nodes import bind_expr
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.runtime.task import TaskContext

    child, _ = convert_plan(df.plan, df.session.conf)
    ex = X.ShuffleExchangeExec(
        df.plan, [child], df.session.conf,
        [bind_expr(col(k), df.plan.schema) for k in keys],
        n_out=n_out)
    os.makedirs(root, exist_ok=True)
    for r in range(n_out):
        blobs = []
        with TaskContext(partition_id=r) as ctx:
            for batch in ex.execute_partition(ctx, r):
                blobs.append(serde.serialize_batch(batch, codec))
        write_shuffle_file(root, 0, r, blobs)
    schema = df.plan.schema
    manifest = {"n_reduce": n_out,
                "names": list(schema.names),
                "types": [serde.dtype_to_json(t) for t in schema.types]}
    with open(os.path.join(root, MANIFEST), "w") as f:
        json.dump(manifest, f)


def read_manifest(root: str) -> dict:
    with open(os.path.join(root, MANIFEST)) as f:
        return json.load(f)


def read_exchange(session, root: str):
    """Mount a shuffle directory as a DataFrame (one partition per reduce
    partition)."""
    from spark_rapids_tpu.plan import nodes as P
    from spark_rapids_tpu.sql.dataframe import DataFrame
    return DataFrame(P.ShuffleFileScan(root), session)


def read_partition_batches(root: str, reduce_id: int):
    for blob in read_reduce_partition(root, reduce_id):
        yield serde.deserialize_batch(blob)
