"""Spillable shuffle store: serialized partitions under a host budget.

Reference parity: ShuffleBufferCatalog.scala / ShuffleReceivedBufferCatalog
(spillable shuffle data) + RapidsShuffleThreadedWriterBase's file output.
Blobs land in host memory; when the store exceeds
spark.rapids.shuffle.hostSpillBudget the largest resident partitions flush
to per-partition spill files (append-only segments). Readers stream blobs
back in insertion order from memory or disk transparently.

This is what stops ExchangeExec being a full in-memory barrier: device
batches are serialized (device planes freed) and the serialized bytes
themselves page out to disk under pressure.
"""
from __future__ import annotations

import os
import shutil
import tempfile
import threading
from typing import Dict, Iterator, List, Optional, Tuple


class _DiskSeg:
    __slots__ = ("path", "off", "length")

    def __init__(self, path: str, off: int, length: int):
        self.path = path
        self.off = off
        self.length = length

    def read(self) -> bytes:
        with open(self.path, "rb") as f:
            f.seek(self.off)
            return f.read(self.length)


class ShuffleStore:
    """One exchange's worth of serialized partitions."""

    def __init__(self, n_partitions: int, host_budget_bytes: int,
                 spill_dir: Optional[str] = None):
        self.n_partitions = n_partitions
        from spark_rapids_tpu.analysis import sanitizer as _san
        self.host_budget = host_budget_bytes
        self._lock = _san.lock("shuffle.store")
        #: partition -> ordered blob list; bytes = resident, _DiskSeg = spilled
        self._parts: List[List[object]] = [[] for _ in range(n_partitions)]
        #: per-partition row tally (writer-supplied host ints): the skew
        #: detector (exec/adaptive.py) sizes serialized partitions from
        #: this instead of decoding blobs — same free-decision contract
        #: as the compact path's offsets vector
        self._rows: List[int] = [0] * n_partitions
        self._resident = 0
        self.bytes_written = 0
        self.bytes_spilled = 0
        self._dir = spill_dir
        self._owns_dir = spill_dir is None
        self._closed = False
        #: partitions with a spill write in flight (guards a victim from
        #: concurrent spills while the file write runs outside the lock)
        self._spilling: set = set()

    def _spill_path(self, p: int) -> str:
        if self._dir is None:
            self._dir = tempfile.mkdtemp(prefix="tpu_shuffle_")
            # spill dirs must not outlive the store: clean on GC/exit even
            # when close() is never called explicitly
            import weakref
            self._finalizer = weakref.finalize(
                self, shutil.rmtree, self._dir, True)
        return os.path.join(self._dir, f"part_{p}.bin")

    def add(self, partition: int, blob: bytes, rows: int = 0) -> None:
        with self._lock:
            assert not self._closed
            self._parts[partition].append(blob)
            self._rows[partition] += int(rows)
            self._resident += len(blob)
            self.bytes_written += len(blob)
        self._enforce_budget()

    def partition_rows(self, partition: int) -> int:
        """Writer-tallied row count for one partition (0 when the writer
        predates the tally or the partition is empty)."""
        with self._lock:
            return self._rows[partition]

    def _enforce_budget(self) -> None:
        # flush the partitions holding the most resident bytes first
        # (largest-victim-first, the spill framework's discipline). The
        # spill-file write runs OUTSIDE self._lock (the TPU-L001 bug
        # class: disk latency was blocking every concurrent writer's
        # add() bookkeeping): victim selection and the bookkeeping swap
        # take the lock, `_spilling` keeps two spills off one partition
        # file, and blob indexes stay stable because partition lists
        # only ever append (always under the lock).
        while True:
            with self._lock:
                if self._closed or self._resident <= self.host_budget:
                    return
                sizes = [(sum(len(b) for b in part if isinstance(b, bytes)),
                          p)
                         for p, part in enumerate(self._parts)
                         if p not in self._spilling]
                if not sizes:
                    return  # every candidate is already being spilled
                size, victim = max(sizes)
                if size == 0:
                    return
                self._spilling.add(victim)
                snapshot = list(self._parts[victim])
                path = self._spill_path(victim)
            try:
                from spark_rapids_tpu.runtime import faults as _faults
                segs = []
                try:
                    # injected disk faults surface exactly like real ones
                    # (the OSError handling below)
                    _faults.site("spill.disk")
                    with open(path, "ab") as f:
                        for i, b in enumerate(snapshot):
                            if isinstance(b, bytes):
                                off = f.tell()
                                f.write(b)
                                segs.append((i, off, len(b)))
                except OSError:
                    if self._closed:  # close() raced the spill: the dir
                        return        # is gone and so is the data's owner
                    raise
                with self._lock:
                    if self._closed:
                        return
                    part = self._parts[victim]
                    for i, off, ln in segs:
                        if isinstance(part[i], bytes):
                            part[i] = _DiskSeg(path, off, ln)
                            self._resident -= ln
                            self.bytes_spilled += ln
            finally:
                with self._lock:
                    self._spilling.discard(victim)

    def totals(self) -> dict:
        """Byte totals for the exchange's metric export (folded into the
        exchange exec's shuffleBytesWritten/Spilled GpuMetrics once per
        materialization — the live registry then rolls them up at query
        end; never read on the per-blob path)."""
        with self._lock:
            return {"bytes_written": self.bytes_written,
                    "bytes_spilled": self.bytes_spilled,
                    "bytes_resident": self._resident}

    def iter_partition(self, partition: int) -> Iterator[bytes]:
        for b in list(self._parts[partition]):
            yield b if isinstance(b, bytes) else b.read()

    def num_blobs(self, partition: int) -> int:
        with self._lock:
            return len(self._parts[partition])

    def read_blob(self, partition: int, index: int) -> bytes:
        """One blob by stable index (partition lists only ever append).
        Disk-resident blobs re-read their file segment on every call —
        the integrity-recovery path re-fetches a corrupt blob through
        here, so a transient disk read error heals on the second pass."""
        with self._lock:
            b = self._parts[partition][index]
        return b if isinstance(b, bytes) else b.read()

    def partition_bytes(self, partition: int) -> int:
        return sum(len(b) if isinstance(b, bytes) else b.length
                   for b in self._parts[partition])

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._parts = [[] for _ in range(self.n_partitions)]
            self._resident = 0
            rm_dir, self._dir = (self._dir if self._owns_dir else None), \
                (None if self._owns_dir else self._dir)
        # directory removal OUTSIDE the lock (TPU-L001): _closed already
        # fences every other method, and rmtree of a large spill dir is
        # unbounded I/O
        if rm_dir and os.path.isdir(rm_dir):
            shutil.rmtree(rm_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Cross-process shuffle files (the Spark-shuffle-files analog): a stable
# on-disk layout one process writes and another reads. Format per file:
# repeated [u64 little-endian blob length][blob bytes]; one file per
# (map partition, reduce partition).
# ---------------------------------------------------------------------------

def shuffle_file(root: str, map_id: int, reduce_id: int) -> str:
    return os.path.join(root, f"map_{map_id}_reduce_{reduce_id}.shuf")


def write_shuffle_file(root: str, map_id: int, reduce_id: int,
                       blobs: List[bytes]) -> str:
    os.makedirs(root, exist_ok=True)
    path = shuffle_file(root, map_id, reduce_id)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        for b in blobs:
            f.write(len(b).to_bytes(8, "little"))
            f.write(b)
    os.replace(tmp, path)
    return path


def read_shuffle_file(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            hdr = f.read(8)
            if len(hdr) < 8:
                return
            ln = int.from_bytes(hdr, "little")
            yield f.read(ln)


def read_reduce_partition(root: str, reduce_id: int) -> Iterator[bytes]:
    """All map outputs for one reduce partition, map order."""
    import glob
    import re
    paths = glob.glob(os.path.join(root, f"map_*_reduce_{reduce_id}.shuf"))

    def map_of(p):
        m = re.search(r"map_(\d+)_reduce_", os.path.basename(p))
        return int(m.group(1))

    for p in sorted(paths, key=map_of):
        yield from read_shuffle_file(p)
