"""Columnar batch <-> wire bytes (the kudo-analog serializer).

Reference parity: GpuColumnarBatchSerializer.scala:132 (kudo wire format
via jni.kudo.KudoSerializer) + TableCompressionCodec (nvcomp lz4/zstd).
Frame assembly/parsing and the integrity hash run in native C++
(native/kudo.cpp) when the toolchain is available; a pure-Python packer
with the identical layout is the fallback. Compression wraps the whole
frame: 1 codec byte + codec payload ('none' | 'zstd' | 'zlib' — the
spark.rapids.shuffle.compression.codec conf).

Planes are TRIMMED to live sizes on the wire (capacity padding never
ships) and re-padded to capacity buckets on deserialize, so a spilled or
remote batch costs bandwidth proportional to data, not to padding.

Integrity: the wire header carries a CRC32 over the codec byte + the
(possibly compressed) payload, verified on read BEFORE decompression —
so corruption anywhere in the blob (header, codec payload, frame) raises
ShuffleCorruptionError instead of a codec-dependent error soup. The
frame body keeps its xxhash64 as a second, codec-independent check.
Readers (exec/tpu_nodes._LazyShuffleBlobs) re-fetch a failing blob from
the shuffle store ONCE before surfacing the error, which recovers
transient disk corruption on the spill path.
"""
from __future__ import annotations

import ctypes
import json
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (
    ColumnVector, ColumnarBatch, round_capacity,
)
from spark_rapids_tpu.native import kudo_lib

_MAGIC = 0x54505544554B4F31
_VERSION = 1

CODEC_NONE = 0
CODEC_ZSTD = 1
CODEC_ZLIB = 2
_CODEC_NAMES = {"none": CODEC_NONE, "zstd": CODEC_ZSTD, "zlib": CODEC_ZLIB}

#: wire layout: [codec byte][CRC32 LE u32 over codec byte + payload][payload]
_WIRE_HEADER = 5


class ShuffleCorruptionError(ValueError):
    """A shuffle blob failed integrity verification (wire CRC or frame
    checksum). A ValueError subclass so pre-existing handlers of frame
    parse errors keep working; readers catch THIS type to drive the
    one-shot re-fetch recovery."""


_AUTO_CODEC: Optional[str] = None


def _resolve_auto() -> str:
    """'auto' -> zstd when its package exists, else stdlib zlib. Probed
    ONCE: a failed import is not negatively cached by Python and costs
    ~0.8 ms, which the per-batch serialize hot path must not repay."""
    global _AUTO_CODEC
    if _AUTO_CODEC is None:
        try:
            import zstandard  # noqa: F401
            _AUTO_CODEC = "zstd"
        except ImportError:
            _AUTO_CODEC = "zlib"
    return _AUTO_CODEC


def codec_id(name: str) -> int:
    key = (name or "none").lower()
    if key == "auto":
        # best available (an explicit 'zstd' below still fails fast when
        # the package is absent)
        key = _resolve_auto()
    if key == "lz4":
        # lz4 is not in this environment; zstd covers the same role
        raise ValueError(
            "shuffle codec 'lz4' is unavailable in this build; use 'zstd', "
            "'zlib', or 'none' (spark.rapids.shuffle.compression.codec)")
    if key not in _CODEC_NAMES:
        raise ValueError(f"unknown shuffle codec {name!r}")
    if key == "zstd":
        try:  # fail fast HERE, not mid-serialization in a worker thread
            import zstandard  # noqa: F401
        except ImportError as e:
            raise ValueError(
                "shuffle codec 'zstd' needs the zstandard package; use "
                "'zlib' or 'none'") from e
    return _CODEC_NAMES[key]


# ---------------------------------------------------------------------------
# dtype <-> json
# ---------------------------------------------------------------------------

def dtype_to_json(dt: T.DataType):
    if isinstance(dt, T.DecimalType):
        return {"t": "decimal", "p": dt.precision, "s": dt.scale}
    if isinstance(dt, T.ArrayType):
        return {"t": "array", "e": dtype_to_json(dt.element)}
    if isinstance(dt, T.MapType):
        return {"t": "map", "k": dtype_to_json(dt.key),
                "v": dtype_to_json(dt.value)}
    if isinstance(dt, T.StructType):
        return {"t": "struct",
                "f": [[f.name, dtype_to_json(f.dtype)] for f in dt.fields]}
    return {"t": type(dt).__name__}


_SIMPLE = {cls.__name__: cls() for cls in
           (T.NullType, T.BooleanType, T.Int8Type, T.Int16Type, T.Int32Type,
            T.Int64Type, T.Float32Type, T.Float64Type, T.StringType,
            T.DateType, T.TimestampType)}


def dtype_from_json(d) -> T.DataType:
    t = d["t"]
    if t == "decimal":
        return T.DecimalType(d["p"], d["s"])
    if t == "array":
        return T.ArrayType(dtype_from_json(d["e"]))
    if t == "map":
        return T.MapType(dtype_from_json(d["k"]), dtype_from_json(d["v"]))
    if t == "struct":
        return T.StructType(tuple(T.StructField(n, dtype_from_json(x))
                                  for n, x in d["f"]))
    return _SIMPLE[t]


# ---------------------------------------------------------------------------
# column <-> (descriptor, planes)
# ---------------------------------------------------------------------------

def _describe_column(col: ColumnVector, n: int, planes: List[np.ndarray]):
    """Append trimmed host planes; return a json-able descriptor. Planes
    must already be host numpy arrays."""
    def add(arr) -> int:
        planes.append(np.ascontiguousarray(arr))
        return len(planes) - 1

    valid_idx = None
    if col.validity is not None:
        valid_idx = add(np.asarray(col.validity)[:n])
    d: Dict = {"dtype": dtype_to_json(col.dtype), "valid": valid_idx}
    if col.is_dict:
        d["kind"] = "dict"
        d["unique"] = bool(col.dict_unique)
        d["planes"] = [add(np.asarray(col.data["codes"])[:n]),
                       add(np.asarray(col.data["dict_offsets"])),
                       add(np.asarray(col.data["dict_bytes"]))]
    elif isinstance(col.dtype, T.StringType):
        off = np.asarray(col.data["offsets"])[: n + 1]
        nbytes = int(off[-1]) if len(off) else 0
        d["kind"] = "str"
        d["planes"] = [add(off), add(np.asarray(col.data["bytes"])[:nbytes])]
    elif isinstance(col.dtype, T.ArrayType):
        off = np.asarray(col.data["offsets"])[: n + 1]
        n_el = int(off[-1]) if len(off) else 0
        d["kind"] = "array"
        d["planes"] = [add(off)]
        d["child"] = _describe_column(col.data["child"], n_el, planes)
    elif isinstance(col.dtype, T.MapType):
        off = np.asarray(col.data["offsets"])[: n + 1]
        n_el = int(off[-1]) if len(off) else 0
        d["kind"] = "map"
        d["planes"] = [add(off)]
        d["keys"] = _describe_column(col.data["keys"], n_el, planes)
        d["values"] = _describe_column(col.data["values"], n_el, planes)
    elif isinstance(col.dtype, T.StructType):
        d["kind"] = "struct"
        d["planes"] = []
        d["children"] = [_describe_column(ch, n, planes)
                         for ch in col.data["children"]]
    else:
        d["kind"] = "fixed"
        d["planes"] = [add(np.asarray(col.data)[:n])]
    return d


def _plane(buffers, idx, np_dtype) -> np.ndarray:
    return np.frombuffer(buffers[idx], dtype=np_dtype)


def _pad(arr: np.ndarray, cap: int, fill=0) -> jnp.ndarray:
    out = np.full((cap,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return jnp.asarray(out)


def _rebuild_column(d, buffers, n: int, cap: int) -> ColumnVector:
    dt = dtype_from_json(d["dtype"])
    validity = None
    if d["valid"] is not None:
        validity = _pad(_plane(buffers, d["valid"], np.bool_), cap, False)
    kind = d["kind"]
    if kind == "dict":
        codes = _pad(_plane(buffers, d["planes"][0], np.int32), cap)
        doff = jnp.asarray(_plane(buffers, d["planes"][1], np.int32))
        dby = _plane(buffers, d["planes"][2], np.uint8)
        dby = jnp.asarray(dby if len(dby) else np.zeros(1, np.uint8))
        return ColumnVector(dt, {"codes": codes, "dict_offsets": doff,
                                 "dict_bytes": dby}, validity,
                            dict_unique=bool(d.get("unique", True)))
    if kind == "str":
        off = _plane(buffers, d["planes"][0], np.int32)
        by = _plane(buffers, d["planes"][1], np.uint8)
        out_off = np.full(cap + 1, off[-1] if len(off) else 0, np.int32)
        out_off[: len(off)] = off
        bcap = round_capacity(max(len(by), 1))
        return ColumnVector(dt, {"offsets": jnp.asarray(out_off),
                                 "bytes": _pad(by, bcap)}, validity)
    if kind == "array":
        off = _plane(buffers, d["planes"][0], np.int32)
        n_el = int(off[-1]) if len(off) else 0
        ccap = round_capacity(max(n_el, 1))
        out_off = np.full(cap + 1, n_el, np.int32)
        out_off[: len(off)] = off
        child = _rebuild_column(d["child"], buffers, n_el, ccap)
        return ColumnVector(dt, {"offsets": jnp.asarray(out_off),
                                 "child": child}, validity)
    if kind == "map":
        off = _plane(buffers, d["planes"][0], np.int32)
        n_el = int(off[-1]) if len(off) else 0
        ccap = round_capacity(max(n_el, 1))
        out_off = np.full(cap + 1, n_el, np.int32)
        out_off[: len(off)] = off
        return ColumnVector(dt, {
            "offsets": jnp.asarray(out_off),
            "keys": _rebuild_column(d["keys"], buffers, n_el, ccap),
            "values": _rebuild_column(d["values"], buffers, n_el, ccap),
        }, validity)
    if kind == "struct":
        kids = [_rebuild_column(c, buffers, n, cap) for c in d["children"]]
        return ColumnVector(dt, {"children": kids}, validity)
    data = _pad(_plane(buffers, d["planes"][0], np.dtype(dt.np_dtype)), cap)
    return ColumnVector(dt, data, validity)


# ---------------------------------------------------------------------------
# frame pack/unpack (native fast path + python fallback, same layout)
# ---------------------------------------------------------------------------

def _align8(x: int) -> int:
    return (x + 7) & ~7


def _pack_frame(meta: bytes, planes: List[np.ndarray]) -> bytes:
    lib = kudo_lib()
    bufs = [p.tobytes() if not p.flags["C_CONTIGUOUS"] else p for p in planes]
    raw = [np.frombuffer(b, np.uint8) if isinstance(b, bytes)
           else b.view(np.uint8).reshape(-1) for b in bufs]
    lens = [int(r.nbytes) for r in raw]
    if lib is not None:
        n = len(raw)
        lens_arr = (ctypes.c_uint64 * n)(*lens)
        size = lib.kudo_frame_size(len(meta), n, lens_arr)
        out = np.empty(size, np.uint8)
        ptrs = (ctypes.POINTER(ctypes.c_uint8) * n)(
            *[r.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) for r in raw])
        written = lib.kudo_pack(
            np.frombuffer(meta, np.uint8).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)) if meta
            else ctypes.cast(ctypes.c_char_p(b"\0"),
                             ctypes.POINTER(ctypes.c_uint8)),
            len(meta), n, ptrs, lens_arr,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        assert written == size, (written, size)
        return out.tobytes()
    # pure-python identical layout
    parts = [struct.pack("<QII", _MAGIC, _VERSION, len(raw))[:16],
             struct.pack("<Q", len(meta)), meta,
             b"\0" * (_align8(len(meta)) - len(meta))]
    for ln in lens:
        parts.append(struct.pack("<Q", ln))
    for r, ln in zip(raw, lens):
        parts.append(r.tobytes())
        parts.append(b"\0" * (_align8(ln) - ln))
    body = b"".join(parts)
    h = _py_xxhash64(body)
    return body + struct.pack("<Q", h)


def _py_xxhash64(data: bytes, seed: int = 0) -> int:
    """Pure-python xxhash64 (spec implementation; slow, fallback only)."""
    P1, P2, P3 = 0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9
    P4, P5 = 0x85EBCA77C2B2AE63, 0x27D4EB2F165667C5
    M = (1 << 64) - 1

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    def rnd(acc, inp):
        return (rotl((acc + inp * P2) & M, 31) * P1) & M

    n = len(data)
    p = 0
    if n >= 32:
        v1, v2, v3, v4 = ((seed + P1 + P2) & M, (seed + P2) & M, seed & M,
                          (seed - P1) & M)
        while p + 32 <= n:
            v1 = rnd(v1, int.from_bytes(data[p:p + 8], "little")); p += 8
            v2 = rnd(v2, int.from_bytes(data[p:p + 8], "little")); p += 8
            v3 = rnd(v3, int.from_bytes(data[p:p + 8], "little")); p += 8
            v4 = rnd(v4, int.from_bytes(data[p:p + 8], "little")); p += 8
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
        for v in (v1, v2, v3, v4):
            h = ((h ^ rnd(0, v)) * P1 + P4) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while p + 8 <= n:
        h = (rotl(h ^ rnd(0, int.from_bytes(data[p:p + 8], "little")), 27)
             * P1 + P4) & M
        p += 8
    if p + 4 <= n:
        h = (rotl(h ^ (int.from_bytes(data[p:p + 4], "little") * P1) & M, 23)
             * P2 + P3) & M
        p += 4
    while p < n:
        h = (rotl(h ^ (data[p] * P5) & M, 11) * P1) & M
        p += 1
    h = ((h ^ (h >> 33)) * P2) & M
    h = ((h ^ (h >> 29)) * P3) & M
    return h ^ (h >> 32)


def _unpack_frame(data: bytes, verify: bool = True
                  ) -> Tuple[bytes, List[bytes]]:
    lib = kudo_lib()
    if lib is not None:
        arr = np.frombuffer(data, np.uint8)
        # size the descriptor tables from the header's own buffer count,
        # clamped by what the frame could possibly hold (a corrupt header
        # must not trigger a giant allocation)
        hdr_bufs = struct.unpack_from("<I", data, 12)[0] if len(data) >= 16 else 0
        max_bufs = max(1, min(hdr_bufs, len(data) // 8))
        meta_off = ctypes.c_uint64()
        meta_len = ctypes.c_uint64()
        n_bufs = ctypes.c_uint32()
        offs = (ctypes.c_uint64 * max_bufs)()
        lens = (ctypes.c_uint64 * max_bufs)()
        rc = lib.kudo_unpack(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(data),
            ctypes.byref(meta_off), ctypes.byref(meta_len),
            ctypes.byref(n_bufs), offs, lens, max_bufs,
            1 if verify else 0)
        if rc < 0:
            raise ShuffleCorruptionError(
                f"kudo frame parse failed (code {rc})")
        meta = data[meta_off.value: meta_off.value + meta_len.value]
        bufs = [data[offs[i]: offs[i] + lens[i]]
                for i in range(n_bufs.value)]
        return meta, bufs
    magic, version, nb = struct.unpack_from("<QII", data, 0)
    if magic != _MAGIC:
        raise ShuffleCorruptionError("bad kudo magic")
    if version != _VERSION:
        raise ValueError(f"unsupported kudo version {version}")
    (ml,) = struct.unpack_from("<Q", data, 16)
    pos = 24
    meta = data[pos: pos + ml]
    pos += _align8(ml)
    lens = []
    for _ in range(nb):
        (ln,) = struct.unpack_from("<Q", data, pos)
        lens.append(ln)
        pos += 8
    bufs = []
    for ln in lens:
        bufs.append(data[pos: pos + ln])
        pos += _align8(ln)
    if verify:
        (want,) = struct.unpack_from("<Q", data, pos)
        if _py_xxhash64(data[:pos]) != want:
            raise ShuffleCorruptionError("kudo frame checksum mismatch")
    return meta, bufs


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def serialize_batch(batch: ColumnarBatch, codec: str = "auto") -> bytes:
    """Device batch -> wire bytes. Masked batches are compacted first (dead
    rows never ship)."""
    import zlib

    from spark_rapids_tpu.ops import kernels as K
    from spark_rapids_tpu.columnar.batch import fetch_batch_host
    from spark_rapids_tpu.runtime import trace as TR
    with TR.span("shuffle.serialize", cat="shuffle",
                 level=TR.DEBUG) as sp:
        if batch.row_mask is not None:
            batch = K.compact_batch(batch)
        host = fetch_batch_host(batch)
        n = int(host.num_rows)
        planes: List[np.ndarray] = []
        cols = [_describe_column(c, n, planes) for c in host.columns]
        meta = json.dumps({"n": n, "cols": cols}).encode()
        frame = _pack_frame(meta, planes)
        cid = codec_id(codec)
        if cid == CODEC_ZSTD:
            import zstandard
            payload = zstandard.ZstdCompressor(level=1).compress(frame)
        elif cid == CODEC_ZLIB:
            payload = zlib.compress(frame, 1)
        else:
            payload = frame
        head = bytes([cid])
        crc = zlib.crc32(payload, zlib.crc32(head)) & 0xFFFFFFFF
        out = head + struct.pack("<I", crc) + payload
        if sp is not None:
            sp.args.update(rows=n, frame_bytes=len(frame),
                           wire_bytes=len(out))
        return out


def deserialize_batch(data: bytes, verify: bool = True) -> ColumnarBatch:
    """Wire bytes -> device batch (planes re-padded to capacity buckets)."""
    import zlib

    from spark_rapids_tpu.runtime import trace as TR
    with TR.span("shuffle.deserialize", cat="shuffle", level=TR.DEBUG,
                 args={"wire_bytes": len(data)}):
        if len(data) < _WIRE_HEADER:
            raise ShuffleCorruptionError(
                f"short shuffle blob ({len(data)} bytes)")
        cid = data[0]
        (want,) = struct.unpack_from("<I", data, 1)
        payload = data[_WIRE_HEADER:]
        if verify:
            got = zlib.crc32(payload, zlib.crc32(data[:1])) & 0xFFFFFFFF
            if got != want:
                raise ShuffleCorruptionError(
                    f"shuffle blob CRC mismatch (stored {want:#010x}, "
                    f"computed {got:#010x}, {len(data)} wire bytes)")
        if cid == CODEC_ZSTD:
            import zstandard
            frame = zstandard.ZstdDecompressor().decompress(payload)
        elif cid == CODEC_ZLIB:
            frame = zlib.decompress(payload)
        elif cid == CODEC_NONE:
            frame = payload
        else:
            raise ValueError(f"unknown codec id {cid}")
        meta, bufs = _unpack_frame(frame, verify=verify)
        desc = json.loads(meta.decode())
        n = desc["n"]
        cap = round_capacity(max(n, 1))
        cols = [_rebuild_column(d, bufs, n, cap) for d in desc["cols"]]
        return ColumnarBatch(cols, n)
