"""Shuffle subsystem: wire serialization, spillable shuffle store,
cross-process exchange (reference SURVEY.md §2.7)."""
