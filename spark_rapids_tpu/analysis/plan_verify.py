"""Plan-invariant verifier: structural checks over a CONVERTED exec tree.

``convert_plan`` ends with three tree rewrites (cost optimizer, stage
fusion, pipeline insertion) whose legality rules live in reviewers'
heads: a fused chain must be linear/narrow/carry-free, a pipeline
boundary must wrap exactly a scan, wrappers must be schema-transparent.
Each rule was hand-checked in the PR that introduced it and nothing
re-checks it as the passes evolve. This module re-derives them from the
tree itself:

- **schema consistency** (PV-SCHEMA): every node exposes a well-formed
  ``types.Schema``; pass-through nodes (Filter/Limit/Sort/TopN/Coalesce/
  Pipeline and the exchanges) must preserve their child's column names
  and types exactly — a wrapper that changes the schema is corrupting
  data, not routing it.
- **fusion-group legality** (PV-FUSE / PV-ABSORB): every
  ``FusedStageExec`` member is statically fusable, >=2 members actually
  dispatch (the pass's own profitability bar), the member chain is
  linked child-most-first, and stage ids are unique; an absorbed
  pre-chain hangs off a partial/complete ``HashAggregateExec`` with
  carry-free bodies.
- **pipeline legality** (PV-PIPE): a ``PipelineExec`` wraps exactly one
  scan, never the root, with depth >= 1 — the exact placement rule of
  ``insert_pipelines``.
- **dispatch budget** (:func:`dispatch_budget`): the static count of
  device dispatches per input batch the plan shape implies, exported as
  data so ``tests/golden_plans/dispatch_budgets.json`` can pin it per
  NDS probe query — a fusion or pipeline regression then fails a test
  instead of showing up as silent perf loss.

Run it two ways: ``spark.rapids.debug.planVerify.enabled`` makes
``convert_plan`` verify every tree it returns (debug conf — the walk is
linear but touches every node), and the golden-budget tests in CI verify
the NDS probe plans unconditionally.

Duck-typed by class NAME (like ``metrics.walk_exec_tree``): the exec
classes for fusion/pipelining are created lazily against the live base,
so isinstance against them would force imports this module doesn't need.
"""
from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["PlanVerifyError", "check_plan", "verify_plan",
           "dispatch_budget", "compare_budget"]


class PlanVerifyError(AssertionError):
    """A converted exec tree violates an engine invariant. Raised before
    execution starts — a malformed plan must never reach the device."""

    def __init__(self, violations: List[str]):
        self.violations = violations
        super().__init__(
            "plan verification failed (%d violation%s):\n  " % (
                len(violations), "s" if len(violations) != 1 else "")
            + "\n  ".join(violations))


#: wrappers that must hand their child's schema through unchanged
_SCHEMA_PRESERVING = {
    "FilterExec", "LimitExec", "SortExec", "TopNExec",
    "CoalesceBatchesExec", "PipelineExec", "ShuffleExchangeExec",
    "RoundRobinExchangeExec", "RangeExchangeExec", "CollectExchangeExec",
}

#: the only nodes insert_pipelines may wrap (its scan_types tuple)
_PIPELINE_WRAPPABLE = {
    "ParquetScanExec", "EncodedParquetSourceExec", "TextScanExec",
    "InMemoryScanExec", "ShuffleFileScanExec",
}


def _cls(node) -> str:
    # PipelineExec.name() renders as "PipelineExec(depth=N)"; the class
    # name is the stable identity
    return type(node).__name__


def _schema_sig(schema) -> Optional[list]:
    try:
        return [(f.name, f.dtype) for f in schema.fields]
    except Exception:  # noqa: BLE001 - malformed schema reported by caller
        return None


def _check_schema(node, path: str, out: List[str]) -> None:
    sig = _schema_sig(node.schema)
    if sig is None:
        out.append(f"PV-SCHEMA {path}: schema is not a well-formed "
                   f"types.Schema (fields of name+dtype)")
        return
    for name, dtype in sig:
        if not isinstance(name, str) or dtype is None:
            out.append(f"PV-SCHEMA {path}: malformed field "
                       f"{name!r}:{dtype!r}")
    if _cls(node) in _SCHEMA_PRESERVING and node.children:
        child_sig = _schema_sig(node.children[0].schema)
        if child_sig is not None and child_sig != sig:
            out.append(
                f"PV-SCHEMA {path}: {_cls(node)} must preserve its "
                f"child's schema but maps {child_sig} -> {sig}")


def _check_fused(node, path: str, seen_stage_ids: Dict[int, str],
                 out: List[str]) -> None:
    from spark_rapids_tpu.exec import stage_fusion as SF
    members = node.members
    if not members:
        out.append(f"PV-FUSE {path}: FusedStageExec with no members")
        return
    if len(node.children) != 1:
        out.append(f"PV-FUSE {path}: fused stage must have exactly one "
                   f"child, has {len(node.children)}")
    for m in members:
        if not SF._fusable(m):
            out.append(f"PV-FUSE {path}: member {_cls(m)} is not a "
                       f"fusable narrow operator")
    n_disp = sum(1 for m in members if SF._dispatching(m))
    if n_disp < 2:
        out.append(f"PV-FUSE {path}: only {n_disp} dispatching member(s) "
                   f"— fusion is only legal when >=2 dispatches collapse "
                   f"(lone narrow ops must stay unfused)")
    for i in range(len(members) - 1):
        nxt = members[i + 1]
        if not nxt.children or nxt.children[0] is not members[i]:
            out.append(f"PV-FUSE {path}: members are not a linked chain "
                       f"child-most-first at position {i + 1} "
                       f"({_cls(nxt)})")
    if node.plan is not members[-1].plan:
        out.append(f"PV-FUSE {path}: fused stage must carry the chain "
                   f"head's plan node ({_cls(members[-1])})")
    _check_stage_id(getattr(node, "stage_id", 0), path, "PV-FUSE",
                    seen_stage_ids, out)


def _check_absorbed(node, path: str, seen_stage_ids: Dict[int, str],
                    out: List[str]) -> None:
    from spark_rapids_tpu.exec import stage_fusion as SF
    if _cls(node) != "HashAggregateExec":
        out.append(f"PV-ABSORB {path}: pre-chain absorbed into "
                   f"{_cls(node)} — only HashAggregateExec may absorb")
        return
    if node.mode not in ("partial", "complete"):
        out.append(f"PV-ABSORB {path}: absorbing aggregate has mode "
                   f"{node.mode!r}; only partial/complete update kernels "
                   f"may run a pre-chain")
    members = node.pre_chain_members
    for m in members:
        if not SF._fusable(m):
            out.append(f"PV-ABSORB {path}: pre-chain member {_cls(m)} is "
                       f"not a fusable narrow operator")
    if not any(SF._dispatching(m) for m in members):
        out.append(f"PV-ABSORB {path}: no pre-chain member dispatches — "
                   f"absorbing saves nothing and costs a retrace")
    for body in node.pre_chain:
        if body.has_carry:
            out.append(f"PV-ABSORB {path}: pre-chain body {body.key!r} "
                       f"carries state — carries cannot thread through "
                       f"the aggregate update kernel")
    for i in range(len(members) - 1):
        nxt = members[i + 1]
        if not nxt.children or nxt.children[0] is not members[i]:
            out.append(f"PV-ABSORB {path}: pre-chain members are not a "
                       f"linked chain child-most-first at position "
                       f"{i + 1} ({_cls(nxt)})")
    _check_stage_id(getattr(node, "fused_stage_id", 0), path, "PV-ABSORB",
                    seen_stage_ids, out)


def _check_stage_id(sid, path: str, rule: str,
                    seen_stage_ids: Dict[int, str], out: List[str]) -> None:
    if not isinstance(sid, int) or sid <= 0:
        out.append(f"{rule} {path}: stage id must be a positive int, "
                   f"got {sid!r}")
        return
    prev = seen_stage_ids.get(sid)
    if prev is not None:
        out.append(f"{rule} {path}: stage id {sid} already used by "
                   f"{prev}")
    else:
        seen_stage_ids[sid] = path


def _check_pipeline(node, path: str, is_root: bool, out: List[str]) -> None:
    if is_root:
        out.append(f"PV-PIPE {path}: PipelineExec at the root — the "
                   f"consumer side of the boundary would be the driver "
                   f"loop itself (insert_pipelines only wraps non-root "
                   f"scans)")
    if len(node.children) != 1:
        out.append(f"PV-PIPE {path}: pipeline boundary must wrap exactly "
                   f"one child, has {len(node.children)}")
        return
    child = node.children[0]
    if _cls(child) not in _PIPELINE_WRAPPABLE:
        out.append(f"PV-PIPE {path}: pipeline wraps {_cls(child)} — only "
                   f"host-producing scans are legal boundaries "
                   f"({sorted(_PIPELINE_WRAPPABLE)})")
    if not isinstance(node.depth, int) or node.depth < 1:
        out.append(f"PV-PIPE {path}: lookahead depth must be >= 1, got "
                   f"{node.depth!r} (depth<=0 plans must stay unwrapped)")


def check_plan(exec_root) -> List[str]:
    """All violations in a converted exec tree (empty list = clean).
    Linear in tree size; no device work, no imports beyond the already-
    loaded exec layer."""
    out: List[str] = []
    seen_stage_ids: Dict[int, str] = {}
    on_stack: set = set()

    def walk(node, path: str, is_root: bool) -> None:
        if id(node) in on_stack:
            out.append(f"PV-TREE {path}: cycle — node {_cls(node)} is "
                       f"its own ancestor")
            return
        on_stack.add(id(node))
        try:
            _check_schema(node, path, out)
            if getattr(node, "members", None):
                _check_fused(node, path, seen_stage_ids, out)
            if getattr(node, "pre_chain_members", None):
                _check_absorbed(node, path, seen_stage_ids, out)
            if _cls(node) == "PipelineExec":
                _check_pipeline(node, path, is_root, out)
            if not isinstance(node.children, list):
                out.append(f"PV-TREE {path}: children must be a list")
                return
            for i, c in enumerate(node.children):
                walk(c, f"{path}/{_cls(c)}[{i}]", False)
        finally:
            on_stack.discard(id(node))

    walk(exec_root, _cls(exec_root), True)
    return out


def verify_plan(exec_root) -> None:
    """Raise :class:`PlanVerifyError` listing every violation (or return
    silently). Called by ``convert_plan`` under
    ``spark.rapids.debug.planVerify.enabled`` and by the CI golden
    tests."""
    violations = check_plan(exec_root)
    if violations:
        raise PlanVerifyError(violations)


# ---------------------------------------------------------------------------
# Dispatch budgets
# ---------------------------------------------------------------------------

def dispatch_budget(exec_root) -> dict:
    """Static per-batch device-dispatch budget of a converted tree.

    Counts the NARROW dispatching sites — the ones stage fusion exists to
    collapse: one per fused stage, one per aggregate update (its absorbed
    pre-chain rides for free), one per standalone Filter/Expand/
    non-trivial Project that escaped fusion. Wide operators (joins,
    sorts, exchanges) dispatch data-dependently and are out of scope —
    the budget pins the plan SHAPE, not the workload. Also exports the
    fusion groups, pipeline-boundary count and exec-class census so a
    golden file diff says exactly what changed."""
    from spark_rapids_tpu.exec import stage_fusion as SF
    from spark_rapids_tpu.runtime.metrics import walk_exec_tree

    narrow = 0
    pipeline_boundaries = 0
    exec_count = 0
    census: Dict[str, int] = {}
    for _key, node, _depth, role, _sid in walk_exec_tree(exec_root):
        name = _cls(node)
        if role is not None:
            # fused members / absorbed pre-chains never dispatch alone
            continue
        exec_count += 1
        census[name] = census.get(name, 0) + 1
        if name == "PipelineExec":
            pipeline_boundaries += 1
        elif name in ("FusedStageExec", "ShardedStageExec"):
            # a sharded stage is still ONE narrow dispatch per batch —
            # per WAVE it is one per n_shards batches, but the budget
            # pins the per-batch upper bound of the plan shape
            narrow += 1
        elif name == "HashAggregateExec":
            narrow += 1
        elif name in ("FilterExec", "ExpandExec", "ProjectExec"):
            if SF._dispatching(node):
                narrow += 1
    groups = SF.fusion_groups(exec_root)
    return {
        "narrow_dispatches_per_batch": narrow,
        "fused_stages": sum(1 for g in groups if g["kind"] == "fused"),
        "absorbed_stages": sum(1 for g in groups
                               if g["kind"] == "absorbed"),
        "fusion_groups": [
            {"kind": g["kind"], "members": g["members"]} for g in groups],
        "pipeline_boundaries": pipeline_boundaries,
        "exec_count": exec_count,
        "exec_census": dict(sorted(census.items())),
    }


def compare_budget(actual: dict, golden: dict) -> List[str]:
    """Human-readable diffs between a plan's budget and its golden pin
    (empty = match). Key-by-key so a failure names the regressed
    dimension instead of dumping two dicts."""
    diffs = []
    for key in sorted(set(golden) | set(actual)):
        if actual.get(key) != golden.get(key):
            diffs.append(f"{key}: golden {golden.get(key)!r} != actual "
                         f"{actual.get(key)!r}")
    return diffs
