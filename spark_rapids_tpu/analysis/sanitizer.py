"""Runtime concurrency sanitizer: instrumented Lock/Condition wrappers.

The engine's concurrency rules are enforced statically by tpulint
(TPU-L001/2) — but a lint can only see syntax. This module is the
runtime half: the ~14 named lock sites in ``runtime/``, ``shuffle/`` and
``io/`` construct their locks through :func:`lock` / :func:`condition`,
and when ``spark.rapids.debug.sanitizer.enabled`` is on each acquire /
release / wait feeds a process-wide analysis:

- **lock-order graph**: acquiring B while holding A records the edge
  A→B (first stacks kept, occurrences counted). A new edge that closes
  a cycle in the name graph is a potential-deadlock (lock inversion)
  finding — the classic ABBA that only hangs under the right
  interleaving, reported on the FIRST run that merely *exhibits both
  orders*, deadlock or not.
- **held-lock blocking**: a lock held longer than
  ``spark.rapids.debug.sanitizer.holdWarnMs`` is reported with the
  acquire-site stack — the runtime signature of I/O (or a wedged
  callback) inside a critical section, the exact bug class TPU-L001
  lints for statically and PR 5 review hit in TrafficController.
- **wait-under-lock**: ``Condition.wait`` releases only its OWN lock;
  waiting while holding any *other* sanitized lock blocks that lock for
  the full wait and is reported immediately.

Overhead discipline (the tracing bar): when the sanitizer is off every
proxy operation is ONE module-global read + a delegated call — gated
<2% end-to-end by ``tools/sanitizer_smoke.py`` the same way
``tools/trace_overhead.py`` gates tracing. Python's GIL already
serializes the interpreter, so unlike a C++ TSAN these wrappers never
need atomics of their own; the internal state lock is held only for
dict bookkeeping, never across emission or user code.

Reporting: findings accumulate process-wide; :func:`report` returns
them ranked (inversions, then waits-under-lock, then longest holds) and
:func:`dump` additionally emits one ``sanitizerFinding`` instant per
finding through the PR 3 trace machinery (``runtime/trace.py``), so a
traced query's Perfetto timeline shows the findings in place.
"""
from __future__ import annotations

import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = ["lock", "condition", "install", "uninstall", "maybe_install",
           "enabled", "report", "dump", "reset"]

#: THE enabled flag: every proxy operation reads this once. None =
#: disabled (delegate straight to the wrapped primitive).
_STATE: "Optional[_SanState]" = None


def _stack(depth: int) -> Tuple[str, ...]:
    """Acquire-site stack, innermost last, sanitizer frames dropped."""
    frames = traceback.extract_stack()
    out = []
    for f in frames:
        if f.filename.endswith("analysis/sanitizer.py"):
            continue
        out.append(f"{f.filename}:{f.lineno} {f.name}")
    return tuple(out[-depth:])


class _SanState:
    """Process-wide sanitizer state. The internal lock guards only the
    graph/finding dicts — it is never held across lock waits, emission,
    or any user code, so it cannot itself participate in a cycle."""

    def __init__(self, hold_warn_ms: float = 50.0, stack_depth: int = 8):
        self.hold_warn_ms = hold_warn_ms
        self.stack_depth = stack_depth
        self._ilock = threading.Lock()
        #: per-thread stack of live holds: [(proxy_id, name, t0_ns, stack)]
        self._tl = threading.local()
        #: (held_name, acquired_name) -> {count, stack_held, stack_acq}
        self.edges: Dict[Tuple[str, str], dict] = {}
        #: out-adjacency over names, for cycle checks
        self._adj: Dict[str, set] = {}
        self.findings: List[dict] = []
        #: finding dedup keys (an inversion/hold site reports once)
        self._seen: set = set()

    # -- hold stack --------------------------------------------------------

    def holds(self) -> List[tuple]:
        h = getattr(self._tl, "holds", None)
        if h is None:
            h = self._tl.holds = []
        return h

    # -- graph -------------------------------------------------------------

    def _path_exists(self, src: str, dst: str) -> bool:
        """DFS over the name graph (tiny: tens of nodes)."""
        seen = set()
        stack = [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self._adj.get(n, ()))
        return False

    def record_acquired(self, proxy, name: str, blocked_ns: int) -> None:
        holds = self.holds()
        t0 = time.perf_counter_ns()
        stack = _stack(self.stack_depth)
        new_findings = []
        with self._ilock:
            for _, held_name, _, held_stack in holds:
                if held_name == name:
                    # same-name edges (two instances of one class) need
                    # an address-ordering discipline to judge; tracked
                    # as an edge, excluded from cycle findings
                    pass
                edge = (held_name, name)
                info = self.edges.get(edge)
                if info is None:
                    # a NEW edge: does it close a cycle?
                    if held_name != name and self._path_exists(
                            name, held_name):
                        key = ("inversion",) + tuple(sorted((held_name,
                                                             name)))
                        if key not in self._seen:
                            self._seen.add(key)
                            new_findings.append({
                                "kind": "lock-inversion",
                                "severity": 0,
                                "locks": [held_name, name],
                                "detail": f"acquired {name!r} while "
                                          f"holding {held_name!r}, but the "
                                          f"opposite order is also on "
                                          f"record — potential deadlock",
                                "stack_held": list(held_stack),
                                "stack": list(stack),
                            })
                    self.edges[edge] = {"count": 1,
                                        "stack_held": list(held_stack),
                                        "stack_acq": list(stack)}
                    self._adj.setdefault(held_name, set()).add(name)
                else:
                    info["count"] += 1
            self.findings.extend(new_findings)
        holds.append((id(proxy), name, t0, stack))

    def record_released(self, proxy, name: str) -> None:
        holds = self.holds()
        # releases are LIFO in the with-statement world, but search back
        # to front so out-of-order manual release() stays correct
        for i in range(len(holds) - 1, -1, -1):
            if holds[i][0] == id(proxy):
                _, _, t0, stack = holds.pop(i)
                held_ms = (time.perf_counter_ns() - t0) / 1e6
                if held_ms >= self.hold_warn_ms:
                    self._add_hold_finding(name, held_ms, stack)
                return
        # acquire predates install() (or a foreign thread releasing):
        # nothing to attribute

    def _add_hold_finding(self, name: str, held_ms: float,
                          stack: Tuple[str, ...]) -> None:
        key = ("hold", name, stack)
        with self._ilock:
            if key in self._seen:
                for f in self.findings:
                    if f.get("_key") == key:
                        f["held_ms"] = max(f["held_ms"], round(held_ms, 3))
                        f["count"] = f.get("count", 1) + 1
                        break
                return
            self._seen.add(key)
            self.findings.append({
                "kind": "held-lock-blocking",
                "severity": 2,
                "locks": [name],
                "held_ms": round(held_ms, 3),
                "count": 1,
                "detail": f"{name!r} held {held_ms:.1f}ms (warn "
                          f"threshold {self.hold_warn_ms:.0f}ms) — "
                          f"blocking work inside the critical section",
                "stack": list(stack),
                "_key": key,
            })

    def record_wait_under_lock(self, cv_name: str) -> None:
        others = [h[1] for h in self.holds() if h[1] != cv_name]
        if not others:
            return
        stack = _stack(self.stack_depth)
        key = ("wait", cv_name, tuple(others), stack)
        with self._ilock:
            if key in self._seen:
                return
            self._seen.add(key)
            self.findings.append({
                "kind": "wait-under-lock",
                "severity": 1,
                "locks": [cv_name] + others,
                "detail": f"Condition {cv_name!r} wait() while holding "
                          f"{others!r} — wait releases only its own "
                          f"lock; the others stay blocked for the full "
                          f"wait",
                "stack": list(stack),
            })


class _SanLock:
    """Lock proxy. Disabled: one global read + delegation. Enabled:
    order-graph + hold-time accounting around the real primitive."""

    __slots__ = ("_lk", "name")

    def __init__(self, name: str, lk=None):
        self._lk = lk if lk is not None else threading.Lock()
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = _STATE
        if st is None:
            return self._lk.acquire(blocking, timeout)
        t0 = time.perf_counter_ns()
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            st.record_acquired(self, self.name,
                               time.perf_counter_ns() - t0)
        return ok

    def release(self) -> None:
        st = _STATE
        # attribute the hold BEFORE the real release: after it, another
        # thread may already be inside the region we are timing
        if st is not None:
            st.record_released(self, self.name)
        self._lk.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _SanCondition(_SanLock):
    """Condition proxy: a _SanLock whose wait() suspends its own hold
    record (wait releases the underlying lock) and reports waits made
    while other sanitized locks are held."""

    __slots__ = ()

    def __init__(self, name: str):
        super().__init__(name, threading.Condition())

    def wait(self, timeout: Optional[float] = None) -> bool:
        st = _STATE
        if st is None:
            return self._lk.wait(timeout)
        st.record_wait_under_lock(self.name)
        # the wait releases this cv's lock: close the hold record now
        # (a long WAIT is idle, not a held-lock block) and re-open it
        # when the wait returns re-acquired
        st.record_released(self, self.name)
        try:
            return self._lk.wait(timeout)
        finally:
            st2 = _STATE
            if st2 is not None:
                st2.record_acquired(self, self.name, 0)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        st = _STATE
        if st is None:
            return self._lk.wait_for(predicate, timeout)
        st.record_wait_under_lock(self.name)
        st.record_released(self, self.name)
        try:
            return self._lk.wait_for(predicate, timeout)
        finally:
            st2 = _STATE
            if st2 is not None:
                st2.record_acquired(self, self.name, 0)

    def notify(self, n: int = 1) -> None:
        self._lk.notify(n)

    def notify_all(self) -> None:
        self._lk.notify_all()


# ---------------------------------------------------------------------------
# Factories (what the engine's lock sites call)
# ---------------------------------------------------------------------------

def lock(name: str) -> _SanLock:
    """A named engine lock. Always a proxy, so the sanitizer can be
    enabled after the lock was created (module-global locks are built at
    import time, long before any session conf exists)."""
    return _SanLock(name)


def condition(name: str) -> _SanCondition:
    return _SanCondition(name)


# ---------------------------------------------------------------------------
# Lifecycle
# ---------------------------------------------------------------------------

def install(hold_warn_ms: float = 50.0, stack_depth: int = 8) -> None:
    global _STATE
    if _STATE is None:
        _STATE = _SanState(hold_warn_ms, stack_depth)


def uninstall() -> None:
    global _STATE
    _STATE = None


def reset() -> None:
    """Drop accumulated state but keep the sanitizer enabled (tests)."""
    global _STATE
    st = _STATE
    if st is not None:
        _STATE = _SanState(st.hold_warn_ms, st.stack_depth)


def enabled() -> bool:
    return _STATE is not None


def maybe_install(conf) -> None:
    """Session bootstrap hook: install when the debug conf says so. A
    later session turning the conf off does NOT uninstall — findings are
    process-scoped and other sessions may still rely on them; call
    :func:`uninstall` explicitly to stop."""
    from spark_rapids_tpu import config as C
    if conf.get(C.SANITIZER_ENABLED):
        install(hold_warn_ms=conf.get(C.SANITIZER_HOLD_WARN_MS),
                stack_depth=conf.get(C.SANITIZER_STACK_DEPTH))


# ---------------------------------------------------------------------------
# Reporting
# ---------------------------------------------------------------------------

def report() -> dict:
    """Ranked findings snapshot: inversions first, then waits-under-lock,
    then held-lock blocks by duration."""
    st = _STATE
    if st is None:
        return {"enabled": False, "findings": [], "edges": 0}
    with st._ilock:
        findings = [dict(f) for f in st.findings]
        n_edges = len(st.edges)
        edges = [{"from": a, "to": b, "count": i["count"]}
                 for (a, b), i in st.edges.items()]
    for f in findings:
        f.pop("_key", None)
    findings.sort(key=lambda f: (f["severity"],
                                 -float(f.get("held_ms", 0.0))))
    return {"enabled": True, "findings": findings, "edges": n_edges,
            "order_edges": edges}


def dump() -> dict:
    """report() + one ``sanitizerFinding`` trace instant per finding (a
    no-op when tracing is off), ranked — the PR 3 machinery is the
    transport, so findings land on the traced query's timeline."""
    rep = report()
    if rep["findings"]:
        from spark_rapids_tpu.runtime import trace
        for f in rep["findings"]:
            trace.instant("sanitizerFinding", cat="sanitizer", args={
                "kind": f["kind"], "locks": f["locks"],
                "detail": f["detail"]})
    return rep
